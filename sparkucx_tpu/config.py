"""Typed configuration for the TPU shuffle framework.

Counterpart of ``UcxShuffleConf`` (UcxShuffleConf.scala:18-93): a typed namespace over
string key/value config, with the same knobs (renamed ``spark.shuffle.ucx.*`` ->
``spark.shuffle.tpu.*``) plus the TPU-specific ones.  Hardcoded POC constants the
reference buried in code are first-class options here (SURVEY.md section 5.6):
device-space sizing (NvkvHandler.scala:26-29), store port 1338
(CommonUcxShuffleManager.scala:84-89), 512-byte alignment (NvkvHandler.scala:244-256).
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Tuple

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kmgt]?i?b?)\s*$", re.IGNORECASE)
_UNITS = {
    "": 1, "b": 1,
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_size(text) -> int:
    """Parse '4k' / '1m' / '30MB' style sizes (Spark's byte-string conf format)."""
    if isinstance(text, (int, float)):
        return int(text)
    m = _SIZE_RE.match(str(text))
    if not m:
        raise ValueError(f"unparseable size: {text!r}")
    return int(float(m.group(1)) * _UNITS[m.group(2).lower()])


CONF_PREFIX = "spark.shuffle.tpu"


@dataclass
class TpuShuffleConf:
    """All framework knobs.  Field-by-field provenance:

    ===============================  ==============================================
    prealloc_buffers                 spark.shuffle.ucx.memory.preAllocateBuffers
                                     (UcxShuffleConf.scala:21-31) — size->count map
    min_buffer_size                  ...memory.minBufferSize = 4096 (:33-39)
    min_allocation_size              ...memory.minAllocationSize = 1 MiB (:41-48)
    listener_address                 ...listener.sockaddr = "0.0.0.0:0" (:50-56)
    use_wakeup                       ...useWakeup = true (:58-64)
    num_io_threads                   ...numIoThreads = 1 (:66-71)
    num_listener_threads             ...numListenerThreads = 3 (:73-78)
    num_client_workers               ...numWorkers (defaults to executor cores,
                                     :80-86)
    max_blocks_per_request           ...maxBlocksPerRequest = 50 (:88-93)
    block_alignment                  NVKV 512-byte write alignment
                                     (NvkvHandler.scala:244-256); 512 = one
                                     exchange row of 128 int32 lanes
    staging_capacity_per_executor    NVKV device-space carve-up / 30 MB read buf
                                     (NvkvHandler.scala:26-29,
                                     NvkvShuffleMapOutputWriter.scala:94-103)
    store_port                       DPU daemon port 1338
                                     (CommonUcxShuffleManager.scala:84-89)
    ===============================  ==============================================
    """

    # memory pool (L1)
    prealloc_buffers: Dict[int, int] = field(default_factory=dict)
    min_buffer_size: int = 4096
    min_allocation_size: int = 1 << 20
    max_host_pool_bytes: int = 1 << 31

    # transport / workers (L3)
    listener_address: Tuple[str, int] = ("0.0.0.0", 0)
    use_wakeup: bool = True
    num_io_threads: int = 1
    num_listener_threads: int = 3
    num_client_workers: int = 1
    max_blocks_per_request: int = 50
    #: Per-block pull-path retries after a failed batch fetch (the reference
    #: never retries — SURVEY.md section 5.3); 0 disables the fallback.
    fetch_retries: int = 1

    # striped zero-copy wire path (transport/peer.py)
    #: Parallel TCP connections (lanes) per peer pair.  1 (default) is the
    #: single-lane path, byte-identical to the pre-striping wire protocol.
    #: With K > 1, large fetch replies stream as fixed chunk frames striped
    #: round-robin across the K lanes (AM ids 5-6, core/definitions.py) and
    #: each lane's recv thread scatters its chunks into the result buffers
    #: concurrently — the FAST/SparkUCX parallel-stream prescription for
    #: saturating a host link from Python.
    wire_streams: int = 1
    #: Chunk frame payload size for striped replies.  Smaller chunks spread
    #: a single hot reply across lanes sooner; larger chunks cut per-frame
    #: syscall + header overhead.  4 MiB is the measured knee on loopback
    #: (1 MiB loses ~15% to per-frame overhead; see docs/PERF.md).
    wire_chunk_bytes: int = 4 << 20
    #: Reduce-side fetch credit budget in bytes: the reader keeps issuing
    #: fetch windows while their expected reply bytes fit the budget, so many
    #: windows pipeline instead of strictly alternating request/drain.  A
    #: request larger than the whole budget is admitted alone (never starved).
    #: 0 disables pipelining — one window in flight, the historical loop.
    wire_credit_bytes: int = 64 << 20
    #: SO_SNDBUF/SO_RCVBUF for every peer/daemon socket, both ends; 0 keeps
    #: the platform default plus the transport's builtin 4 MiB reply windows.
    wire_sock_buf_bytes: int = 0
    #: Socket timeout (ms) for connect/handshake and every mid-frame read on
    #: both client and server wire paths.  A peer that hangs (alive socket, no
    #: bytes) mid-frame for longer than this raises a TransportError naming the
    #: peer address instead of blocking forever.  Idle waits between frames are
    #: exempt — only a partially received frame can time out.  0 = no timeout
    #: (the historical block-forever behavior).
    wire_timeout_ms: int = 30000

    # fault tolerance (replication + reducer failover)
    #: Number of ring-neighbor executors that receive an asynchronous copy of
    #: each sealed round's host snapshot (REPLICA_PUT frames).  0 (default)
    #: disables replication entirely — no frames, no replica storage, wire and
    #: store behavior byte-identical to pre-replication builds.  With factor k,
    #: executor e pushes to the k successors of e in the sorted executor ring,
    #: and reducers fail over to those replicas when the primary dies.
    replication_factor: int = 0
    #: Reduce-side fetch deadline (ms) per window: if a window's requests have
    #: not completed within this budget the reader declares the peer hung,
    #: fails the window locally, and enters the retry/failover path.  0 = wait
    #: forever (historical behavior).
    fetch_deadline_ms: int = 30000
    #: Base backoff (ms) between reduce-side fetch retry attempts; actual
    #: sleep is jittered uniformly in [base/2, base] and doubles per attempt
    #: (bounded exponential backoff, decorrelated across reducers).
    fetch_backoff_ms: int = 50
    #: Per-chunk CRC32C on striped-wire chunk frames and REPLICA_PUT frames.
    #: The 4-byte checksum rides as a header extension, detected by header
    #: length on the receiving side, so mixed-config peers interoperate.  A
    #: mismatch raises a typed BlockCorruptError that enters the reducer's
    #: retry/failover path — corruption becomes a detected, recovered fault
    #: instead of silent bad bytes.  Default off: frames stay byte-identical
    #: to the golden captures the CI wire gate pins.
    wire_checksum: bool = False
    #: Lossless wire compression codec for striped-wire chunk frames and
    #: REPLICA_PUT bodies: 'off' (default) | 'dict' | 'rle' | 'delta'
    #: (utils/pagecodec.py page formats).  The codec id and decoded length
    #: ride as a chunk-header extension (core/definitions.py), each lane's
    #: recv thread decodes independently into the chunk's final buffer
    #: offset, and unprofitable pages fall back to raw per chunk — lossless
    #: always, bit-identical shuffle results.  Composes with wire_checksum
    #: (crc covers the encoded bytes) and the CreditGate (credits account
    #: DECODED bytes — the reader admits windows by expected block sizes,
    #: which are decoded sizes; wire savings show up as faster drains, not
    #: looser admission).  Default off: frames stay byte-identical to the
    #: golden captures the CI wire gate pins.
    wire_compress_codec: str = "off"
    #: Pages smaller than this ship raw without attempting encode — below a
    #: few KiB the codec header + python-call overhead beats any shrink.
    compress_min_chunk_bytes: int = 4096
    #: Lossy block quantization of aggregate-tolerant ICI exchange payloads
    #: (ops/relational.py groupby partials; ops/ici_exchange.py quantized
    #: builders): 'off' (default) | 'int8' (linear scale per block) |
    #: 'blockfloat' (power-of-two shared exponent per block).  OPT-IN LOSSY:
    #: float aggregate lanes travel as int8 (4x fewer exchange bytes) with a
    #: per-block scale, bounding relative error at ~amax/254 per block; keys
    #: and counts are never quantized.  'off' is exactly the stock path.
    quantize_mode: str = "off"
    #: Quantization block width (values per scale block along the row), a
    #: multiple of 4 (int8x4-in-int32 packing granularity).
    quantize_block_size: int = 128
    #: Elastic mesh recovery (transport/tpu.py): when an executor dies
    #: mid-exchange, abort the in-flight round, shrink the mesh to the
    #: surviving pow2 bucket, restage the dead executor's rounds from its
    #: ring-successor's replica tier, and re-run the round deterministically
    #: (bit-identical at replication_factor >= 1).  Default off: loss raises
    #: a typed ExecutorLostError naming the dead executor (no hang) and
    #: nothing about membership is tracked or sent on the wire.
    elastic: bool = False
    #: How long (ms) a peer wire error must stand before the membership layer
    #: marks the executor suspect.  0 marks suspect immediately on the first
    #: addressed wire error (the loopback-test-friendly default behavior when
    #: elasticity is on).
    membership_suspect_after_ms: int = 0
    #: Byte bound on the replicator's pending-push backlog per executor: when
    #: a stalled ring successor lets un-acked snapshot pushes accumulate past
    #: this budget, the OLDEST un-pushed snapshot is dropped (drop-oldest-
    #: unsealed policy; counted in replica_stats["dropped_rounds"]) so memory
    #: stays bounded.  0 = unbounded (the historical behavior).
    replication_max_backlog_bytes: int = 0
    #: Hedged-fetch delay floor (ms): once a fetch window has stragglers
    #: outstanding past a hedge delay, the reader issues a duplicate request
    #: for each straggling block to a replica holder; the first completion
    #: wins bit-identically and the loser's buffer is quarantined.  The actual
    #: delay is derived from the wire's observed rx stall p99
    #: (``wire_lane_stats``) clamped to [fetch_hedge_ms, fetch_hedge_max_ms].
    #: 0 (default) disables hedging entirely — no duplicate requests, reader
    #: behavior byte-identical to the un-hedged path.
    fetch_hedge_ms: int = 0
    #: Hedge delay ceiling (ms): bounds how long the p99-derived hedge delay
    #: can grow on a wire whose tail is already bad.  0 = unbounded ceiling
    #: (the floor alone governs).  Ignored while fetch_hedge_ms is 0.
    fetch_hedge_max_ms: int = 0
    #: Per-peer circuit breaker: consecutive fetch failures/timeouts that trip
    #: an executor's breaker from closed to open.  While open, new fetches
    #: route straight to the replica ring without burning the full deadline
    #: on the sick primary; after ``breaker_cooldown_ms`` the breaker goes
    #: half-open and admits ONE probe — success closes it, failure re-opens.
    #: 0 (default) disables breakers — health EWMAs are still tracked (pure
    #: local accounting, no wire impact) but routing never changes.
    breaker_failure_threshold: int = 0
    #: Cooldown (ms) an open breaker waits before going half-open and
    #: admitting a probe request to the sick executor.  Only meaningful when
    #: ``breaker_failure_threshold`` > 0.
    breaker_cooldown_ms: int = 1000

    # popularity-aware serving tier (hot-block replica fanout + serve cache)
    #: Per-block fetch-rate promotion threshold (fetches/sec, EWMA —
    #: store/hbm_store.py ``BlockPopularity``): when a served block's observed
    #: fetch rate crosses it, the serving executor promotes the block's
    #: shuffle to HOT — the replicator widens the shuffle's replica set to
    #: ``serve.hotReplicas`` ring successors (reusing the REPLICA_PUT/
    #: REPLICA_ACK plane) and advertises the widened holder list through the
    #: HotSetPull AM so readers spread fetches across every holder instead of
    #: queueing on the primary.  Cooling below half the threshold demotes the
    #: advertisement again (hysteresis) — never below the
    #: ``replication.factor`` fault-tolerance floor.  0 (default) disables
    #: popularity tracking entirely: no tracker state, no HotSetPull frames,
    #: wire and store behavior byte-identical to the golden captures.
    serve_hot_threshold_fetches_per_sec: float = 0.0
    #: Widened replica-set width for HOT shuffles: how many ring successors a
    #: hot shuffle is replicated to (total holders = the primary + this many),
    #: clamped to at least ``replication.factor`` so promotion can only ever
    #: ADD holders and demotion can only retreat to the fault-tolerance
    #: floor.  Inert while ``serve.hotThresholdFetchesPerSec`` is 0.
    serve_hot_replicas: int = 4
    #: Byte budget for the serve-side decoded-block cache
    #: (service/eviction.py ``ServeCache``): blocks the popularity tracker
    #: marks hot are pinned decoded in a byte-budgeted LRU above the eviction
    #: tiers — charged against the owning tenant's HBM quota — so serving the
    #: hot set never pays a demotion restage.  0 (default) = no serve cache;
    #: store serve behavior byte-identical to the golden captures.
    serve_cache_bytes: int = 0
    #: Byte cap for the serve-side encoded-chunk pool (transport/peer.py
    #: BlockServer): sealed chunks pay the encoder once and every later fetch
    #: serves the cached encoding, evicted least-recently-served (LRU) once
    #: the held encoded bytes exceed this cap.  Only consulted while
    #: ``compress.codec`` is on; the default preserves the historical 128 MiB
    #: pool.
    compress_cache_bytes: int = 128 << 20
    #: Freshness TTL (ms) of the reader-side hot-holder advertisement cache:
    #: ``hot_holders`` answers from its last ``HOT_SET_PULL`` for this long
    #: before re-pulling, amortizing one round-trip per primary over every
    #: fetch in between.  Only consulted while
    #: ``serve.hotThresholdFetchesPerSec`` is on; the default preserves the
    #: historical hard-coded 250 ms.
    serve_holders_ttl_ms: int = 250

    # query DAG runner (sparkucx_tpu/query) — cross-query shuffle reuse
    #: Lineage cache master switch: when on, the QueryRunner keys every
    #: sealed exchange by its lineage hash (input fingerprint + canonical
    #: sub-DAG + byte-affecting conf tiers) and keeps the exchanged shuffle
    #: registered so a repeated sub-DAG serves from the store/eviction/serve
    #: tiers instead of re-executing.  Off (default) = every exchange runs
    #: and is unregistered after the query, byte-identical to a cache-less
    #: runner.
    query_cache_enabled: bool = False
    #: Byte budget for lineage-cached shuffles (sum of exchanged payload
    #: bytes kept resident across queries).  0 = no runner-level cap: cached
    #: rounds are bounded only by the owning tenant's HBM quota (admission
    #: still charges the tenant).  Over-budget admissions evict cached
    #: entries largest-footprint-first, keeping the smallest-footprint
    #: entries resident (arXiv:2112.01075's cost model applied to the
    #: keep/recompute decision).
    query_cache_max_bytes: int = 0

    # staged store (HBM; NVKV analogue).  512 = one exchange row (128 int32
    # lanes, the native XLA:TPU tile width) and exactly NVKV's sector alignment
    # (NvkvHandler.scala:244-256).
    block_alignment: int = 512
    staging_capacity_per_executor: int = 64 << 20
    store_port: int = 1338
    serve_from_store: bool = True  # spark.dpuTest.enabled analogue
    # (compat/spark_3_0/UcxShuffleBlockResolver.scala:86-90, default true)
    #: Stage shuffle output in named shared memory so co-located executor
    #: processes serve blocks zero-copy (single-host NVKV-store analogue).
    use_shm_staging: bool = False
    shm_namespace: str = "sparkucx_tpu"
    #: Disk round tier — the capacity-beyond-RAM role of the reference's
    #: DPU-attached NVMe (NvkvHandler.scala:160-242).  When a staging round
    #: rolls over, the completed round is written to an ``np.memmap`` file and
    #: its RAM is released, so a shuffle larger than host memory streams
    #: through bounded staging.  ``spill_dir=None`` -> a per-store temp dir.
    spill_to_disk: bool = True
    spill_dir: Optional[str] = None
    #: Total on-disk spill budget per store; 0 = unbounded.  Counts staged
    #: (padded) bytes — spill files are sparse, holes cost nothing.  Exceeding
    #: it is a TransportError at rollover (like region overflow), not silent
    #: data loss.  ``host_recv_mode='memmap'`` received-shard spill is charged
    #: against the same budget (cluster-wide).
    spill_disk_cap_bytes: int = 0
    #: Reduce-side combine/sort memory budget (the ExternalSorter role,
    #: UcxShuffleReader.scala:137-199): crossing it spills sorted runs to
    #: ``spill_dir`` and the reader k-way-merges them back.
    reduce_memory_budget: int = 64 << 20
    #: Soft memory-pressure watermark (bytes) on the store's resident staged
    #: footprint (live regions + RAM-tier sealed rounds + replica bytes;
    #: disk-tier memmap rounds cost nothing): crossing it triggers ONE
    #: out-of-band EvictionManager sweep (``run_epoch(max_demotions=1)`` —
    #: demote one tier, smallest-footprint-first per arXiv:2112.01075) on a
    #: background thread, off the allocating caller's path.  0 (default) =
    #: no soft watermark, store behavior byte-identical.
    store_soft_watermark: int = 0
    #: Hard memory-pressure watermark (bytes): an allocation-bearing write or
    #: serve (region charge, replica install, restage) that would push the
    #: resident staged footprint past this bound fails BEFORE any mutation
    #: with a typed retryable ResourceExhaustedError, carried on the wire as
    #: the dedicated SIZE_RESOURCE_EXHAUSTED code — clients back off and
    #: retry instead of the store OOMing.  0 (default) = no hard watermark.
    store_hard_watermark: int = 0

    # multi-tenant shuffle service (service/ — ROADMAP item 4)
    #: Multi-tenant mode: shuffles are keyed ``(app_id, shuffle_id)`` through a
    #: TenantRegistry (service/tenants.py), fetch requests carry the tenant's
    #: ``app_id`` as a self-describing FETCH_BLOCK_REQ header extension, HBM
    #: quotas are enforced at region-allocation time, and the serving planes
    #: run on the shared reactor event loop.  Default off: wire frames and
    #: store behavior stay byte-identical to the single-tenant build (the
    #: golden captures the CI wire gate pins).
    tenants_enabled: bool = False
    #: Default per-tenant HBM staging quota in bytes, charged at region
    #: allocation time against the tenant's registered budget; an over-quota
    #: write raises a typed TenantQuotaExceededError instead of eating a
    #: neighbor tenant's HBM.  0 = unlimited (admission checks disabled for
    #: tenants registered without an explicit quota).
    tenant_hbm_quota_bytes: int = 0
    #: Tiered-eviction epoch (ms): every epoch the EvictionManager
    #: (service/eviction.py) demotes the least-recently-fetched sealed rounds
    #: one tier down (HBM-resident jax.Array -> host snapshot -> np.memmap
    #: spill), and fetches restage demoted rounds transparently.  0 = no
    #: background demotion (manual ``run_epoch()`` only).
    eviction_epoch_ms: int = 0
    #: Serving-plane worker pool size for the shared selectors-based reactor
    #: (service/reactor.py) that replaces thread-per-connection accept loops
    #: in shuffle/daemon.py and the transport/peer.py block server.  0 keeps
    #: the historical thread-per-connection serving plane (tenants.enabled
    #: implies a reactor with a default-sized pool when left at 0).
    server_workers: int = 0
    #: Bounded accept backlog for the reactor serving plane: when the reactor
    #: already holds this many resident connections, a new accept is SHED —
    #: the server sends one best-effort SERVER_BUSY frame (AM id 13) and
    #: closes, instead of queuing work unboundedly.  Clients treat the busy
    #: reply as a retryable ResourceExhaustedError (back off, retry/fail
    #: over).  0 (default) = unbounded accepts, the historical behavior.
    #: Only applies when the reactor serving plane is active (server_workers
    #: > 0 or tenants_enabled).
    server_accept_backlog: int = 0

    # TPU mesh (L2)
    mesh_axis_name: str = "ex"
    num_executors: int = 1
    #: Multi-slice factorization: when > 1, the cluster's exchange routes in
    #: two phases (ICI aggregate within a slice, ONE DCN crossing between
    #: slices — ops/hierarchy.py).  Executors are slice-major:
    #: executor = slice * (num_executors // num_slices) + chip.
    num_slices: int = 1

    #: Keep each executor's received exchange shard resident in HBM after the
    #: superstep, enabling device-side block fetch (ops/pallas_kernels.py) —
    #: the serving analogue of the reference's registered bounce buffers that
    #: never leave the NIC-visible pool (MemoryPool.scala).  Costs one extra
    #: device-resident copy of the received bytes per round, doubling the HBM
    #: envelope of received bytes — opt-in (default off) so large multi-round
    #: shuffles keep the donation that halves peak HBM.
    keep_device_recv: bool = False
    #: Where the post-exchange received shards live on the HOST (SURVEY §7's
    #: "HBM budget" hard-part, host half).  ``'array'`` keeps one RAM copy per
    #: round (fastest fetches; ~1x received bytes of host RSS on top of the
    #: store's staging).  ``'memmap'`` writes each round's shards to disk
    #: (``spill_dir``) and serves fetches through ``np.memmap`` views — host
    #: RSS stays bounded by one round regardless of round count, the page
    #: cache does the rest.  ``'device'`` keeps NO host copy at all: fetches
    #: slice the HBM-resident shard and D2H only the requested block
    #: (requires ``keep_device_recv``) — the reference's serve-from-NVKV
    #: mode, where host memory never holds the shuffle.  The SPMD
    #: multi-controller executor honors 'array'/'memmap' per host ('device'
    #: raises there: it releases device shards after the collective).
    host_recv_mode: str = "array"
    #: Ragged block-gather lowering: 'auto' (pipelined DMA kernel on TPU, XLA
    #: gather elsewhere) | 'dma' | 'tiled' | 'xla'.
    gather_impl: str = "auto"
    #: Inter-chip exchange implementation (ops/ici_exchange.py): 'stock'
    #: (default — the byte-for-byte ragged_all_to_all/dense collective path),
    #: 'pallas' (hand-rolled bidirectional-ring supersteps with FAST-style
    #: per-destination chunk interleaving: remote-DMA kernel on TPU, scheduled
    #: ppermute lowering elsewhere — bit-identical results, pinned by
    #: tests/test_ici_exchange.py), or 'auto' (pallas on multi-chip TPU
    #: meshes, stock everywhere else).
    exchange_impl: str = "stock"
    #: Receive-side compute-in-exchange for partial grouped aggregations
    #: (ops/combine.py + ops/relational.py): fold each landed exchange window
    #: into a fixed per-group accumulator inside the collective instead of
    #: staging it — O(groups) post-exchange memory and drain bytes instead of
    #: O(rows), and one fused kernel launch under the Pallas DMA lowering.
    #: Default off = the unfused path, byte-identical to every prior release.
    #: The planner picks the tier ('dense' when the key domain is
    #: dense-representable and the accumulator undercuts recv staging,
    #: 'sorted' bounded merge otherwise); raw block exchanges ignore the knob.
    exchange_fused_combine: bool = False
    #: Map-side partial aggregation below the exchange for GROUP BY jobs —
    #: Spark's HashAggregateExec(partial) under the ShuffleExchange, on by
    #: default exactly as in Spark.  Consumed by ``AggregateSpec.from_conf``
    #: (ops/relational.py), which defaults ``AggregateSpec.partial`` to this
    #: value; specs built directly ignore the conf.  Shrinks exchange traffic
    #: by the group-reduction factor and bounds hot-key skew to one partial
    #: row per (sender, key); disable to force the raw-row exchange
    #: (count_distinct plans do so automatically — partials don't compose).
    partial_aggregation: bool = True

    #: Device-resident map-output staging (store/hbm_store.py device rounds +
    #: ops/pallas_kernels.build_block_scatter): device-born map output is
    #: written as ``(rows, lane)`` int32 device arrays and placed into the
    #: HBM staging array by the block-scatter kernel, so seal returns the
    #: exchange payload with zero D2H -> host memcpy -> H2D round trip.
    #: Gates ``write_partition_device`` / ``DeviceMapWriter``
    #: (shuffle/writer.py).  Default off: the host byte path stays the
    #: reference-faithful default.
    device_staging: bool = False

    #: Superstep pipelining across spill rounds: how many rounds may be in
    #: flight at once in the multi-round exchange (transport/tpu.py /
    #: transport/spmd.py).  At depth d, round k's collective overlaps round
    #: k+1's host assembly + H2D staging and round k-1's D2H drain, at the
    #: cost of (d-1) extra in-flight receive buffers of HBM/RAM.  1 = the
    #: strictly serial engine (bit-identical results either way; the pipeline
    #: only reorders WHEN stages run, never what they compute).
    pipeline_depth: int = 2

    #: Skew-aware exchange planning (ops/skew.py): cap each destination's
    #: exchange slot at this many rows and chunk hotter lanes across extra
    #: pipelined sub-rounds instead of inflating every slot to the global max
    #: — the extra rounds ride the pipeline_depth overlap, so hot-lane bytes
    #: stream while cold lanes finish.  Shrinks staged HBM and (under the
    #: portable dense lowering) wire bytes on Zipf-skewed shuffles; results
    #: are bit-identical to the single-shot exchange.  0 (default) disables
    #: the planner entirely — the unchunked path runs byte-for-byte as before.
    slot_quota_rows: int = 0

    #: Exchange planner selection (ops/planner.py).  'static' (default) maps
    #: the legacy knobs 1:1 onto an ExchangePlan — byte-identical outputs and
    #: wire frames.  'adaptive' re-plans per shuffle per epoch from the
    #: telemetry plane: quota/chunking from the sealed size matrices, hedge
    #: delay from rx stall tails + peer health, codec from observed
    #: compression ratios, streams from credit stalls, depth from drain-lane
    #: occupancy.  Results stay bit-identical either way — plans only change
    #: the schedule, never the bytes.
    planner_mode: str = "static"
    #: Run the plan-optimization passes (pow2 slot bucketing, chunk
    #: coalescing, staging-footprint sub-round reordering per
    #: arXiv:2112.01075) over static plans.  Off (default) keeps the legacy
    #: schedule verbatim; adaptive plans always optimize.
    planner_optimize: bool = False
    #: Adaptive planner only: when the single-shot plan's predicted staging
    #: padding fraction (from the sealed size matrices) exceeds this, switch
    #: to a quota-chunked plan sized near the mean lane.
    planner_target_padding: float = 0.5
    #: Adaptive planner only: floor for a telemetry-derived slot quota, so
    #: extreme skew cannot chunk a shuffle into thousands of tiny sub-rounds.
    planner_min_quota_rows: int = 256

    # instrumentation
    collect_stats: bool = True

    #: Distributed-trace context propagation (obs plane): when on, fetch
    #: requests and replica pushes carry the issuing span's (trace_id,
    #: span_id) as a self-describing trailing header extension
    #: (core/definitions.py ``_TRACE_EXT`` / ``_REPLICA_TRACE_EXT``), so
    #: server-side serve/read/restage spans parent under the reducer's fetch
    #: span in the merged Perfetto view (TpuShuffleCluster.export_trace).
    #: Default off: every golden wire frame stays byte-identical.
    obs_trace_context: bool = False
    #: Local Prometheus scrape endpoint port (obs/metrics.py
    #: ``start_http_server``): GET /metrics serves this executor's
    #: MetricsRegistry text exposition.  0 (default) = no HTTP server; the
    #: peer-plane METRICS_PULL Active Message works regardless.
    obs_metrics_port: int = 0
    #: Flight-recorder ring capacity (utils/trace.py): the bounded
    #: drop-oldest event ring that backs both full tracing and the always-on
    #: postmortem recorder.  Oldest events are evicted (and counted) once the
    #: ring is full, so long-running tracing can't OOM an executor.
    obs_ring_capacity: int = 8192
    #: Postmortem bundle directory (obs/recorder.py): when set, every
    #: flight-recorder capture (TransportError, elastic recovery, chaos
    #: fault) is additionally written as a JSON file here.  Empty (default) =
    #: in-memory only (``FlightRecorder.last_postmortem``) — no file writes.
    obs_postmortem_dir: str = ""
    #: Runtime buffer sanitizer (memory/sanitizer.py): track pooled-handle
    #: lifecycles, poison freed host buffers with 0xDD, and RAISE on
    #: double-release / use-after-release / re-pooling a buffer with live
    #: exported views.  Debug tool — default off; in normal mode release
    #: stays idempotent (see MemoryBlock.close / BlockFetchResult.release).
    sanitize: bool = False

    # ------------------------------------------------------------------
    @classmethod
    def from_spark_conf(cls, conf: Mapping[str, str]) -> "TpuShuffleConf":
        """Build from a flat spark-style key/value map.

        Recognized keys: ``spark.shuffle.tpu.memory.preAllocateBuffers`` (a
        ``size:count,size:count`` list — UcxShuffleConf.scala:21-31 format),
        ``...memory.minBufferSize``, ``...memory.minAllocationSize``,
        ``...listener.sockaddr``, ``...useWakeup``, ``...numIoThreads``,
        ``...numListenerThreads``, ``...numClientWorkers``,
        ``...maxBlocksPerRequest``, ``...blockAlignment``, ``...stagingCapacity``,
        ``...storePort``, ``...serveFromStore``, ``...numExecutors``.
        """
        p = CONF_PREFIX

        def get(key: str, default=None):
            return conf.get(f"{p}.{key}", default)

        out = cls()
        pre = get("memory.preAllocateBuffers")
        if pre:
            buffers: Dict[int, int] = {}
            for item in str(pre).split(","):
                if not item.strip():
                    continue
                size_s, count_s = item.split(":")
                buffers[parse_size(size_s)] = int(count_s)
            out.prealloc_buffers = buffers
        if get("memory.minBufferSize") is not None:
            out.min_buffer_size = parse_size(get("memory.minBufferSize"))
        if get("memory.minAllocationSize") is not None:
            out.min_allocation_size = parse_size(get("memory.minAllocationSize"))
        sock = get("listener.sockaddr")
        if sock:
            host, _, port = str(sock).rpartition(":")
            out.listener_address = (host or "0.0.0.0", int(port))
        for name, attr, conv in [
            ("useWakeup", "use_wakeup", lambda v: str(v).lower() == "true"),
            ("numIoThreads", "num_io_threads", int),
            ("numListenerThreads", "num_listener_threads", int),
            ("numClientWorkers", "num_client_workers", int),
            ("maxBlocksPerRequest", "max_blocks_per_request", int),
            ("fetchRetries", "fetch_retries", int),
            ("wire.streams", "wire_streams", int),
            ("wire.chunkBytes", "wire_chunk_bytes", parse_size),
            ("wire.creditBytes", "wire_credit_bytes", parse_size),
            ("wire.sockBufBytes", "wire_sock_buf_bytes", parse_size),
            ("wire.timeoutMs", "wire_timeout_ms", int),
            ("replication.factor", "replication_factor", int),
            ("replication.maxBacklogBytes", "replication_max_backlog_bytes", parse_size),
            ("fetch.deadlineMs", "fetch_deadline_ms", int),
            ("fetch.backoffMs", "fetch_backoff_ms", int),
            ("fetch.hedgeMs", "fetch_hedge_ms", int),
            ("fetch.hedgeMaxMs", "fetch_hedge_max_ms", int),
            ("breaker.failureThreshold", "breaker_failure_threshold", int),
            ("breaker.cooldownMs", "breaker_cooldown_ms", int),
            ("serve.hotThresholdFetchesPerSec", "serve_hot_threshold_fetches_per_sec", float),
            ("serve.hotReplicas", "serve_hot_replicas", int),
            ("serve.cacheBytes", "serve_cache_bytes", parse_size),
            ("serve.holdersTtlMs", "serve_holders_ttl_ms", int),
            ("compress.cacheBytes", "compress_cache_bytes", parse_size),
            ("query.cacheEnabled", "query_cache_enabled", lambda v: str(v).lower() == "true"),
            ("query.cacheMaxBytes", "query_cache_max_bytes", parse_size),
            ("store.softWatermark", "store_soft_watermark", parse_size),
            ("store.hardWatermark", "store_hard_watermark", parse_size),
            ("server.acceptBacklog", "server_accept_backlog", int),
            ("wire.checksum", "wire_checksum", lambda v: str(v).lower() == "true"),
            ("compress.codec", "wire_compress_codec", str),
            ("compress.minChunkBytes", "compress_min_chunk_bytes", parse_size),
            ("quantize.mode", "quantize_mode", str),
            ("quantize.blockSize", "quantize_block_size", int),
            ("elastic.enabled", "elastic", lambda v: str(v).lower() == "true"),
            ("membership.suspectAfterMs", "membership_suspect_after_ms", int),
            ("blockAlignment", "block_alignment", parse_size),
            ("stagingCapacity", "staging_capacity_per_executor", parse_size),
            ("storePort", "store_port", int),
            ("serveFromStore", "serve_from_store", lambda v: str(v).lower() == "true"),
            ("useShmStaging", "use_shm_staging", lambda v: str(v).lower() == "true"),
            ("shmNamespace", "shm_namespace", str),
            ("numExecutors", "num_executors", int),
            ("numSlices", "num_slices", int),
            ("meshAxisName", "mesh_axis_name", str),
            ("keepDeviceRecv", "keep_device_recv", lambda v: str(v).lower() == "true"),
            ("gatherImpl", "gather_impl", str),
            ("exchange.impl", "exchange_impl", str),
            ("exchange.fusedCombine", "exchange_fused_combine", lambda v: str(v).lower() == "true"),
            ("partialAggregation", "partial_aggregation", lambda v: str(v).lower() == "true"),
            ("hostRecvMode", "host_recv_mode", str),
            ("spillToDisk", "spill_to_disk", lambda v: str(v).lower() == "true"),
            ("spillDir", "spill_dir", str),
            ("spillDiskCap", "spill_disk_cap_bytes", parse_size),
            ("reduceMemoryBudget", "reduce_memory_budget", parse_size),
            ("tenants.enabled", "tenants_enabled", lambda v: str(v).lower() == "true"),
            ("tenants.hbmQuotaBytes", "tenant_hbm_quota_bytes", parse_size),
            ("eviction.epochMs", "eviction_epoch_ms", int),
            ("server.workers", "server_workers", int),
            ("pipelineDepth", "pipeline_depth", int),
            ("slotQuotaRows", "slot_quota_rows", int),
            ("planner.mode", "planner_mode", str),
            ("planner.optimize", "planner_optimize", lambda v: str(v).lower() == "true"),
            ("planner.targetPaddingFraction", "planner_target_padding", float),
            ("planner.minQuotaRows", "planner_min_quota_rows", int),
            ("deviceStaging", "device_staging", lambda v: str(v).lower() == "true"),
            ("sanitize", "sanitize", lambda v: str(v).lower() == "true"),
            ("obs.traceContext", "obs_trace_context", lambda v: str(v).lower() == "true"),
            ("obs.metricsPort", "obs_metrics_port", int),
            ("obs.ringCapacity", "obs_ring_capacity", int),
            ("obs.postmortemDir", "obs_postmortem_dir", str),
        ]:
            v = get(name)
            if v is not None:
                setattr(out, attr, conv(v))
        # spark.executor.cores fallback for worker count (UcxShuffleConf.scala:80-86)
        if get("numClientWorkers") is None and "spark.executor.cores" in conf:
            out.num_client_workers = int(conf["spark.executor.cores"])
        out.validate()
        return out

    def validate(self) -> None:
        if self.block_alignment <= 0 or (self.block_alignment & (self.block_alignment - 1)):
            raise ValueError("block_alignment must be a positive power of two")
        if self.block_alignment % 4:
            raise ValueError("block_alignment must be a multiple of 4 (int32 exchange lanes)")
        if self.min_buffer_size <= 0:
            raise ValueError("min_buffer_size must be positive")
        if self.max_blocks_per_request <= 0:
            raise ValueError("max_blocks_per_request must be positive")
        if self.num_executors <= 0:
            raise ValueError("num_executors must be positive")
        if self.gather_impl not in ("auto", "dma", "tiled", "xla"):
            raise ValueError(f"unknown gather_impl {self.gather_impl!r}")
        if self.exchange_impl not in ("stock", "pallas", "auto"):
            raise ValueError(f"unknown exchange_impl {self.exchange_impl!r}")
        if self.num_slices <= 0:
            raise ValueError("num_slices must be positive")
        if self.num_slices > 1 and self.num_executors % self.num_slices:
            raise ValueError("num_executors must be divisible by num_slices")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1 (1 = serial engine)")
        if self.slot_quota_rows < 0:
            raise ValueError("slot_quota_rows must be >= 0 (0 = no quota)")
        if self.planner_mode not in ("static", "adaptive"):
            raise ValueError(f"unknown planner_mode {self.planner_mode!r}")
        if not (0 <= self.planner_target_padding < 1):
            raise ValueError("planner_target_padding must be in [0, 1)")
        if self.planner_min_quota_rows < 1:
            raise ValueError("planner_min_quota_rows must be >= 1")
        if self.wire_streams < 1:
            raise ValueError("wire_streams must be >= 1 (1 = single-lane wire)")
        if self.wire_chunk_bytes < 4096:
            raise ValueError("wire_chunk_bytes must be >= 4096")
        if self.wire_credit_bytes < 0:
            raise ValueError("wire_credit_bytes must be >= 0 (0 = no pipelining)")
        if self.wire_sock_buf_bytes < 0:
            raise ValueError("wire_sock_buf_bytes must be >= 0 (0 = platform default)")
        if self.wire_timeout_ms < 0:
            raise ValueError("wire_timeout_ms must be >= 0 (0 = no timeout)")
        if self.replication_factor < 0:
            raise ValueError("replication_factor must be >= 0 (0 = replication off)")
        if self.fetch_deadline_ms < 0:
            raise ValueError("fetch_deadline_ms must be >= 0 (0 = no deadline)")
        if self.fetch_backoff_ms < 0:
            raise ValueError("fetch_backoff_ms must be >= 0")
        if self.membership_suspect_after_ms < 0:
            raise ValueError("membership_suspect_after_ms must be >= 0")
        if self.replication_max_backlog_bytes < 0:
            raise ValueError("replication_max_backlog_bytes must be >= 0 (0 = unbounded)")
        if self.wire_compress_codec not in ("off", "dict", "rle", "delta"):
            raise ValueError(f"unknown wire_compress_codec {self.wire_compress_codec!r}")
        if self.compress_min_chunk_bytes < 0:
            raise ValueError("compress_min_chunk_bytes must be >= 0")
        if self.quantize_mode not in ("off", "int8", "blockfloat"):
            raise ValueError(f"unknown quantize_mode {self.quantize_mode!r}")
        if self.quantize_block_size <= 0 or self.quantize_block_size % 4:
            raise ValueError("quantize_block_size must be a positive multiple of 4")
        if self.tenant_hbm_quota_bytes < 0:
            raise ValueError("tenant_hbm_quota_bytes must be >= 0 (0 = unlimited)")
        if self.eviction_epoch_ms < 0:
            raise ValueError("eviction_epoch_ms must be >= 0 (0 = manual epochs)")
        if self.server_workers < 0:
            raise ValueError("server_workers must be >= 0 (0 = thread-per-connection)")
        if self.fetch_hedge_ms < 0:
            raise ValueError("fetch_hedge_ms must be >= 0 (0 = hedging off)")
        if self.fetch_hedge_max_ms < 0:
            raise ValueError("fetch_hedge_max_ms must be >= 0 (0 = unbounded ceiling)")
        if self.fetch_hedge_max_ms and self.fetch_hedge_max_ms < self.fetch_hedge_ms:
            raise ValueError("fetch_hedge_max_ms must be >= fetch_hedge_ms when set")
        if self.breaker_failure_threshold < 0:
            raise ValueError("breaker_failure_threshold must be >= 0 (0 = breakers off)")
        if self.breaker_cooldown_ms < 0:
            raise ValueError("breaker_cooldown_ms must be >= 0")
        if self.serve_hot_threshold_fetches_per_sec < 0:
            raise ValueError(
                "serve_hot_threshold_fetches_per_sec must be >= 0 (0 = popularity tracking off)"
            )
        if self.serve_hot_replicas < 0:
            raise ValueError("serve_hot_replicas must be >= 0")
        if self.serve_cache_bytes < 0:
            raise ValueError("serve_cache_bytes must be >= 0 (0 = no serve-side cache)")
        if self.compress_cache_bytes < 0:
            raise ValueError("compress_cache_bytes must be >= 0 (0 = no encoded-chunk pool)")
        if self.serve_holders_ttl_ms < 0:
            raise ValueError(
                "serve_holders_ttl_ms must be >= 0 (0 = re-pull the holder set every fetch)"
            )
        if self.query_cache_max_bytes < 0:
            raise ValueError("query_cache_max_bytes must be >= 0 (0 = tenant quotas only)")
        if self.store_soft_watermark < 0:
            raise ValueError("store_soft_watermark must be >= 0 (0 = no soft watermark)")
        if self.store_hard_watermark < 0:
            raise ValueError("store_hard_watermark must be >= 0 (0 = no hard watermark)")
        if (
            self.store_soft_watermark
            and self.store_hard_watermark
            and self.store_soft_watermark > self.store_hard_watermark
        ):
            raise ValueError("store_soft_watermark must be <= store_hard_watermark")
        if self.server_accept_backlog < 0:
            raise ValueError("server_accept_backlog must be >= 0 (0 = unbounded accepts)")
        if not (0 <= self.obs_metrics_port <= 65535):
            raise ValueError("obs_metrics_port must be in [0, 65535] (0 = no HTTP endpoint)")
        if self.obs_ring_capacity <= 0:
            raise ValueError("obs_ring_capacity must be positive (the ring is always bounded)")

    def replace(self, **kw) -> "TpuShuffleConf":
        out = dataclasses.replace(self, **kw)
        out.validate()
        return out
