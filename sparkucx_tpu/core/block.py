"""Block identity and memory contracts.

Counterpart of the reference's block/memory API surface:

* ``BlockId`` / ``Block`` / ``MemoryBlock`` traits — ShuffleTransport.scala:13-53
* ``UcxShuffleBlockId`` (shuffleId, mapId, reduceId) — UcxShuffleTransport.scala:55-72

Differences by design (TPU-first):

* ``MemoryBlock`` wraps a ``memoryview``/numpy buffer or a ``jax.Array`` rather than a
  raw address; zero-copy views are ordinary array slices instead of
  ``sun.nio.ch.DirectBuffer`` reflection (UnsafeUtils.scala:25-36).
* ``ShuffleBlockId.serialize`` writes all three ids (12 bytes, little-endian int32).
  The reference's fork elides shuffleId and writes 8 bytes
  (UcxShuffleTransport.scala:55-72, "shuffleId commented out") — an acknowledged POC
  shortcut we do not reproduce.
"""

from __future__ import annotations

import struct
import sys
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Optional, Union

import numpy as np

#: Wire format of a ShuffleBlockId: little-endian (shuffle_id, map_id, reduce_id).
_BLOCK_ID_STRUCT = struct.Struct("<iii")


class BlockId(ABC):
    """Opaque identifier of a shuffle block (ShuffleTransport.scala:22-27)."""

    @abstractmethod
    def serialized_size(self) -> int:
        ...

    @abstractmethod
    def serialize(self) -> bytes:
        ...


@dataclass(frozen=True, order=True)
class ShuffleBlockId(BlockId):
    """(shuffleId, mapId, reduceId) triple (UcxShuffleTransport.scala:55-72)."""

    shuffle_id: int
    map_id: int
    reduce_id: int

    def serialized_size(self) -> int:
        return _BLOCK_ID_STRUCT.size

    def serialize(self) -> bytes:
        return _BLOCK_ID_STRUCT.pack(self.shuffle_id, self.map_id, self.reduce_id)

    @staticmethod
    def deserialize(data: Union[bytes, memoryview]) -> "ShuffleBlockId":
        s, m, r = _BLOCK_ID_STRUCT.unpack_from(data)
        return ShuffleBlockId(s, m, r)

    @property
    def name(self) -> str:
        return f"shuffle_{self.shuffle_id}_{self.map_id}_{self.reduce_id}"


BufferLike = Union[np.ndarray, memoryview, bytearray]


def _as_u8(buf: BufferLike) -> np.ndarray:
    """View any writable byte-ish buffer as a 1-D uint8 numpy array (zero copy)."""
    if isinstance(buf, np.ndarray):
        return buf.reshape(-1).view(np.uint8)
    return np.frombuffer(buf, dtype=np.uint8)


@dataclass
class MemoryBlock:
    """A sized region of host or device memory (ShuffleTransport.scala:13-20).

    ``data`` is either a host buffer (numpy uint8 array / memoryview) or a
    ``jax.Array`` resident in HBM.  ``is_host_memory`` mirrors the reference field
    that anticipated GPU buffers (ShuffleTransport.scala:16); here device memory is
    the *normal* case for staged shuffle blocks.

    ``close()`` releases the block back to its owning pool (MemoryPool.scala:22-24);
    pools install ``_on_close``.
    """

    data: object  # np.ndarray[uint8] | jax.Array | memoryview
    size: int
    is_host_memory: bool = True
    #: opaque owning-allocator bookkeeping slot (e.g. the backing slab) —
    #: reserved for the pool that created this block; never interpreted here
    allocator_token: Optional[object] = field(default=None, repr=False)
    _on_close: Optional[callable] = field(default=None, repr=False)
    _closed: bool = field(default=False, repr=False)
    #: sanitize-mode hook (memory/sanitizer.py): called on a close() of an
    #: already-closed block.  Normal mode leaves it None and close() stays
    #: idempotent — the documented contract free-list parking depends on.
    _on_double_close: Optional[callable] = field(default=None, repr=False)

    def host_view(self) -> np.ndarray:
        """1-D uint8 view of the first ``size`` bytes (host memory only)."""
        if not self.is_host_memory:
            raise TransportMemoryError("host_view() on device MemoryBlock")
        return _as_u8(self.data)[: self.size]

    def to_bytes(self) -> bytes:
        if self.is_host_memory:
            return self.host_view().tobytes()
        return np.asarray(self.data).reshape(-1).view(np.uint8)[: self.size].tobytes()

    def close(self) -> None:
        if self._closed:
            if self._on_double_close is not None:
                self._on_double_close(self)  # raises under sanitize mode
            return
        self._closed = True
        if self._on_close is not None:
            try:
                self._on_close(self)
            except BaseException:
                # A failed recycle (e.g. sanitize-mode live-view raise) must
                # leave the block checked out and closeable, not half-dead.
                self._closed = False
                raise

    def rearm(self) -> None:
        """Allocator checkout hook: make ``close()`` live again after a pooled
        block is handed back out.  Blocks parked in a free list stay closed so a
        stale holder's second ``close()`` is a no-op, not a double-free."""
        self._closed = False


class TransportMemoryError(RuntimeError):
    pass


class Block(ABC):
    """Server-side registered block (ShuffleTransport.scala:29-53).

    The reference guards mutation with a ``StampedLock`` (ShuffleTransport.scala:31-34,
    unused in practice); we keep an honest ``threading.RLock`` used by
    ``ShuffleTransport.mutate``.
    """

    def __init__(self) -> None:
        self.lock = threading.RLock()

    @abstractmethod
    def get_size(self) -> int:
        ...

    @abstractmethod
    def get_block(self, dest: BufferLike) -> None:
        """Copy block contents into ``dest`` (at least ``get_size()`` bytes)."""

    def get_memory_block(self) -> MemoryBlock:
        """Materialize into a fresh host MemoryBlock.

        The reference leaves this as an unimplemented stub (``???``,
        ShuffleTransport.scala:43); here it is a working default.
        """
        out = np.empty(self.get_size(), dtype=np.uint8)
        self.get_block(out)
        return MemoryBlock(data=out, size=out.size, is_host_memory=True)

    def memory_view(self) -> Optional[np.ndarray]:
        """Zero-copy serving hook: a stable uint8 view of the block's bytes,
        or None when no such view exists (an unmappable source — the server
        then materializes via ``get_memory_block``).  Serving paths capture
        the view under ``self.lock``; a concurrent ``mutate`` swaps the
        backing array but the captured view keeps the old one alive — the
        same consistent-at-capture semantics as ``get_memory_block``.
        Subclasses should override where a stable view is possible
        (BytesBlock: the payload array; FileBackedBlock: a cached read-only
        mmap): materializing a fresh buffer per fetch was the measured wall
        of the peer-serving path (allocation + copy + page faults per
        request, docs/PERF.md peer row)."""
        return None

    def close(self) -> None:
        """Release resources held for serving (mappings, fds).  Called by the
        transports on block unregistration / shuffle removal; must be safe to
        call more than once, and the block must still be servable afterwards
        (a later ``memory_view``/``get_block`` may recreate the resource)."""


class BytesBlock(Block):
    """A block backed by an in-memory byte buffer (test/loopback helper)."""

    def __init__(self, payload: Union[bytes, np.ndarray]) -> None:
        super().__init__()
        self._payload = _as_u8(np.asarray(bytearray(payload)) if isinstance(payload, (bytes, bytearray)) else payload)

    def get_size(self) -> int:
        return int(self._payload.size)

    def get_block(self, dest: BufferLike) -> None:
        view = _as_u8(dest)
        view[: self._payload.size] = self._payload

    def memory_view(self) -> np.ndarray:
        return self._payload

    def set_payload(self, payload: Union[bytes, np.ndarray]) -> None:
        with self.lock:
            self._payload = _as_u8(
                np.asarray(bytearray(payload)) if isinstance(payload, (bytes, bytearray)) else payload
            )


class FileBackedBlock(Block):
    """Positioned-read block over a file segment.

    Counterpart of ``FileBackedMemoryBlock`` + the resolver's registered blocks that
    do positioned ``FileChannel.read`` (CommonUcxShuffleBlockResolver.scala:37-61).
    Serving goes through a lazily created read-only ``np.memmap`` of the
    segment (``memory_view``), so the peer server's vectored ``sendmsg``
    transmits straight from the page cache — the mmap analogue of
    ``UnsafeUtils.mmap`` (UnsafeUtils.scala:38-56), with no per-fetch read
    or copy.  ``get_block`` stays a plain positioned read for callers that
    want bytes in their own buffer.
    """

    def __init__(self, path: str, offset: int, length: int) -> None:
        super().__init__()
        self.path = path
        self.offset = int(offset)
        self.length = int(length)
        self._mm: Optional[np.ndarray] = None

    def get_size(self) -> int:
        return self.length

    def get_block(self, dest: BufferLike) -> None:
        view = _as_u8(dest)
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            data = f.read(self.length)
        view[: len(data)] = np.frombuffer(data, dtype=np.uint8)

    def memory_view(self) -> Optional[np.ndarray]:
        if self.length == 0:
            return np.empty(0, dtype=np.uint8)
        if self._mm is None:
            try:
                self._mm = np.memmap(
                    self.path, dtype=np.uint8, mode="r",
                    offset=self.offset, shape=(self.length,),
                )
            except (OSError, ValueError):
                return None  # unmappable (e.g. pipe): materialize instead
        return self._mm

    def close(self) -> None:
        """Drop the cached mapping so its fd and pages are released now, not
        never — without this every served spill segment pins an open fd for
        the life of the process (the leak: unregistration never dropped
        ``self._mm``).  The map is unmapped eagerly only when this block holds
        the sole reference; numpy 2.x lets ``mmap.close()`` succeed with live
        views, so closing under an in-flight fetch would turn its captured
        view into a use-after-unmap.  With views outstanding the reference is
        merely dropped and CPython refcounting closes the fd the moment the
        last view dies.  A later ``memory_view`` simply remaps."""
        with self.lock:
            mm, self._mm = self._mm, None
            if mm is None or not isinstance(mm, np.memmap):
                return
            if sys.getrefcount(mm) == 2:  # only `mm` + getrefcount's argument
                try:
                    mm._mmap.close()
                except (AttributeError, BufferError):
                    pass  # numpy internals moved / exporter alive: defer to GC
