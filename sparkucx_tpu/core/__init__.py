"""Core contracts of the shuffle framework (L0/L3 API layer).

Python counterparts of the reference's pure-API file
``shuffle/ucx/ShuffleTransport.scala`` (block/transport contracts) and
``shuffle/ucx/Definitions.scala`` (wire-protocol ids).
"""
