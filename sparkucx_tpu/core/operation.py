"""Async-operation contracts: status, stats, results, requests.

Counterpart of ShuffleTransport.scala:56-93 (``OperationStatus``, ``OperationStats``,
``OperationCallback``, ``OperationResult``, ``Request``) and of the concrete
``UcxStats``/``UcxRequest`` (UcxShuffleTransport.scala:23-53).

TPU-first twist: the reference's explicit ``progress()`` polling contract
(ShuffleTransport.scala:158-165) maps onto JAX's async dispatch.  A ``Request`` may
wrap in-flight ``jax.Array`` results; ``completed()`` polls ``jax.Array.is_ready()``
without blocking, and ``wait()`` blocks via ``block_until_ready`` — so the reduce-side
spin loop (UcxShuffleReader.scala:116-134) has a faithful, non-blocking analogue.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from sparkucx_tpu.core.block import MemoryBlock


class OperationStatus(enum.Enum):
    """ShuffleTransport.scala:56-58."""

    SUCCESS = "SUCCESS"
    CANCELED = "CANCELED"
    FAILURE = "FAILURE"


#: Observer callbacks fired when any TransportError (or subclass) is
#: constructed — the flight recorder (obs/recorder.py) registers here to
#: capture a postmortem bundle at the instant a transport-level failure is
#: born, before the catch-site decides whether it is retryable.  Lives in
#: this leaf module so obs can hook transports without an import cycle.
_failure_hooks: List[Callable[["TransportError"], None]] = []


def register_failure_hook(hook: Callable[["TransportError"], None]) -> None:
    if hook not in _failure_hooks:
        _failure_hooks.append(hook)


def unregister_failure_hook(hook: Callable[["TransportError"], None]) -> None:
    try:
        _failure_hooks.remove(hook)
    except ValueError:
        pass


class TransportError(RuntimeError):
    """ShuffleTransport.scala:60-62 (``TransportError`` wraps an error message)."""

    def __init__(self, *args) -> None:
        super().__init__(*args)
        for hook in list(_failure_hooks):
            try:
                hook(self)
            except Exception:
                pass  # observability must never turn a failure into two


class BlockNotFoundError(TransportError):
    """A fetch named a block the serving executor does not hold.

    Subclasses TransportError so existing catch-sites keep working, but is
    typed + addressed so the reducer can tell "retryable: not yet committed /
    primary lost, try a replica" apart from programming errors (bad ids).
    """

    def __init__(self, shuffle_id: int, map_id: int, reduce_id: int, detail: str = "") -> None:
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.reduce_id = reduce_id
        msg = f"no block (shuffle={shuffle_id}, map={map_id}, reduce={reduce_id}) found"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class BlockCorruptError(TransportError):
    """A block's wire payload failed its integrity check (wire.checksum).

    Typed + addressed like BlockNotFoundError so the reducer's failover path
    can treat "bytes arrived but are wrong" exactly like "peer died": retry
    against the next candidate executor instead of propagating garbage.
    """

    def __init__(self, shuffle_id: int, map_id: int, reduce_id: int, detail: str = "") -> None:
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.reduce_id = reduce_id
        msg = f"block (shuffle={shuffle_id}, map={map_id}, reduce={reduce_id}) failed checksum"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class UnknownTenantError(TransportError):
    """A multi-tenant operation named an ``app_id`` the serving executor's
    TenantRegistry does not know (never registered, or already unregistered).

    Typed + addressed like BlockNotFoundError — but NOT retryable: an unknown
    tenant stays unknown no matter which replica a reducer fails over to, so
    the reader propagates it immediately instead of burning the retry budget.
    """

    def __init__(self, app_id: str, detail: str = "") -> None:
        self.app_id = app_id
        msg = f"unknown tenant app_id={app_id!r}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class TenantQuotaExceededError(TransportError):
    """A tenant's HBM byte quota would be exceeded by an admission-checked
    allocation (map-output region allocation, or restaging a demoted round).

    Typed + addressed — names the tenant, the shuffle, and the budget
    arithmetic — and, like UnknownTenantError, NOT retryable over the wire:
    every replica enforces the same registry budget, so reducers fail fast
    instead of retrying a quota rejection through the failover path.
    """

    def __init__(
        self,
        app_id: str,
        shuffle_id: int,
        requested: int = 0,
        quota: int = 0,
        used: int = 0,
        detail: str = "",
    ) -> None:
        self.app_id = app_id
        self.shuffle_id = shuffle_id
        self.requested = requested
        self.quota = quota
        self.used = used
        msg = (
            f"tenant {app_id!r} over HBM quota on shuffle {shuffle_id}"
            f" (requested={requested}, used={used}, quota={quota})"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ResourceExhaustedError(TransportError):
    """The serving executor is under memory pressure: an allocation-bearing
    write/serve hit the store's hard watermark (``store.hardWatermark``), the
    host buffer pool's cap, or the reactor shed the connection past its accept
    backlog (``server.acceptBacklog``).

    Typed + addressed like TenantQuotaExceededError — but RETRYABLE WITH
    BACKOFF, the third arm of the failure taxonomy: unlike a quota rejection
    (every replica enforces the same registry, fail fast) memory pressure is a
    transient, per-executor condition — the soft-watermark eviction sweep or a
    drained backlog clears it — so clients back off and retry the same or a
    replica holder instead of failing the job.  Carried on the wire as the
    dedicated ``SIZE_RESOURCE_EXHAUSTED`` fetch-reply size code.
    """

    def __init__(
        self,
        requested: int = 0,
        used: int = 0,
        watermark: int = 0,
        detail: str = "",
    ) -> None:
        self.requested = requested
        self.used = used
        self.watermark = watermark
        msg = (
            "resource exhausted under memory pressure"
            f" (requested={requested}, used={used}, watermark={watermark})"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ExecutorLostError(TransportError):
    """An executor died while an exchange depended on it and no recovery path
    exists (elasticity off, replication factor 0, or an unsupported exchange
    configuration).  Typed + addressed — names the lost executor and the
    membership epoch — so drivers can tell "re-run after repair" apart from
    programming errors, and so the no-hang guarantee is testable.
    """

    def __init__(self, executor_id: int, epoch: int = 0, detail: str = "") -> None:
        self.executor_id = executor_id
        self.epoch = epoch
        msg = f"executor {executor_id} lost (membership epoch {epoch})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


@dataclass
class OperationStats:
    """Per-operation timing/size stats (ShuffleTransport.scala:64-69).

    Concrete semantics follow ``UcxStats`` (UcxShuffleTransport.scala:36-53):
    ``start_time_ns`` at submit, ``end_time_ns`` at callback, ``recv_size`` bytes
    received, plus the fork's AM-handle timestamps.
    """

    start_time_ns: int = field(default_factory=time.monotonic_ns)
    end_time_ns: Optional[int] = None
    am_handle_start_ns: Optional[int] = None
    am_handle_end_ns: Optional[int] = None
    recv_size: int = 0

    def elapsed_ns(self) -> int:
        end = self.end_time_ns if self.end_time_ns is not None else time.monotonic_ns()
        return end - self.start_time_ns

    def mark_done(self, recv_size: int = 0) -> None:
        self.end_time_ns = time.monotonic_ns()
        self.recv_size += recv_size


@dataclass
class OperationResult:
    """ShuffleTransport.scala:77-81: status + error + stats + resulting data."""

    status: OperationStatus
    error: Optional[TransportError] = None
    stats: Optional[OperationStats] = None
    data: Optional[MemoryBlock] = None


#: ShuffleTransport.scala:71-75 — callback invoked on operation completion.
OperationCallback = Callable[[OperationResult], None]


class Request:
    """Handle for an async transport operation (ShuffleTransport.scala:83-93).

    ``completed()`` never blocks: it drains any attached futures whose results are
    ready (``jax.Array.is_ready()``) and returns whether the whole operation
    finished.  ``progress()`` on the owning transport drives completion.
    """

    def __init__(self, stats: Optional[OperationStats] = None) -> None:
        self._done = threading.Event()
        self._cancelled = False
        self.stats = stats or OperationStats()
        self.result: Optional[OperationResult] = None
        self._poll: Optional[Callable[[], bool]] = None

    def attach_poll(self, poll: Callable[[], bool]) -> None:
        """Install a non-blocking poll that returns True once the op finished."""
        self._poll = poll

    def complete(self, result: OperationResult) -> None:
        self.result = result
        if result.stats is None:
            result.stats = self.stats
        self._done.set()

    def cancel(self) -> None:
        self._cancelled = True
        self.complete(OperationResult(OperationStatus.CANCELED, stats=self.stats))

    def is_cancelled(self) -> bool:
        return self._cancelled

    def completed(self) -> bool:
        if self._done.is_set():
            return True
        if self._poll is not None and self._poll():
            return self._done.is_set()
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> OperationResult:
        deadline = None if timeout is None else time.monotonic() + timeout
        # Spin via the poll hook (the reference's while(!done) progress() loop,
        # UcxShuffleClient.scala:44-46) but yield the GIL between polls.
        while not self._done.is_set():
            if self._poll is not None:
                self._poll()
            if self._done.wait(timeout=0.0005):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("Request.wait timed out")
        assert self.result is not None
        return self.result


def wait_all(requests: Sequence[Request], timeout: Optional[float] = None) -> List[OperationResult]:
    """Wait for a batch of requests (the benchmark's outstanding-window join,
    UcxPerfBenchmark.scala:129-151)."""
    return [r.wait(timeout) for r in requests]
