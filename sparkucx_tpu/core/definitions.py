"""Wire-protocol message ids and frame formats (control/data plane RPC schema).

Counterpart of ``shuffle/ucx/Definitions.scala:22-29`` — the 5 UCX Active-Message ids
the reference speaks with its DPU daemon.  Here the same schema is carried over TCP
sockets (the peer/block-server path and the JVM<->Python plugin shim both speak it):

====================  ==  =======================================================
InitExecutorReq        0  executor handshake: staged-store context blob
InitExecutorAck        1  handshake ack: remote store connected
MapperInfo             2  map-side commit: {numPartitions, mapId, (offset,len)*R}
FetchBlockReq          3  fetch one (shuffleId, mapId, reduceId) block
FetchBlockReqAck       4  fetch reply: block bytes (eager) or rndv handle
FetchBlockChunk        5  striped-wire continuation: one chunk of a streaming
                          fetch reply (tag, block, seq, offset) + payload
WireHello              6  striped-wire lane handshake: (group, lane, nlanes,
                          chunk_bytes) — joins this connection to a stripe group
ReplicaPut             7  neighbor replication: one sealed round's host snapshot
                          {shuffle, srcExecutor, round, (map,reduce,len)*N} + body
ReplicaAck             8  replication ack: echoes (shuffle, srcExecutor, round)
MemberSuspect          9  membership: (epoch, executor, observer) — the observer
                          saw a wire error / timeout naming this executor
MemberRejoin          10  membership: (epoch, executor, observer) — the executor
                          came back; the full mesh returns next shuffle epoch
TracePull             11  observability: pull the peer's trace-event ring —
                          request (tag), reply body = JSON event buffer
MetricsPull           12  observability: pull the peer's metrics snapshot —
                          request (tag), reply body = Prometheus text
ServerBusy            13  load shedding: the server's accept backlog is full
                          (``server.acceptBacklog``) — sent best-effort before
                          closing the shed connection; headerless, bodyless.
                          Clients surface it as retryable ResourceExhaustedError
HotSetPull            14  popularity-aware serving: pull the peer's hot-set
                          advertisement — request (tag), reply body = packed
                          {shuffle: [holder executor ids]} table (hot shuffles
                          whose replica sets were widened beyond
                          ``replication.factor``)
====================  ==  =======================================================

Ids 5-6 extend the reference schema for the striped zero-copy wire path: a
fetch reply in striped mode is a size *manifest* (a FetchBlockReqAck frame with
``body_len == 0``) plus ``FetchBlockChunk`` frames carrying fixed-size slices
of the reply body round-robin across the group's lanes.  Chunks address their
destination directly — ``(tag, block index, offset within block)`` — so lanes
need no cross-lane ordering and the manifest may arrive before, between, or
after the chunks; the fetch completes when the manifest has arrived AND every
payload byte has been scattered.  ``wire.streams = 1`` never emits ids 5-6:
the single-lane wire stays byte-identical to the pre-striping protocol.

Frame format (all little-endian):  ``<u32 am_id> <u64 header_len> <u64 body_len>
<header bytes> <body bytes>`` — the (header, body) split mirrors jucx's
``sendAmNonBlocking(header, body)`` (UcxWorkerWrapper.scala:96-126).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class AmId(enum.IntEnum):
    """Definitions.scala:22-29."""

    INIT_EXECUTOR_REQ = 0
    INIT_EXECUTOR_ACK = 1
    MAPPER_INFO = 2
    FETCH_BLOCK_REQ = 3
    FETCH_BLOCK_REQ_ACK = 4
    FETCH_BLOCK_CHUNK = 5
    WIRE_HELLO = 6
    REPLICA_PUT = 7
    REPLICA_ACK = 8
    MEMBER_SUSPECT = 9
    MEMBER_REJOIN = 10
    TRACE_PULL = 11
    METRICS_PULL = 12
    SERVER_BUSY = 13
    HOT_SET_PULL = 14


_FRAME = struct.Struct("<IQQ")
FRAME_HEADER_SIZE = _FRAME.size

#: Frame size ceiling shared by every frame-reading loop (peer plane + daemon):
#: a corrupt/hostile header claiming a huge length is dropped, never streamed.
MAX_FRAME_BYTES = 1 << 31

#: FetchBlockReq header: (shuffleId, mapId, reduceId) — 12 bytes, matching the
#: reference's header layout (UcxWorkerWrapper.scala:96-126).
_FETCH_REQ = struct.Struct("<iii")


def pack_frame(am_id: AmId, header: bytes = b"", body: bytes = b"") -> bytes:
    return _FRAME.pack(int(am_id), len(header), len(body)) + header + body


def pack_frame_prefix(am_id: AmId, header: bytes, body_len: int) -> bytes:
    """Frame prefix announcing a ``body_len``-byte body that the caller sends
    separately (scatter-send of a large zero-copy reply buffer)."""
    return _FRAME.pack(int(am_id), len(header), body_len) + header


def unpack_frame_header(data: bytes) -> Tuple[AmId, int, int]:
    am_id, hlen, blen = _FRAME.unpack_from(data)
    return AmId(am_id), hlen, blen


def pack_fetch_req(shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
    return _FETCH_REQ.pack(shuffle_id, map_id, reduce_id)


def unpack_fetch_req(data: bytes) -> Tuple[int, int, int]:
    return _FETCH_REQ.unpack_from(data)


#: FetchBlockChunk header: which batch (tag), which block of the batch, the
#: global chunk sequence number (stripe lane = seq % nlanes; telemetry and
#: interleave testing), and the chunk's offset *within its block* — the chunk
#: is self-addressing, so lanes never need cross-lane ordering.
_CHUNK_HDR = struct.Struct("<QIIQ")
CHUNK_HEADER_SIZE = _CHUNK_HDR.size

#: WireHello header: stripe-group id (client-random u64), this connection's
#: lane index, the group's lane count, and the chunk frame size the client
#: expects replies striped into.
_HELLO = struct.Struct("<QIIQ")


def pack_chunk_hdr(tag: int, block: int, seq: int, offset: int) -> bytes:
    return _CHUNK_HDR.pack(tag, block, seq, offset)


def unpack_chunk_hdr(data) -> Tuple[int, int, int, int]:
    return _CHUNK_HDR.unpack_from(data)


#: FetchBlockChunk / ReplicaPut header extensions, detected by header length
#: on the receiving side so mixed-config peers interoperate (same mechanism as
#: the crc32c trailer, config.py ``wire_checksum``).  Chunk header layouts:
#:
#: ====================  =====================================================
#: 24 (base)             plain chunk, payload = raw slice
#: 28 (base+crc)         + u32 crc32c trailer over the WIRE payload
#: 32 (base+codec)       + (u32 codec_id, u32 raw_len): payload is the page
#:                       encoded under codec_id (utils/pagecodec.py) and
#:                       expands to raw_len bytes at (block, offset)
#: 36 (base+codec+crc)   codec ext first, crc trailer LAST — the crc covers
#:                       the ENCODED payload, so corruption is detected
#:                       before the decoder ever parses the page
#: ====================  =====================================================
#:
#: ReplicaPut reuses the same two extensions after its entry table, same
#: order (codec ext, then crc), detected by the residue of
#: ``len(header) - REPLICA_HEADER_SIZE`` modulo ``REPLICA_ENTRY_SIZE``
#: (entries are 16 B; residues 0/4/8/12 = plain/crc/codec/codec+crc).  The
#: 18-byte trace-context extension (``_REPLICA_TRACE_EXT``, obs plane) — when
#: present — is appended LAST, after the crc trailer, shifting every residue
#: by 2 (residues 2/6/10/14); receivers strip it first, then dispatch the
#: remaining residue through the table above unchanged.
#: When a server's codec is on, EVERY chunk carries the codec ext —
#: unprofitable pages ship ``codec_id = 0`` (raw) with ``raw_len`` equal to
#: the payload length, keeping the header length uniform per reply.
_CHUNK_CODEC = struct.Struct("<II")
CHUNK_CODEC_EXT_SIZE = _CHUNK_CODEC.size


def pack_chunk_codec_ext(codec_id: int, raw_len: int) -> bytes:
    return _CHUNK_CODEC.pack(codec_id, raw_len)


def unpack_chunk_codec_ext(data, offset: int = 0) -> Tuple[int, int]:
    return _CHUNK_CODEC.unpack_from(data, offset)


def pack_wire_hello(group: int, lane: int, nlanes: int, chunk_bytes: int) -> bytes:
    return _HELLO.pack(group, lane, nlanes, chunk_bytes)


def unpack_wire_hello(data) -> Tuple[int, int, int, int]:
    return _HELLO.unpack_from(data)


#: ReplicaPut header prefix: (shuffle_id, src_executor, round, num_blocks);
#: followed by num_blocks ``_REPLICA_ENT`` entries (map_id, reduce_id, length)
#: describing the body — the concatenated unpadded block payloads in table
#: order.  ReplicaAck reuses the prefix with num_blocks = 0 and no body.
_REPLICA_HDR = struct.Struct("<iiiI")
_REPLICA_ENT = struct.Struct("<iiq")
REPLICA_HEADER_SIZE = _REPLICA_HDR.size
REPLICA_ENTRY_SIZE = _REPLICA_ENT.size


def pack_replica_put(
    shuffle_id: int, src_executor: int, round_idx: int, entries: List[Tuple[int, int, int]]
) -> bytes:
    """Pack a ReplicaPut header; ``entries`` = (map_id, reduce_id, length)."""
    out = bytearray(_REPLICA_HDR.pack(shuffle_id, src_executor, round_idx, len(entries)))
    for map_id, reduce_id, length in entries:
        out += _REPLICA_ENT.pack(map_id, reduce_id, length)
    return bytes(out)


def unpack_replica_put(data) -> Tuple[int, int, int, List[Tuple[int, int, int]]]:
    sid, src, rnd, n = _REPLICA_HDR.unpack_from(data)
    entries: List[Tuple[int, int, int]] = []
    pos = _REPLICA_HDR.size
    for _ in range(n):
        entries.append(_REPLICA_ENT.unpack_from(data, pos))
        pos += _REPLICA_ENT.size
    return sid, src, rnd, entries


def pack_replica_ack(shuffle_id: int, src_executor: int, round_idx: int) -> bytes:
    return _REPLICA_HDR.pack(shuffle_id, src_executor, round_idx, 0)


def unpack_replica_ack(data) -> Tuple[int, int, int]:
    sid, src, rnd, _ = _REPLICA_HDR.unpack_from(data)
    return sid, src, rnd


#: Distributed-trace context extensions (obs plane, ``obs.traceContext``).
#: Self-describing trailers in the same family as the tenant app-id ext
#: (transport/peer.py ``_APP``): default-off keeps every golden frame
#: byte-identical, and old receivers that don't know the ext still parse the
#: base layout because they validate exact lengths / residues.
#:
#: FetchBlockReq carries a 20-byte ``<IQQ>`` trailer (magic, trace_id,
#: span_id) appended LAST — after the optional app-id ext.  The magic
#: disambiguates it from an app-id ext whose utf-8 payload happens to be
#: 16 bytes: ``unpack_fetch_req_app_id`` requires the app ext to account for
#: the EXACT remaining length, so a trailing trace ext simply reads as "not
#: an app ext" to pre-obs servers.
#:
#: ReplicaPut carries an 18-byte ``<HQQ>`` trailer (u16 magic, trace_id,
#: span_id) appended LAST — after the crc trailer — giving header residues
#: {2, 6, 10, 14} mod 16, disjoint from the crc/codec residues {0, 4, 8, 12}:
#: receivers detect ``residue % 4 == 2``, strip the last 18 bytes, and run
#: the existing codec/crc dispatch on what remains.
TRACE_EXT_MAGIC = 0x54524143  # "TRAC"
REPLICA_TRACE_MAGIC = 0x5443  # "TC"
_TRACE_EXT = struct.Struct("<IQQ")
_REPLICA_TRACE_EXT = struct.Struct("<HQQ")
TRACE_EXT_SIZE = _TRACE_EXT.size
REPLICA_TRACE_EXT_SIZE = _REPLICA_TRACE_EXT.size


def pack_trace_ext(trace_id: int, span_id: int) -> bytes:
    """FetchBlockReq trace-context trailer."""
    return _TRACE_EXT.pack(TRACE_EXT_MAGIC, trace_id, span_id)


def unpack_trace_ext(data) -> Optional[Tuple[int, int]]:
    """(trace_id, span_id) when ``data`` ends in a trace ext, else None."""
    if len(data) < TRACE_EXT_SIZE:
        return None
    magic, trace_id, span_id = _TRACE_EXT.unpack_from(data, len(data) - TRACE_EXT_SIZE)
    if magic != TRACE_EXT_MAGIC:
        return None
    return trace_id, span_id


def pack_replica_trace_ext(trace_id: int, span_id: int) -> bytes:
    """ReplicaPut trace-context trailer (appended after the crc trailer)."""
    return _REPLICA_TRACE_EXT.pack(REPLICA_TRACE_MAGIC, trace_id, span_id)


def unpack_replica_trace_ext(data) -> Optional[Tuple[int, int]]:
    """(trace_id, span_id) when ``data`` ends in a ReplicaPut trace ext."""
    if len(data) < REPLICA_TRACE_EXT_SIZE:
        return None
    magic, trace_id, span_id = _REPLICA_TRACE_EXT.unpack_from(
        data, len(data) - REPLICA_TRACE_EXT_SIZE
    )
    if magic != REPLICA_TRACE_MAGIC:
        return None
    return trace_id, span_id


#: HotSetPull reply body (popularity-aware serving): the advertised hot-set
#: table, ``{shuffle_id: [holder executor ids]}``.  Layout: a ``_HOT_HDR``
#: shuffle count, then per shuffle a ``_HOT_ENT`` (shuffle_id, num_holders)
#: followed by num_holders ``_HOT_EID`` executor ids.  Requests reuse the
#: obs-plane pull shape (u64 tag header, empty body) so the reply can be
#: parked on the tag like TracePull/MetricsPull.  An empty table (count 0)
#: is a valid reply — nothing is hot.
_HOT_HDR = struct.Struct("<I")
_HOT_ENT = struct.Struct("<iI")
_HOT_EID = struct.Struct("<i")


def pack_hot_set(hot: Dict[int, List[int]]) -> bytes:
    """Pack the hot-set advertisement table (sorted for determinism)."""
    out = bytearray(_HOT_HDR.pack(len(hot)))
    for sid in sorted(hot):
        holders = sorted(hot[sid])
        out += _HOT_ENT.pack(sid, len(holders))
        for eid in holders:
            out += _HOT_EID.pack(eid)
    return bytes(out)


def unpack_hot_set(data) -> Dict[int, List[int]]:
    (n,) = _HOT_HDR.unpack_from(data)
    pos = _HOT_HDR.size
    out: Dict[int, List[int]] = {}
    for _ in range(n):
        sid, nh = _HOT_ENT.unpack_from(data, pos)
        pos += _HOT_ENT.size
        holders: List[int] = []
        for _ in range(nh):
            holders.append(_HOT_EID.unpack_from(data, pos)[0])
            pos += _HOT_EID.size
        out[sid] = holders
    return out


#: Membership frame header (MemberSuspect / MemberRejoin): the observer's
#: membership epoch AFTER applying the event, the subject executor, and the
#: observing executor.  Bodyless — membership is metadata, never payload.
#: Receivers apply the event to their local membership view; epoch is
#: advisory (views converge by union of suspects, not by epoch ordering).
_MEMBER_HDR = struct.Struct("<Qii")


def pack_member_event(epoch: int, executor_id: int, observer_id: int) -> bytes:
    return _MEMBER_HDR.pack(epoch, executor_id, observer_id)


def unpack_member_event(data) -> Tuple[int, int, int]:
    return _MEMBER_HDR.unpack_from(data)


@dataclass(frozen=True)
class MapperInfo:
    """Map-side commit record.

    Counterpart of the packed commit blob
    ``{1, numPartitions, mapId, (offset, len) * numPartitions}``
    (NvkvShuffleMapOutputWriter.scala:116-148).  We add shuffle_id explicitly
    instead of relying on device-space carve-up by shuffleId, and an optional
    per-partition staging-round index (multi-round spill) carried as a
    backward-compatible tail: blobs without the tail decode with all rounds 0.
    """

    shuffle_id: int
    map_id: int
    partitions: Tuple[Tuple[int, int], ...]  # (offset, length) per reduce partition
    rounds: Optional[Tuple[int, ...]] = None  # staging round per partition

    _HDR = struct.Struct("<iii")  # shuffle_id, map_id, num_partitions
    _ENT = struct.Struct("<qq")  # offset, length
    _RND = struct.Struct("<i")  # round index

    def round_of(self, reduce_id: int) -> int:
        return self.rounds[reduce_id] if self.rounds is not None else 0

    def pack(self) -> bytes:
        out = bytearray(self._HDR.pack(self.shuffle_id, self.map_id, len(self.partitions)))
        for off, ln in self.partitions:
            out += self._ENT.pack(off, ln)
        if self.rounds is not None and any(self.rounds):
            out += b"\x01"
            for r in self.rounds:
                out += self._RND.pack(r)
        return bytes(out)

    @classmethod
    def unpack(cls, data: bytes) -> "MapperInfo":
        sid, mid, n = cls._HDR.unpack_from(data)
        offs: List[Tuple[int, int]] = []
        pos = cls._HDR.size
        for _ in range(n):
            off, ln = cls._ENT.unpack_from(data, pos)
            offs.append((off, ln))
            pos += cls._ENT.size
        rounds: Optional[Tuple[int, ...]] = None
        if pos < len(data) and data[pos] == 1:
            pos += 1
            rounds = tuple(cls._RND.unpack_from(data, pos + i * cls._RND.size)[0] for i in range(n))
        return cls(sid, mid, tuple(offs), rounds)
