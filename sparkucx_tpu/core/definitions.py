"""Wire-protocol message ids and frame formats (control/data plane RPC schema).

Counterpart of ``shuffle/ucx/Definitions.scala:22-29`` — the 5 UCX Active-Message ids
the reference speaks with its DPU daemon.  Here the same schema is carried over TCP
sockets (the peer/block-server path and the JVM<->Python plugin shim both speak it):

====================  ==  =======================================================
InitExecutorReq        0  executor handshake: staged-store context blob
InitExecutorAck        1  handshake ack: remote store connected
MapperInfo             2  map-side commit: {numPartitions, mapId, (offset,len)*R}
FetchBlockReq          3  fetch one (shuffleId, mapId, reduceId) block
FetchBlockReqAck       4  fetch reply: block bytes (eager) or rndv handle
====================  ==  =======================================================

Frame format (all little-endian):  ``<u32 am_id> <u64 header_len> <u64 body_len>
<header bytes> <body bytes>`` — the (header, body) split mirrors jucx's
``sendAmNonBlocking(header, body)`` (UcxWorkerWrapper.scala:96-126).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple


class AmId(enum.IntEnum):
    """Definitions.scala:22-29."""

    INIT_EXECUTOR_REQ = 0
    INIT_EXECUTOR_ACK = 1
    MAPPER_INFO = 2
    FETCH_BLOCK_REQ = 3
    FETCH_BLOCK_REQ_ACK = 4


_FRAME = struct.Struct("<IQQ")
FRAME_HEADER_SIZE = _FRAME.size

#: Frame size ceiling shared by every frame-reading loop (peer plane + daemon):
#: a corrupt/hostile header claiming a huge length is dropped, never streamed.
MAX_FRAME_BYTES = 1 << 31

#: FetchBlockReq header: (shuffleId, mapId, reduceId) — 12 bytes, matching the
#: reference's header layout (UcxWorkerWrapper.scala:96-126).
_FETCH_REQ = struct.Struct("<iii")


def pack_frame(am_id: AmId, header: bytes = b"", body: bytes = b"") -> bytes:
    return _FRAME.pack(int(am_id), len(header), len(body)) + header + body


def pack_frame_prefix(am_id: AmId, header: bytes, body_len: int) -> bytes:
    """Frame prefix announcing a ``body_len``-byte body that the caller sends
    separately (scatter-send of a large zero-copy reply buffer)."""
    return _FRAME.pack(int(am_id), len(header), body_len) + header


def unpack_frame_header(data: bytes) -> Tuple[AmId, int, int]:
    am_id, hlen, blen = _FRAME.unpack_from(data)
    return AmId(am_id), hlen, blen


def pack_fetch_req(shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
    return _FETCH_REQ.pack(shuffle_id, map_id, reduce_id)


def unpack_fetch_req(data: bytes) -> Tuple[int, int, int]:
    return _FETCH_REQ.unpack_from(data)


@dataclass(frozen=True)
class MapperInfo:
    """Map-side commit record.

    Counterpart of the packed commit blob
    ``{1, numPartitions, mapId, (offset, len) * numPartitions}``
    (NvkvShuffleMapOutputWriter.scala:116-148).  We add shuffle_id explicitly
    instead of relying on device-space carve-up by shuffleId, and an optional
    per-partition staging-round index (multi-round spill) carried as a
    backward-compatible tail: blobs without the tail decode with all rounds 0.
    """

    shuffle_id: int
    map_id: int
    partitions: Tuple[Tuple[int, int], ...]  # (offset, length) per reduce partition
    rounds: Optional[Tuple[int, ...]] = None  # staging round per partition

    _HDR = struct.Struct("<iii")  # shuffle_id, map_id, num_partitions
    _ENT = struct.Struct("<qq")  # offset, length
    _RND = struct.Struct("<i")  # round index

    def round_of(self, reduce_id: int) -> int:
        return self.rounds[reduce_id] if self.rounds is not None else 0

    def pack(self) -> bytes:
        out = bytearray(self._HDR.pack(self.shuffle_id, self.map_id, len(self.partitions)))
        for off, ln in self.partitions:
            out += self._ENT.pack(off, ln)
        if self.rounds is not None and any(self.rounds):
            out += b"\x01"
            for r in self.rounds:
                out += self._RND.pack(r)
        return bytes(out)

    @classmethod
    def unpack(cls, data: bytes) -> "MapperInfo":
        sid, mid, n = cls._HDR.unpack_from(data)
        offs: List[Tuple[int, int]] = []
        pos = cls._HDR.size
        for _ in range(n):
            off, ln = cls._ENT.unpack_from(data, pos)
            offs.append((off, ln))
            pos += cls._ENT.size
        rounds: Optional[Tuple[int, ...]] = None
        if pos < len(data) and data[pos] == 1:
            pos += 1
            rounds = tuple(cls._RND.unpack_from(data, pos + i * cls._RND.size)[0] for i in range(n))
        return cls(sid, mid, tuple(offs), rounds)
