"""The transport trait — the framework's central abstraction.

Counterpart of the ``ShuffleTransport`` trait (ShuffleTransport.scala:110-167) plus the
fork's DPU extensions ``initExecuter``/``commitBlock``/``fetchBlock``
(UcxShuffleTransport.scala:281-298).  Usage flow (ShuffleTransport.scala:95-109):

1. ``init()`` on each executor; exchange ``executor_id -> address`` via the control
   plane (parallel/bootstrap.py) and ``add_executor`` peers.
2. Map side ``register``\\ s produced blocks (or writes them through the staged
   store + ``commit_block``).
3. Reduce side calls ``fetch_blocks_by_block_ids`` and drives ``progress()``
   until the requests complete.
4. ``unregister_shuffle``/``close`` tear down.

The trait is deliberately implementation-neutral so that a loopback transport can
back unit tests (the reference documents exactly this intent on ``addExecutor``,
ShuffleTransport.scala:124-128) while the real implementation lowers batched fetches
to a ragged all_to_all over the TPU mesh (transport/tpu.py).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Sequence

from sparkucx_tpu.core.block import Block, BlockId, MemoryBlock
from sparkucx_tpu.core.operation import OperationCallback, Request

ExecutorId = int


class ShuffleTransport(ABC):
    """ShuffleTransport.scala:110-167."""

    # -- lifecycle ---------------------------------------------------------

    @abstractmethod
    def init(self) -> bytes:
        """Initialize the transport; returns the serialized local address blob
        other executors use to connect (ShuffleTransport.scala:113-117)."""

    @abstractmethod
    def close(self) -> None:
        ...

    # -- membership --------------------------------------------------------

    @abstractmethod
    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        """Register a peer executor's address (ShuffleTransport.scala:124-131)."""

    def add_executors(self, executors: Dict[ExecutorId, bytes]) -> None:
        for eid, addr in executors.items():
            self.add_executor(eid, addr)

    @abstractmethod
    def remove_executor(self, executor_id: ExecutorId) -> None:
        ...

    def pre_connect(self) -> None:
        """Eagerly establish connections to all known peers
        (UcxWorkerWrapper.preconnect semantics via UcxExecutorRpcEndpoint.scala:19-39)."""

    # -- server side (map output) -----------------------------------------

    @abstractmethod
    def register(self, block_id: BlockId, block: Block) -> None:
        """Publish a block for serving (ShuffleTransport.scala:133-138)."""

    @abstractmethod
    def mutate(self, block_id: BlockId, block: Block, callback: Optional[OperationCallback]) -> None:
        """Replace a registered block under its lock (ShuffleTransport.scala:140-146)."""

    @abstractmethod
    def unregister(self, block_id: BlockId) -> None:
        ...

    @abstractmethod
    def unregister_shuffle(self, shuffle_id: int) -> None:
        """Bulk-remove a shuffle's blocks (UcxShuffleTransport.scala:249-259)."""

    # -- client side (reduce fetch) ---------------------------------------

    @abstractmethod
    def fetch_blocks_by_block_ids(
        self,
        executor_id: ExecutorId,
        block_ids: Sequence[BlockId],
        result_buffers: Sequence[MemoryBlock],
        callbacks: Sequence[Optional[OperationCallback]],
    ) -> List[Request]:
        """Batch fetch of remote blocks into caller-provided buffers
        (ShuffleTransport.scala:148-156)."""

    @abstractmethod
    def progress(self) -> None:
        """Advance outstanding operations; requests complete only under progress
        (ShuffleTransport.scala:158-165).  For the TPU transport this polls async
        XLA executions instead of a UCX worker."""

    # -- fork extensions (staged-store path) -------------------------------

    def init_executor(self, num_mappers: int, num_reducers: int) -> None:
        """Executor<->store handshake (UcxShuffleTransport.scala:281-284).

        In the reference this ships the NVKV context to the DPU daemon
        (InitExecutorReq/Ack); here it sizes/creates the HBM staged store."""
        raise NotImplementedError

    def commit_block(self, mapper_info_blob: bytes, callback: Optional[OperationCallback] = None) -> None:
        """Commit map-output metadata (UcxShuffleTransport.scala:286-291)."""
        raise NotImplementedError

    def fetch_block(
        self,
        executor_id: ExecutorId,
        shuffle_id: int,
        map_id: int,
        reduce_id: int,
        result_buffer: MemoryBlock,
        callback: Optional[OperationCallback] = None,
    ) -> Request:
        """Fetch a single staged block (UcxShuffleTransport.scala:293-298)."""
        raise NotImplementedError
