"""Fault-injection harness: named failure points for chaos testing.

Production code calls ``faults.check("point", **ctx)`` (may raise or stall)
and ``faults.transform("point", data, **ctx)`` (may corrupt bytes) at named
points.  With nothing armed — the production state — both are a module
attribute read plus a falsy branch; no locks, no dict lookups.

Tests arm faults with :func:`arm` and an action built by the factories below
(:func:`sever`, :func:`stall`, :func:`garble`, :func:`delay`, :func:`fail`),
optionally scoped to a context match and a finite fire count, and clean up
with :func:`reset` (or the :func:`injected_faults` context manager, which
resets on exit even when the test body raises).

Named points currently instrumented (transport/peer.py):

====================  ==========================================================
peer.client.recv      top of a client lane's recv loop, before each frame
                      (ctx: ``peer``, ``lane``)
peer.client.frame     transform hook over each received client frame header
                      (ctx: ``peer``, ``lane``) — garbling it kills the lane
peer.server.frame     server dispatch, after each decoded frame
                      (ctx: ``peer``, ``am_id``)
peer.server.chunk     transform hook over each striped chunk's payload, after
                      its crc trailer is computed (ctx: ``tag``, ``block``) —
                      garbling it models in-flight corruption the client-side
                      ``wire.checksum`` verify must catch

replica.push          replicator thread, before pushing a sealed shuffle
                      (ctx: ``shuffle_id``, ``executor``)
replica.apply         server side, before installing a received replica round
                      (ctx: ``shuffle_id``, ``src_executor``, ``round_idx``)
exchange.submit       collective plane (transport/tpu.py), before each round's
                      submit (ctx: ``shuffle_id``, ``round``) — the hook that
                      lets chaos tests kill an executor mid-superstep
store.mem_pressure    store/hbm_store.py + memory/pool.py, before each
                      allocation-bearing mutation (close_partition, device
                      write, replica install, restage, pool growth) — arming
                      ``fail(ResourceExhaustedError(...))`` models a host
                      under memory pressure (ctx: ``site``, ``nbytes``)
====================  ==========================================================

:func:`kill_executor` force-kills a loopback-cluster executor: its server
socket, accepted connections, and outbound client connections all die
abruptly (peers observe EOF/reset, never a goodbye) — the in-process stand-in
for SIGKILLing an executor process mid-superstep.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: Fast-path flag: every check/transform hook bails immediately when False.
#: Written only under _lock; read racily by hooks (benign — worst case one
#: extra locked lookup around an arm/reset edge).
active = False

_lock = threading.Lock()


@dataclass
class _Armed:
    point: str
    action: Callable[..., Any]
    times: Optional[int] = None  # remaining fires; None = unlimited
    match: Optional[Dict[str, Any]] = None  # ctx subset that must match
    fired: int = 0


_armed: List[_Armed] = []  #: guarded by _lock
#: total fires per point (telemetry for tests); guarded by _lock
fired: Dict[str, int] = {}

#: Fault observers, called ``(point, **ctx)`` AFTER an armed fault fires —
#: the flight recorder (obs/recorder.py) subscribes so chaos events land in
#: postmortem bundles.  Called outside _lock, before the fault's own action
#: (which may raise); observer exceptions are swallowed: observability must
#: never change what a chaos test injects.
on_fault: List[Callable[..., None]] = []


def _notify(point: str, ctx: Dict[str, Any]) -> None:
    for cb in list(on_fault):
        try:
            cb(point, **ctx)
        except Exception:
            pass


def arm(
    point: str,
    action: Callable[..., Any],
    *,
    times: Optional[int] = None,
    match: Optional[Dict[str, Any]] = None,
) -> _Armed:
    """Arm ``action`` at ``point``.  ``times`` bounds how often it fires;
    ``match`` restricts it to calls whose context contains the given items."""
    global active
    entry = _Armed(point, action, times, match)
    with _lock:
        _armed.append(entry)
        active = True
    return entry


def disarm(entry: _Armed) -> None:
    global active
    with _lock:
        if entry in _armed:
            _armed.remove(entry)
        active = bool(_armed)


def reset() -> None:
    """Disarm everything and clear telemetry."""
    global active
    with _lock:
        _armed.clear()
        fired.clear()
        active = False


@contextlib.contextmanager
def injected_faults(*arms):
    """``with injected_faults((point, action), ...):`` — resets on exit even
    when the body raises, so one chaotic test cannot poison the next."""
    entries = [arm(point, action) for point, action in arms]
    try:
        yield entries
    finally:
        reset()


def _select(point: str, ctx: Dict[str, Any]) -> List[_Armed]:
    out = []
    for entry in _armed:
        if entry.point != point:
            continue
        if entry.times is not None and entry.fired >= entry.times:
            continue
        if entry.match and any(ctx.get(k) != v for k, v in entry.match.items()):
            continue
        out.append(entry)
    return out


def check(point: str, **ctx) -> None:
    """Fire any armed action at ``point``.  Actions may raise (sever), sleep
    (stall/delay), or no-op; their return value is ignored."""
    if not active:
        return
    with _lock:
        hits = _select(point, ctx)
        for entry in hits:
            entry.fired += 1
        if hits:
            fired[point] = fired.get(point, 0) + len(hits)
    if hits:
        _notify(point, ctx)
    for entry in hits:  # run actions outside the lock: they may sleep
        entry.action(point=point, **ctx)


def transform(point: str, data, **ctx):
    """Pass ``data`` through any armed transform at ``point``; actions return
    the (possibly corrupted) replacement."""
    if not active:
        return data
    with _lock:
        hits = _select(point, ctx)
        for entry in hits:
            entry.fired += 1
        if hits:
            fired[point] = fired.get(point, 0) + len(hits)
    if hits:
        _notify(point, ctx)
    for entry in hits:
        data = entry.action(data, point=point, **ctx)
    return data


# -- action factories ------------------------------------------------------


def sever(message: str = "fault injected: connection severed"):
    """check-action: raise ConnectionResetError, as if the peer RST the lane."""

    def _act(**_ctx):
        raise ConnectionResetError(message)

    return _act


def stall(seconds: float):
    """check-action: hang the calling thread, as if the peer stopped sending
    mid-frame (long enough past ``wire.timeoutMs`` and the timeout fires)."""

    def _act(**_ctx):
        time.sleep(seconds)

    return _act


#: Replication-delay alias — same behavior, clearer chaos-test intent.
delay = stall


def garble(xor: int = 0xFF):
    """transform-action: corrupt every byte (XOR) of the passing data."""

    def _act(data, **_ctx):
        # vectorized buffer XOR — MiB-scale chunks pass through chaos tests
        # at memcpy speed instead of a per-byte Python loop
        arr = np.frombuffer(bytes(data), dtype=np.uint8) ^ np.uint8(xor)
        return bytearray(arr.tobytes())

    return _act


def throttle(bytes_per_sec: float):
    """transform-action: pace the passing data to ``bytes_per_sec`` — the
    gray-failure stand-in for a congested / degraded link.  Sleeps
    ``len(data) / bytes_per_sec`` and returns the data unchanged, so the
    peer is slow but every byte still arrives bit-identically."""

    def _act(data, **_ctx):
        n = len(data)
        if n and bytes_per_sec > 0:
            time.sleep(n / bytes_per_sec)
        return data

    return _act


def flaky(p: float, seed: int = 0):
    """check-action: raise ConnectionResetError with probability ``p`` per
    call, from a private deterministic stream — the same ``seed`` replays the
    same failure pattern, so flaky-peer chaos tests are reproducible."""
    rng = random.Random(seed)
    rng_lock = threading.Lock()

    def _act(**_ctx):
        with rng_lock:
            roll = rng.random()
        if roll < p:
            raise ConnectionResetError(f"fault injected: flaky peer (p={p})")

    return _act


def fail(exc: BaseException):
    """check-action: raise an arbitrary prepared exception."""

    def _act(**_ctx):
        raise exc

    return _act


# -- executor chaos --------------------------------------------------------


def kill_executor(transport) -> None:
    """Abruptly kill a loopback-cluster executor (a ``PeerTransport``).

    Closes the listen socket, every accepted serving connection, and every
    outbound client connection with no goodbye — peers see EOF/ECONNRESET
    exactly as if the executor process died.  The transport object itself is
    left unusable (fetches through it fail), matching a dead process.

    Transports that model in-process executors (``TpuShuffleTransport``)
    expose a ``chaos_kill`` hook instead of sockets: it closes the executor's
    store and reports the death to cluster membership, so the collective
    plane observes the loss the same way the wire plane observes a RST.

    Idempotent: a second kill of the same transport is a no-op — real
    processes only die once, and chaos tests that tear down in both the test
    body and a finally block must not trip over the first kill's cleanup.
    """
    if getattr(transport, "_chaos_killed", False):
        return
    try:
        transport._chaos_killed = True
    except AttributeError:
        pass  # __slots__-style transports: kill proceeds, just not recorded
    recorder = getattr(transport, "recorder", None)
    if recorder is not None:
        # full bundle BEFORE the kill: no subsystem lock is held here, and
        # the dying executor's last metrics view is the interesting one —
        # including its final peer-health/breaker view, the postmortem's
        # best clue about WHY chaos chose this executor
        health_snapshot = getattr(transport, "health_snapshot", None)
        context = {"executor": getattr(transport, "executor_id", None)}
        if health_snapshot is not None:
            try:
                context["peer_health"] = health_snapshot()
            except Exception:
                pass
        recorder.capture("chaos_kill", **context)
    chaos_kill = getattr(transport, "chaos_kill", None)
    if chaos_kill is not None:
        chaos_kill()
    server = getattr(transport, "server", None)
    if server is not None:
        server.close()
    conn_lock = getattr(transport, "_conn_lock", None)
    if conn_lock is not None:
        with conn_lock:
            conns = list(transport._conns.values()) + list(transport._zombies)
            transport._conns.clear()
            transport._zombies = []
        for c in conns:
            c.close()
