"""Test-support utilities (fault injection, chaos helpers).

Not imported by production code paths except through the near-zero-cost
``faults.check`` hooks — with no fault armed, every hook is one module
attribute read and a falsy branch.
"""
