"""HBM-staged shuffle block store — the NVKV/DPU-NVMe analogue.

Counterpart of ``NvkvHandler`` (NvkvHandler.scala, 266 LoC): where the reference
stages map output through an 8 KB pinned buffer into DPU-attached NVMe
(``write``/``postWrite`` :213-242, ``read``/``postRead`` :160-211) and tracks a
numMappers x numReducers offset table (:258-265), this store stages map output in a
host staging area carved into **per-peer regions** and seals it into **TPU HBM** as a
single ``jax.device_put`` — one large H2D DMA instead of thousands of small ones,
which is the bandwidth-correct shape for TPU.

Key design departures (TPU-first, each replacing a reference POC shortcut):

* **Dynamic space accounting** instead of the static device carve-up
  ``shuffleId * shuffleBlockSize + mapId * alignedMapBlockSize``
  (NvkvShuffleMapOutputWriter.scala:94-103): regions track a used-watermark and
  overflow is an error, not silent corruption.
* **Peer-major regions**: reduce partitions are owned by executors in contiguous
  ranges; each map task's partition bytes append into the owning peer's region.
  Because Spark map writers emit partitions in increasing reduce order
  (enforced sequentially, NvkvShuffleMapOutputWriter.scala:108), region writes
  stay append-only AND the sealed buffer is already in the exact slot layout the
  exchange collective consumes (ops/exchange.py) — zero repacking between "write
  shuffle output" and "run the all_to_all".
* **Alignment**: every block is padded to ``conf.block_alignment`` (default 128,
  the TPU lane width) — the role NVKV's 512-byte sector alignment plays in
  ``writeRemaining`` (NvkvHandler.scala:244-256).  Padding is recorded per block
  like the reference records it per partition (NvkvShuffleMapOutputWriter.scala:236-246).
* The offset table is the authoritative metadata (``commitPartition`` /
  ``getPartitonOffset``/``getPartitonLength``, NvkvHandler.scala:258-265) and is
  exported as a ``MapperInfo`` blob per map task — the same commit payload the
  reference ships to the DPU daemon (NvkvShuffleMapOutputWriter.scala:116-148).
* ``read_block`` serves a staged block back from HBM (after seal) or the host
  staging area (before seal) — the two arms of the reference's A/B path
  ``spark.dpuTest.enabled`` (compat/spark_3_0/UcxShuffleBlockResolver.scala:86-97).
"""

from __future__ import annotations

import shutil
import threading
import time
import weakref
from bisect import bisect_right
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import (
    BlockNotFoundError,
    ResourceExhaustedError,
    TenantQuotaExceededError,
    TransportError,
)
from sparkucx_tpu.service.eviction import ServeCache
from sparkucx_tpu.testing import faults
from sparkucx_tpu.utils.trace import span


def default_peer_ranges(num_reducers: int, num_peers: int) -> List[Tuple[int, int]]:
    """Contiguous reducer ownership: peer p owns [start, end).  Balanced like
    Spark's range partitioning of reduce ids over executors."""
    base, rem = divmod(num_reducers, num_peers)
    ranges = []
    start = 0
    for p in range(num_peers):
        n = base + (1 if p < rem else 0)
        ranges.append((start, start + n))
        start += n
    return ranges


def _purge_spill_dir(holder: Dict[str, Optional[str]]) -> None:
    """Remove a store's private spill tempdir wholesale.  Module-level so the
    ``weakref.finalize`` registered at store construction holds no reference
    to the store itself — the one spill-dir leak is a store dropped without
    ``close()`` (GC / interpreter exit), and a bound method would keep the
    store alive forever."""
    path = holder.get("dir")
    if path is not None:
        shutil.rmtree(path, ignore_errors=True)
        holder["dir"] = None


@dataclass
class _BlockEntry:
    offset: int  # absolute offset in the staging buffer (of its round)
    length: int  # true payload bytes
    padded: int  # bytes including alignment padding
    round: int = 0  # staging round (multi-round spill; round 0 = common case)
    #: False for entries installed from a peer's MapperInfo — their offsets are
    #: sender-relative, so the bytes live on the SENDER, not in local staging.
    #: The replicator only pushes local entries.
    local: bool = True


class _ShuffleState:
    def __init__(
        self,
        shuffle_id: int,
        num_mappers: int,
        num_reducers: int,
        peer_ranges: List[Tuple[int, int]],
        capacity: int,
        alignment: int,
        staging: Optional[np.ndarray] = None,
        staging_closer=None,
    ) -> None:
        self.shuffle_id = shuffle_id
        self.num_mappers = num_mappers
        self.num_reducers = num_reducers
        self.peer_ranges = peer_ranges
        self.alignment = alignment
        self.staging_closer = staging_closer
        n = len(peer_ranges)
        self.region_size = (capacity // n) // alignment * alignment
        if self.region_size <= 0:
            raise ValueError(f"staging capacity {capacity} too small for {n} regions")
        if staging is not None:
            if staging.size < n * self.region_size:
                raise ValueError("provided staging buffer too small")
            self._staging = staging[: n * self.region_size]
        else:
            self._staging = None  # allocated lazily on first host-path touch
        #: Write-path mode latch: None until the first partition lands, then
        #: False (host MapWriter.write) or True (write_partition_device) — a
        #: shuffle is host- or device-staged, never both.
        self.device_mode: Optional[bool] = None
        #: Current device round's blocks awaiting scatter materialization:
        #: (dst_row, rows, payload) triples in append order, plus a per-block
        #: map for serving reads of the not-yet-sealed round.
        self.device_pending: List[Tuple[int, int, object]] = []
        self.device_blocks: Dict[Tuple[int, int], object] = {}
        #: Multi-round spill state: when a region fills, the whole staging epoch
        #: is snapshotted and writing continues in a fresh round — the exchange
        #: then runs one collective per round.  This is the data-volume scaling
        #: the reference windows with maxBlocksPerRequest/numOutstanding
        #: (SURVEY.md section 5.7) applied to the bulk-synchronous plane.
        self.round = 0
        self.prev_rounds: List[Tuple[np.ndarray, np.ndarray]] = []  # (staging, region_used)
        #: (path, nbytes) of rounds spilled to the disk tier (conf.spill_to_disk)
        self.spill_files: List[Tuple[str, int]] = []
        self.region_used = np.zeros(n, dtype=np.int64)
        self.blocks: Dict[Tuple[int, int], _BlockEntry] = {}  # (map, reduce) -> entry
        self.committed_maps: set = set()
        self.sealed_payload: Optional[object] = None  # jax.Array | np.ndarray
        self._range_starts = [r[0] for r in peer_ranges]
        #: Owning tenant (multi-tenant service, service/tenants.py); None for
        #: single-tenant shuffles — no charges, no translation, no wire ext.
        self.app_id: Optional[str] = None
        #: Bytes currently charged against the owning tenant's HBM quota
        #: (region allocations + restaged rounds, minus disk-tier demotions).
        self.tenant_charged = 0  #: guarded by the owning store's _lock

    @property
    def staging(self) -> Optional[np.ndarray]:
        """Host staging buffer, allocated on first touch.  Device-staged
        shuffles never read this property, so the buffer is never allocated
        for them — the observable form of the tentpole's "no host round trip"
        guarantee (``HbmBlockStore.host_staging_allocated``)."""
        if self._staging is None:
            self._staging = np.zeros(
                len(self.peer_ranges) * self.region_size, dtype=np.uint8
            )
        return self._staging

    @staging.setter
    def staging(self, value: Optional[np.ndarray]) -> None:
        self._staging = value

    @property
    def host_staging_allocated(self) -> bool:
        return self._staging is not None

    def owner_of(self, reduce_id: int) -> int:
        if not (0 <= reduce_id < self.num_reducers):
            raise ValueError(f"reduce_id {reduce_id} out of range [0, {self.num_reducers})")
        return bisect_right(self._range_starts, reduce_id) - 1

    @property
    def sealed(self) -> bool:
        return self.sealed_payload is not None


class MapWriter:
    """Sequential per-map partition writer handle.

    Mirrors the ``NvkvShufflePartitionWriter``/``PartitionWriterStream`` protocol:
    partitions must be opened in increasing reduce order
    (NvkvShuffleMapOutputWriter.scala:108), a partition's bytes stream in via any
    number of ``write`` calls, and ``close_partition`` pads to alignment and
    records (offset, length) (:236-246).

    Concurrency: streamed bytes buffer writer-locally (the role of the
    reference's 8 KB pinned write buffer, NvkvHandler.scala:26,213-242) and the
    region allocate + copy + table record happen atomically at close — so any
    number of map tasks can write concurrently, and a staging-round rollover can
    never interleave with a half-written partition.
    """

    def __init__(
        self, store: "HbmBlockStore", state: _ShuffleState, map_id: int, discard: bool = False
    ) -> None:
        self._store = store
        self._state = state
        self.map_id = map_id
        self._last_reduce = -1
        self._open_reduce: Optional[int] = None
        self._chunks: List[bytes] = []
        self._written = 0
        #: First-commit-wins task-retry semantics: when a successful commit for
        #: this map already exists, the retry attempt's writes are swallowed and
        #: commit() returns the existing table — the reference's atomic
        #: check-or-replace protocol (IndexShuffleBlockResolver.scala:161-217:
        #: "if an existing index is valid, keep it and discard this attempt").
        self._discard = discard

    def open_partition(self, reduce_id: int) -> None:
        if self._open_reduce is not None:
            raise TransportError("previous partition still open")
        if reduce_id <= self._last_reduce:
            raise TransportError(
                f"partitions must be opened in increasing reduce order "
                f"(got {reduce_id} after {self._last_reduce})"
            )
        self._state.owner_of(reduce_id)  # validate range
        self._open_reduce = reduce_id
        self._chunks = []
        self._written = 0

    def write(self, data: bytes) -> None:
        if self._open_reduce is None:
            raise TransportError("no open partition")
        if self._written + len(data) > self._state.region_size and not self._discard:
            raise TransportError(
                f"single partition ({self.map_id},{self._open_reduce}) exceeds a "
                f"whole region ({self._state.region_size} B) — raise stagingCapacity"
            )
        if not self._discard:
            self._chunks.append(bytes(data))
        self._written += len(data)

    def close_partition(self) -> None:
        if self._open_reduce is None:
            raise TransportError("no open partition")
        st = self._state
        reduce_id = self._open_reduce
        peer = st.owner_of(reduce_id)
        if not self._discard:
            padded = -(-self._written // st.alignment) * st.alignment
            # watermark gate before taking the lock: a shed write fails typed
            # (retryable ResourceExhaustedError) with nothing allocated
            self._store.check_memory_pressure("close_partition", padded)
            with self._store._lock:
                if st.device_mode:
                    raise TransportError(
                        f"shuffle {st.shuffle_id} already has device-staged rounds — "
                        "host and device writes cannot mix"
                    )
                st.device_mode = False
                # Admission check first: an over-quota tenant write must fail
                # typed with nothing allocated, rolled over, or copied.
                self._store._charge_tenant(st, padded)  #: balanced by _release_tenant
                # Allocate in the current round; roll the staging epoch when the
                # region can't take this partition (multi-round spill).
                if int(st.region_used[peer]) + padded > st.region_size:
                    if st.staging_closer is not None:
                        raise TransportError(
                            "region overflow with shm staging — multi-round spill "
                            "requires private staging; raise stagingCapacity"
                        )
                    self._store._rollover(st)
                start = peer * st.region_size + int(st.region_used[peer])
                pos = start
                for chunk in self._chunks:
                    st.staging[pos : pos + len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
                    pos += len(chunk)
                st.blocks[(self.map_id, reduce_id)] = _BlockEntry(
                    offset=start, length=self._written, padded=padded, round=st.round
                )
                st.region_used[peer] += padded
        self._last_reduce = reduce_id
        self._open_reduce = None
        self._chunks = []

    def write_partition(self, reduce_id: int, data: bytes) -> None:
        """Convenience: open + write + close in one call."""
        self.open_partition(reduce_id)
        if data:
            self.write(data)
        self.close_partition()

    def write_partition_device(self, reduce_id: int, rows, length: Optional[int] = None) -> None:
        """Device-path partition write (conf.device_staging): ``rows`` is a
        ``(r, lane)`` int32 device array — one row per ``alignment`` bytes,
        already the exchange's wire unit.  The payload never visits host
        memory: it stays device-resident until the block-scatter kernel places
        the whole round into HBM staging at seal (or at D2H rollover, the one
        point where a host copy is unavoidable).  Same protocol and offset
        table as the host path: increasing reduce order, one write per
        partition, first commit wins.  ``length`` is the true payload byte
        count when the last row is padding-tailed (defaults to the full
        ``rows`` extent)."""
        if self._open_reduce is not None:
            raise TransportError("previous partition still open")
        if reduce_id <= self._last_reduce:
            raise TransportError(
                f"partitions must be opened in increasing reduce order "
                f"(got {reduce_id} after {self._last_reduce})"
            )
        st = self._state
        peer = st.owner_of(reduce_id)
        lane = st.alignment // 4
        if getattr(rows, "ndim", 0) != 2 or rows.shape[1] != lane:
            raise TransportError(
                f"device partition must be (rows, {lane}) int32, got shape "
                f"{getattr(rows, 'shape', None)}"
            )
        nrows = int(rows.shape[0])
        padded = nrows * st.alignment
        if length is None:
            length = padded
        min_len = (nrows - 1) * st.alignment + 1 if nrows else 0
        if not (min_len <= length <= padded):
            raise TransportError(
                f"length {length} inconsistent with {nrows} staged rows of "
                f"{st.alignment} B each"
            )
        if not self._discard:
            if padded > st.region_size:
                raise TransportError(
                    f"single partition ({self.map_id},{reduce_id}) exceeds a "
                    f"whole region ({st.region_size} B) — raise stagingCapacity"
                )
            self._store.check_memory_pressure("write_partition_device", padded)
            with self._store._lock:
                if st.device_mode is False:
                    raise TransportError(
                        f"shuffle {st.shuffle_id} already has host-staged blocks — "
                        "host and device writes cannot mix"
                    )
                st.device_mode = True
                self._store._charge_tenant(st, padded)  #: balanced by _release_tenant
                if int(st.region_used[peer]) + padded > st.region_size:
                    if st.staging_closer is not None:
                        raise TransportError(
                            "region overflow with shm staging — multi-round spill "
                            "requires private staging; raise stagingCapacity"
                        )
                    self._store._rollover_device(st)
                start = peer * st.region_size + int(st.region_used[peer])
                if nrows:
                    st.device_pending.append((start // st.alignment, nrows, rows))
                    st.device_blocks[(self.map_id, reduce_id)] = rows
                st.blocks[(self.map_id, reduce_id)] = _BlockEntry(
                    offset=start, length=length, padded=padded, round=st.round
                )
                st.region_used[peer] += padded
        self._last_reduce = reduce_id

    def commit(self) -> MapperInfo:
        """Commit this map task's outputs — the ``commitAllPartitions`` packing
        (NvkvShuffleMapOutputWriter.scala:116-148).  Returns the MapperInfo blob
        object the transport ships as AM id 2.  For a retry attempt (discard
        mode) this returns the FIRST successful attempt's table."""
        if self._open_reduce is not None:
            raise TransportError("commit with open partition")
        st = self._state
        parts, rounds = [], []
        for r in range(st.num_reducers):
            e = st.blocks.get((self.map_id, r))
            parts.append((e.offset, e.length) if e is not None else (0, 0))
            rounds.append(e.round if e is not None else 0)
        with self._store._lock:
            st.committed_maps.add(self.map_id)
        return MapperInfo(
            st.shuffle_id, self.map_id, tuple(parts),
            tuple(rounds) if any(rounds) else None,
        )

    @property
    def is_retry_discard(self) -> bool:
        return self._discard


class _BlockRate:
    """One block's fetch-rate state (all fields guarded by the owning
    tracker's ``_lock``)."""

    __slots__ = ("ewma", "last_ns", "hot")

    def __init__(self, now_ns: int) -> None:
        self.ewma = 0.0  # fetches/sec EWMA of instantaneous 1/dt rates
        self.last_ns = now_ns
        self.hot = False


class BlockPopularity:
    """Per-block fetch-rate EWMAs driving the popularity-aware serving tier.

    The same EWMA shape as the transport's ``_PeerHealth`` latency tracker,
    pointed at demand instead of health: every served fetch folds its
    instantaneous rate (``1e9 / dt_ns`` since the block's previous fetch)
    into a per-block EWMA.  A block whose rate crosses
    ``serve.hotThresholdFetchesPerSec`` is *hot*; the serving plane reacts at
    shuffle granularity (replication pushes whole sealed rounds), so
    :meth:`observe` reports shuffle-level transitions — the first block of a
    shuffle to heat up promotes the shuffle, and the shuffle demotes only
    when :meth:`sweep` finds every one of its blocks cooled below HALF the
    threshold (hysteresis: the promote and demote edges never chatter on a
    rate hovering at the threshold).  Cooling is rate-decay aware: a block
    that simply stops being fetched demotes once ``1e9 / elapsed_ns`` falls
    under the demote edge, even though no new sample ever arrives.

    ``now_ns`` is injectable for deterministic tests.  ``_lock`` is a LEAF:
    no calls out while held (the lock-order pass pins this via
    LOCK_ATTR_CLASSES).
    """

    #: demote edge = threshold * _COOL_FRACTION (hysteresis band)
    _COOL_FRACTION = 0.5
    #: cold entries idle this long are forgotten (memory bound)
    _IDLE_GC_NS = 60 * 1_000_000_000

    def __init__(
        self,
        hot_threshold_per_sec: float,
        alpha: float = 0.25,
        now_ns: Optional[Callable[[], int]] = None,
    ) -> None:
        self.hot_threshold = float(hot_threshold_per_sec)
        self.alpha = float(alpha)
        self._now_ns = now_ns if now_ns is not None else time.monotonic_ns
        self._rates: Dict[Tuple[int, int, int], _BlockRate] = {}  #: guarded by self._lock
        self._hot_counts: Dict[int, int] = {}  #: shuffle -> hot-block count; guarded by self._lock
        self.stats: Dict[str, int] = {"promotions": 0, "demotions": 0}  #: guarded by self._lock
        self._last_sweep_ns = 0  #: guarded by self._lock
        self._lock = threading.Lock()  # LEAF: no calls out while held

    def observe(
        self, shuffle_id: int, map_id: int, reduce_id: int
    ) -> Tuple[bool, List[Tuple[int, bool]]]:
        """Fold one served fetch into the block's EWMA.  Returns
        ``(block_is_hot, [(shuffle_id, True)] when this fetch promoted the
        shuffle)`` — the serving plane widens the shuffle's replica set on
        that transition and admits the block to the serve cache while hot."""
        if self.hot_threshold <= 0:
            return False, []
        now = self._now_ns()
        key = (shuffle_id, map_id, reduce_id)
        with self._lock:
            r = self._rates.get(key)
            if r is None:
                self._rates[key] = _BlockRate(now)
                return False, []
            dt = max(now - r.last_ns, 1)
            r.last_ns = now
            r.ewma = self.alpha * (1e9 / dt) + (1.0 - self.alpha) * r.ewma
            transitions: List[Tuple[int, bool]] = []
            if not r.hot and r.ewma >= self.hot_threshold:
                r.hot = True
                self.stats["promotions"] += 1
                n = self._hot_counts.get(shuffle_id, 0)
                self._hot_counts[shuffle_id] = n + 1
                if n == 0:
                    transitions.append((shuffle_id, True))
            return r.hot, transitions

    def sweep(self, now_ns: Optional[int] = None) -> List[Tuple[int, bool]]:
        """Cool-down pass: demote hot blocks whose effective rate —
        ``min(ewma, 1e9 / elapsed_ns)``, so silence decays the rate — fell
        below the demote edge, and forget long-idle cold blocks.  Returns
        ``[(shuffle_id, False)]`` for every shuffle whose LAST hot block
        cooled (the serving plane drops the widened advertisement then)."""
        now = self._now_ns() if now_ns is None else now_ns
        cool_edge = self.hot_threshold * self._COOL_FRACTION
        transitions: List[Tuple[int, bool]] = []
        with self._lock:
            for key, r in list(self._rates.items()):
                elapsed = max(now - r.last_ns, 1)
                effective = min(r.ewma, 1e9 / elapsed)
                if r.hot:
                    if effective < cool_edge:
                        r.hot = False
                        r.ewma = effective
                        self.stats["demotions"] += 1
                        n = self._hot_counts.get(key[0], 1) - 1
                        if n <= 0:
                            self._hot_counts.pop(key[0], None)
                            transitions.append((key[0], False))
                        else:
                            self._hot_counts[key[0]] = n
                elif elapsed > self._IDLE_GC_NS:
                    del self._rates[key]
        return transitions

    def maybe_sweep(
        self, min_interval_ns: int = 1_000_000_000
    ) -> List[Tuple[int, bool]]:
        """Rate-limited :meth:`sweep`, safe to call on every served batch:
        at most one cool-down pass per ``min_interval_ns`` actually scans."""
        if self.hot_threshold <= 0:
            return []
        now = self._now_ns()
        with self._lock:
            if now - self._last_sweep_ns < min_interval_ns:
                return []
            self._last_sweep_ns = now
        return self.sweep(now)

    def is_hot(self, shuffle_id: int) -> bool:
        with self._lock:
            return self._hot_counts.get(shuffle_id, 0) > 0

    def hot_shuffles(self) -> List[int]:
        with self._lock:
            return sorted(self._hot_counts)

    def snapshot(self) -> Dict[str, int]:
        """Counter snapshot for MetricsRegistry export (``serve`` family)."""
        with self._lock:
            return {
                "promotions": self.stats["promotions"],
                "demotions": self.stats["demotions"],
                "tracked_blocks": len(self._rates),
                "hot_blocks": sum(self._hot_counts.values()),
                "hot_shuffles": len(self._hot_counts),
            }


class HbmBlockStore:
    """Per-executor staged shuffle store.  See module docstring."""

    def __init__(
        self, conf: Optional[TpuShuffleConf] = None, device=None, executor_id: int = 0
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.device = device
        self.executor_id = executor_id
        self._shuffles: Dict[int, _ShuffleState] = {}  #: guarded by self._lock
        # Commits that raced ahead of create_shuffle (a peer's MapperInfo can
        # arrive before this process registers the shuffle); applied at creation.
        self._pending_infos: Dict[int, List[MapperInfo]] = {}  #: guarded by self._lock
        self._lock = threading.RLock()
        # disk round tier accounting (conf.spill_to_disk).  The tempdir path
        # lives in a plain dict holder so the weakref.finalize below can purge
        # it when the store is dropped WITHOUT close() (GC / interpreter
        # exit) — the one path that used to leak sparkucx_tpu_spill_e* dirs.
        self._spill_holder: Dict[str, Optional[str]] = {"dir": None}  #: guarded by self._lock
        self._spill_finalizer = weakref.finalize(self, _purge_spill_dir, self._spill_holder)
        self._spill_bytes = 0  #: guarded by self._lock
        #: Optional TenantRegistry (service/tenants.py).  When set, shuffles
        #: created with an ``app_id`` are admission-checked: region
        #: allocations charge the tenant's HBM quota and over-quota writes
        #: raise TenantQuotaExceededError.  Written once at service wiring.
        self.tenants = None
        #: Optional EvictionManager hook (service/eviction.py): notified on
        #: every block access so disk-tier rounds restage transparently.
        #: Written once at service wiring.
        self.eviction = None
        #: Bounded serve-side decoded-block cache (popularity tier): hot
        #: blocks pinned ABOVE the eviction tiers, so demotion/restage churn
        #: never hits the hot set.  None when serve.cacheBytes is 0 (default)
        #: — the off path allocates nothing and touches no new locks.
        self.serve_cache: Optional[ServeCache] = (
            ServeCache(self.conf.serve_cache_bytes)
            if self.conf.serve_cache_bytes > 0
            else None
        )
        #: build_block_scatter compile cache keyed by pow2-bucketed geometry —
        #: the _gather_fn discipline (transport/tpu.py) applied to the write
        #: path, so varying-shape device rounds share a handful of compiles.
        self._scatter_cache: Dict[Tuple[int, int, int], object] = {}  #: guarded by self._lock
        # -- neighbor-replication tier (REPLICA_PUT landing zone) ----------
        #: (shuffle_id, src_executor) -> round -> ((map, reduce) -> (offset,
        #: length) index, contiguous body array).  Bodies are whole replicated
        #: rounds, so replica_view serves zero-copy like block_staging_view.
        self._replicas: Dict[Tuple[int, int], Dict[int, Tuple[Dict[Tuple[int, int], Tuple[int, int]], np.ndarray]]] = {}  #: guarded by self._lock
        self._replica_bytes = 0  #: guarded by self._lock
        #: Post-seal hook (PeerTransport installs its replication push here).
        #: Written once at transport construction, invoked by seal() AFTER the
        #: store lock is released — implementations may call back into the
        #: store freely.
        self.on_seal: Optional[Callable[[int], None]] = None
        # -- memory-pressure watermarks (gray-failure load shedding) -------
        #: out-of-band soft-watermark eviction sweeps kicked so far
        self._watermark_sweeps = 0  #: guarded by self._lock
        #: single-flight latch: at most one sweep thread runs at a time
        self._sweeping = False  #: guarded by self._lock

    @property
    def _spill_dir(self) -> Optional[str]:
        return self._spill_holder["dir"]

    @_spill_dir.setter
    def _spill_dir(self, value: Optional[str]) -> None:
        """Caller holds self._lock (both writers: _spill_round's lazy mkdtemp
        and _release_spill's last-shuffle rmdir)."""
        self._spill_holder["dir"] = value

    def _shm_staging(self, shuffle_id: int, nbytes: int):
        """Shared-memory staging for single-host zero-copy serving
        (conf.use_shm_staging — the NVKV shared-store analogue)."""
        from sparkucx_tpu import native

        name = f"/{self.conf.shm_namespace}_e{self.executor_id}_s{shuffle_id}"
        arena = native.SharedArena(name, nbytes, create=True)

        def closer(_arena=arena):
            _arena.close()
            _arena.unlink()

        return arena.array, closer

    # -- lifecycle ---------------------------------------------------------

    def create_shuffle(
        self,
        shuffle_id: int,
        num_mappers: int,
        num_reducers: int,
        peer_ranges: Optional[Sequence[Tuple[int, int]]] = None,
        capacity: Optional[int] = None,
        app_id: Optional[str] = None,
    ) -> None:
        if app_id is not None and self.tenants is not None:
            self.tenants.resolve(app_id)  # typed UnknownTenantError if not registered
        with self._lock:
            if shuffle_id in self._shuffles:
                raise TransportError(f"shuffle {shuffle_id} already exists")
            ranges = list(peer_ranges) if peer_ranges is not None else default_peer_ranges(num_reducers, 1)
            cap = capacity if capacity is not None else self.conf.staging_capacity_per_executor
            staging, closer = None, None
            if self.conf.use_shm_staging:
                n = len(ranges)
                region = (cap // n) // self.conf.block_alignment * self.conf.block_alignment
                staging, closer = self._shm_staging(shuffle_id, max(n * region, 1))
            self._shuffles[shuffle_id] = _ShuffleState(
                shuffle_id,
                num_mappers,
                num_reducers,
                ranges,
                cap,
                self.conf.block_alignment,
                staging=staging,
                staging_closer=closer,
            )
            self._shuffles[shuffle_id].app_id = app_id
            pending = self._pending_infos.pop(shuffle_id, [])
        for info in pending:
            self.apply_mapper_info(info)

    def remove_shuffle(self, shuffle_id: int) -> None:
        """unregisterShuffle analogue (UcxShuffleTransport.scala:249-259).
        The shm closer runs under the store lock so no reader holding the lock
        can see a staging mapping that is about to be munmapped."""
        with self._lock:
            st = self._shuffles.pop(shuffle_id, None)
            if st is not None and st.staging_closer is not None:
                st.staging = None
                st.staging_closer()
            if st is not None:
                self._release_spill(st)
                self._release_tenant(st, st.tenant_charged)
            for key in [k for k in self._replicas if k[0] == shuffle_id]:
                for _index, arr in self._replicas[key].values():
                    self._replica_bytes -= int(arr.size)
                del self._replicas[key]
        # Serve-cache entries of the removed shuffle are dropped WITHOUT a
        # per-entry quota release: the blanket _release_tenant above already
        # returned st.tenant_charged, which includes every cache charge.
        # Sequential lock scopes — the cache lock is a leaf, never nested
        # under self._lock.
        if self.serve_cache is not None:
            self.serve_cache.invalidate_shuffle(shuffle_id)
        if self.eviction is not None:
            # the LRU access table must not outlive the shuffle: recycled ids
            # (lineage-cache recomputes) would inherit stale recency
            self.eviction.forget_shuffle(shuffle_id)

    def close(self) -> None:
        with self._lock:
            states, self._shuffles = list(self._shuffles.values()), {}
            self._replicas.clear()
            self._replica_bytes = 0
            for st in states:
                if st.staging_closer is not None:
                    st.staging = None
                    st.staging_closer()
                self._release_spill(st)
                self._release_tenant(st, st.tenant_charged)
            # The mkdtemp'd spill dir is store-private, so close() may remove
            # it wholesale even when foreign files crept in (the rmdir in
            # _release_spill only handles the empty-dir case).
            _purge_spill_dir(self._spill_holder)
            self._spill_bytes = 0

    def _charge_tenant(self, st: _ShuffleState, nbytes: int) -> None:
        """Admission check at allocation time (caller holds self._lock): claim
        ``nbytes`` against the owning tenant's HBM quota.  Raises the typed
        TenantQuotaExceededError BEFORE any state mutation, so a rejected
        write leaves the store exactly as it was.  The charge is tracked in
        ``st.tenant_charged`` and released by ``_release_tenant`` on shuffle
        removal, store close, or tier demotion — ownership transfers to the
        shuffle state, not the calling frame."""
        if self.tenants is None or st.app_id is None or nbytes <= 0:
            return
        self.tenants.charge(st.app_id, st.shuffle_id, nbytes)
        st.tenant_charged += nbytes

    def _release_tenant(self, st: _ShuffleState, nbytes: int) -> None:
        """Return quota bytes (caller holds self._lock): shuffle removal,
        store close, or a round demoted off the HBM/host tiers."""
        if self.tenants is None or st.app_id is None or nbytes <= 0:
            return
        self.tenants.release(st.app_id, nbytes)
        st.tenant_charged = max(0, st.tenant_charged - nbytes)

    def _state(self, shuffle_id: int) -> _ShuffleState:
        with self._lock:
            st = self._shuffles.get(shuffle_id)
        if st is None:
            raise TransportError(f"unknown shuffle {shuffle_id}")
        return st

    # -- memory-pressure watermarks (gray-failure load shedding) ----------

    def _pressure_locked(self) -> int:
        """Host bytes this store is holding live (caller holds self._lock):
        every shuffle's staged bytes in RAM rounds plus the replica tier.
        Disk-tier (memmap) rounds are excluded — they are exactly the bytes
        the watermark machinery already shed."""
        total = self._replica_bytes
        for st in self._shuffles.values():
            total += int(st.region_used.sum())
            for snap, used in st.prev_rounds:
                if not isinstance(snap, np.memmap):
                    total += int(used.sum())
        return total

    def memory_pressure_bytes(self) -> int:
        with self._lock:
            return self._pressure_locked()

    def _check_pressure_locked(self, site: str, nbytes: int) -> bool:
        """Watermark gate body; caller holds ``self._lock``.  Raises the
        typed RETRYABLE ``ResourceExhaustedError`` past the hard watermark;
        returns True when the soft watermark is crossed — the caller MUST
        call ``_kick_watermark_sweep()`` AFTER releasing the lock (the kick
        takes the lock itself, and the sweep latch must never be reached
        through a held-lock path).  The ``store.mem_pressure`` fault point
        fires first either way, so chaos tests inject pressure without
        configuring watermarks."""
        faults.check("store.mem_pressure", site=site, nbytes=nbytes)
        soft = self.conf.store_soft_watermark
        hard = self.conf.store_hard_watermark
        if soft <= 0 and hard <= 0:
            return False
        pressure = self._pressure_locked()
        if hard > 0 and pressure + nbytes > hard:
            raise ResourceExhaustedError(
                requested=nbytes,
                used=pressure,
                watermark=hard,
                detail=f"store hard watermark at {site} (executor {self.executor_id})",
            )
        return soft > 0 and pressure + nbytes > soft

    def check_memory_pressure(self, site: str, nbytes: int = 0) -> None:
        """Gate an allocation-bearing mutation against the watermarks
        (``store.softWatermark`` / ``store.hardWatermark``); called BEFORE any
        state changes, so a shed write leaves the store exactly as it was.

        Soft watermark crossed: kick one out-of-band eviction sweep (demote
        one round a tier down) and admit the write.  Hard watermark crossed:
        raise the typed RETRYABLE ``ResourceExhaustedError`` — on the wire it
        becomes ``SIZE_RESOURCE_EXHAUSTED`` and clients back off and retry.
        Both knobs default 0 = off, the byte-identical store."""
        with self._lock:
            kick = self._check_pressure_locked(site, nbytes)
        if kick:
            self._kick_watermark_sweep()

    def _kick_watermark_sweep(self) -> None:
        """Single-flight out-of-band eviction sweep: demote ONE round a tier
        down (the EvictionManager's documented demotion order), off-thread so
        the writer that crossed the soft watermark never blocks on IO."""
        ev = self.eviction
        if ev is None:
            return
        with self._lock:
            if self._sweeping:
                return
            self._sweeping = True
            self._watermark_sweeps += 1

        def _sweep() -> None:
            try:
                ev.run_epoch(max_demotions=1)
            except Exception:
                pass  # shedding pressure is best-effort; the hard gate holds
            finally:
                with self._lock:
                    self._sweeping = False

        threading.Thread(
            target=_sweep, daemon=True, name=f"wm-sweep-e{self.executor_id}"
        ).start()

    def watermark_stats(self) -> Dict[str, int]:
        """Watermark telemetry for the metrics registry (eviction family)."""
        with self._lock:
            return {
                "watermark_sweeps": self._watermark_sweeps,
                "pressure_bytes": self._pressure_locked(),
            }

    def _rollover(self, st: _ShuffleState) -> None:
        """Snapshot the current staging epoch and start a fresh round (caller
        holds self._lock).

        With ``conf.spill_to_disk`` (default) the completed round moves to an
        ``np.memmap`` file and its RAM is released — the capacity-beyond-memory
        tier the reference gets from DPU-attached NVMe (NvkvHandler.scala:
        160-242); ``read_block``/``block_staging_view``/``seal`` serve spilled
        rounds through the memmap transparently.  With it off, the round stays
        as a RAM snapshot (bounded by host memory)."""
        snap = st.staging
        if self.conf.spill_to_disk:
            snap = self._spill_round(st, snap)
        st.prev_rounds.append((snap, st.region_used))
        st.staging = np.zeros_like(st.staging)
        st.region_used = np.zeros_like(st.region_used)
        st.round += 1

    def _rollover_device(self, st: _ShuffleState) -> None:
        """Device-round analogue of ``_rollover``: materialize the full round
        in HBM via the scatter kernel, pull it D2H ONCE as the round snapshot
        (the spill boundary is where a host copy is unavoidable — HBM cannot
        hold every round), and continue in a fresh device round (caller holds
        self._lock).  The lazy host staging buffer stays unallocated."""
        payload = self._materialize_device_round(st)
        snap = np.asarray(payload).reshape(-1).view(np.uint8)
        if self.conf.spill_to_disk:
            snap = self._spill_round(st, snap)
        st.prev_rounds.append((snap, st.region_used))
        st.region_used = np.zeros_like(st.region_used)
        st.device_pending = []
        st.device_blocks = {}
        st.round += 1

    def _spill_round(
        self,
        st: _ShuffleState,
        staging: np.ndarray,
        round_idx: Optional[int] = None,
        region_used: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Write one round's staging to the disk tier; returns the memmap that
        replaces the RAM snapshot (caller holds self._lock).

        The file is logically full-capacity (so block offsets are unchanged)
        but only each region's used prefix is written — the rest stays a sparse
        hole, so disk writes and the spillDiskCap budget are proportional to
        bytes actually staged, not to stagingCapacity.

        Defaults spill the LIVE round (rollover); the eviction manager passes
        ``round_idx``/``region_used`` to demote an already-completed round."""
        import os
        import tempfile

        if round_idx is None:
            round_idx = st.round
        if region_used is None:
            region_used = st.region_used
        if self._spill_dir is None:
            if self.conf.spill_dir is not None:
                os.makedirs(self.conf.spill_dir, exist_ok=True)
            self._spill_dir = tempfile.mkdtemp(
                prefix=f"sparkucx_tpu_spill_e{self.executor_id}_",
                dir=self.conf.spill_dir,
            )
        cap = self.conf.spill_disk_cap_bytes
        nbytes = int(region_used.sum())
        if cap and self._spill_bytes + nbytes > cap:
            raise TransportError(
                f"disk spill cap exceeded: {self._spill_bytes} B spilled + "
                f"{nbytes} B round > spillDiskCap {cap} B"
            )
        path = os.path.join(self._spill_dir, f"s{st.shuffle_id}_r{round_idx}.bin")
        mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=staging.shape)
        for p in range(len(st.peer_ranges)):
            used = int(region_used[p])
            if used:
                start = p * st.region_size
                mm[start : start + used] = staging[start : start + used]
        mm.flush()
        st.spill_files.append((path, nbytes))
        self._spill_bytes += nbytes
        return mm

    def _unspill_file(self, st: _ShuffleState, path: str) -> None:
        """Drop one spill file after its round restaged to RAM (caller holds
        self._lock): unlink, return its budget, forget the bookkeeping entry.
        A later re-demotion simply recreates the file."""
        import os

        for i, (p, nbytes) in enumerate(st.spill_files):
            if p == path:
                self._spill_bytes -= nbytes
                del st.spill_files[i]
                break
        try:
            os.unlink(path)
        except OSError:
            pass

    def _release_spill(self, st: _ShuffleState) -> None:
        """Unlink a removed shuffle's spill files (caller holds self._lock).

        The state object is deliberately NOT mutated: a reader that resolved
        the state before removal keeps serving correct bytes — open memmaps
        stay readable after unlink (the inode lives until the mapping drops),
        and GC reclaims everything once in-flight readers finish."""
        import os

        for path, nbytes in st.spill_files:
            self._spill_bytes -= nbytes
            try:
                os.unlink(path)
            except OSError:
                pass
        st.spill_files = []
        if self._spill_dir is not None and not any(
            s.spill_files for s in self._shuffles.values()
        ):
            try:
                os.rmdir(self._spill_dir)
            except OSError:
                pass  # non-empty (foreign files) or already gone
            else:
                self._spill_dir = None

    # -- device staging rounds (conf.device_staging) -----------------------

    def _scatter_fn(self, num_blocks: int, max_rows: int, out_rows: int):
        """Compiled block scatter for the staging geometry, pow2-bucketed on
        batch size and largest-block window so varying device rounds reuse a
        handful of compiles (the exchange's ``_gather_fn`` discipline).
        Returns ``(fn, bucketed_num_blocks)``; callers pad the plan arrays to
        the bucket with zero-count entries.  Caller holds ``self._lock``
        (its one call site is ``_materialize_device_round``)."""
        b = max(1 << max(num_blocks - 1, 0).bit_length(), 1)
        w = max(1 << max(max_rows - 1, 0).bit_length(), 1)
        key = (b, w, out_rows)
        fn = self._scatter_cache.get(key)
        if fn is None:
            from sparkucx_tpu.ops.pallas_kernels import build_block_scatter

            fn = build_block_scatter(b, out_rows, max_block_rows=w)
            self._scatter_cache[key] = fn
        return fn, b

    def _materialize_device_round(self, st: _ShuffleState):
        """Place the current device round's pending blocks into one
        HBM-resident slot-layout array via the block-scatter kernel (caller
        holds self._lock).  This is the zero-round-trip write path: the result
        is exactly the ``(total_rows, lane)`` payload ``seal`` would otherwise
        build on the host and ``device_put`` — but no host byte ever moves."""
        import jax
        import jax.numpy as jnp

        lane = st.alignment // 4
        total_rows = len(st.peer_ranges) * (st.region_size // st.alignment)
        dst = jnp.zeros((total_rows, lane), dtype=jnp.int32)
        if self.device is not None:
            dst = jax.device_put(dst, self.device)
        pending = st.device_pending
        if not pending:
            return dst
        starts = np.asarray([p[0] for p in pending], dtype=np.int32)
        counts = np.asarray([p[1] for p in pending], dtype=np.int32)
        outs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
        total = int(counts.sum())
        blocks = [p[2] for p in pending]
        packed = blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=0)
        fn, b = self._scatter_fn(len(pending), int(counts.max()), total_rows)
        if b > len(pending):
            pad = b - len(pending)
            starts = np.pad(starts, (0, pad))
            counts = np.pad(counts, (0, pad))
            outs = np.pad(outs, (0, pad), constant_values=total)
        # One (3, B) upload instead of three tiny H2D transfers (same trick as
        # the fetch path's plan upload, transport/tpu.py).
        plan = np.stack([starts, counts, outs])
        if self.device is not None:
            plan = jax.device_put(plan, self.device)
            packed = jax.device_put(packed, self.device)
        return fn(plan[0], plan[1], plan[2], packed, dst)

    # -- write path --------------------------------------------------------

    def map_writer(self, shuffle_id: int, map_id: int) -> MapWriter:
        st = self._state(shuffle_id)
        if st.sealed:
            raise TransportError(f"shuffle {shuffle_id} already sealed")
        if not (0 <= map_id < st.num_mappers):
            raise ValueError(f"map_id {map_id} out of range [0, {st.num_mappers})")
        with self._lock:
            discard = map_id in st.committed_maps  # first commit wins (task retry)
        return MapWriter(self, st, map_id, discard=discard)

    def apply_mapper_info(self, info: MapperInfo) -> None:
        """Install commit metadata received from a peer process (AM id 2 inbound —
        what the DPU daemon does with MapperInfo).  Commits for a shuffle this
        process hasn't created yet are queued and applied at creation."""
        with self._lock:
            if info.shuffle_id not in self._shuffles:
                self._pending_infos.setdefault(info.shuffle_id, []).append(info)
                return
        st = self._state(info.shuffle_id)
        with self._lock:
            for r, (off, ln) in enumerate(info.partitions):
                if ln:
                    padded = -(-ln // st.alignment) * st.alignment
                    st.blocks[(info.map_id, r)] = _BlockEntry(
                        off, ln, padded, info.round_of(r), local=False
                    )
            st.committed_maps.add(info.map_id)

    # -- seal + exchange hand-off -----------------------------------------

    def seal(self, shuffle_id: int):
        """Freeze the staging area and stage it into device HBM.

        Returns a list with one ``(payload, send_sizes)`` entry per staging
        round (a single entry in the common no-spill case) — payload is that
        round's slot-layout staging buffer shaped ``(total_rows, lane)`` int32
        where one row is ``alignment`` bytes (the exchange's wire unit; a
        ``jax.Array`` on ``self.device`` when set, else host ndarray);
        ``send_sizes[p]`` is the used row count of peer p's region (the round's
        exchange size-matrix row).

        The sealed payloads must stay valid until ``remove_shuffle``: the
        quota-capped exchange (ops/skew.py, conf.slot_quota_rows) slices chunk
        windows out of them across multiple pipelined sub-rounds, and the pull
        fallback reads blocks from them after the exchange.
        """
        st = self._state(shuffle_id)
        with self._lock:
            if st.sealed:
                raise TransportError(f"shuffle {shuffle_id} already sealed")
            lane = st.alignment // 4
            out = []
            # Staging (completed rounds) stays host-resident until
            # remove_shuffle — it is the shuffle's backing store, the same
            # retention contract as Spark's map-output files on disk.  HBM is
            # only committed one round at a time: the single-round common case
            # seals straight to device; multi-round payloads are uploaded
            # per-round by the exchange so device memory stays bounded by one
            # round.
            device_put_here = self.device is not None and not st.prev_rounds
            for staging, used in st.prev_rounds:
                payload = staging.view(np.int32).reshape(-1, lane)
                out.append((payload, (used // st.alignment).astype(np.int32)))
            final_sizes = (st.region_used // st.alignment).astype(np.int32)
            if st.device_mode:
                # Device write path: the final round seals as the HBM-resident
                # scatter output — zero device_put, zero host staging; the
                # per-block device arrays in device_blocks back read_block.
                payload = self._materialize_device_round(st)
            else:
                payload = st.staging.view(np.int32).reshape(-1, lane)
                if device_put_here:
                    import jax

                    payload = jax.device_put(payload, self.device)
            out.append((payload, final_sizes))
            st.sealed_payload = [p for p, _ in out]
        # Replication hook, outside the lock: the sealed rounds are now
        # immutable, so the background replicator can snapshot them safely.
        cb = self.on_seal
        if cb is not None:
            cb(shuffle_id)
        return out

    def num_rounds(self, shuffle_id: int) -> int:
        st = self._state(shuffle_id)
        return st.round + 1

    def region_slot_rows(self, shuffle_id: int) -> int:
        st = self._state(shuffle_id)
        return st.region_size // st.alignment

    def region_bytes(self, shuffle_id: int) -> int:
        """Per-peer region size in bytes — public form of the staging geometry
        the transports need for offset math (was reached via ``_state``)."""
        return self._state(shuffle_id).region_size

    def round_max_rows(self, shuffle_id: int) -> List[int]:
        """Per staging round, this executor's hottest destination region in
        rows (completed rollover rounds first, the live round last) — the
        local input to the skew planner (ops/skew.plan_exchange; the SPMD
        executor all-gathers these so every process derives one schedule)."""
        st = self._state(shuffle_id)
        with self._lock:
            maxes = [int(used.max()) // st.alignment for _, used in st.prev_rounds]
            maxes.append(int(st.region_used.max()) // st.alignment)
        return maxes

    def host_staging_allocated(self, shuffle_id: int) -> bool:
        """True when the host staging buffer exists for this shuffle.  The
        device write path's no-host-round-trip guarantee is observable here:
        it stays False for device-staged shuffles (rollover snapshots live in
        ``prev_rounds`` / the memmap spill tier, never in host staging)."""
        return self._state(shuffle_id).host_staging_allocated

    def committed_map_ids(self, shuffle_id: int) -> frozenset:
        """Snapshot of map ids with a successful commit (getPartitonOffset-table
        coverage, NvkvHandler.scala:258-265)."""
        st = self._state(shuffle_id)
        with self._lock:
            return frozenset(st.committed_maps)

    def mapper_info(self, shuffle_id: int, map_id: int) -> MapperInfo:
        """Reconstruct a committed map's MapperInfo from the offset table —
        what a peer's AM id 2 blob would carry (used by the SPMD executor when
        the commit landed in the store before the info arrived)."""
        st = self._state(shuffle_id)
        with self._lock:
            if map_id not in st.committed_maps:
                raise TransportError(f"map {map_id} not committed in shuffle {shuffle_id}")
            parts, rounds = [], []
            for r in range(st.num_reducers):
                e = st.blocks.get((map_id, r))
                parts.append((e.offset, e.length) if e is not None else (0, 0))
                rounds.append(e.round if e is not None else 0)
        return MapperInfo(
            shuffle_id, map_id, tuple(parts), tuple(rounds) if any(rounds) else None
        )

    # -- tiered eviction (service/eviction.py drives these) ----------------

    def _round_nbytes(self, st: _ShuffleState, round_idx: int) -> int:
        """Staged (padded) bytes of one round (caller holds self._lock)."""
        used = (
            st.prev_rounds[round_idx][1]
            if round_idx < len(st.prev_rounds)
            else st.region_used
        )
        return int(used.sum())

    def _tier_of(self, st: _ShuffleState, round_idx: int) -> str:
        """Which tier currently backs a round (caller holds self._lock):
        ``'hbm'`` (live device payload), ``'host'`` (RAM snapshot/staging),
        ``'disk'`` (np.memmap spill)."""
        if round_idx < len(st.prev_rounds):
            arr = st.prev_rounds[round_idx][0]
            return "disk" if isinstance(arr, np.memmap) else "host"
        if st.sealed:
            payload = st.sealed_payload[round_idx]
            if hasattr(payload, "is_deleted"):
                if not payload.is_deleted():
                    return "hbm"
            elif st._staging is None:
                # demoted device round: the snapshot in sealed_payload is the
                # only backing (device shuffles never allocate host staging)
                return "disk" if isinstance(payload, np.memmap) else "host"
        return "disk" if isinstance(st._staging, np.memmap) else "host"

    def round_tier(self, shuffle_id: int, round_idx: int) -> Optional[str]:
        """Public tier probe; None for unknown shuffles/rounds."""
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is None or not (0 <= round_idx <= st.round):
                return None
            return self._tier_of(st, round_idx)

    def round_bytes(self, shuffle_id: int, round_idx: int) -> int:
        """Staged bytes of one round — the footprint the eviction manager's
        restage plan orders by (arXiv:2112.01075)."""
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is None or not (0 <= round_idx <= st.round):
                return 0
            return self._round_nbytes(st, round_idx)

    def eviction_candidates(self) -> List[Tuple[int, int, str, int]]:
        """``(shuffle_id, round, tier, staged_bytes)`` for every SEALED round
        — the eviction manager's demotion/restage work list.  Unsealed
        shuffles are excluded: their rounds are still being written and their
        HBM payloads may be owned by an in-flight exchange."""
        out: List[Tuple[int, int, str, int]] = []
        with self._lock:
            for sid, st in self._shuffles.items():
                if not st.sealed:
                    continue
                for r in range(st.round + 1):
                    out.append((sid, r, self._tier_of(st, r), self._round_nbytes(st, r)))
        return out

    def demote_round(self, shuffle_id: int, round_idx: int) -> Optional[str]:
        """Move one sealed round ONE tier down: ``hbm -> host`` (drop the
        device payload, keep/snapshot the host bytes) or ``host -> disk``
        (``_spill_round`` memmap, RAM released, tenant quota bytes returned).
        Returns the transition performed, or None when nothing moved (unknown
        round, unsealed shuffle, already on disk, shm staging, or
        spill_to_disk off).  ``read_block``/``block_staging_view`` keep
        serving the round at every tier."""
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is None or not st.sealed or not (0 <= round_idx <= st.round):
                return None
            lane = st.alignment // 4
            tier = self._tier_of(st, round_idx)
            if tier == "hbm":
                payload = st.sealed_payload[round_idx]
                if st.device_mode:
                    # Device shuffles have no host staging: snapshot D2H once
                    # (the same boundary _rollover_device pays), THEN delete.
                    st.sealed_payload[round_idx] = np.asarray(payload)
                else:
                    st.sealed_payload[round_idx] = st.staging.view(np.int32).reshape(-1, lane)
                try:
                    payload.delete()
                except Exception:
                    pass  # already donated to an exchange
                return "hbm->host"
            if tier != "host" or not self.conf.spill_to_disk:
                return None
            if st.staging_closer is not None:
                return None  # shm staging is shared with other processes
            nbytes = self._round_nbytes(st, round_idx)
            if round_idx < len(st.prev_rounds):
                snap, used = st.prev_rounds[round_idx]
                mm = self._spill_round(st, snap, round_idx, used)
                st.prev_rounds[round_idx] = (mm, used)
                st.sealed_payload[round_idx] = mm.view(np.int32).reshape(-1, lane)
            elif st.device_mode:
                host = st.sealed_payload[round_idx]
                flat = np.asarray(host).reshape(-1).view(np.uint8)
                mm = self._spill_round(st, flat, round_idx, st.region_used)
                st.sealed_payload[round_idx] = mm.view(np.int32).reshape(-1, lane)
            else:
                snap = st.staging
                mm = self._spill_round(st, snap, round_idx, st.region_used)
                st.staging = mm
                st.sealed_payload[round_idx] = mm.view(np.int32).reshape(-1, lane)
            self._release_tenant(st, nbytes)
            return "host->disk"

    def restage_round(self, shuffle_id: int, round_idx: int) -> bool:
        """Promote one disk-tier round back to host RAM (restage-on-fetch).
        Re-charges the owning tenant's quota FIRST — an over-quota tenant
        gets the typed TenantQuotaExceededError and the round stays on disk,
        still serveable through the memmap.  The spill file is dropped once
        the RAM copy is installed (a later demotion recreates it)."""
        # span OUTSIDE the store lock: restage-on-fetch runs under a serve
        # thread's remote trace context, so the restage shows up as a child
        # of the reducer's window in the merged trace
        kick = False
        try:
            with span("store.restage", shuffle_id=shuffle_id, round=round_idx), self._lock:
                st = self._shuffles.get(shuffle_id)
                if st is None or not (0 <= round_idx <= st.round):
                    return False
                if self._tier_of(st, round_idx) != "disk":
                    return False
                lane = st.alignment // 4
                # watermark gate BEFORE the quota charge: a pressured store
                # must not admit the very bytes its sweep is trying to shed.
                # The soft-watermark kick is deferred past the lock release
                # (try/finally) — the sweep latch is never reached through a
                # held-lock path.
                kick = self._check_pressure_locked(
                    "restage_round", self._round_nbytes(st, round_idx)
                )
                self._charge_tenant(st, self._round_nbytes(st, round_idx))  #: balanced by _release_tenant
                if round_idx < len(st.prev_rounds):
                    mm, used = st.prev_rounds[round_idx]
                    arr = np.array(mm)
                    st.prev_rounds[round_idx] = (arr, used)
                    if st.sealed:
                        st.sealed_payload[round_idx] = arr.view(np.int32).reshape(-1, lane)
                elif st.device_mode:
                    mm = st.sealed_payload[round_idx]
                    arr = np.array(mm)
                    st.sealed_payload[round_idx] = arr
                else:
                    mm = st.staging
                    arr = np.array(mm)
                    st.staging = arr
                    if st.sealed:
                        st.sealed_payload[round_idx] = arr.view(np.int32).reshape(-1, lane)
                path = getattr(mm, "filename", None)
                if path:
                    self._unspill_file(st, str(path))
                return True
        finally:
            if kick:
                self._kick_watermark_sweep()

    # -- read path (serve staged blocks) ----------------------------------

    def read_block(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        """Direct block read — HBM after seal, host staging before
        (the two arms of UcxShuffleBlockResolver.getBlockData,
        compat/spark_3_0/UcxShuffleBlockResolver.scala:86-97).

        The exchange collective *donates* sealed device payloads (the aliasing
        that halves peak HBM), so post-exchange the HBM copy may be deleted;
        the host staging area is retained until ``remove_shuffle`` exactly so
        this read — the pull-fallback/retry path — keeps working."""
        with self._lock:
            st = self._shuffles.get(shuffle_id)
        e = st.blocks.get((map_id, reduce_id)) if st is not None else None
        if e is None:
            # Replica tier: a ring neighbor's pushed copy serves even for a
            # shuffle this executor never created locally (failover serving).
            replica = self.replica_view(shuffle_id, map_id, reduce_id)
            if replica is not None:
                with span(
                    "store.read.replica",
                    shuffle_id=shuffle_id, map_id=map_id, reduce_id=reduce_id,
                ):
                    arr, off, ln = replica
                    return arr[off : off + ln].tobytes()
            if st is None:
                raise TransportError(f"unknown shuffle {shuffle_id}")
            raise BlockNotFoundError(shuffle_id, map_id, reduce_id, "not staged")
        if e.length == 0:
            return b""
        # Eviction hook (no lock held): bumps the round's LRU clock and
        # transparently restages a disk-tier round to RAM before we serve.
        ev = self.eviction
        if ev is not None:
            ev.on_access(shuffle_id, e.round)
        if st.sealed:
            payload = st.sealed_payload[e.round]
            if not (hasattr(payload, "is_deleted") and payload.is_deleted()):
                flat = np.asarray(payload).reshape(-1).view(np.uint8)
                return flat[e.offset : e.offset + e.length].tobytes()
        # Lock: (prev_rounds, staging) must be read atomically vs _rollover,
        # and the bytes copy must complete before a concurrent remove_shuffle
        # can munmap shm staging (the closer also runs under this lock).
        with self._lock:
            if e.round < len(st.prev_rounds):
                staging = st.prev_rounds[e.round][0]
            elif st.device_mode:
                # Current device round: serve straight from the per-block
                # device array (one tiny D2H) — there is no host staging.
                rows = st.device_blocks.get((map_id, reduce_id))
                if rows is None:
                    raise TransportError(
                        f"device block ({shuffle_id},{map_id},{reduce_id}) no longer resident"
                    )
                flat = np.asarray(rows).reshape(-1).view(np.uint8)
                return flat[: e.length].tobytes()
            else:
                staging = st.staging
            if staging is None:
                raise TransportError(f"shuffle {shuffle_id} staging already released")
            return staging[e.offset : e.offset + e.length].tobytes()

    def block_staging_view(
        self, shuffle_id: int, map_id: int, reduce_id: int
    ) -> Optional[Tuple[np.ndarray, int, int]]:
        """Zero-copy serving handle: (host staging uint8 array, offset, length)
        for a staged block, or None when unknown.  Staging is append-only and
        retained until ``remove_shuffle`` (it is the shuffle's backing store),
        so the view stays valid for the shuffle's lifetime even after the seal
        donated the device copy — this is what the batch reply's native gather
        (``ts_batch_copy``) reads from."""
        st = self._state(shuffle_id)
        e = st.blocks.get((map_id, reduce_id))
        if e is None:
            return None
        ev = self.eviction
        if ev is not None:
            ev.on_access(shuffle_id, e.round)
        with self._lock:
            if e.round >= len(st.prev_rounds) and st.device_mode:
                rows = st.device_blocks.get((map_id, reduce_id))
                if rows is None:
                    return None
                # Current device round: hand out a private host copy of the
                # block (the device array can be superseded by a rollover).
                flat = np.array(np.asarray(rows).reshape(-1).view(np.uint8)[: e.length])
                return flat, 0, e.length
            staging = (
                st.prev_rounds[e.round][0] if e.round < len(st.prev_rounds) else st.staging
            )
            if staging is None:
                return None
            if st.staging_closer is not None:
                # shm-backed staging can be munmapped by remove_shuffle at any
                # time after we release the lock — hand out a private copy, not
                # a view into the mapping (private ndarray staging is safe: a
                # rollover replaces the reference, never the array contents).
                return np.array(staging[e.offset : e.offset + e.length]), 0, e.length
        return staging, e.offset, e.length

    # -- serve-side decoded-block cache (popularity tier) -----------------

    def serve_cache_get(
        self, shuffle_id: int, map_id: int, reduce_id: int
    ) -> Optional[Tuple[np.ndarray, int, int]]:
        """Serving handle from the hot-block cache, shaped like
        ``block_staging_view`` — ``(uint8 array, offset, length)`` — or None
        on miss/disabled.  A hit bypasses the eviction tiers entirely: no
        ``on_access`` bump, no restage, no store lock."""
        cache = self.serve_cache
        if cache is None:
            return None
        data = cache.get((shuffle_id, map_id, reduce_id))
        if data is None:
            return None
        return np.frombuffer(data, dtype=np.uint8), 0, len(data)

    def serve_cache_offer(
        self, shuffle_id: int, map_id: int, reduce_id: int, data: bytes
    ) -> bool:
        """Pin one hot decoded block in the serve cache, charging its bytes
        against the owning tenant's quota (``#: balanced by _release_tenant``
        — released when LRU pressure or shuffle removal drops the entry).
        Returns False when the cache is off, the block outsizes the whole
        budget, or the tenant has no quota headroom — the fetch still serves
        from the normal tiers, the block just isn't pinned.

        Lock discipline: three SEQUENTIAL scopes (charge under the store
        lock, insert under the cache's leaf lock, release evictees under the
        store lock again) — the two locks never nest."""
        cache = self.serve_cache
        if cache is None or not data or len(data) > cache.capacity_bytes:
            return False
        key = (shuffle_id, map_id, reduce_id)
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is not None:
                try:
                    self._charge_tenant(st, len(data))  #: balanced by _release_tenant
                except TenantQuotaExceededError:
                    return False
        evicted = cache.put(key, data)
        if evicted:
            with self._lock:
                for (sid, _m, _r), nbytes in evicted:
                    est = self._shuffles.get(sid)
                    if est is not None:
                        self._release_tenant(est, nbytes)
        return True

    def block_length(self, shuffle_id: int, map_id: int, reduce_id: int) -> int:
        """getPartitonLength analogue (NvkvHandler.scala:258-265)."""
        e = self._state(shuffle_id).blocks.get((map_id, reduce_id))
        return e.length if e is not None else 0

    def block_offset(self, shuffle_id: int, map_id: int, reduce_id: int) -> int:
        """getPartitonOffset analogue."""
        e = self._state(shuffle_id).blocks.get((map_id, reduce_id))
        if e is None:
            raise TransportError(f"no block ({shuffle_id},{map_id},{reduce_id}) staged")
        return e.offset

    # -- neighbor-replication tier (REPLICA_PUT/failover serving) ----------

    def replica_source(self, shuffle_id: int) -> List[Tuple[int, List[Tuple[int, int, int]], bytes]]:
        """Snapshot this executor's sealed rounds for replication: one
        ``(round, [(map, reduce, length)...], body bytes)`` per staging round,
        body = the unpadded block payloads concatenated in table order.  Only
        locally staged entries are included — entries installed from peers'
        MapperInfo carry sender-relative offsets and no local bytes."""
        st = self._state(shuffle_id)
        out: List[Tuple[int, List[Tuple[int, int, int]], bytes]] = []
        with self._lock:
            for rnd in range(st.round + 1):
                keys = sorted(
                    k for k, e in st.blocks.items() if e.round == rnd and e.local
                )
                entries: List[Tuple[int, int, int]] = []
                body = bytearray()
                for m, r in keys:
                    e = st.blocks[(m, r)]
                    entries.append((m, r, e.length))
                    if not e.length:
                        continue
                    if rnd < len(st.prev_rounds):
                        staging = st.prev_rounds[rnd][0]
                        body += staging[e.offset : e.offset + e.length].tobytes()
                    elif st.device_mode:
                        rows = st.device_blocks.get((m, r))
                        if rows is None:
                            raise TransportError(
                                f"device block ({shuffle_id},{m},{r}) no longer "
                                "resident — cannot replicate"
                            )
                        flat = np.asarray(rows).reshape(-1).view(np.uint8)
                        body += flat[: e.length].tobytes()
                    else:
                        body += st.staging[e.offset : e.offset + e.length].tobytes()
                if entries:
                    out.append((rnd, entries, bytes(body)))
        return out

    def put_replica(
        self,
        shuffle_id: int,
        src_executor: int,
        round_idx: int,
        entries: Sequence[Tuple[int, int, int]],
        body,
    ) -> None:
        """Install one replicated round pushed by a ring neighbor.  ``body``
        is the concatenated unpadded payloads in ``entries`` order; a repeated
        put for the same (shuffle, src, round) replaces the old copy (the
        replicator may re-push after a transient failure)."""
        # a pressured receiver sheds replica installs (best-effort durability:
        # the pushing neighbor accounts it as a failed push and moves on)
        self.check_memory_pressure("put_replica", len(body))
        index: Dict[Tuple[int, int], Tuple[int, int]] = {}
        pos = 0
        for m, r, ln in entries:
            index[(m, r)] = (pos, ln)
            pos += ln
        if pos != len(body):
            raise TransportError(
                f"replica round (shuffle={shuffle_id}, src={src_executor}, "
                f"round={round_idx}) table claims {pos} B but body is {len(body)} B"
            )
        # bytes bodies wrap zero-copy (np.frombuffer over bytes never copies);
        # a decoded bytearray from the compressed replica path (transport/
        # peer.py) also wraps directly — the receiver hands ownership over, so
        # the historical defensive bytes() copy only remains for exotic
        # bytes-likes (non-contiguous memoryviews)
        if not len(body):
            arr = np.empty(0, dtype=np.uint8)
        elif isinstance(body, (bytes, bytearray)):
            arr = np.frombuffer(body, dtype=np.uint8)
        else:
            arr = np.frombuffer(bytes(body), dtype=np.uint8)
        with self._lock:
            rounds = self._replicas.setdefault((shuffle_id, src_executor), {})
            old = rounds.get(round_idx)
            if old is not None:
                self._replica_bytes -= int(old[1].size)
            rounds[round_idx] = (index, arr)
            self._replica_bytes += int(arr.size)

    def replica_view(
        self, shuffle_id: int, map_id: int, reduce_id: int
    ) -> Optional[Tuple[np.ndarray, int, int]]:
        """Zero-copy serving handle into a replicated round — the failover
        analogue of ``block_staging_view``.  None when no replica of the block
        has landed (including: replication disabled, or still in flight)."""
        with self._lock:
            for (sid, _src), rounds in self._replicas.items():
                if sid != shuffle_id:
                    continue
                for index, arr in rounds.values():
                    hit = index.get((map_id, reduce_id))
                    if hit is not None:
                        return arr, hit[0], hit[1]
        return None

    def replica_block(
        self, shuffle_id: int, src_executor: int, map_id: int, reduce_id: int
    ) -> Optional[bytes]:
        """The replicated bytes of one block FROM A NAMED SOURCE executor —
        the restage path's accessor (elastic recovery rebuilds a dead
        executor's staging from its ring-successor's replica tier, and must
        not accidentally serve a same-keyed block replicated from a different
        source).  None when no replica of (src, block) landed here."""
        with self._lock:
            rounds = self._replicas.get((shuffle_id, src_executor))
            if not rounds:
                return None
            for index, arr in rounds.values():
                hit = index.get((map_id, reduce_id))
                if hit is not None:
                    return arr[hit[0] : hit[0] + hit[1]].tobytes()
        return None

    def replica_stats(self) -> Dict[str, int]:
        """Replica-tier accounting across all shuffles."""
        with self._lock:
            return {
                "replica_bytes": self._replica_bytes,
                "replica_rounds": sum(len(r) for r in self._replicas.values()),
                "replica_sources": len(self._replicas),
            }

    # -- introspection -----------------------------------------------------

    def stats(self, shuffle_id: int) -> Dict[str, object]:
        st = self._state(shuffle_id)
        # per staging round (rollovers then the live round), (used, padded)
        # rows of the slot layout — the store-side view of the imbalance the
        # skew planner (conf.slot_quota_rows) caps.  Computed inline: _lock is
        # a plain (non-reentrant) Lock, so this must not call the locked
        # round_max_rows helper.
        slot_rows = st.region_size // st.alignment
        occupancy = []
        for _, used in st.prev_rounds:
            u = int(used.sum()) // st.alignment
            occupancy.append((u, int(used.size) * slot_rows - u))
        u = int(st.region_used.sum()) // st.alignment
        occupancy.append((u, int(st.region_used.size) * slot_rows - u))
        with self._lock:
            replica_bytes = sum(
                int(arr.size)
                for (sid, _src), rounds in self._replicas.items()
                if sid == shuffle_id
                for _index, arr in rounds.values()
            )
        return {
            "replica_bytes": replica_bytes,
            "num_blocks": len(st.blocks),
            "bytes_staged": int(sum(e.length for e in st.blocks.values())),
            "bytes_padded": int(sum(e.padded for e in st.blocks.values())),
            "region_used": st.region_used.tolist(),
            "region_size": st.region_size,
            "round_occupancy": occupancy,
            "committed_maps": sorted(st.committed_maps),
            "sealed": st.sealed,
            "device_mode": st.device_mode,
            "host_staging_allocated": st.host_staging_allocated,
        }
