"""sparkucx_tpu — a TPU-native shuffle framework.

A brand-new framework with the capabilities of SparkUCX (a Spark ``ShuffleManager``
plugin that replaces TCP shuffle with UCX/RDMA and, in the reference fork, offloads
block storage/serving to a BlueField DPU over NVKV).  Instead of UCX active messages
over RDMA, this framework targets TPU interconnects:

* map-side shuffle blocks are staged into TPU **HBM** (the NVKV/DPU-NVMe analogue),
* the reduce-side batch fetch lowers to a JAX **ragged all_to_all** over the ICI mesh
  (DCN across slices) instead of UCP get/tag-recv,
* the registered-bounce-buffer memory pool is rebuilt over pinned host /
  ``jax.device_put``-backed arrays,
* executor bootstrap discovers the TPU slice topology and builds the
  executor<->chip mapping.

Layer map (mirrors SURVEY.md section 1; reference file:line cites in each module):

====  =====================================  =========================================
L7    shuffle/manager.py                     plugin boundary (ShuffleManager SPI)
L6    shuffle/manager.py (common base)       transport lifecycle + bootstrap kick-off
L5    shuffle/reader.py                      reduce-side read path
L4    shuffle/writer.py, shuffle/resolver.py map-side write path + block resolver
L3    core/transport.py, transport/*         transport trait + loopback/TPU/peer impls
L2    parallel/bootstrap.py, parallel/mesh.py control plane, topology discovery
L1    memory/pool.py                         registered/staged memory pool
L0    config.py, core/*, utils/*             contracts, config, low-level utils
====  =====================================  =========================================
"""

from sparkucx_tpu.version import __version__

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import (
    Block,
    BlockId,
    MemoryBlock,
    ShuffleBlockId,
)
from sparkucx_tpu.core.operation import (
    OperationCallback,
    OperationResult,
    OperationStats,
    OperationStatus,
    Request,
    TransportError,
)
from sparkucx_tpu.core.transport import ShuffleTransport

__all__ = [
    "__version__",
    "TpuShuffleConf",
    "Block",
    "BlockId",
    "MemoryBlock",
    "ShuffleBlockId",
    "OperationCallback",
    "OperationResult",
    "OperationStats",
    "OperationStatus",
    "Request",
    "TransportError",
    "ShuffleTransport",
]
