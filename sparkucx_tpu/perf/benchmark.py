"""Standalone transport benchmark CLI — the ``UcxPerfBenchmark`` analogue.

Counterpart of ``shuffle/ucx/perf/UcxPerfBenchmark.scala`` (221 LoC): a
no-Spark-required driver for the transport layers.  Same CLI shape
(UcxPerfBenchmark.scala:41-59):

========  ==========================================  =====================
flag      reference meaning                            here
========  ==========================================  =====================
-a        server socket address                        same (host:port)
-f        file to serve blocks from                    same (optional)
-n        number of blocks                             same
-s        block size                                   same (byte suffixes ok)
-i        iterations                                   same
-o        outstanding requests per batch               same
-r        requests in flight / reuse address           iterations per print
-t        client threads                               same
========  ==========================================  =====================

Modes:

* ``server`` — register -n blocks of -s bytes (file-backed when -f is given,
  synthetic otherwise) on a PeerTransport BlockServer and wait
  (UcxPerfBenchmark.scala:156-208).
* ``client`` — connect, issue -o-deep batches of ``fetch_blocks_by_block_ids``
  across -t threads, spin ``progress()``, print per-batch bandwidth
  (UcxPerfBenchmark.scala:100-154, bandwidth print :140-143).
* ``wire`` — loopback peer-fetch throughput at several ``wire.streams`` lane
  counts (the striped zero-copy wire path): one in-process BlockServer, one
  client per streams value fetching -n blocks of -s bytes per iteration.
  Prints GB/s, receive syscalls/MB, and p99 frame stall per streams value;
  ``--streams 1`` is the byte-identical pre-striping wire, so it doubles as
  the before/after baseline.
* ``compress`` — tier-(a)/(b) payload reduction, ratio x GB/s: loopback fetch
  throughput at codec in {off, dict, rle, delta} on a dictionary-heavy
  (clustered low-cardinality u32 keys) and an incompressible matrix, with
  bit-equality asserted on EVERY lossless pass and compression ratio /
  encoded-chunk-pool hits from the server's ``compress_stats``; an
  end-to-end ``TpuShuffleReader`` pass per codec (credit gate budgets
  decoded bytes); and, when >= 2 devices are up, the quantized-vs-f32 ICI
  exchange (int8 / blockfloat) with the dequant error bound asserted.
* ``failover`` — executor-loss robustness under traffic: a 3-executor
  loopback cluster with ``replication.factor = 1`` (seal pushes every round
  to the ring neighbor), a reducer streaming -n blocks of -s bytes from the
  primary.  Steady-state fetch GB/s first, then one pass where the primary
  is killed at t=50% (testing/faults.kill_executor) and the reader fails
  over to the replica holder.  Prints both GB/s, the recovery time (kill ->
  first replica-served block), failovers, and p99 frame stall.
* ``gray`` — gray-failure robustness under traffic: the ``failover`` cluster
  shape, but the primary is THROTTLED to ~10% of the measured healthy rate
  (every served frame stalls) instead of killed — the degraded-but-alive
  peer that trips no deadline.  Measures GB/s + p99 frame stall healthy,
  throttled with hedging off, and throttled with ``fetch.hedgeMs`` on
  (hedges rescue straggling blocks from the replica holder); one unclocked
  hedged pass asserts every block bit-identical to the staged payload.
* ``tenants`` — multi-tenant serving plane under concurrent fan-in: one
  tenants-enabled loopback server (the shared-selector reactor plane,
  service/reactor.py) stages -n blocks of -s bytes per registered app;
  ``--apps`` synthetic applications then stream their own set back
  CONCURRENTLY, each through its own client transport carrying its app_id
  as the FETCH_BLOCK_REQ extension (tenant-local shuffle ids, server-side
  TenantRegistry translation).  Prints aggregate GB/s, per-app GB/s, the
  min/max per-app fairness ratio, and p50/p99 per-block fetch latency.
* ``fanin`` — popularity-aware serving under N-reducer fan-in on ONE hot
  block: per replica-set width (1/2/4 holders), a fresh loopback cluster of
  single-worker servers with a fixed per-FETCH_BLOCK_REQ service stall (the
  deterministic single-server ceiling); a bootstrap storm promotes the block
  (``serve.hotThresholdFetchesPerSec``), the primary advertises every holder
  over HOT_SET_PULL, and -t (default 8) concurrent readers rotate their
  fetches across the set.  Prints aggregate GB/s + pooled p99 per-fetch
  latency per width and the width-4/width-1 speedup; off the clock the block
  is asserted bit-identical from EVERY holder.
* ``elastic`` — degraded-mode exchange recovery under chaos: an
  ``--executors``-wide loopback cluster with ``elastic.enabled`` and
  ``replication.factor = 1`` runs multi-round shuffles of -s-byte blocks.
  Steady-state full-mesh exchange GB/s first, then one pass where an
  executor is killed MID-SUPERSTEP — the cluster shrinks to the surviving
  pow2 bucket, restages the dead executor's rounds from ring-successor
  replicas, and re-runs in degraded waves (output asserted byte-identical).
  Prints both GB/s, the recovery time, and the shrunk mesh shape.
* ``superstep`` — the TPU-only mode with no reference counterpart: time the
  collective exchange on the local mesh (what bench.py wraps).
* ``pipeline`` — multi-round (spilled) shuffle throughput with host staging in
  the loop, at pipeline depths 1/2/3 (transport/pipeline.py): -n rounds of -s
  bytes each through H2D -> collective -> D2H; depth 1 is the serial engine,
  deeper rings overlap the three stages.  Prints GB/s per depth.
* ``gather`` — time the device-side ragged block gather (ops/pallas_kernels.py),
  the reply-packing hot path (UcxWorkerWrapper.scala:397-448 analogue): -n
  blocks of -s bytes scattered through a source buffer, packed into one HBM
  buffer.  ``--impl`` selects the lowering (dma | tiled | xla | auto).
* ``sort`` — time the device-resident TeraSort step (ops/sort.py): -n rows of
  100 B (uint32 key + 24 int32 lanes) through sample-sort over ``--executors``
  devices; prints M rows/s.  The on-device analogue of the reference harness's
  TeraSort workload (BASELINE.json configs[1]).  ``--batches B`` > 1 instead
  drives the out-of-core driver (run_external_sort): the -n rows pass through
  B device batches and a stable host merge — the "TeraSort 10GB on one chip"
  path; expect host-merge-bound numbers.
* ``columnar`` — time the device-resident columnar shuffle (ops/columnar.py,
  the GpuColumnarExchange analogue; BASELINE.json columnar config): -n rows of
  -s bytes repartitioned in HBM by a random owner vector; prints GB/s.
* ``groupby`` — time the device-resident GROUP BY (ops/relational.py): -n rows
  of 100 B (uint32 key from ``--keys`` distinct values + 24 summed int32
  lanes) through hash exchange + segment reduction over ``--executors``
  devices; prints M rows/s.  The on-device analogue of the workload the
  reference gates on — ``GroupByTest`` generates random (key, value) pairs and
  groups them by key (buildlib/test.sh:163-173, BASELINE.json configs[0]).
* ``ici`` — the FAST-scheduled ring exchange (ops/ici_exchange.py) vs the
  stock collective at mesh widths 2/4/8 (``--executors N`` pins one width):
  aggregate and per-directed-link GB/s for both impls, superstep/occupancy
  telemetry (utils/stats.py), bit-equality asserted, plus the fused
  scatter+exchange single-launch check.  ``--chunks`` sets the FAST
  per-destination interleave depth.
* ``join`` — time the device-resident hash join (ops/relational.py): a PK-FK
  inner join in the TPC-H shape (BASELINE.json configs[2]) — ``--build-rows``
  dimension rows (unique keys, 8 int32 lanes) probed by -n fact rows (16
  lanes), both sides hash-exchanged then matched; prints M probe rows/s.
* ``combine`` — the receive-side fused-combine exchange
  (ops/ici_exchange.build_combine_exchange) vs the unfused reference
  (scheduled exchange, then a separate fold over the landed grid): partial
  aggregate rows with ``--keys`` distinct groups, -s bytes per peer slot,
  over ``--executors`` devices.  Asserts the fused accumulator bit-identical
  to the reference fold off the clock and prints the drain-bytes collapse
  (O(rows) landed grid vs O(groups) accumulator) plus the launch-count
  collapse (one fused kernel vs one dispatch per schedule item + the fold).
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import List

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf, parse_size
from sparkucx_tpu.core.block import BytesBlock, FileBackedBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.peer import PeerTransport


def _parse_args(argv):
    p = argparse.ArgumentParser(prog="sparkucx-tpu-perf", description=__doc__.split("\n")[0])
    p.add_argument(
        "mode",
        choices=[
            "server", "client", "superstep", "pipeline", "gather", "sort",
            "columnar", "groupby", "join", "write", "skew", "adaptive", "wire",
            "ici", "combine", "failover", "elastic", "compress", "tenants",
            "obs", "gray", "fanin", "queries",
        ],
    )
    p.add_argument("-a", "--address", default="127.0.0.1:13337", help="server host:port")
    p.add_argument("-f", "--file", default=None, help="file to serve blocks from (server)")
    p.add_argument("-n", "--num-blocks", type=int, default=8)
    p.add_argument("-s", "--block-size", default="4m")
    p.add_argument("-i", "--iterations", type=int, default=5)
    p.add_argument("-o", "--outstanding", type=int, default=8)
    p.add_argument("-r", "--reports", type=int, default=1, help="batches per bandwidth print")
    p.add_argument("-t", "--threads", type=int, default=1)
    p.add_argument("--executors", type=int, default=1, help="mesh size (superstep mode)")
    p.add_argument(
        "--slices", type=int, default=1,
        help="factor the superstep mesh into this many slices (two-phase ICI+DCN route)",
    )
    p.add_argument(
        "--impl", default="auto",
        help="block-gather lowering: auto|dma|tiled|xla (gather mode), or a "
        "comma list of staging paths to compare: host,device (write mode)",
    )
    p.add_argument(
        "--keys", type=int, default=100,
        help="distinct group keys (groupby mode; GroupByTest's numKVPairs keyspace)",
    )
    p.add_argument(
        "--build-rows", type=int, default=0,
        help="dimension-side rows (join mode); 0 means -n // 4",
    )
    p.add_argument(
        "--partial", action="store_true",
        help="map-side partial aggregation below the exchange (groupby mode; "
        "conf spark.shuffle.tpu.partialAggregation)",
    )
    p.add_argument(
        "--join-type", default="inner",
        choices=["inner", "left_outer", "left_semi", "left_anti",
                 "right_outer", "full_outer"],
        help="join arm to benchmark (join mode); half the probe keys miss so "
        "every arm's matched AND unmatched branches do real work",
    )
    p.add_argument(
        "--sort-impl", default="auto",
        choices=["auto", "single", "radix", "ragged", "dense"],
        help="sort lowering (sort mode); 'radix' = the Pallas LSD radix "
        "kernel with fused key+payload segment-DMA scatter (n=1 only)",
    )
    p.add_argument(
        "--batches", type=int, default=1,
        help="device batches for the out-of-core sort driver (sort mode)",
    )
    p.add_argument(
        "--depths", default="1,2,3",
        help="comma-separated pipeline depths to compare (pipeline mode)",
    )
    p.add_argument(
        "--streams", default="1,2,4",
        help="comma-separated wire.streams values to compare (wire mode)",
    )
    p.add_argument(
        "--chunk-bytes", default="4m",
        help="chunk frame size for striped lanes (wire mode; wire.chunkBytes)",
    )
    p.add_argument(
        "--zipf-alpha", type=float, default=1.2,
        help="Zipf exponent for the per-peer size distribution (skew mode)",
    )
    p.add_argument(
        "--quota", type=int, default=0,
        help="slot quota in rows (skew mode); 0 picks the pow2 ceiling of the "
        "mean lane size automatically",
    )
    p.add_argument(
        "--chunks", type=int, default=0,
        help="FAST chunks per destination (ici mode); 0 picks the default "
        "interleave depth (ops/ici_exchange.py DEFAULT_CHUNKS_PER_DEST)",
    )
    p.add_argument(
        "--apps", type=int, default=8,
        help="concurrent synthetic applications (tenants mode)",
    )
    return p.parse_args(argv)


def run_server(args) -> None:
    host, _, port = args.address.rpartition(":")
    size = parse_size(args.block_size)
    conf = TpuShuffleConf(listener_address=(host or "127.0.0.1", int(port)))
    transport = PeerTransport(conf, executor_id=0)
    addr = transport.init()
    rng = np.random.default_rng(0)
    for i in range(args.num_blocks):
        if args.file:
            block = FileBackedBlock(args.file, offset=(i * size), length=size)
        else:
            block = BytesBlock(rng.integers(0, 256, size=size, dtype=np.uint8))
        transport.register(ShuffleBlockId(0, 0, i), block)
    print(f"serving {args.num_blocks} x {size} B blocks on {addr.decode()}", flush=True)
    try:
        while True:
            time.sleep(1)  # server threads do the work (UcxPerfBenchmark.scala:204-207)
    except KeyboardInterrupt:
        transport.close()


def run_client(args) -> None:
    host, _, port = args.address.rpartition(":")
    size = parse_size(args.block_size)
    conf = TpuShuffleConf(max_blocks_per_request=max(args.outstanding, 1))
    results_lock = threading.Lock()
    printed: List[str] = []

    def worker(tid: int) -> None:
        transport = PeerTransport(conf, executor_id=100 + tid)
        transport.add_executor(0, f"{host or '127.0.0.1'}:{port}".encode())
        # -o bounds the blocks (and result buffers) in flight per window —
        # numOutstanding semantics (UcxPerfBenchmark.scala:129-151): issue a
        # window, progress until it drains, issue the next.  For peak
        # localhost throughput run with -o = -n (whole set in flight) so the
        # next request is queued at the server while a reply streams.
        bufs = [MemoryBlock(np.zeros(size, dtype=np.uint8), size=size) for _ in range(args.outstanding)]
        for it in range(args.iterations):
            t0 = time.perf_counter()
            done_bytes = 0
            for base in range(0, args.num_blocks, args.outstanding):
                bids = [
                    ShuffleBlockId(0, 0, (base + k) % args.num_blocks)
                    for k in range(min(args.outstanding, args.num_blocks - base))
                ]
                reqs = transport.fetch_blocks_by_block_ids(
                    0, bids, bufs[: len(bids)], [None] * len(bids)
                )
                while not all(r.completed() for r in reqs):
                    transport.progress()
                    # wakeup park instead of burning the recv thread's GIL
                    transport.wait_for_activity(0.002)
                for r in reqs:
                    res = r.wait(1)
                    assert res.status == OperationStatus.SUCCESS, str(res.error)
                    done_bytes += res.stats.recv_size
            dt = time.perf_counter() - t0
            # Mb/s like the reference print (UcxPerfBenchmark.scala:140-143)
            line = (
                f"[thread {tid}] iter {it}: {done_bytes} bytes in {dt*1e3:.1f} ms "
                f"= {done_bytes * 8 / dt / 1e6:.0f} Mb/s ({done_bytes / dt / 1e9:.2f} GB/s)"
            )
            with results_lock:
                printed.append(line)
                print(line, flush=True)
        transport.close()

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_superstep(args) -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh

    size = parse_size(args.block_size)
    n = args.executors
    rows_per_peer = max(1, size // 512)
    send_rows = n * rows_per_peer
    spec = ExchangeSpec(num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=128)
    if args.slices > 1:
        from sparkucx_tpu.ops.hierarchy import (
            build_hierarchical_exchange,
            make_hierarchical_mesh,
        )

        mesh = make_hierarchical_mesh(args.slices, n // args.slices)
        fn = build_hierarchical_exchange(mesh, spec.resolve_impl())
        sharding = NamedSharding(mesh, P(("dcn", "ici"), None))
    else:
        mesh = make_mesh(n)
        fn = build_exchange(mesh, spec)
        sharding = NamedSharding(mesh, P("ex", None))
    rng = np.random.default_rng(0)
    data = jax.device_put(
        rng.integers(-100, 100, size=(n * send_rows, 128), dtype=np.int32), sharding
    )
    sizes = jax.device_put(
        np.full((n, n), rows_per_peer, dtype=np.int32), sharding
    )
    out, _ = fn(data, sizes)
    jax.block_until_ready(out)
    moved = n * n * rows_per_peer * 512
    for it in range(args.iterations):
        t0 = time.perf_counter()
        cur = out
        for _ in range(args.outstanding):
            cur, _ = fn(cur, sizes)
        jax.block_until_ready(cur)
        dt = time.perf_counter() - t0
        out = cur
        total = moved * args.outstanding
        print(
            f"iter {it}: {total} bytes in {dt*1e3:.1f} ms = {total * 8 / dt / 1e6:.0f} Mb/s "
            f"({total / dt / 1e9:.2f} GB/s) [impl={fn.spec.impl}]",
            flush=True,
        )


def measure_wire(
    streams_list=(1, 2, 4),
    num_blocks: int = 8,
    block_bytes: int = 32 << 20,
    iterations: int = 5,
    chunk_bytes: int = 4 << 20,
    report=None,
) -> dict:
    """Measurement core of the ``wire`` mode — loopback peer-fetch throughput
    at several ``wire.streams`` lane counts (the striped zero-copy wire path).

    One BlockServer-backed PeerTransport registers ``num_blocks`` blocks of
    ``block_bytes``; for each streams value a fresh client fetches the whole
    set per iteration (the whole batch in flight, the -o = -n shape).  Per
    streams value the result carries best GB/s, receive syscalls per MB
    (``recv_into`` calls / MB landed, from ``wire_lane_stats``), and the worst
    lane's p99 frame stall.  ``streams = 1`` is the byte-identical single-lane
    wire, so its row IS the pre-striping baseline.  ``report(streams, it,
    seconds, bytes)`` per iteration.  Shared by the CLI and bench.py."""
    server = PeerTransport(TpuShuffleConf(), executor_id=0)
    addr = server.init()
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8)
    bids = [ShuffleBlockId(0, 0, i) for i in range(num_blocks)]
    for bid in bids:
        server.register(bid, BytesBlock(payload.tobytes()))
    total = num_blocks * block_bytes
    results = {}
    try:
        for streams in streams_list:
            conf = TpuShuffleConf(
                wire_streams=streams,
                wire_chunk_bytes=chunk_bytes,
                max_blocks_per_request=num_blocks,
            )
            client = PeerTransport(conf, executor_id=100 + streams)
            client.add_executor(0, addr)
            bufs = [
                MemoryBlock(np.zeros(block_bytes, dtype=np.uint8), size=block_bytes)
                for _ in range(num_blocks)
            ]

            def fetch_once():
                reqs = client.fetch_blocks_by_block_ids(
                    0, bids, bufs, [None] * num_blocks
                )
                while not all(r.completed() for r in reqs):
                    client.progress()
                    client.wait_for_activity(0.002)
                for r in reqs:
                    res = r.wait(1)
                    assert res.status == OperationStatus.SUCCESS, str(res.error)

            fetch_once()  # warmup: connect (+ stripe handshake), page in
            assert bytes(bufs[0].host_view()[:64].tobytes()) == payload[:64].tobytes()
            best = 0.0
            t_all0 = time.perf_counter()
            for it in range(iterations):
                t0 = time.perf_counter()
                fetch_once()
                dt = time.perf_counter() - t0
                best = max(best, total / dt / 1e9)
                if report is not None:
                    report(streams, it, dt, total)
            wall = time.perf_counter() - t_all0
            lanes = client.wire_lane_stats()
            rx_bytes = sum(s["rx_bytes"] for s in lanes)
            rx_syscalls = sum(s["rx_syscalls"] for s in lanes)
            results[streams] = {
                "gbps": best,
                "mean_gbps": total * iterations / wall / 1e9,
                "syscalls_per_mb": rx_syscalls / max(rx_bytes / 1e6, 1e-9),
                "p99_frame_stall_ms": max(s["rx_stall_p99_ns"] for s in lanes) / 1e6,
                "lanes": len(lanes),
            }
            client.close()
    finally:
        server.close()
    return results


#: ``measure_compress`` payload matrices.  "dictkeys" is the dictionary-heavy
#: shape the tier-(a) codecs target: a low-cardinality u32 key column laid out
#: clustered (map-side combine emits key-grouped rows), so dict sees a
#: 256-entry alphabet (4x) and word-RLE sees the runs.  "noise" is the
#: incompressible floor: every codec must detect it, ship raw, and cost ~0.
def _compress_matrices(block_bytes: int, rng) -> dict:
    words = block_bytes // 4
    alpha = rng.integers(0, 2**32, size=256, dtype=np.uint32)
    dictkeys = np.repeat(alpha, (words + 255) // 256)[:words]
    dictkeys = dictkeys.astype("<u4").tobytes().ljust(block_bytes, b"\0")
    noise = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
    return {"dictkeys": dictkeys, "noise": noise}


def _compress_e2e(
    codec: str, payload: bytes, num_blocks: int, iterations: int, report=None
) -> float:
    """End-to-end shuffle GB/s at one codec: store-staged blocks on executor 1
    streamed back through a credit-gated ``TpuShuffleReader`` on executor 0
    (the CreditGate budgets DECODED bytes, so this leg exercises exactly the
    composition the wire-level fetch loop does not).  Returns best GB/s;
    every pass asserts bit-equality against the staged payload."""
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader

    block_bytes = len(payload)
    conf = TpuShuffleConf(
        wire_compress_codec=codec,
        wire_timeout_ms=10_000,
        staging_capacity_per_executor=num_blocks * block_bytes + (1 << 20),
    )
    ts = [PeerTransport(conf, executor_id=i) for i in (0, 1)]
    addrs = [t.init() for t in ts]
    ts[0].add_executor(1, addrs[1])
    ts[1].add_executor(0, addrs[0])
    total = num_blocks * block_bytes
    try:
        ts[1].store.create_shuffle(0, 1, num_blocks)
        w = ts[1].store.map_writer(0, 0)
        for r in range(num_blocks):
            w.write_partition(r, payload)
        w.commit()
        ts[1].store.seal(0)

        def consume() -> float:
            reader = TpuShuffleReader(
                ts[0],
                executor_id=0,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_blocks,
                num_mappers=1,
                block_sizes=lambda m, r: block_bytes,
                sender_of=lambda m: 1,
                # several windows in flight under the credit budget: credits
                # meter DECODED bytes, so this is the codec x CreditGate
                # composition path, not just the raw fetch loop
                max_blocks_per_request=2,
                credit_bytes=64 << 20,
            )
            t0 = time.perf_counter()
            blocks = []
            for blk in reader.fetch_blocks():
                blocks.append(blk)
            dt = time.perf_counter() - t0
            assert len(blocks) == num_blocks
            for blk in blocks:  # lossless contract: checked OUTSIDE the clock
                assert bytes(blk.data) == payload, f"e2e codec={codec} corrupted"
                blk.release()
            return dt

        consume()  # warmup: connect + populate the server's encode pool
        best = 0.0
        for it in range(iterations):
            dt = consume()
            best = max(best, total / dt / 1e9)
            if report is not None:
                report(f"e2e:{codec}", it, dt, total)
        return best
    finally:
        for t in ts:
            t.close()


def measure_compress(
    codecs=("off", "dict", "rle", "delta"),
    num_blocks: int = 8,
    block_bytes: int = 8 << 20,
    iterations: int = 5,
    chunk_bytes: int = 4 << 20,
    streams: int = 1,
    e2e: bool = True,
    report=None,
) -> dict:
    """Measurement core of the ``compress`` mode — loopback fetch throughput
    with the tier-(a) wire codecs, ratio x GB/s (never ratio alone).

    Per (matrix, codec): a fresh codec-configured server registers
    ``num_blocks`` blocks of the matrix, a fresh client streams the set per
    iteration, and EVERY iteration's buffers are compared byte-for-byte
    against the source (the lossless contract is asserted, not assumed —
    outside the timed region).  The first (warmup) pass also charges the
    server's encoded-chunk pool, so timed passes measure the steady serve
    state: sealed blocks are immutable, each chunk pays the encoder once per
    lifetime, not once per fetch.  Results per cell: best/mean effective GB/s
    (DECODED bytes over the wall clock), compression ratio and wire bytes
    from the server's ``compress_stats``, and pool hit count.  ``e2e`` adds a
    store-staged ``TpuShuffleReader`` pass per codec on the dictionary-heavy
    matrix (credit gate budgets decoded bytes).  ``report(label, it, seconds,
    bytes)`` per iteration.  Shared by the CLI and bench.py."""
    rng = np.random.default_rng(0)
    matrices = _compress_matrices(block_bytes, rng)
    total = num_blocks * block_bytes
    results: dict = {name: {} for name in matrices}
    for name, payload in matrices.items():
        for codec in codecs:
            server = PeerTransport(
                TpuShuffleConf(wire_compress_codec=codec), executor_id=0
            )
            addr = server.init()
            bids = [ShuffleBlockId(0, 0, i) for i in range(num_blocks)]
            for bid in bids:
                server.register(bid, BytesBlock(payload))
            client = PeerTransport(
                TpuShuffleConf(
                    wire_compress_codec=codec,
                    wire_streams=streams,
                    wire_chunk_bytes=chunk_bytes,
                    max_blocks_per_request=num_blocks,
                ),
                executor_id=1,
            )
            client.add_executor(0, addr)
            try:
                bufs = [
                    MemoryBlock(np.zeros(block_bytes, dtype=np.uint8), size=block_bytes)
                    for _ in range(num_blocks)
                ]

                def fetch_once():
                    reqs = client.fetch_blocks_by_block_ids(
                        0, bids, bufs, [None] * num_blocks
                    )
                    while not all(r.completed() for r in reqs):
                        client.progress()
                        client.wait_for_activity(0.002)
                    for r in reqs:
                        res = r.wait(1)
                        assert res.status == OperationStatus.SUCCESS, str(res.error)

                fetch_once()  # warmup: connect + charge the encode pool
                best = 0.0
                t_all0 = time.perf_counter()
                wall = 0.0
                for it in range(iterations):
                    t0 = time.perf_counter()
                    fetch_once()
                    dt = time.perf_counter() - t0
                    wall += dt
                    best = max(best, total / dt / 1e9)
                    if report is not None:
                        report(f"{name}:{codec}", it, dt, total)
                    for b in bufs:  # bit-equality EVERY lossless run
                        got = b.host_view().tobytes()
                        assert got == payload, (
                            f"lossless fetch diverged: matrix={name} codec={codec}"
                        )
                st = server.server.compress_snapshot()
                cell = {
                    "gbps": best,
                    "mean_gbps": total * iterations / max(wall, 1e-9) / 1e9,
                    "ratio": st["raw_bytes"] / max(st["wire_bytes"], 1),
                    "wire_bytes": st["wire_bytes"],
                    "raw_bytes": st["raw_bytes"],
                    "encoded_chunks": st["encoded_chunks"],
                    "raw_chunks": st["raw_chunks"],
                    "pool_hits": st["cache_hits"],
                }
            finally:
                client.close()
                server.close()
            if e2e and name == "dictkeys":
                cell["e2e_gbps"] = _compress_e2e(
                    codec, payload, num_blocks, iterations, report=report
                )
            results[name][codec] = cell
    for name in results:
        base = results[name].get("off", {}).get("gbps")
        if base:
            for codec, cell in results[name].items():
                cell["speedup_vs_off"] = cell["gbps"] / base
    return results


def measure_quantized_ici(
    num_executors: int = 4,
    slot_rows: int = 1024,
    lane: int = 128,
    iterations: int = 5,
    modes=("int8", "blockfloat"),
    report=None,
) -> dict:
    """Tier-(b) leg of the ``compress`` mode — quantized vs f32 ICI exchange.

    Builds the stock f32 exchange (float rows bitcast through the int32 lane)
    and ``build_quantized_exchange`` per mode over the same mesh, feeds both
    identical seeded payloads, asserts the dequantized result within the
    spec's per-block error bound (exact for the row sizes/counts), and times
    chained donated iterations.  Effective GB/s counts the LOGICAL f32 bytes
    delivered, so the quantized rows' win is wire-bytes (reported as
    ``wire_reduction``) showing up as throughput.  Requires >= 2 devices."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.compress import QuantizeSpec
    from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh
    from sparkucx_tpu.ops.ici_exchange import build_quantized_exchange

    avail = jax.device_count()
    n = min(num_executors, avail)
    if n < 2:
        raise RuntimeError(f"quantized ici leg needs >=2 devices (have {avail})")
    slot = slot_rows
    send_rows = n * slot
    spec = ExchangeSpec(
        num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=lane
    )
    mesh = make_mesh(n)
    sharding = NamedSharding(mesh, P("ex", None))
    stock = build_exchange(mesh, spec)

    rng = np.random.default_rng(11)
    sizes_host = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
    data_f32 = rng.standard_normal((n * send_rows, lane), dtype=np.float32)
    sizes = jax.device_put(sizes_host, sharding)
    remote_bytes = n * (n - 1) * slot * lane * 4

    def time_impl(label, fn, make_data):
        best = 0.0
        for it in range(iterations):
            data = jax.device_put(make_data(), sharding)
            t0 = time.perf_counter()
            cur = data
            for _ in range(4):  # chained: donation recycles the buffer
                cur, _ = fn(cur, sizes)
            jax.block_until_ready(cur)
            dt = time.perf_counter() - t0
            best = max(best, 4 * remote_bytes / dt / 1e9)
            if report is not None:
                report(label, n, it, dt, 4 * remote_bytes)
        return best

    # oracle: the exact f32 rows every mode must approximate
    ref, ref_sizes = stock(
        jax.device_put(data_f32.view(np.int32), sharding), sizes
    )
    ref = np.asarray(ref).view(np.float32)
    ref_sizes = np.asarray(ref_sizes)
    stock_gbps = time_impl(
        "f32", stock, lambda: data_f32.view(np.int32)
    )
    out: dict = {"n": n, "f32_gbps": stock_gbps, "modes": {}}
    for mode in modes:
        q = QuantizeSpec(mode=mode, block_size=128)
        qfn = build_quantized_exchange(mesh, spec, q)
        got, got_sizes = qfn(jax.device_put(data_f32, sharding), sizes)
        got = np.asarray(got)
        assert np.array_equal(np.asarray(got_sizes), ref_sizes), (
            f"quantized exchange sizes diverged ({mode})"
        )
        bound = q.error_bound(float(np.abs(data_f32).max()))
        err = float(np.abs(got - ref).max())
        assert err <= bound + 1e-7, (
            f"dequant error {err} above bound {bound} ({mode})"
        )
        mode_gbps = time_impl(mode, qfn, lambda: data_f32)
        out["modes"][mode] = {
            "gbps": mode_gbps,
            "speedup_vs_f32": mode_gbps / max(stock_gbps, 1e-9),
            "wire_reduction": lane / q.quantized_width(lane),
            "max_err": err,
            "err_bound": bound,
        }
    return out


def measure_failover(
    num_blocks: int = 8,
    block_bytes: int = 4 << 20,
    iterations: int = 3,
    report=None,
) -> dict:
    """Measurement core of the ``failover`` mode — fetch throughput through
    executor loss.

    Three loopback executors with ``replication.factor = 1``: executor 1
    stages ``num_blocks`` blocks of ``block_bytes`` and seals (the background
    replicator pushes every round to ring neighbor 2); executor 0 streams the
    set back with a failover-enabled reader.  Phase one measures steady-state
    GB/s over ``iterations`` passes.  Phase two runs one more pass and kills
    executor 1 after half the blocks have landed — the reader re-resolves the
    rest to the replica holder.  Returns steady vs killed GB/s, recovery time
    (kill -> first replica-served block), failover/retry counts, and the worst
    lane's p99 frame stall.  ``report(phase, it, seconds, bytes)`` per pass.
    Shared by the CLI and bench.py."""
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader
    from sparkucx_tpu.shuffle.resolver import ring_neighbors
    from sparkucx_tpu.testing import faults

    conf = TpuShuffleConf(
        replication_factor=1,
        wire_timeout_ms=10_000,
        staging_capacity_per_executor=num_blocks * block_bytes + (1 << 20),
    )
    executors = [0, 1, 2]
    ts = [PeerTransport(conf, executor_id=i) for i in executors]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    total = num_blocks * block_bytes
    try:
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
        ts[1].store.create_shuffle(0, 1, num_blocks)
        w = ts[1].store.map_writer(0, 0)
        for r in range(num_blocks):
            w.write_partition(r, payload)
        w.commit()
        ts[1].store.seal(0)
        assert ts[1].replication_wait(0, timeout=60.0), "replication did not settle"

        def make_reader():
            return TpuShuffleReader(
                ts[0],
                executor_id=0,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_blocks,
                num_mappers=1,
                block_sizes=lambda m, r: block_bytes,
                max_blocks_per_request=1,  # one window per block: the kill
                sender_of=lambda m: 1,     # lands between windows, mid-stream
                replica_of=lambda p: ring_neighbors(p, executors, 1),
                fetch_retries=3,
                fetch_deadline_ms=2000,
                fetch_backoff_ms=10,
            )

        def consume(reader, kill_at=None):
            """Drain the reader; returns (seconds, kill->next-block seconds)."""
            n = 0
            t_kill = recovery = None
            t0 = time.perf_counter()
            for blk in reader.fetch_blocks():
                blk.release()
                n += 1
                if t_kill is not None and recovery is None:
                    recovery = time.perf_counter() - t_kill
                if n == kill_at:
                    t_kill = time.perf_counter()
                    faults.kill_executor(ts[1])
            assert n == num_blocks
            return time.perf_counter() - t0, recovery

        consume(make_reader())  # warmup: connect (+ stripe handshake), page in
        steady = 0.0
        for it in range(iterations):
            dt, _ = consume(make_reader())
            steady = max(steady, total / dt / 1e9)
            if report is not None:
                report("steady", it, dt, total)
        kill_reader = make_reader()
        dt, recovery = consume(kill_reader, kill_at=max(1, num_blocks // 2))
        if report is not None:
            report("killed", 0, dt, total)
        lanes = ts[0].wire_lane_stats()
        return {
            "steady_gbps": steady,
            "killed_gbps": total / dt / 1e9,
            "recovery_ms": (recovery or 0.0) * 1e3,
            "failovers": kill_reader.metrics.failovers,
            "blocks_retried": kill_reader.metrics.blocks_retried,
            "fetch_timeouts": kill_reader.metrics.fetch_timeouts,
            "rx_stall_p99_ms": max(
                (s["rx_stall_p99_ns"] for s in lanes), default=0
            ) / 1e6,
        }
    finally:
        for t in ts:
            t.close()


def measure_gray(
    num_blocks: int = 8,
    block_bytes: int = 4 << 20,
    iterations: int = 3,
    report=None,
) -> dict:
    """Measurement core of the ``gray`` mode — fetch throughput through a
    gray (degraded-but-alive) primary, hedging off vs on.

    Same 3-executor loopback shape as ``failover`` (executor 1 stages +
    seals, the replicator pushes to ring neighbor 2, executor 0 streams the
    set back) — but instead of killing the primary, every frame it serves is
    stalled so its effective rate is ~10% of the measured healthy rate (the
    gray failure the breaker/deadline machinery can't see: the peer answers,
    just slowly).  Three phases over ``iterations`` passes each:

    1. healthy, hedging off — the baseline GB/s,
    2. primary throttled to ~10%, hedging off — the un-hedged collapse,
    3. primary throttled to ~10%, ``fetch.hedgeMs`` on — hedges fire after
       the delay and the replica holder serves the straggling blocks.

    One extra UNCLOCKED hedged pass asserts every delivered block is
    bit-identical to the staged payload (first-completion-wins must never
    surface replica/primary divergence), so the equality check can't pollute
    the timed numbers.  Returns per-phase GB/s + p99 frame stall, hedge
    counters, and the derived per-frame stall.  ``report(phase, it, seconds,
    bytes)`` per timed pass.  Shared by the CLI and bench.py."""
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader
    from sparkucx_tpu.shuffle.resolver import ring_neighbors
    from sparkucx_tpu.testing import faults

    conf = TpuShuffleConf(
        replication_factor=1,
        wire_timeout_ms=60_000,
        staging_capacity_per_executor=num_blocks * block_bytes + (1 << 20),
    )
    executors = [0, 1, 2]
    ts = [PeerTransport(conf, executor_id=i) for i in executors]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    total = num_blocks * block_bytes
    try:
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
        ts[1].store.create_shuffle(0, 1, num_blocks)
        w = ts[1].store.map_writer(0, 0)
        for r in range(num_blocks):
            w.write_partition(r, payload)
        w.commit()
        ts[1].store.seal(0)
        assert ts[1].replication_wait(0, timeout=60.0), "replication did not settle"

        def make_reader(hedge_ms=0):
            return TpuShuffleReader(
                ts[0],
                executor_id=0,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_blocks,
                num_mappers=1,
                block_sizes=lambda m, r: block_bytes,
                max_blocks_per_request=1,  # one window per block: each frame
                sender_of=lambda m: 1,     # the gray primary serves stalls
                replica_of=lambda p: ring_neighbors(p, executors, 1),
                fetch_retries=3,
                fetch_deadline_ms=30_000,  # gray peers answer — no deadline
                fetch_backoff_ms=10,       # trips, hedges do the rescuing
                fetch_hedge_ms=hedge_ms,
                fetch_hedge_max_ms=hedge_ms,
            )

        def consume(reader, collect=None):
            n = 0
            t0 = time.perf_counter()
            for blk in reader.fetch_blocks():
                if collect is not None:
                    collect.append(bytes(blk.data))
                blk.release()
                n += 1
            assert n == num_blocks
            return time.perf_counter() - t0

        def p99_ms():
            return max(
                (s["rx_stall_p99_ns"] for s in ts[0].wire_lane_stats()), default=0
            ) / 1e6

        consume(make_reader())  # warmup: connect, page in
        out: dict = {}
        healthy = 0.0
        for it in range(iterations):
            dt = consume(make_reader())
            healthy = max(healthy, total / dt / 1e9)
            if report is not None:
                report("healthy", it, dt, total)
        out["healthy_gbps"] = healthy
        out["healthy_p99_ms"] = p99_ms()

        # Throttle the primary to ~10%: each served frame sleeps 9x the
        # healthy per-block time, so primary-served traffic runs at a tenth
        # of the measured healthy rate.  The faults registry is process-
        # global — the executor match key pins the stall to server 1 only.
        stall_s = min(max(9.0 * (total / (healthy * 1e9)) / num_blocks, 0.005), 2.0)
        out["frame_stall_ms"] = stall_s * 1e3
        entry = faults.arm(
            "peer.server.frame", faults.stall(stall_s), match={"executor": 1}
        )
        try:
            degraded = 0.0
            for it in range(iterations):
                dt = consume(make_reader())
                degraded = max(degraded, total / dt / 1e9)
                if report is not None:
                    report("throttled", it, dt, total)
            out["degraded_gbps"] = degraded
            out["degraded_p99_ms"] = p99_ms()

            # hedge delay: a fraction of the injected stall, so hedges fire
            # well before the gray primary answers but never on healthy peers
            hedge_ms = max(1, int(stall_s * 1e3 / 4))
            hedged = 0.0
            hedge_reader = None
            for it in range(iterations):
                hedge_reader = make_reader(hedge_ms=hedge_ms)
                dt = consume(hedge_reader)
                hedged = max(hedged, total / dt / 1e9)
                if report is not None:
                    report("hedged", it, dt, total)
            out["hedged_gbps"] = hedged
            out["hedged_p99_ms"] = p99_ms()
            out["hedge_ms"] = hedge_ms
            m = hedge_reader.metrics
            out["hedges_issued"] = m.hedges_issued
            out["hedge_wins"] = m.hedge_wins
            out["hedge_losses"] = m.hedge_losses
            out["fetch_timeouts"] = m.fetch_timeouts

            # bit-equality OUTSIDE the clock: one unclocked hedged pass, every
            # delivered block compared against the staged payload
            got: List[bytes] = []
            consume(make_reader(hedge_ms=hedge_ms), collect=got)
            assert len(got) == num_blocks and all(b == payload for b in got), (
                "hedged read diverged from the staged payload"
            )
            out["bit_identical"] = True
        finally:
            faults.disarm(entry)
        return out
    finally:
        for t in ts:
            t.close()


def measure_tenants(
    num_apps: int = 8,
    num_blocks: int = 8,
    block_bytes: int = 1 << 20,
    iterations: int = 2,
    server_workers: int = 8,
    report=None,
) -> dict:
    """Measurement core of the ``tenants`` mode — the multi-tenant serving
    plane under concurrent fan-in.

    One tenants-enabled loopback server (the shared-selector reactor plane,
    service/reactor.py, ``server_workers`` pool threads) registers
    ``num_apps`` applications in a TenantRegistry and stages ``num_blocks``
    blocks of ``block_bytes`` per app, each under the app's own shuffle-id
    namespace (tenant-local shuffle id 0, translated server-side).  Every app
    then streams its set back concurrently through its own client transport
    — the ``app_id`` rides the FETCH_BLOCK_REQ extension.  The best-aggregate
    pass reports per-app GB/s; latency percentiles pool every per-block fetch
    gap across all apps and iterations.  Returns aggregate GB/s, per-app
    GB/s, the fairness ratio (min/max per-app GB/s — 1.0 is perfectly fair),
    p50/p99 per-block fetch latency, and the registry's usage snapshot.
    ``report(phase, it, seconds, bytes)`` per concurrent pass.  Shared by the
    CLI and bench.py."""
    from sparkucx_tpu.service.tenants import TenantRegistry
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader

    total_per_app = num_blocks * block_bytes
    conf = TpuShuffleConf(
        tenants_enabled=True,
        server_workers=server_workers,
        wire_timeout_ms=10_000,
        staging_capacity_per_executor=num_apps * total_per_app + (1 << 20),
    )
    registry = TenantRegistry()
    server = PeerTransport(conf, executor_id=1)
    server.store.tenants = registry  # before init(): BlockServer captures it
    addr = server.init()
    apps = [f"app-{i:03d}" for i in range(num_apps)]
    clients: List[PeerTransport] = []
    try:
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
        for app in apps:
            registry.register(app, hbm_quota_bytes=2 * total_per_app)
            gsid = registry.sid_for(app, 0)
            server.store.create_shuffle(gsid, 1, num_blocks, app_id=app)
            w = server.store.map_writer(gsid, 0)
            for r in range(num_blocks):
                w.write_partition(r, payload)
            w.commit()
            server.store.seal(gsid)
        for i, app in enumerate(apps):
            c = PeerTransport(conf, executor_id=100 + i)
            c.app_id = app
            c.init()
            c.add_executor(1, addr)
            clients.append(c)

        def make_reader(c):
            # tenant-LOCAL shuffle id 0: the server translates via the wire ext
            return TpuShuffleReader(
                c,
                executor_id=c.executor_id,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_blocks,
                num_mappers=1,
                block_sizes=lambda m, r: block_bytes,
                max_blocks_per_request=1,  # one window per block: per-block latency
                sender_of=lambda m: 1,
                fetch_retries=2,
                fetch_deadline_ms=10_000,
                fetch_backoff_ms=10,
            )

        def drain(c, lat, elapsed, idx):
            t0 = prev = time.perf_counter()
            n = 0
            for blk in make_reader(c).fetch_blocks():
                blk.release()
                now = time.perf_counter()
                lat.append(now - prev)
                prev = now
                n += 1
            assert n == num_blocks
            elapsed[idx] = time.perf_counter() - t0

        for c in clients:  # warmup: connect (+ stripe handshake), page in
            for blk in make_reader(c).fetch_blocks():
                blk.release()

        latencies: List[float] = []
        best_agg = 0.0
        per_app_gbps: dict = {}
        for it in range(iterations):
            lat = [[] for _ in clients]
            elapsed = [0.0] * len(clients)
            threads = [
                threading.Thread(target=drain, args=(c, lat[i], elapsed, i))
                for i, c in enumerate(clients)
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            agg = num_apps * total_per_app / wall / 1e9
            if agg > best_agg:
                best_agg = agg
                per_app_gbps = {
                    app: total_per_app / max(elapsed[i], 1e-12) / 1e9
                    for i, app in enumerate(apps)
                }
            for per_client in lat:
                latencies.extend(per_client)
            if report is not None:
                report("concurrent", it, wall, num_apps * total_per_app)
        lats = np.sort(np.asarray(latencies))
        p50 = float(lats[len(lats) // 2]) * 1e3
        p99 = float(lats[min(len(lats) - 1, int(0.99 * len(lats)))]) * 1e3
        fairness = min(per_app_gbps.values()) / max(max(per_app_gbps.values()), 1e-12)
        return {
            "apps": num_apps,
            "agg_gbps": best_agg,
            "per_app_gbps": per_app_gbps,
            "fairness": fairness,
            "p50_fetch_ms": p50,
            "p99_fetch_ms": p99,
            "tenant_stats": registry.stats(),
        }
    finally:
        for c in clients:
            c.close()
        server.close()


def measure_fanin(
    num_readers: int = 8,
    block_bytes: int = 256 << 10,
    iterations: int = 3,
    widths=(1, 2, 4),
    fetches_per_reader: int = 4,
    serve_stall_ms: float = 2.0,
    report=None,
) -> dict:
    """Measurement core of the ``fanin`` mode — N-reducer fan-in on ONE hot
    block vs the popularity tier's replica-set width.

    Per width ``w``: a fresh loopback cluster of ``w`` servers (primary +
    ``w - 1`` ring successors at ``replication.factor = w - 1``), each with a
    single-worker reactor (``server.workers = 1``) and every FETCH_BLOCK_REQ
    stalled ``serve_stall_ms`` — a deterministic per-request service-time
    ceiling, so one server saturates and the only way up is MORE HOLDERS.
    A bootstrap storm promotes the block past
    ``serve.hotThresholdFetchesPerSec``; the primary then advertises all
    ``w`` holders over HOT_SET_PULL, and ``num_readers`` concurrent reader
    transports (deterministic per-reader rotation) fan their fetches out
    across the set.  The stall is armed AFTER staging/replication and
    disarmed before the off-clock pass, which asserts the block bit-identical
    from EVERY holder.  Returns per-width aggregate GB/s and pooled p99
    per-fetch latency plus the width-max/width-1 speedup.
    ``report(phase, it, seconds, bytes)`` per pass.  Shared by the CLI and
    bench.py."""
    from sparkucx_tpu.core.definitions import AmId
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader
    from sparkucx_tpu.testing import faults

    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
    per_width: dict = {}
    for w in widths:
        conf = TpuShuffleConf(
            replication_factor=w - 1,
            serve_hot_threshold_fetches_per_sec=1.0,
            serve_hot_replicas=w - 1,
            serve_cache_bytes=4 * block_bytes,
            server_workers=1,
            wire_timeout_ms=10_000,
            staging_capacity_per_executor=block_bytes + (1 << 20),
        )
        servers = [PeerTransport(conf, executor_id=i) for i in range(w)]
        addrs = [t.init() for t in servers]
        for t in servers:
            for j, a in enumerate(addrs):
                if j != t.executor_id:
                    t.add_executor(j, a)
        clients: List[PeerTransport] = []
        try:
            servers[0].store.create_shuffle(0, 1, 1)
            mw = servers[0].store.map_writer(0, 0)
            mw.write_partition(0, payload)
            mw.commit()
            servers[0].store.seal(0)
            assert servers[0].replication_wait(0, timeout=60.0)

            for i in range(num_readers):
                c = PeerTransport(conf, executor_id=100 + i)
                c.init()
                c.add_executor(0, addrs[0])
                for j in range(1, w):
                    c.add_executor(j, addrs[j])
                clients.append(c)

            def fetch_once(c, target):
                buf = MemoryBlock(np.zeros(block_bytes, np.uint8), size=block_bytes)
                req = c.fetch_block(target, 0, 0, 0, buf)
                deadline = time.monotonic() + 10.0
                while not req.completed() and time.monotonic() < deadline:
                    c.progress()
                res = req.wait(1)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                return buf

            # bootstrap storm: back-to-back fetches promote the block and
            # (w > 1) stand up the widened advertisement
            for _ in range(6):
                fetch_once(clients[0], 0).close()
            assert servers[0].popularity.is_hot(0)
            holders = clients[0].hot_holders(0, 0) or [0]
            assert len(holders) == w, f"width {w}: advertised {holders}"

            def make_reader(c):
                return TpuShuffleReader(
                    c,
                    executor_id=c.executor_id,
                    shuffle_id=0,
                    start_partition=0,
                    end_partition=1,
                    num_mappers=1,
                    block_sizes=lambda m, r: block_bytes,
                    max_blocks_per_request=1,
                    sender_of=lambda m: 0,
                    holders_of=c.hot_holders,
                    fetch_retries=2,
                    fetch_deadline_ms=10_000,
                    fetch_backoff_ms=10,
                )

            def drain(c, lat):
                for _ in range(fetches_per_reader):
                    t0 = time.perf_counter()
                    for blk in make_reader(c).fetch_blocks():
                        blk.release()
                    lat.append(time.perf_counter() - t0)

            for c in clients:  # warmup: connect, learn the hot set
                for blk in make_reader(c).fetch_blocks():
                    blk.release()

            # service-time ceiling, armed only for the timed passes
            entry = faults.arm(
                "peer.server.frame",
                faults.stall(serve_stall_ms / 1e3),
                match={"am_id": int(AmId.FETCH_BLOCK_REQ)},
            )
            total = num_readers * fetches_per_reader * block_bytes
            best = 0.0
            latencies: List[float] = []
            for it in range(iterations):
                lat = [[] for _ in clients]
                threads = [
                    threading.Thread(target=drain, args=(c, lat[i]))
                    for i, c in enumerate(clients)
                ]
                t0 = time.perf_counter()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                wall = time.perf_counter() - t0
                best = max(best, total / wall / 1e9)
                for per_client in lat:
                    latencies.extend(per_client)
                if report is not None:
                    report(f"width-{w}", it, wall, total)
            faults.disarm(entry)

            # off-clock: the same bytes from EVERY advertised holder
            for holder in holders:
                buf = fetch_once(clients[0], holder)
                assert bytes(buf.host_view()[:block_bytes]) == payload, (
                    f"width {w}: holder {holder} served different bytes"
                )
                buf.close()

            lats = np.sort(np.asarray(latencies))
            per_width[w] = {
                "agg_gbps": best,
                "p99_fetch_ms": float(
                    lats[min(len(lats) - 1, int(0.99 * len(lats)))]
                ) * 1e3,
                "holders": holders,
            }
        finally:
            faults.reset()
            for c in clients:
                c.close()
            for t in servers:
                t.close()
    w_lo, w_hi = min(widths), max(widths)
    return {
        "readers": num_readers,
        "block_bytes": block_bytes,
        "per_width": per_width,
        "speedup": per_width[w_hi]["agg_gbps"]
        / max(per_width[w_lo]["agg_gbps"], 1e-12),
    }


def measure_elastic(
    num_executors: int = 4,
    block_bytes: int = 8 << 10,
    iterations: int = 3,
    report=None,
) -> dict:
    """Measurement core of the ``elastic`` mode — collective-exchange
    throughput through an executor death with degraded-mode recovery.

    A ``num_executors``-wide loopback cluster with ``elastic.enabled`` and
    ``replication.factor = 1`` runs 3n x 2n shuffles whose staging budget
    forces multiple collective rounds.  Phase one measures steady-state
    full-mesh exchange GB/s over ``iterations`` fresh shuffles.  Phase two
    stages one more shuffle and kills an executor mid-superstep (the
    ``exchange.submit`` chaos hook): the cluster shrinks to the surviving
    pow2 bucket, restages the dead executor's rounds from its ring
    successor's replicas, and re-runs in degraded waves — output asserted
    byte-identical to the staged payloads.  Returns steady vs shrink-recover
    GB/s plus the recovery telemetry from ``TpuShuffleCluster.elastic_stats``.
    ``report(phase, it, seconds, bytes)`` per pass.  Shared by the CLI and
    bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    from sparkucx_tpu.testing import faults
    from sparkucx_tpu.transport.tpu import TpuShuffleCluster

    n = num_executors
    M, R = 3 * n, 2 * n
    align = 512
    padded = -(-block_bytes // align) * align
    total = M * R * block_bytes

    def mk_cluster():
        conf = TpuShuffleConf(
            num_executors=n,
            elastic=True,
            replication_factor=1,
            block_alignment=align,
            # ~2 maps per staging round: the shuffle spans several collective
            # rounds, so the kill lands mid-superstep with rounds left both
            # to restage from replicas and to re-run on the shrunk mesh
            staging_capacity_per_executor=2 * R * padded,
        )
        return TpuShuffleCluster(conf, num_executors=n)

    def run_once(cluster, shuffle_id, kill=None, verify=False):
        meta = cluster.create_shuffle(shuffle_id, M, R)
        rng = np.random.default_rng(shuffle_id)
        oracle = {}
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(shuffle_id, m)
            for r in range(R):
                payload = rng.integers(
                    0, 256, size=block_bytes, dtype=np.uint8
                ).tobytes()
                if verify:
                    oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        if kill is not None:
            def die(**_ctx):
                faults.kill_executor(cluster.transport(kill))

            faults.arm("exchange.submit", die, times=1, match={"round": 1})
        try:
            t0 = time.perf_counter()
            cluster.run_exchange(shuffle_id)
            dt = time.perf_counter() - t0
        finally:
            faults.reset()
        for (m, r), want in oracle.items():
            consumer = meta.owner_of_reduce(r)
            view, length = cluster.locate_received_block(consumer, shuffle_id, m, r)
            assert bytes(view[:length]) == want, "recovered block diverged"
        return dt

    steady = 0.0
    cluster = mk_cluster()
    try:
        run_once(cluster, 0)  # warmup: compile the full-mesh exchange
        for it in range(iterations):
            dt = run_once(cluster, it + 1)
            steady = max(steady, total / dt / 1e9)
            if report is not None:
                report("steady", it, dt, total)
    finally:
        for t in cluster.transports:
            t.close()
    cluster = mk_cluster()
    try:
        # kill the highest executor id: the survivors are the contiguous pow2
        # prefix, the common shrink shape (any id recovers identically)
        dt = run_once(cluster, 0, kill=n - 1, verify=True)
        if report is not None:
            report("shrink", 0, dt, total)
        stats = dict(cluster.elastic_stats)
    finally:
        for t in cluster.transports:
            t.close()
    m_deg, phys = stats["degraded_mesh"] or (0, ())
    return {
        "steady_gbps": steady,
        "degraded_gbps": total / dt / 1e9,
        "recovery_ms": stats["last_recovery_ms"],
        "recoveries": stats["recoveries"],
        "epoch": stats["last_epoch"],
        "degraded_mesh": m_deg,
        "survivors": tuple(phys),
    }


def measure_obs(
    num_blocks: int = 8,
    block_bytes: int = 4 << 20,
    iterations: int = 3,
    report=None,
) -> dict:
    """Measurement core of the ``obs`` mode — telemetry-plane overhead.

    Two loopback executors; executor 1 stages ``num_blocks`` blocks and
    executor 0 streams them back, with ``obs.traceContext`` compiled in but
    the process tracer flipped per leg:

    * ``off``     — tracing AND recording disabled (the always-on flight
      recorder switched off; nothing rides the wire, ``span()`` returns the
      shared no-op singleton);
    * ``ring``    — recording only: the flight recorder's steady-state
      default.  Spans land in the bounded ring, nothing rides the wire.
      The always-on contract is ``ring`` overhead < 1% — asserted here
      against the ACCOUNTED cost (events recorded per pass x measured
      ns/record, over the pass wall time), because a loopback socket's
      run-to-run throughput jitter is itself several percent and would
      swamp a wall-clock delta of microseconds;
    * ``full``    — tracing enabled: span contexts ride FetchBlockReq as the
      trailing ext, the server re-parents serve spans, and afterwards the
      buffers are pulled over TracePull and merged into one event list
      (export timed separately, not inside the fetch loop).

    Also times the disabled-``span()`` fast path (ns/call).  Returns GB/s per
    leg, overhead percentages, the fast-path cost, and the merged-export
    stats.  ``report(leg, it, seconds, bytes)`` per pass.  Shared by the CLI
    and bench.py."""
    from sparkucx_tpu.shuffle.reader import TpuShuffleReader
    from sparkucx_tpu.utils.trace import TRACER, merge_events, span

    conf = TpuShuffleConf(
        obs_trace_context=True,
        staging_capacity_per_executor=num_blocks * block_bytes + (1 << 20),
    )
    executors = [0, 1]
    ts = [PeerTransport(conf, executor_id=i) for i in executors]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    total = num_blocks * block_bytes
    saved = (TRACER.enabled, TRACER.recording)
    try:
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 256, size=block_bytes, dtype=np.uint8).tobytes()
        ts[1].store.create_shuffle(0, 1, num_blocks)
        w = ts[1].store.map_writer(0, 0)
        for r in range(num_blocks):
            w.write_partition(r, payload)
        w.commit()
        ts[1].store.seal(0)

        def make_reader():
            return TpuShuffleReader(
                ts[0],
                executor_id=0,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_blocks,
                num_mappers=1,
                block_sizes=lambda m, r: block_bytes,
                max_blocks_per_request=1,  # one window per block: every block
                sender_of=lambda m: 1,     # fetch is its own read.window span
            )

        def consume():
            n = 0
            t0 = time.perf_counter()
            for blk in make_reader().fetch_blocks():
                blk.release()
                n += 1
            assert n == num_blocks
            return time.perf_counter() - t0

        # disabled-span fast path: one attribute check + the shared singleton
        TRACER.enabled = False
        TRACER.recording = False
        calls = 200_000
        t0 = time.perf_counter()
        for _ in range(calls):
            with span("bench.noop"):
                pass
        span_disabled_ns = (time.perf_counter() - t0) / calls * 1e9

        consume()  # warmup: connect, page in

        def leg(name, enabled, recording):
            TRACER.clear()
            TRACER.enabled = enabled
            TRACER.recording = recording
            # both transports share ``conf``: the ext rides only on the full
            # leg, so ``ring`` measures exactly the always-on default
            conf.obs_trace_context = enabled
            best_dt = float("inf")
            for it in range(iterations):
                dt = consume()
                best_dt = min(best_dt, dt)
                if report is not None:
                    report(name, it, dt, total)
            return best_dt, len(TRACER.events)

        off_dt, _ = leg("off", False, False)
        ring_dt, ring_events = leg("ring", False, True)
        full_dt, _ = leg("full", True, True)
        off = total / off_dt / 1e9
        ring = total / ring_dt / 1e9
        full = total / full_dt / 1e9

        # the full leg's export (while its events are still in the ring):
        # pull the server's buffer over the TracePull AM and merge with the
        # local ring — ONE event list, two pids
        t0 = time.perf_counter()
        remote = ts[0].pull_trace(1)
        merged = merge_events([TRACER.events, remote["events"]])
        export_ms = (time.perf_counter() - t0) * 1e3

        # record-path cost: time actual ring appends while recording
        TRACER.clear()
        TRACER.enabled = False
        TRACER.recording = True
        calls = 50_000
        t0 = time.perf_counter()
        for _ in range(calls):
            with span("bench.record"):
                pass
        span_record_ns = (time.perf_counter() - t0) / calls * 1e9

        # the always-on contract: the recorder's accounted steady-state cost
        # (events it records per pass x the measured cost of recording one)
        # must be < 1% of the pass — the wall-clock ring-vs-off delta is also
        # reported but NOT asserted on, since loopback jitter exceeds 1%
        events_per_pass = ring_events / max(iterations, 1)
        ring_overhead = events_per_pass * span_record_ns / (ring_dt * 1e9)
        assert ring_overhead < 0.01, (
            f"always-on recorder overhead {ring_overhead * 100:.3f}% >= 1% "
            f"({events_per_pass:.0f} events/pass x {span_record_ns:.0f} ns "
            f"over {ring_dt * 1e3:.1f} ms)"
        )

        return {
            "off_gbps": off,
            "ring_gbps": ring,
            "full_gbps": full,
            "ring_overhead_pct": ring_overhead * 100.0,
            "ring_wall_delta_pct": (1.0 - ring / max(off, 1e-9)) * 100.0,
            "full_wall_delta_pct": (1.0 - full / max(off, 1e-9)) * 100.0,
            "events_per_pass": events_per_pass,
            "span_record_ns": span_record_ns,
            "span_disabled_ns": span_disabled_ns,
            "export_ms": export_ms,
            "merged_events": len(merged),
            "merged_pids": len({e.get("pid") for e in merged}),
        }
    finally:
        TRACER.enabled, TRACER.recording = saved
        TRACER.clear()
        for t in ts:
            t.close()


def measure_pipeline(
    executors: int, round_bytes: int, rounds: int, iterations: int,
    depths=(1, 2, 3), report=None,
) -> dict:
    """Measurement core of the ``pipeline`` mode — multi-round (spilled)
    shuffle throughput WITH host staging in the loop, at several pipeline
    depths.  Unlike ``superstep`` (HBM-resident payloads chained K deep),
    every round here pays the full H2D -> collective -> D2H path the spill
    engine drives; depth d overlaps round k's collective with round k+1's
    staging and round k-1's drain (transport/pipeline.py — the tentpole
    overlap).  Returns ``{depth: best GB/s of payload moved}``;
    ``report(depth, it, seconds, bytes)`` is called per iteration when given.
    Shared by the CLI and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import (
        ExchangeSpec, bucket_send_rows, build_exchange, make_mesh,
    )
    from sparkucx_tpu.transport.pipeline import RoundPipeline

    n = executors
    rows_per_peer = max(1, round_bytes // (512 * n))
    send_rows = bucket_send_rows(n * rows_per_peer, n)
    spec = ExchangeSpec(
        num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=128
    )
    mesh = make_mesh(n)
    fn = build_exchange(mesh, spec)
    sharding = NamedSharding(mesh, P("ex", None))
    rng = np.random.default_rng(0)
    host_rounds = [
        rng.integers(-100, 100, size=(n * send_rows, 128), dtype=np.int32)
        for _ in range(rounds)
    ]
    sizes = np.full((n, n), rows_per_peer, dtype=np.int32)
    moved_per_round = n * n * rows_per_peer * 512
    results = {}
    for depth in depths:
        size_mat = jax.device_put(sizes, sharding)  # never donated: hoist

        def submit(rnd):
            data = jax.device_put(host_rounds[rnd], sharding)  # H2D (async)
            recv, _ = fn(data, size_mat)                       # collective
            shards = [s.data for s in recv.addressable_shards]
            for a in shards:
                a.copy_to_host_async()                         # D2H kick-off
            return shards

        def drain(rnd, shards):
            for a in shards:
                np.asarray(a)  # observe completion: materialize host-side
            return None

        pipe = RoundPipeline(depth, submit, drain, name=f"bench.pipeline.d{depth}")
        pipe.run(rounds)  # warmup: compile + first H2D/D2H
        best = 0.0
        for it in range(iterations):
            t0 = time.perf_counter()
            pipe.run(rounds)
            dt = time.perf_counter() - t0
            tot = moved_per_round * rounds
            best = max(best, tot / dt / 1e9)
            if report is not None:
                report(depth, it, dt, tot)
        results[depth] = best
    return results


def measure_gather(
    num_blocks: int,
    block_bytes: int,
    iterations: int,
    outstanding: int,
    impl: str | None = None,
    report=None,
) -> float:
    """Measurement core of the ``gather`` mode — device-side ragged block gather
    (the reply-packing hot path, UcxWorkerWrapper.scala:397-448 analogue).
    Returns best GB/s across iterations; ``report(it, seconds, bytes, impl)`` is
    called per iteration when given.  Shared by the CLI and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax

    from sparkucx_tpu.ops.pallas_kernels import build_block_gather, pack_plan

    row = 512
    rows_each = max(1, block_bytes // row)
    b = num_blocks
    # blocks scattered at 2x stride through the source (every other slot used)
    src_rows = 2 * b * rows_each
    rng = np.random.default_rng(0)
    src = jax.device_put(
        rng.integers(-100, 100, size=(src_rows, row // 4), dtype=np.int32)
    )
    plan = [(2 * i * rows_each * row, rows_each * row) for i in range(b)]
    starts, counts, outs, total = pack_plan(plan, row)
    fn = build_block_gather(b, total, impl=impl)
    dev = src.device
    sargs = tuple(jax.device_put(a, dev) for a in (starts, counts, outs))
    out = jax.block_until_ready(fn(*sargs, src))  # compile
    assert np.array_equal(np.asarray(out[:rows_each]), np.asarray(src[:rows_each]))
    moved = total * row
    best = 0.0
    for it in range(iterations):
        t0 = time.perf_counter()
        for _ in range(outstanding):
            out = fn(*sargs, src)
        jax.block_until_ready(out)
        np.asarray(out[0, :4])  # force completion through async tunnels
        dt = time.perf_counter() - t0
        tot = moved * outstanding
        best = max(best, tot / dt / 1e9)
        if report is not None:
            report(it, dt, tot, fn.impl)
    return best


def run_wire(args) -> None:
    size = parse_size(args.block_size)
    streams_list = tuple(int(s) for s in args.streams.split(","))

    def report(streams, it, dt, tot):
        print(
            f"streams {streams} iter {it}: {args.num_blocks} x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    results = measure_wire(
        streams_list, args.num_blocks, size, args.iterations,
        chunk_bytes=parse_size(args.chunk_bytes), report=report,
    )
    base = results.get(1, {}).get("gbps")
    for streams, r in sorted(results.items()):
        speedup = (
            f" ({r['gbps'] / base:.2f}x vs streams=1)"
            if base and streams != 1
            else ""
        )
        print(
            f"wire streams {streams}: {r['gbps']:.2f} GB/s, "
            f"{r['syscalls_per_mb']:.1f} syscalls/MB, "
            f"p99 frame stall {r['p99_frame_stall_ms']:.2f} ms{speedup}",
            flush=True,
        )


def run_compress(args) -> None:
    size = parse_size(args.block_size)

    def report(label, it, dt, tot):
        print(
            f"{label} iter {it}: {tot} B in {dt*1e3:.1f} ms = "
            f"{tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    results = measure_compress(
        num_blocks=args.num_blocks,
        block_bytes=size,
        iterations=args.iterations,
        chunk_bytes=parse_size(args.chunk_bytes),
        streams=int(args.streams.split(",")[0]),
        report=report,
    )
    for name, row in results.items():
        for codec, r in row.items():
            speed = (
                f" ({r['speedup_vs_off']:.2f}x vs off)"
                if codec != "off" and "speedup_vs_off" in r
                else ""
            )
            e2e = f", e2e {r['e2e_gbps']:.2f} GB/s" if "e2e_gbps" in r else ""
            print(
                f"compress {name:9s} codec={codec:5s}: {r['gbps']:.2f} GB/s"
                f"{speed}, ratio {r['ratio']:.2f}x "
                f"({r['encoded_chunks']} enc / {r['raw_chunks']} raw chunks, "
                f"{r['pool_hits']} pool hits){e2e}",
                flush=True,
            )
    try:
        q = measure_quantized_ici(
            num_executors=args.executors if args.executors > 1 else 4,
            iterations=args.iterations,
        )
    except RuntimeError as e:
        print(f"quantized ici leg skipped: {e}", flush=True)
        return
    print(f"quantized ici n={q['n']}: f32 {q['f32_gbps']:.2f} GB/s", flush=True)
    for mode, m in q["modes"].items():
        print(
            f"quantized ici {mode}: {m['gbps']:.2f} GB/s "
            f"({m['speedup_vs_f32']:.2f}x vs f32), "
            f"wire bytes {m['wire_reduction']:.2f}x fewer, "
            f"max err {m['max_err']:.3g} <= bound {m['err_bound']:.3g}",
            flush=True,
        )


def run_failover(args) -> None:
    size = parse_size(args.block_size)

    def report(phase, it, dt, tot):
        print(
            f"{phase} iter {it}: {args.num_blocks} x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_failover(args.num_blocks, size, args.iterations, report=report)
    ratio = r["killed_gbps"] / max(r["steady_gbps"], 1e-9)
    print(
        f"failover: steady {r['steady_gbps']:.2f} GB/s, "
        f"primary killed at t=50% {r['killed_gbps']:.2f} GB/s ({ratio:.2f}x), "
        f"recovery {r['recovery_ms']:.1f} ms, "
        f"{r['failovers']} failovers / {r['blocks_retried']} retried / "
        f"{r['fetch_timeouts']} timeouts, "
        f"p99 frame stall {r['rx_stall_p99_ms']:.2f} ms",
        flush=True,
    )


def run_gray(args) -> None:
    size = parse_size(args.block_size)

    def report(phase, it, dt, tot):
        print(
            f"{phase} iter {it}: {args.num_blocks} x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_gray(args.num_blocks, size, args.iterations, report=report)
    collapse = r["degraded_gbps"] / max(r["healthy_gbps"], 1e-9)
    rescue = r["hedged_gbps"] / max(r["healthy_gbps"], 1e-9)
    print(
        f"gray: healthy {r['healthy_gbps']:.2f} GB/s (p99 stall "
        f"{r['healthy_p99_ms']:.2f} ms); primary throttled to ~10% "
        f"({r['frame_stall_ms']:.1f} ms/frame): hedging off "
        f"{r['degraded_gbps']:.2f} GB/s ({collapse:.2f}x, p99 "
        f"{r['degraded_p99_ms']:.2f} ms), hedging on ({r['hedge_ms']} ms) "
        f"{r['hedged_gbps']:.2f} GB/s ({rescue:.2f}x, p99 "
        f"{r['hedged_p99_ms']:.2f} ms), "
        f"{r['hedges_issued']} hedges / {r['hedge_wins']} wins / "
        f"{r['hedge_losses']} losses / {r['fetch_timeouts']} timeouts, "
        f"bit-identical {r['bit_identical']}",
        flush=True,
    )


def run_tenants(args) -> None:
    size = parse_size(args.block_size)

    def report(phase, it, dt, tot):
        print(
            f"{phase} iter {it}: {args.apps} apps x {args.num_blocks} x {size} B "
            f"in {dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_tenants(
        num_apps=args.apps,
        num_blocks=args.num_blocks,
        block_bytes=size,
        iterations=args.iterations,
        report=report,
    )
    print(
        f"tenants: {r['apps']} apps, aggregate {r['agg_gbps']:.2f} GB/s, "
        f"fairness {r['fairness']:.2f} (min/max per-app GB/s), "
        f"p50 fetch {r['p50_fetch_ms']:.2f} ms, "
        f"p99 fetch {r['p99_fetch_ms']:.2f} ms",
        flush=True,
    )
    for app, gbps in sorted(r["per_app_gbps"].items()):
        used = r["tenant_stats"].get(app, {}).get("used_bytes", 0)
        print(f"tenants   {app}: {gbps:.3f} GB/s, hbm used {used} B", flush=True)


def measure_queries(
    num_apps: int = 4,
    queries_per_app: int = 5,
    rows_per_query: int = 2000,
    keys: int = 64,
    report=None,
) -> dict:
    """Measurement core of the ``queries`` mode — M concurrent tenant DAGs
    with repeated sub-DAGs through the query runner (sparkucx_tpu/query).

    Each of ``num_apps`` tenants drives ``queries_per_app`` repetitions of a
    GroupByTest-shaped DAG (scan -> hash exchange -> grouped aggregate) over
    its own input, one thread per tenant, twice: a COLD pass on a cache-less
    manager (every exchange executes — the baseline a cache-less runner
    pays) and a CACHED pass with ``query.cacheEnabled`` on a shared
    LineageCache, where every repeat after the first serves the sealed
    shuffle straight from the store tiers and skips the exchange entirely.
    Asserts every cached-hit result bit-identical to the cold pass off the
    clock.  Returns cold/warm queries-per-second, the measured hit rate,
    p50/p99 per-stage latency for both passes, and the tenant usage
    snapshot.  ``report(phase, app_idx, seconds, queries)`` per tenant
    drain.  Shared by the CLI and bench.py."""
    import jax

    from sparkucx_tpu.query import LineageCache, QueryRunner, Stage, StageDag
    from sparkucx_tpu.service.tenants import TenantRegistry
    from sparkucx_tpu.shuffle.manager import TpuShuffleManager

    num_executors = max(1, min(4, jax.device_count()))
    dag = StageDag(
        [
            Stage.make("src", "scan"),
            Stage.make("ex", "exchange", ["src"]),
            Stage.make("agg", "aggregate", ["ex"]),
        ]
    )
    apps = [f"app-{i:03d}" for i in range(num_apps)]
    rng = np.random.default_rng(7)
    inputs = {
        app: [
            (int(k), int(v))
            for k, v in zip(
                rng.integers(0, keys, rows_per_query),
                rng.integers(0, 1 << 20, rows_per_query),
            )
        ]
        for app in apps
    }

    def _conf(cache_on: bool) -> TpuShuffleConf:
        return TpuShuffleConf(
            staging_capacity_per_executor=8 << 20,
            num_executors=num_executors,
            query_cache_enabled=cache_on,
        )

    def _pass(cache_on: bool, phase: str):
        mgr = TpuShuffleManager(_conf(cache_on), num_executors=num_executors)
        registry = TenantRegistry()
        cache = LineageCache() if cache_on else None
        try:
            stage_ms: List[float] = []
            stage_lock = threading.Lock()
            results: dict = {}
            runners = {}
            for app in apps:
                r = QueryRunner(mgr, app, tenants=registry, cache=cache)

                def observe(name, op, ms):
                    with stage_lock:
                        stage_ms.append(ms)

                r.on_stage = observe
                runners[app] = r
            # warmup: compile the exchange path once, off the clock
            runners[apps[0]].run(dag, {"src": inputs[apps[0]]})

            def drain(app):
                t0 = time.perf_counter()
                outs = [
                    runners[app].run(dag, {"src": inputs[app]})
                    for _ in range(queries_per_app)
                ]
                dt = time.perf_counter() - t0
                results[app] = (outs, dt)
                if report is not None:
                    report(phase, app, dt, queries_per_app)

            threads = [threading.Thread(target=drain, args=(app,)) for app in apps]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            qps = num_apps * queries_per_app / wall
            lat = np.sort(np.asarray(stage_ms))
            p50 = float(lat[len(lat) // 2])
            p99 = float(lat[min(len(lat) - 1, int(0.99 * len(lat)))])
            hits = misses = 0
            if cache is not None:
                snap = cache.snapshot()
                hits, misses = snap["cache_hits"], snap["cache_misses"]
            return {
                "qps": qps,
                "p50_stage_ms": p50,
                "p99_stage_ms": p99,
                "hits": hits,
                "misses": misses,
                "results": {app: results[app][0] for app in apps},
                "tenant_stats": registry.stats(),
            }
        finally:
            mgr.stop()

    cold = _pass(False, "cold")
    warm = _pass(True, "cached")
    for app in apps:
        # every cached-hit result bit-identical to cold execution
        assert warm["results"][app] == cold["results"][app], f"{app} result drift"
    total = warm["hits"] + warm["misses"]
    return {
        "apps": num_apps,
        "queries_per_app": queries_per_app,
        "executors": num_executors,
        "cold_qps": cold["qps"],
        "warm_qps": warm["qps"],
        "speedup": warm["qps"] / max(cold["qps"], 1e-12),
        "hit_rate": warm["hits"] / max(total, 1),
        "cold_p99_stage_ms": cold["p99_stage_ms"],
        "p50_stage_ms": warm["p50_stage_ms"],
        "p99_stage_ms": warm["p99_stage_ms"],
        "tenant_stats": warm["tenant_stats"],
        "bit_identical": True,
    }


def run_queries(args) -> None:
    def report(phase, app, dt, n):
        print(
            f"{phase} {app}: {n} queries in {dt*1e3:.1f} ms "
            f"= {n / dt:.1f} q/s",
            flush=True,
        )

    r = measure_queries(
        num_apps=args.apps,
        queries_per_app=args.iterations,
        rows_per_query=args.keys * 32,
        keys=args.keys,
        report=report,
    )
    print(
        f"queries: {r['apps']} apps x {r['queries_per_app']} queries, "
        f"cold {r['cold_qps']:.1f} q/s -> cached {r['warm_qps']:.1f} q/s "
        f"({r['speedup']:.2f}x at {r['hit_rate']:.0%} hit rate), "
        f"p99 stage {r['cold_p99_stage_ms']:.2f} -> {r['p99_stage_ms']:.2f} ms, "
        f"hit results bit-identical",
        flush=True,
    )
    for app, st in sorted(r["tenant_stats"].items()):
        print(
            f"queries   {app}: hbm charged {st['used_bytes']} B "
            f"(cached rounds stay on the tenant's quota)",
            flush=True,
        )


def run_fanin(args) -> None:
    size = parse_size(args.block_size)
    readers = args.threads if args.threads > 1 else 8

    def report(phase, it, dt, tot):
        print(
            f"{phase} iter {it}: {readers} readers x 1 hot block x {size} B "
            f"in {dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_fanin(
        num_readers=readers,
        block_bytes=size,
        iterations=args.iterations,
        report=report,
    )
    for w, m in sorted(r["per_width"].items()):
        print(
            f"fanin width {w}: {m['agg_gbps']:.2f} GB/s aggregate, "
            f"p99 fetch {m['p99_fetch_ms']:.2f} ms, holders {m['holders']}",
            flush=True,
        )
    print(
        f"fanin: width-{max(r['per_width'])} / width-{min(r['per_width'])} "
        f"speedup {r['speedup']:.2f}x, bit-identical from every holder",
        flush=True,
    )


def run_elastic(args) -> None:
    size = parse_size(args.block_size)
    n = args.executors if args.executors > 1 else 4

    def report(phase, it, dt, tot):
        print(
            f"{phase} iter {it}: {3*n}x{2*n} x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_elastic(n, size, args.iterations, report=report)
    ratio = r["degraded_gbps"] / max(r["steady_gbps"], 1e-9)
    print(
        f"elastic: steady {r['steady_gbps']:.2f} GB/s, "
        f"killed mid-superstep {r['degraded_gbps']:.2f} GB/s ({ratio:.2f}x), "
        f"recovery {r['recovery_ms']:.1f} ms "
        f"(epoch {r['epoch']}, mesh {n} -> {r['degraded_mesh']} "
        f"on {list(r['survivors'])}), "
        f"{r['recoveries']} recoveries, bit-identical asserted",
        flush=True,
    )


def run_obs(args) -> None:
    size = parse_size(args.block_size)

    def report(leg, it, dt, tot):
        print(
            f"{leg} iter {it}: {args.num_blocks} x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_obs(args.num_blocks, size, args.iterations, report=report)
    print(
        f"obs: off {r['off_gbps']:.2f} GB/s, "
        f"ring-only {r['ring_gbps']:.2f} GB/s, "
        f"full export {r['full_gbps']:.2f} GB/s; "
        f"always-on recorder {r['events_per_pass']:.0f} events/pass x "
        f"{r['span_record_ns']:.0f} ns = {r['ring_overhead_pct']:.3f}% "
        f"accounted overhead (<1% asserted; wall delta "
        f"{r['ring_wall_delta_pct']:+.1f}% ring / "
        f"{r['full_wall_delta_pct']:+.1f}% full, loopback jitter included), "
        f"disabled span() {r['span_disabled_ns']:.0f} ns/call, "
        f"TracePull merge {r['merged_events']} events from "
        f"{r['merged_pids']} executors in {r['export_ms']:.1f} ms",
        flush=True,
    )


def run_pipeline(args) -> None:
    size = parse_size(args.block_size)
    depths = tuple(int(d) for d in args.depths.split(","))

    def report(depth, it, dt, tot):
        print(
            f"depth {depth} iter {it}: {args.num_blocks} rounds x {size} B in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    results = measure_pipeline(
        args.executors, size, args.num_blocks, args.iterations,
        depths=depths, report=report,
    )
    base = results.get(1)
    for depth, gbps in sorted(results.items()):
        speedup = f" ({gbps / base:.2f}x vs serial)" if base and depth != 1 else ""
        print(f"pipeline depth {depth}: {gbps:.2f} GB/s{speedup}", flush=True)


def run_gather(args) -> None:
    size = parse_size(args.block_size)
    rows_each = max(1, size // 512)

    def report(it, dt, tot, impl):
        print(
            f"iter {it}: {args.num_blocks} blocks x {rows_each * 512} B packed "
            f"{args.outstanding}x: {tot} bytes in {dt*1e3:.1f} ms = "
            f"{tot / dt / 1e9:.2f} GB/s [impl={impl}]",
            flush=True,
        )

    measure_gather(
        args.num_blocks,
        size,
        args.iterations,
        args.outstanding,
        impl=None if args.impl == "auto" else args.impl,
        report=report,
    )


def measure_write(
    num_blocks: int,
    block_bytes: int,
    iterations: int,
    impls=("host", "device"),
    report=None,
) -> dict:
    """Measurement core of the ``write`` mode — map-output staging throughput,
    host byte path vs device staging path (ISSUE 2's tentpole comparison).

    ``host``: ``MapWriter.write_partition`` copies bytes into host staging and
    ``seal`` uploads the whole buffer H2D — the reference-faithful shape
    (NvkvHandler.scala:213-242 pinned-buffer staging).  ``device``:
    ``write_partition_device`` keeps the blocks device-resident and ``seal``
    places them with the block-scatter kernel, returning the HBM payload with
    no host round trip.  One map task writes ``num_blocks`` partitions of
    ``block_bytes`` each into a fresh shuffle per iteration; the clock covers
    write -> seal -> payload ready.  Returns ``{impl: best GB/s}``;
    ``report(impl, it, seconds, bytes)`` per iteration.  Shared by the CLI and
    bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax

    from sparkucx_tpu.store.hbm_store import HbmBlockStore

    row = 512
    rows_each = max(1, block_bytes // row)
    total = num_blocks * rows_each * row
    conf = TpuShuffleConf(
        device_staging=True,
        staging_capacity_per_executor=max(2 * total, 1 << 20),
        spill_to_disk=False,
    )
    device = jax.devices()[0]
    rng = np.random.default_rng(0)
    host_blocks = [
        rng.integers(0, 256, size=rows_each * row, dtype=np.uint8).tobytes()
        for _ in range(num_blocks)
    ]
    dev_blocks = [
        jax.device_put(
            np.frombuffer(b, np.uint8).view(np.int32).reshape(rows_each, row // 4),
            device,
        )
        for b in host_blocks
    ]
    jax.block_until_ready(dev_blocks)
    results = {}
    for impl in impls:
        if impl not in ("host", "device"):
            raise ValueError(f"unknown write impl {impl!r} (host|device)")
        store = HbmBlockStore(conf, device=device)
        best = 0.0
        for it in range(iterations + 1):  # iteration 0 = warmup (compiles)
            sid = it
            store.create_shuffle(sid, 1, num_blocks)
            t0 = time.perf_counter()
            w = store.map_writer(sid, 0)
            for r in range(num_blocks):
                if impl == "host":
                    w.write_partition(r, host_blocks[r])
                else:
                    w.write_partition_device(r, dev_blocks[r])
            w.commit()
            payload = store.seal(sid)[-1][0]
            jax.block_until_ready(payload)
            np.asarray(payload[0, :4])  # force completion through async tunnels
            dt = time.perf_counter() - t0
            store.remove_shuffle(sid)
            if it == 0:
                continue
            best = max(best, total / dt / 1e9)
            if report is not None:
                report(impl, it - 1, dt, total)
        results[impl] = best
    return results


def zipf_size_matrix(executors: int, max_peer_rows: int, alpha: float) -> np.ndarray:
    """A deterministic Zipf-skewed exchange size matrix: ``sizes[i, j]`` rows
    from sender i to destination j follow ``(rank + 1) ** -alpha`` scaled so
    each sender's hottest lane is ``max_peer_rows`` (min 1 row), with the rank
    order permuted per sender (seeded) so the hot destination varies — the
    shape real shuffle workloads take (ISSUE: TPC-DS/TPC-H are Zipf-skewed)."""
    n = executors
    rng = np.random.default_rng(0)
    weights = (np.arange(1, n + 1, dtype=np.float64)) ** (-alpha)
    base = np.maximum(1, np.round(max_peer_rows * weights / weights[0])).astype(np.int64)
    sizes = np.empty((n, n), dtype=np.int32)
    for i in range(n):
        sizes[i] = base[rng.permutation(n)]
    return sizes


def measure_skew(
    executors: int, max_peer_rows: int, iterations: int,
    zipf_alpha: float = 1.2, quota_rows: int = 0, report=None,
) -> dict:
    """Measurement core of the ``skew`` mode — the quota-capped plan
    (ops/skew.py) vs the max-sized single-shot plan on a Zipf-skewed shuffle.

    The max plan stages every peer slot at the hottest lane's pow2 bucket (the
    ``bucket_send_rows`` behavior the quota exists to cap): one exchange, most
    of it padding.  The quota plan caps the slot at ``quota_rows`` (0 = the
    pow2 ceiling of the mean lane size) and chunks hot lanes across sub-round
    exchanges.  Both produce bit-identical receive bytes (asserted); the
    returned dict carries effective GB/s (useful bytes / wall time), staged
    rows, dense-lowering wire bytes, and padding fraction per plan — the
    measured table in docs/PERF.md.  ``report(plan, it, seconds, bytes)`` per
    iteration.  Shared by the CLI and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import (
        ExchangeSpec, bucket_send_rows, build_exchange, make_mesh,
    )
    from sparkucx_tpu.ops.skew import (
        chunk_size_rows, plan_exchange, quota_slot_rows, reassemble_round,
        slice_subround,
    )

    n = executors
    row_bytes = 512
    lane = row_bytes // 4
    sizes = zipf_size_matrix(n, max_peer_rows, zipf_alpha)
    slot = bucket_send_rows(int(sizes.max()) * n, n) // n  # the max plan's slot
    if quota_rows <= 0:
        quota_rows = int(quota_slot_rows(slot, int(np.ceil(sizes.mean()))))
    plan = plan_exchange([int(sizes.max())], slot, quota_rows)
    q = plan.slot_rows

    mesh = make_mesh(n)
    sharding = NamedSharding(mesh, P("ex", None))
    rng = np.random.default_rng(1)
    # slot-layout staging payload per sender, hot lanes filled to their size
    payloads = []
    for i in range(n):
        p = np.zeros((n * slot, lane), dtype=np.int32)
        for j in range(n):
            p[j * slot : j * slot + sizes[i, j]] = rng.integers(
                -100, 100, size=(int(sizes[i, j]), lane), dtype=np.int32
            )
        payloads.append(p)
    used_rows = int(sizes.sum())
    useful_bytes = used_rows * row_bytes

    def run_max():
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=lane
        )
        fn = build_exchange(mesh, spec)
        size_mat = jax.device_put(sizes, sharding)
        data_host = np.concatenate(payloads)

        def shot():
            data = jax.device_put(data_host, sharding)
            recv, rs = fn(data, size_mat)
            jax.block_until_ready(recv)
            return recv, rs

        recv, rs = shot()  # warmup/compile + the oracle output
        rs_host = np.asarray(rs)
        devices = list(mesh.devices.reshape(-1))
        by_device = {s.device: s.data for s in recv.addressable_shards}
        shards = [
            np.asarray(by_device[devices[j]]).reshape(-1).view(np.uint8)[
                : int(rs_host[j].sum()) * row_bytes
            ]
            for j in range(n)
        ]
        best = 0.0
        for it in range(iterations):
            t0 = time.perf_counter()
            shot()
            dt = time.perf_counter() - t0
            best = max(best, useful_bytes / dt / 1e9)
            if report is not None:
                report("max", it, dt, useful_bytes)
        staged = n * n * slot
        return shards, best, staged

    def run_quota():
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * q, recv_rows=n * q, lane=lane
        )
        fn = build_exchange(mesh, spec)
        nchunks = plan.chunks_per_round[0]
        sub_size_mats = [
            np.stack([chunk_size_rows(sizes[i], c, q) for i in range(n)])
            for c in range(nchunks)
        ]
        size_mats = [jax.device_put(m, sharding) for m in sub_size_mats]

        def shot():
            outs = []
            for c in range(nchunks):
                data = jax.device_put(
                    np.concatenate(
                        [slice_subround(p, n, c, q) for p in payloads]
                    ),
                    sharding,
                )
                recv, _ = fn(data, size_mats[c])
                outs.append(recv)
            jax.block_until_ready(outs[-1])
            return outs

        outs = shot()  # warmup/compile + the compared output
        devices = list(mesh.devices.reshape(-1))
        shards = []
        for j in range(n):
            # consumer j reassembles from column j (rows j received per sender)
            sub_sizes = [m[:, j] for m in sub_size_mats]
            sub_shards = [
                np.asarray(
                    next(s.data for s in o.addressable_shards if s.device == devices[j])
                ).reshape(-1).view(np.uint8)
                for o in outs
            ]
            shards.append(reassemble_round(sub_shards, sub_sizes, row_bytes))
        best = 0.0
        for it in range(iterations):
            t0 = time.perf_counter()
            shot()
            dt = time.perf_counter() - t0
            best = max(best, useful_bytes / dt / 1e9)
            if report is not None:
                report("quota", it, dt, useful_bytes)
        return shards, best, plan.staged_rows(n)

    max_shards, max_gbps, max_staged = run_max()
    quota_shards, quota_gbps, quota_staged = run_quota()
    for j in range(n):
        assert bytes(quota_shards[j]) == bytes(max_shards[j]), (
            f"quota plan diverged from single-shot on consumer {j}"
        )
    return {
        "executors": n,
        "zipf_alpha": zipf_alpha,
        "max_peer_rows": int(sizes.max()),
        "quota_slot": q,
        "subrounds": plan.num_subrounds,
        "used_rows": used_rows,
        "bit_identical": True,
        "max": {
            "gbps": max_gbps,
            "staged_rows": max_staged,
            "wire_bytes": max_staged * row_bytes,
            "padding_fraction": 1.0 - used_rows / max_staged,
        },
        "quota": {
            "gbps": quota_gbps,
            "staged_rows": quota_staged,
            "wire_bytes": quota_staged * row_bytes,
            "padding_fraction": 1.0 - used_rows / quota_staged,
        },
    }


def run_skew(args) -> None:
    size = parse_size(args.block_size)
    max_peer_rows = max(1, size // 512)

    def report(plan, it, dt, tot):
        print(
            f"{plan} iter {it}: {tot} useful bytes in {dt*1e3:.1f} ms = "
            f"{tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_skew(
        args.executors, max_peer_rows, args.iterations,
        zipf_alpha=args.zipf_alpha, quota_rows=args.quota, report=report,
    )
    print(
        f"zipf(alpha={r['zipf_alpha']}) over {r['executors']} executors: "
        f"hottest lane {r['max_peer_rows']} rows, quota slot {r['quota_slot']} "
        f"rows, {r['subrounds']} sub-rounds",
        flush=True,
    )
    for plan in ("max", "quota"):
        p = r[plan]
        print(
            f"{plan:5} plan: {p['gbps']:.2f} GB/s effective, "
            f"{p['staged_rows']} staged rows, {p['wire_bytes']} wire bytes "
            f"(dense), padding {p['padding_fraction']:.1%}",
            flush=True,
        )
    staged_cut = r["max"]["staged_rows"] / max(r["quota"]["staged_rows"], 1)
    print(
        f"quota plan stages {staged_cut:.2f}x fewer rows; outputs bit-identical",
        flush=True,
    )


def measure_adaptive(
    executors: int = 8, max_peer_rows: int = 2048, iterations: int = 2,
    link_gbps: float = 1.0, stall_ms: float = 40.0, report=None,
) -> dict:
    """Measurement core of the ``adaptive`` mode — the telemetry-fed
    AdaptivePlanner (ops/planner.py) against every static configuration on a
    skew x payload-entropy x fault cell matrix.

    Per cell the EXCHANGE leg is measured (the same machinery as
    ``measure_skew``: compiled collective over the loopback mesh, best-of-N
    wall time, bit-equality of every chunked schedule's reassembled shards
    against the single-shot reference), while the SERVE-plane legs are
    modeled from measured inputs, because loopback has no real wire: codec
    cost = measured ``encode_chunk`` time + shipped bytes / ``link_gbps``
    (encoded bytes measured per cell payload), and the fault cell charges a
    gray straggler of ``5 x stall_ms`` to any config that does not hedge,
    vs ``hedge_ms + one peer-shard refetch`` for one that does (the
    docs/PERF.md hedged-fetch measurements are the grounding for that shape).

    Static candidates: quota arms {single-shot, the adaptive quota formula's
    pick, 2x it} x codec {off, rle}, all with hedging off — the legacy knob
    grid an operator would sweep by hand.  The adaptive arm builds real
    ``PlanSignals`` per cell (observed compression ratio from the sample
    encode; the fault cell's stall tail and degraded peer health) and
    executes whatever plan ``AdaptivePlanner`` returns.  Reported per cell:
    every arm's effective GB/s, the static oracle (best arm), the adaptive
    arm's distance from it, and the plan fields it chose; aggregate = mean
    GB/s over cells, adaptive vs each static config held fixed across the
    matrix.  Shared by the CLI and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.ops.compress import CompressSpec, encode_chunk
    from sparkucx_tpu.ops.exchange import (
        ExchangeSpec, bucket_send_rows, build_exchange, make_mesh,
    )
    from sparkucx_tpu.ops.planner import AdaptivePlanner, PlanContext, PlanSignals
    from sparkucx_tpu.ops.skew import (
        chunk_size_rows, plan_exchange, reassemble_round, slice_subround,
    )

    n = executors
    row_bytes = 512
    lane = row_bytes // 4
    mesh = make_mesh(n)
    sharding = NamedSharding(mesh, P("ex", None))
    fns: dict = {}

    def exchange_fn(rows):
        fn = fns.get(rows)
        if fn is None:
            fn = fns[rows] = build_exchange(
                mesh,
                ExchangeSpec(num_executors=n, send_rows=rows, recv_rows=rows, lane=lane),
            )
        return fn

    def prepare_arm(payloads, sizes, slot, quota):
        """Build one quota arm's exchange leg: compiled schedule, warmed up,
        reassembled tight shards for the bit-equality gate.  Returns a dict
        with the replayable ``shot`` thunk (timed later, INTERLEAVED across
        arms — back-to-back per-arm loops pick up correlated scheduler noise
        on the loopback CPU mesh).  quota == 0 is the single-shot arm (one
        chunk at the full slot)."""
        plan = plan_exchange([int(sizes.max())], slot, quota)
        q, nchunks = plan.slot_rows, plan.chunks_per_round[0]
        fn = exchange_fn(n * q)
        sub_size_mats = [
            np.stack([chunk_size_rows(sizes[i], c, q) for i in range(n)])
            for c in range(nchunks)
        ]
        size_mats = [jax.device_put(m, sharding) for m in sub_size_mats]
        sub_payloads = [
            np.concatenate([slice_subround(p, n, c, q) for p in payloads])
            for c in range(nchunks)
        ]

        def shot():
            outs = []
            for c in range(nchunks):
                recv, _ = fn(jax.device_put(sub_payloads[c], sharding), size_mats[c])
                outs.append(recv)
            jax.block_until_ready(outs[-1])
            return outs

        outs = shot()  # warmup/compile + the compared output
        devices = list(mesh.devices.reshape(-1))
        shards = []
        for j in range(n):
            sub_shards = [
                np.asarray(
                    next(s.data for s in o.addressable_shards if s.device == devices[j])
                ).reshape(-1).view(np.uint8)
                for o in outs
            ]
            shards.append(
                bytes(reassemble_round(sub_shards, [m[:, j] for m in sub_size_mats], row_bytes))
            )
        return {
            "shot": shot,
            "shards": shards,
            "staged": plan.staged_rows(n),
            "best": float("inf"),
        }

    rle = CompressSpec(codec="rle", min_chunk_bytes=0)
    straggler_s = 5.0 * stall_ms / 1e3  # gray tail: well past the p99 signal

    def serve_time(raw_bytes, enc_bytes, enc_s, codec, hedge_ms, fault):
        ship = enc_bytes if codec != "off" else raw_bytes
        t = ship / (link_gbps * 1e9) + (enc_s if codec != "off" else 0.0)
        if fault == "degraded":
            if hedge_ms <= 0:
                t += straggler_s
            else:
                t += min(straggler_s, hedge_ms / 1e3) + (
                    raw_bytes / n / (link_gbps * 1e9)
                )
        return t

    cells = []
    rng = np.random.default_rng(3)
    base = 512  # pow2 floor of the requested hottest lane, min 512
    while base * 2 <= max_peer_rows:
        base *= 2
    for alpha in (0.0, 1.8):
        # balanced cells stage padding-free at a pow2 hottest lane; skewed
        # cells put the hottest lane just past the pow2 boundary — the
        # geometry where chunking beats the single-shot round-up (the same
        # regime the docs/PERF.md skew table pins)
        hot = base if alpha == 0.0 else base * 5 // 4
        sizes = zipf_size_matrix(n, hot, alpha)
        slot = bucket_send_rows(int(sizes.max()) * n, n) // n
        used_rows = int(sizes.sum())
        useful = used_rows * row_bytes
        # static quota candidates keep only DISTINCT footprints: a quota whose
        # chunked schedule stages exactly the single-shot row count moves the
        # same bytes in more launches — same config class, and its loopback
        # delta is dispatch granularity (CPU cache effects), not plan quality
        single_staged = plan_exchange([int(sizes.max())], slot, 0).staged_rows(n)
        quotas = sorted(
            q
            for q in {0, max(256, slot // 4), max(256, slot // 2)}
            if q == 0
            or plan_exchange([int(sizes.max())], slot, q).staged_rows(n) < single_staged
        )
        for entropy in ("low", "high"):
            # slot-layout staging payloads: zeros (RLE-collapsible) vs
            # full-range random rows (incompressible — RLE ships raw)
            payloads = []
            for i in range(n):
                p = np.zeros((n * slot, lane), dtype=np.int32)
                if entropy == "high":
                    for j in range(n):
                        p[j * slot : j * slot + sizes[i, j]] = rng.integers(
                            -(2**30), 2**30, size=(int(sizes[i, j]), lane), dtype=np.int32
                        )
                payloads.append(p)
            # arms cached by REALIZED schedule (slot, chunks): distinct conf
            # quotas that lower to the same sub-round schedule share one
            # measurement, so identical schedules can't diverge by CPU noise
            arm_cache: dict = {}

            def arm(quota):
                p = plan_exchange([int(sizes.max())], slot, quota)
                key = (p.slot_rows, p.chunks_per_round[0])
                if key not in arm_cache:
                    arm_cache[key] = prepare_arm(payloads, sizes, slot, quota)
                return arm_cache[key]

            conf = TpuShuffleConf(
                planner_mode="adaptive",
                wire_compress_codec="rle",
                fetch_hedge_ms=1,
                fetch_hedge_max_ms=int(stall_ms * 4),
            )

            def plan_ctx(signals):
                return PlanContext(
                    num_executors=n,
                    staging_slot_rows=slot,
                    round_max_rows=(int(sizes.max()),),
                    used_rows_total=used_rows,
                    row_bytes=row_bytes,
                    platform="cpu",
                    signals=signals,
                )

            # the adaptive quota is geometry-only (SPMD lockstep discipline),
            # so it is known before any fault cell: prepare its arm alongside
            # the static candidates, then bit-equality-gate every schedule
            neutral = AdaptivePlanner(conf).plan(plan_ctx(PlanSignals()))
            ad_q = 0 if neutral.single_shot else neutral.slot_rows
            ref = arm(0)["shards"]  # single-shot reference shards
            for q in sorted(set(quotas) | {ad_q}):
                shards = arm(q)["shards"]
                for j in range(n):
                    assert shards[j] == ref[j], (
                        f"quota {q} diverged from single-shot on consumer {j}"
                    )
            # interleaved best-of timing: one pass times every arm once, so
            # slow-drift scheduler noise hits all arms alike
            for _ in range(max(2, iterations)):
                for a in arm_cache.values():
                    t0 = time.perf_counter()
                    a["shot"]()
                    a["best"] = min(a["best"], time.perf_counter() - t0)
            # measured codec leg on the reference shards (what the serve
            # plane would ship): encoded bytes + encode seconds
            enc_bytes, t0 = 0, time.perf_counter()
            for shard in ref:
                _, enc = encode_chunk(rle, shard)
                enc_bytes += len(enc) if enc is not None else len(shard)
            enc_s = time.perf_counter() - t0
            for fault in ("none", "degraded"):
                statics = {}
                for q in quotas:
                    ex_s = arm(q)["best"]
                    for codec in ("off", "rle"):
                        name = f"{'single' if q == 0 else f'q{q}'}/{codec}"
                        t = ex_s + serve_time(useful, enc_bytes, enc_s, codec, 0, fault)
                        statics[name] = useful / t / 1e9
                signals = PlanSignals(
                    rx_stall_p99_ns=int(stall_ms * 1e6) if fault == "degraded" else 0,
                    worst_peer_health=0.3 if fault == "degraded" else 1.0,
                    compression_ratio=useful / max(enc_bytes, 1),
                )
                plan = AdaptivePlanner(conf).plan(plan_ctx(signals))
                assert (0 if plan.single_shot else plan.slot_rows) == ad_q
                ad_ex_s = arm(ad_q)["best"]
                hedge = plan.hedge_ms if fault == "degraded" else 0
                ad_t = ad_ex_s + serve_time(
                    useful, enc_bytes, enc_s, plan.codec, hedge, fault
                )
                ad_gbps = useful / ad_t / 1e9
                oracle_name, oracle_gbps = max(statics.items(), key=lambda kv: kv[1])
                cell = {
                    "alpha": alpha,
                    "entropy": entropy,
                    "fault": fault,
                    "static_gbps": {k: round(v, 4) for k, v in statics.items()},
                    "oracle": oracle_name,
                    "oracle_gbps": round(oracle_gbps, 4),
                    "adaptive_gbps": round(ad_gbps, 4),
                    "distance_from_oracle": round(1.0 - ad_gbps / oracle_gbps, 4),
                    "adaptive_choice": {
                        "quota": ad_q,
                        "codec": plan.codec,
                        "hedge_ms": plan.hedge_ms,
                        "subrounds": plan.num_subrounds,
                    },
                    "bit_identical": True,
                }
                cells.append(cell)
                if report is not None:
                    report(cell)
    # aggregate: each static config held fixed across the whole matrix vs
    # the adaptive planner re-planning per cell
    static_names = sorted({k for c in cells for k in c["static_gbps"]})
    agg_static = {
        name: sum(c["static_gbps"].get(name, 0.0) for c in cells) / len(cells)
        for name in static_names
    }
    agg_adaptive = sum(c["adaptive_gbps"] for c in cells) / len(cells)
    best_static = max(agg_static.items(), key=lambda kv: kv[1])
    return {
        "executors": n,
        "max_peer_rows": max_peer_rows,
        "link_gbps_model": link_gbps,
        "stall_ms_model": stall_ms,
        "cells": cells,
        "aggregate_static_gbps": {k: round(v, 4) for k, v in agg_static.items()},
        "aggregate_adaptive_gbps": round(agg_adaptive, 4),
        "best_static": best_static[0],
        "best_static_gbps": round(best_static[1], 4),
        "adaptive_beats_every_static": agg_adaptive >= best_static[1],
        "worst_cell_distance": round(
            max(c["distance_from_oracle"] for c in cells), 4
        ),
    }


def run_adaptive(args) -> None:
    size = parse_size(args.block_size)
    max_peer_rows = max(512, size // 512)

    def report(cell):
        print(
            f"cell alpha={cell['alpha']} entropy={cell['entropy']} "
            f"fault={cell['fault']}: adaptive {cell['adaptive_gbps']:.3f} GB/s "
            f"(chose quota={cell['adaptive_choice']['quota']} "
            f"codec={cell['adaptive_choice']['codec']} "
            f"hedge={cell['adaptive_choice']['hedge_ms']}ms) vs oracle "
            f"{cell['oracle']} {cell['oracle_gbps']:.3f} GB/s "
            f"(distance {cell['distance_from_oracle']:+.1%})",
            flush=True,
        )

    r = measure_adaptive(
        args.executors, max_peer_rows, args.iterations, report=report
    )
    print(
        f"aggregate over {len(r['cells'])} cells: adaptive "
        f"{r['aggregate_adaptive_gbps']:.3f} GB/s vs best static "
        f"{r['best_static']} {r['best_static_gbps']:.3f} GB/s "
        f"(beats every static: {r['adaptive_beats_every_static']}); "
        f"worst cell distance {r['worst_cell_distance']:+.1%}; "
        f"outputs bit-identical",
        flush=True,
    )


def measure_ici(
    executors_list=(2, 4, 8), slot_rows: int = 1024, lane: int = 128,
    chunks_per_dest: int = 0, iterations: int = 5, report=None, stats=None,
) -> dict:
    """Measurement core of the ``ici`` mode — the FAST-scheduled ring exchange
    (ops/ici_exchange.py) head-to-head against the stock collective
    (ops/exchange.py) at each mesh width in ``executors_list`` (clamped to the
    devices actually present).

    Per width: both impls are compiled over the same mesh, fed identical
    seeded slot-layout payloads with ragged per-peer sizes, asserted
    bit-identical (recv bytes AND recv_sizes), then timed over chained
    donated iterations.  Bandwidth is reported two ways: aggregate GB/s
    (remote bytes / wall) and per-link GB/s (a width-n bidirectional ring has
    2n directed ICI links, so per-link = aggregate / 2n — the number that maps
    onto a chip's per-direction ICI bandwidth).  Per-superstep span and link
    occupancy land in ``stats`` (utils/stats.py StatsAggregator,
    ``record_counters`` under kind ``ici_n{n}``): supersteps per exchange,
    busy/idle directed-link slots from ``step_occupancy``, and the measured
    mean span per superstep.  The fused send side
    (build_fused_ici_exchange: block scatter + exchange, ONE launch) is
    checked at the widest mesh against the two-launch scatter-then-exchange
    reference — bit-equality asserted, staging-launch elimination recorded.
    ``report(impl, n, it, seconds, bytes)`` per iteration.  Shared by the CLI
    and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh
    from sparkucx_tpu.ops.ici_exchange import (
        DEFAULT_CHUNKS_PER_DEST,
        build_fused_ici_exchange,
        build_ici_exchange,
        schedule_chunks,
        step_occupancy,
    )

    if chunks_per_dest <= 0:
        chunks_per_dest = DEFAULT_CHUNKS_PER_DEST
    avail = jax.device_count()
    widths = sorted({n for n in executors_list if 2 <= n <= avail})
    if not widths:
        raise RuntimeError(
            f"ici mode needs >=2 devices (have {avail}); widths {executors_list}"
        )
    row_bytes = lane * 4
    per_n: dict = {}
    for n in widths:
        slot = max(chunks_per_dest, slot_rows)
        chunks = schedule_chunks(slot, chunks_per_dest)
        send_rows = n * slot
        spec = ExchangeSpec(
            num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=lane
        )
        mesh = make_mesh(n)
        sharding = NamedSharding(mesh, P("ex", None))
        stock = build_exchange(mesh, spec)
        pallas = build_ici_exchange(mesh, spec, chunks_per_dest=chunks_per_dest)
        sched = pallas.schedule

        rng = np.random.default_rng(7)
        sizes_host = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
        data_host = rng.integers(
            -100, 100, size=(n * send_rows, lane), dtype=np.int32
        )
        sizes = jax.device_put(sizes_host, sharding)

        def shot(fn):
            data = jax.device_put(data_host, sharding)
            recv, rs = fn(data, sizes)
            jax.block_until_ready(recv)
            return np.asarray(recv), np.asarray(rs)

        recv_s, rs_s = shot(stock)  # warmup/compile + oracle
        recv_p, rs_p = shot(pallas)
        assert np.array_equal(rs_s, rs_p), f"recv_sizes diverged at n={n}"
        assert recv_s.tobytes() == recv_p.tobytes(), (
            f"scheduled exchange diverged from stock at n={n}"
        )
        # every device ships (n-1) remote slots per exchange; local slot is
        # a same-chip copy, not ICI traffic
        remote_bytes = n * (n - 1) * slot * row_bytes

        def time_impl(name, fn):
            best = 0.0
            for it in range(iterations):
                data = jax.device_put(data_host, sharding)
                t0 = time.perf_counter()
                cur = data
                for _ in range(4):  # chained: donation recycles the buffer
                    cur, _ = fn(cur, sizes)
                jax.block_until_ready(cur)
                dt = time.perf_counter() - t0
                best = max(best, 4 * remote_bytes / dt / 1e9)
                if report is not None:
                    report(name, n, it, dt, 4 * remote_bytes)
            return best

        stock_gbps = time_impl("stock", stock)
        pallas_gbps = time_impl("pallas", pallas)
        occ = step_occupancy(sched)
        if stats is not None:
            span_ns = int(remote_bytes / max(pallas_gbps, 1e-9) / sched.num_steps)
            stats.record_counters(
                f"ici_n{n}",
                supersteps=sched.num_steps,
                busy_link_slots=sum(b for b, _ in occ),
                idle_link_slots=sum(i for _, i in occ),
                superstep_span_ns=span_ns,
            )
            used = int(sizes_host.sum())
            stats.record_rows(f"ici_n{n}", used, n * n * slot - used)
        per_n[n] = {
            "stock_gbps": stock_gbps,
            "pallas_gbps": pallas_gbps,
            "pallas_per_link_gbps": pallas_gbps / (2 * n),
            "stock_per_link_gbps": stock_gbps / (2 * n),
            "supersteps": sched.num_steps,
            "chunks": sched.chunks,
            "lowering": pallas.lowering,
            "bit_identical": True,
        }

    # Fused send side at the widest mesh: scatter + exchange in one launch
    # vs the two-launch reference (host-built staged layout -> stock fn).
    n = widths[-1]
    slot = max(chunks_per_dest, slot_rows)
    send_rows = n * slot
    spec = ExchangeSpec(
        num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=lane
    )
    mesh = make_mesh(n)
    sharding = NamedSharding(mesh, P("ex", None))
    rng = np.random.default_rng(11)
    sizes_host = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
    # one block per destination: packed rows consecutive per sender, scattered
    # to the head of each destination slot (build_block_scatter plan triple)
    starts = np.zeros((n, n), dtype=np.int32)
    counts = np.zeros((n, n), dtype=np.int32)
    outs = np.zeros((n, n), dtype=np.int32)
    packed_host = np.zeros((n * send_rows, lane), dtype=np.int32)
    staged_ref = np.zeros((n * send_rows, lane), dtype=np.int32)
    for i in range(n):
        off = 0
        for j in range(n):
            c = int(sizes_host[i, j])
            rows = rng.integers(-100, 100, size=(c, lane), dtype=np.int32)
            packed_host[i * send_rows + off : i * send_rows + off + c] = rows
            staged_ref[i * send_rows + j * slot : i * send_rows + j * slot + c] = rows
            starts[i, j], counts[i, j], outs[i, j] = j * slot, c, off
            off += c
    fused = build_fused_ici_exchange(
        mesh, spec, n, chunks_per_dest=chunks_per_dest, max_block_rows=slot
    )
    stock = build_exchange(mesh, spec)
    sizes = jax.device_put(sizes_host, sharding)
    recv_ref, rs_ref = stock(jax.device_put(staged_ref, sharding), sizes)
    recv_f, rs_f = fused(
        jax.device_put(starts, sharding),
        jax.device_put(counts, sharding),
        jax.device_put(outs, sharding),
        jax.device_put(packed_host, sharding),
        jax.device_put(np.zeros((n * send_rows, lane), dtype=np.int32), sharding),
        sizes,
    )
    assert np.array_equal(np.asarray(rs_ref), np.asarray(rs_f)), (
        "fused recv_sizes diverged"
    )
    assert np.asarray(recv_ref).tobytes() == np.asarray(recv_f).tobytes(), (
        "fused scatter+exchange diverged from scatter-then-exchange"
    )
    return {
        "slot_rows": max(chunks_per_dest, slot_rows),
        "chunks_per_dest": chunks_per_dest,
        "per_n": per_n,
        "fused": {
            "executors": n,
            "bit_identical": True,
            # one jitted launch covers scatter AND exchange; the reference
            # needs a separate staging launch before its exchange
            "launches": 1,
            "reference_launches": 2,
        },
    }


def run_ici(args) -> None:
    from sparkucx_tpu.utils.stats import StatsAggregator

    size = parse_size(args.block_size)
    slot_rows = max(1, size // 512)
    stats = StatsAggregator()

    def report(impl, n, it, dt, tot):
        print(
            f"n={n} {impl:6} iter {it}: {tot} remote bytes in {dt*1e3:.1f} ms "
            f"= {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    widths = (2, 4, 8) if args.executors <= 1 else (args.executors,)
    r = measure_ici(
        widths, slot_rows, 128, chunks_per_dest=args.chunks,
        iterations=args.iterations, report=report, stats=stats,
    )
    print(
        f"slot {r['slot_rows']} rows, {r['chunks_per_dest']} chunks/dest "
        f"requested",
        flush=True,
    )
    for n, p in sorted(r["per_n"].items()):
        print(
            f"n={n}: stock {p['stock_gbps']:.2f} GB/s, pallas "
            f"{p['pallas_gbps']:.2f} GB/s ({p['pallas_per_link_gbps']:.3f} "
            f"GB/s/link over {2*n} links), {p['supersteps']} supersteps x "
            f"{p['chunks']} chunks [{p['lowering']}]; bit-identical",
            flush=True,
        )
    f = r["fused"]
    print(
        f"fused send side (n={f['executors']}): scatter+exchange in "
        f"{f['launches']} launch vs {f['reference_launches']} "
        f"(separate staging launch eliminated); bit-identical",
        flush=True,
    )
    print(stats.report(), flush=True)


def measure_combine(
    executors: int = 8, slot_rows: int = 1024, num_groups: int = 128,
    iterations: int = 5, chunks_per_dest: int = 0, report=None,
) -> dict:
    """Measurement core of the ``combine`` mode — the receive-side fused
    combine (ops/ici_exchange.build_combine_exchange) against the unfused
    reference: the same FAST-scheduled exchange followed by a SEPARATE fold
    launch over the landed O(rows) grid.

    Both sides are fed identical seeded partial-aggregate rows (``[key |
    sum/min/max/avg lanes | count]``, keys in ``[0, num_groups)``) with
    ragged per-peer sizes; the fused accumulator is asserted BIT-IDENTICAL
    to the reference fold off the clock (int32 folds are order-exact), then
    both are timed over chained donated iterations.  The two headline
    numbers of the compute-in-exchange argument land in the result dict:

    * ``drain``: the reference drains the landed grid — ``n * slot_rows *
      lane * 4`` B per device, O(rows) — where the fused side drains only
      the accumulator (``CombineSpec.acc_bytes``, O(groups));
    * ``launches``: the fused exchange+fold is ONE jitted launch (one
      Pallas kernel under the DMA lowering) vs the reference's exchange
      launch plus fold launch, with one dispatch per schedule item inside
      the scheduled-XLA walk.

    ``report(impl, it, seconds, bytes)`` per iteration.  Shared by the CLI
    and bench.py."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops._compat import shard_map
    from sparkucx_tpu.ops.combine import CombineSpec, acc_init, combine_window
    from sparkucx_tpu.ops.exchange import ExchangeSpec, make_mesh
    from sparkucx_tpu.ops.ici_exchange import (
        DEFAULT_CHUNKS_PER_DEST,
        build_combine_exchange,
        build_ici_exchange,
    )

    if chunks_per_dest <= 0:
        chunks_per_dest = DEFAULT_CHUNKS_PER_DEST
    avail = jax.device_count()
    n = min(executors, avail)
    if n < 2:
        raise RuntimeError(f"combine mode needs >=2 devices (have {avail})")
    cspec = CombineSpec(num_groups=num_groups, aggs=("sum", "min", "max", "avg"))
    lane = cspec.row_width
    slot = max(chunks_per_dest, slot_rows)
    send_rows = n * slot
    spec = ExchangeSpec(
        num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=lane
    )
    mesh = make_mesh(n)
    pspec = P("ex", None)
    sharding = NamedSharding(mesh, pspec)
    fused = build_combine_exchange(mesh, spec, cspec, chunks_per_dest=chunks_per_dest)
    ref_ex = build_ici_exchange(mesh, spec, chunks_per_dest=chunks_per_dest)

    # the reference's post-exchange fold: a second launch over the landed
    # grid (int32 folds are order-insensitive, so one whole-grid window
    # reproduces the fused canonical order bit-exactly)
    def _fold(grid):
        return combine_window(cspec, grid, *acc_init(cspec))

    fold = jax.jit(
        shard_map(
            _fold, mesh=mesh, in_specs=(pspec,), out_specs=(pspec, pspec),
            check_vma=False,
        ),
        in_shardings=(sharding,),
        out_shardings=(sharding, sharding),
    )

    # seeded partial rows: every staged row is a real partial (count >= 1)
    # up to its ragged per-peer size; padding rows stay all-zero (count 0)
    rng = np.random.default_rng(23)
    sizes_host = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
    data_host = np.zeros((n * send_rows, lane), dtype=np.int32)
    for i in range(n):
        for j in range(n):
            c = int(sizes_host[i, j])
            base = i * send_rows + j * slot
            data_host[base : base + c, 0] = rng.integers(0, num_groups, size=c)
            data_host[base : base + c, 1:-1] = rng.integers(
                -100, 100, size=(c, cspec.width)
            )
            data_host[base : base + c, -1] = rng.integers(1, 5, size=c)
    av0, ac0 = acc_init(cspec)
    av_host = np.tile(np.asarray(av0), (n, 1))
    ac_host = np.tile(np.asarray(ac0), (n, 1))
    sizes = jax.device_put(sizes_host, sharding)
    data = jax.device_put(data_host, sharding)

    # warmup/compile + off-clock bit-equality: fused fold vs exchange-then-fold
    recv, rs_ref = ref_ex(jax.device_put(data_host, sharding), sizes)
    rv_ref, rc_ref = fold(recv)
    fv, fc, rs_f = fused(
        data, sizes,
        jax.device_put(av_host, sharding), jax.device_put(ac_host, sharding),
    )
    assert np.array_equal(np.asarray(rs_ref), np.asarray(rs_f)), (
        "fused recv_sizes diverged from the scheduled exchange"
    )
    assert np.asarray(rv_ref).tobytes() == np.asarray(fv).tobytes(), (
        "fused accumulator values diverged from exchange-then-fold"
    )
    assert np.asarray(rc_ref).tobytes() == np.asarray(fc).tobytes(), (
        "fused accumulator counts diverged from exchange-then-fold"
    )

    remote_bytes = n * (n - 1) * slot * lane * 4

    def time_fused():
        best = 0.0
        for it in range(iterations):
            av = jax.device_put(av_host, sharding)
            ac = jax.device_put(ac_host, sharding)
            t0 = time.perf_counter()
            for _ in range(4):  # chained: the donated accumulator recycles
                av, ac, _ = fused(data, sizes, av, ac)
            jax.block_until_ready(av)
            dt = time.perf_counter() - t0
            best = max(best, 4 * remote_bytes / dt / 1e9)
            if report is not None:
                report("fused", it, dt, 4 * remote_bytes)
        return best

    def time_reference():
        best = 0.0
        for it in range(iterations):
            cur = jax.device_put(data_host, sharding)
            t0 = time.perf_counter()
            for _ in range(4):  # chained: exchange donates, then the fold
                cur, _ = ref_ex(cur, sizes)
                accs = fold(cur)
            jax.block_until_ready(accs)
            dt = time.perf_counter() - t0
            best = max(best, 4 * remote_bytes / dt / 1e9)
            if report is not None:
                report("unfused", it, dt, 4 * remote_bytes)
        return best

    fused_gbps = time_fused()
    ref_gbps = time_reference()
    sched = fused.schedule
    ref_drain = n * slot * lane * 4  # the landed grid, per device — O(rows)
    return {
        "executors": n,
        "slot_rows": slot,
        "groups": num_groups,
        "lane": lane,
        "lowering": fused.lowering,
        "supersteps": sched.num_steps,
        "chunks": sched.chunks,
        "fused_gbps": fused_gbps,
        "unfused_gbps": ref_gbps,
        "bit_identical": True,
        "drain": {
            "reference_bytes": ref_drain,
            "fused_bytes": cspec.acc_bytes,
            "ratio": ref_drain / cspec.acc_bytes,
        },
        # one jitted launch folds windows as they land (one Pallas kernel
        # under the DMA lowering); the reference needs its exchange launch
        # plus a separate fold launch, with one dispatch per schedule item
        # inside the scheduled-XLA walk
        "launches": 1,
        "reference_launches": 2,
        "reference_dispatches": len(sched.items()) + 1,
    }


def run_combine(args) -> None:
    size = parse_size(args.block_size)
    n = args.executors if args.executors > 1 else 8

    def report(impl, it, dt, tot):
        print(
            f"{impl:7} iter {it}: {tot} remote bytes in {dt*1e3:.1f} ms "
            f"= {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    r = measure_combine(
        n, max(1, size // 512), max(2, args.keys),
        iterations=args.iterations, chunks_per_dest=args.chunks, report=report,
    )
    d = r["drain"]
    print(
        f"n={r['executors']}: fused {r['fused_gbps']:.2f} GB/s vs unfused "
        f"{r['unfused_gbps']:.2f} GB/s, {r['supersteps']} supersteps x "
        f"{r['chunks']} chunks [{r['lowering']}]; bit-identical",
        flush=True,
    )
    print(
        f"drain per device: {d['reference_bytes']} B landed grid (O(rows)) -> "
        f"{d['fused_bytes']} B accumulator (O(groups)), {d['ratio']:.1f}x less",
        flush=True,
    )
    print(
        f"launches: exchange+fold in {r['launches']} vs "
        f"{r['reference_launches']} (separate fold launch eliminated; "
        f"{r['reference_dispatches']} scheduled dispatches collapse under "
        f"the DMA lowering)",
        flush=True,
    )


def run_write(args) -> None:
    size = parse_size(args.block_size)
    impls = (
        ("host", "device")
        if args.impl == "auto"
        else tuple(s.strip() for s in args.impl.split(",") if s.strip())
    )

    def report(impl, it, dt, tot):
        print(
            f"iter {it}: staged {args.num_blocks} x {size} B via {impl} path in "
            f"{dt*1e3:.1f} ms = {tot / dt / 1e9:.2f} GB/s",
            flush=True,
        )

    results = measure_write(
        args.num_blocks, size, args.iterations, impls=impls, report=report
    )
    host = results.get("host")
    for impl in impls:
        gbps = results[impl]
        speedup = f" ({gbps / host:.2f}x vs host)" if host and impl == "device" else ""
        print(f"write {impl}: {gbps:.2f} GB/s{speedup}", flush=True)


def measure_sort(
    executors: int, total_rows: int, iterations: int, report=None,
    outstanding: int = 8, sort_impl: str = "auto",
) -> float:
    """Measurement core of the ``sort`` mode — device-resident TeraSort step
    (100 B rows: uint32 key + 24 int32 lanes; BASELINE.json configs[1]).
    Returns best M rows/s; ``report(it, seconds, rows, impl)`` per iteration.
    Shared by the CLI and bench.py.  ``outstanding`` independent steps are
    chained per sync so the tunnel's readback latency is amortized like the
    other modes (UcxPerfBenchmark.scala:129-151's outstanding window)."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, build_distributed_sort

    n = executors
    cap = -(-total_rows // n)
    # skew headroom only matters when splitters can misjudge a range; one
    # executor owns the whole range, so n=1 needs none (and the 'single'
    # lowering then skips the output pad copy entirely)
    spec = SortSpec(
        num_executors=n, capacity=cap, recv_capacity=2 * cap if n > 1 else cap,
        width=24, impl=sort_impl,
    )
    mesh = make_mesh(n)
    fn = build_distributed_sort(mesh, spec)
    rng = np.random.default_rng(0)
    keys = jax.device_put(
        rng.integers(0, 1 << 32, size=n * cap, dtype=np.uint32),
        NamedSharding(mesh, P("ex")),
    )
    payload = jax.device_put(
        np.zeros((n * cap, 24), np.int32), NamedSharding(mesh, P("ex", None))
    )
    nv = jax.device_put(
        np.full(n, cap, np.int32), NamedSharding(mesh, P("ex"))
    )
    out = jax.block_until_ready(fn(keys, payload, nv))  # compile
    assert int(np.asarray(out[2]).sum()) == n * cap, "sort dropped rows"
    best = 0.0
    for it in range(iterations):
        t0 = time.perf_counter()
        for _ in range(outstanding):
            out = fn(keys, payload, nv)
        jax.block_until_ready(out)
        np.asarray(out[0][:4])  # force completion through async tunnels
        dt = time.perf_counter() - t0
        rows = outstanding * n * cap
        best = max(best, rows / dt / 1e6)
        if report is not None:
            report(it, dt, rows, fn.spec.impl)
    return best


def measure_columnar(
    executors: int, total_rows: int, width: int, iterations: int,
    outstanding: int = 8, report=None,
) -> float:
    """Measurement core of the ``columnar`` mode — the device-resident columnar
    shuffle (the GpuColumnarExchange analogue, ops/columnar.py): rows already
    in HBM are repartitioned by a random owner vector, no host round-trip.
    Returns best GB/s of rows moved; ``report(it, seconds, bytes, impl)`` per
    iteration."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.columnar import ColumnarSpec, build_columnar_shuffle
    from sparkucx_tpu.ops.exchange import make_mesh

    n = executors
    cap = -(-total_rows // n)
    # worst-case skew headroom: all rows could land on one executor only when
    # n == 1; for n > 1 use 2x balanced (random owners stay well inside it)
    spec = ColumnarSpec(
        num_executors=n, capacity=cap,
        recv_capacity=cap if n == 1 else 2 * cap, width=width,
    )
    mesh = make_mesh(n)
    fn = build_columnar_shuffle(mesh, spec)
    rng = np.random.default_rng(0)
    rows = jax.device_put(
        rng.normal(size=(n * cap, width)).astype(np.float32),
        NamedSharding(mesh, P("ex", None)),
    )
    owners = jax.device_put(
        rng.integers(0, n, size=n * cap).astype(np.int32),
        NamedSharding(mesh, P("ex")),
    )
    recv, counts = fn(rows, owners)
    jax.block_until_ready(recv)  # compile
    assert int(np.asarray(counts).sum()) == n * cap, "columnar shuffle dropped rows"
    moved = n * cap * width * 4
    best = 0.0
    for it in range(iterations):
        t0 = time.perf_counter()
        for _ in range(outstanding):
            recv, counts = fn(rows, owners)
        jax.block_until_ready(recv)
        np.asarray(recv[0, :1])  # force completion through async tunnels
        dt = time.perf_counter() - t0
        tot = moved * outstanding
        best = max(best, tot / dt / 1e9)
        if report is not None:
            report(it, dt, tot, fn.spec.impl)
    return best


def measure_groupby(
    executors: int, total_rows: int, iterations: int,
    outstanding: int = 8, num_keys: int = 100, report=None,
    partial: bool = False, wire_rows=None,
) -> float:
    """Measurement core of the ``groupby`` mode — the device-resident GROUP BY
    (100 B rows: uint32 key + 24 summed int32 lanes; the GroupByTest workload
    shape, BASELINE.json configs[0]).  Returns best M input rows/s;
    ``report(it, seconds, rows, impl)`` per iteration.  Shared by the CLI and
    bench.py like measure_sort.  ``partial`` enables map-side partial
    aggregation below the exchange (conf ``partialAggregation``);
    ``wire_rows``, if a list, receives the TRUE exchanged row count — the
    before/after traffic comparison is ``total_rows`` vs that number."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        AggregateSpec, build_grouped_aggregate, hash_owners_host,
    )

    n = executors
    cap = -(-total_rows // n)
    rng = np.random.default_rng(0)
    host_keys = rng.integers(0, num_keys, size=n * cap).astype(np.uint32)
    # Size receive buffers from the ACTUAL hash placement (like measure_join):
    # per-shard key granularity concentrates rows far past any fixed headroom
    # when num_keys is small relative to n.  The overflow assert below then
    # guards host/device placement agreement, not luck.  With partial
    # aggregation each sender exchanges at most one row per local distinct
    # key, so the placement twin counts per-sender distinct keys instead.
    if partial:
        per_owner = np.zeros(n, np.int64)
        for s in range(n):
            uk = np.unique(host_keys[s * cap : (s + 1) * cap])
            np.add.at(per_owner, hash_owners_host(uk, n), 1)
        recv = int(per_owner.max())
    else:
        recv = int(np.bincount(hash_owners_host(host_keys, n), minlength=n).max())
    spec = AggregateSpec(
        num_executors=n, capacity=cap, recv_capacity=recv,
        aggs=("sum",) * 24, partial=partial,
    )
    mesh = make_mesh(n)
    fn = build_grouped_aggregate(mesh, spec)
    keys = jax.device_put(host_keys, NamedSharding(mesh, P("ex")))
    # zeros like measure_sort's payload: the aggregation cost is value-
    # independent, and 200 MB of random host data would crawl through remote
    # device tunnels (the keys, which steer the exchange, stay random)
    values = jax.device_put(
        np.zeros((n * cap, 24), np.int32), NamedSharding(mesh, P("ex", None))
    )
    nv = jax.device_put(np.full(n, cap, np.int32), NamedSharding(mesh, P("ex")))
    out = jax.block_until_ready(fn(keys, values, nv))  # compile
    # overflow guard first (measure_sort's "dropped rows" check): hash skew
    # past the 2x headroom truncates shards — and can drop whole keys, which
    # would otherwise fire the group-count assert with a misleading message
    recv_totals = np.asarray(out[4])
    assert (recv_totals <= spec.recv_capacity).all(), (
        f"hash skew overflowed recv_capacity ({recv_totals.max()} > "
        f"{spec.recv_capacity}): use more --keys or fewer executors"
    )
    if wire_rows is not None:
        wire_rows.append(int(recv_totals.sum()))
    rows_aggregated = int(np.asarray(out[2]).sum())
    assert rows_aggregated == n * cap, (
        f"groupby dropped rows ({rows_aggregated} != {n * cap})"
    )
    got_groups = int(np.asarray(out[3]).sum())
    want_groups = len(np.unique(host_keys))
    assert got_groups == want_groups, (
        f"groupby produced {got_groups} groups, expected {want_groups}"
    )
    best = 0.0
    for it in range(iterations):
        t0 = time.perf_counter()
        for _ in range(outstanding):
            out = fn(keys, values, nv)
        jax.block_until_ready(out)
        np.asarray(out[0][:4])  # force completion through async tunnels
        dt = time.perf_counter() - t0
        rows = outstanding * n * cap
        best = max(best, rows / dt / 1e6)
        if report is not None:
            report(it, dt, rows, fn.spec.impl)
    return best


def run_groupby(args) -> None:
    def report(it, dt, rows, impl):
        print(
            f"iter {it}: grouped {rows} x 100 B rows in {dt*1e3:.1f} ms = "
            f"{rows / dt / 1e6:.2f} M rows/s ({rows * 100 / dt / 1e9:.2f} GB/s) "
            f"[impl={impl}]",
            flush=True,
        )

    wire = []
    measure_groupby(
        args.executors, args.num_blocks, args.iterations,
        outstanding=args.outstanding, num_keys=args.keys, report=report,
        partial=args.partial, wire_rows=wire,
    )
    mode = "partial (map-side agg below the exchange)" if args.partial else "raw rows"
    print(
        f"exchange traffic [{mode}]: {wire[0]} rows on the wire for "
        f"{args.num_blocks} input rows ({args.num_blocks / max(wire[0], 1):.0f}x reduction)"
        if args.partial
        else f"exchange traffic [{mode}]: {wire[0]} rows on the wire",
        flush=True,
    )


def measure_join(
    executors: int, probe_rows: int, build_rows: int, iterations: int,
    outstanding: int = 8, report=None, join_type: str = "inner",
) -> float:
    """Measurement core of the ``join`` mode — the device-resident PK-FK hash
    join (TPC-H's plan shape, BASELINE.json configs[2]): ``build_rows``
    dimension rows with globally unique keys, ``probe_rows`` fact rows each
    referencing a key in [0, 2*build_rows) — half the probes hit, so every
    ``join_type`` arm (inner/left_outer/left_semi/left_anti/right_outer/
    full_outer) has real work on both its matched and unmatched branches.
    The expected output count is computed with numpy set logic and asserted.
    Returns best M probe rows/s; ``report(it, seconds, rows, impl)``."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        JoinSpec,
        build_hash_join,
        plan_join_capacities,
    )

    n = executors
    build_rows = build_rows or probe_rows // 4  # the CLI's documented default
    pcap = -(-probe_rows // n)
    bcap = -(-max(build_rows, n) // n)
    rng = np.random.default_rng(0)
    nb = n * bcap
    bkeys_h = rng.permutation(nb).astype(np.uint32)  # unique PKs, shuffled
    # FK keyspace = [0, 2*nb): ~half the probe rows match a PK, half miss
    pkeys_h = rng.integers(0, 2 * nb, size=n * pcap, dtype=np.uint64).astype(np.uint32)
    # Exact per-shard receive/output capacities from the host twin of the
    # device placement hash (plan_join_capacities) — the asserts below then
    # guard host/device placement agreement, not skew luck.
    brecv, precv, out_cap = plan_join_capacities(
        bkeys_h, pkeys_h, n, join_type=join_type
    )
    probe_hits = int(np.isin(pkeys_h, bkeys_h).sum())
    build_missed = int((~np.isin(bkeys_h, pkeys_h)).sum())
    expect = {
        "inner": probe_hits,
        "left_outer": n * pcap,                       # misses null-extend
        "left_semi": probe_hits,                      # unique PKs: 1 emit/hit
        "left_anti": n * pcap - probe_hits,
        "right_outer": probe_hits + build_missed,
        "full_outer": n * pcap + build_missed,
    }[join_type]
    spec = JoinSpec(
        num_executors=n,
        build_capacity=bcap, build_recv_capacity=brecv, build_width=8,
        probe_capacity=pcap, probe_recv_capacity=precv, probe_width=16,
        out_capacity=out_cap, join_type=join_type,
    )
    mesh = make_mesh(n)
    fn = build_hash_join(mesh, spec)
    key_sh = NamedSharding(mesh, P("ex"))
    row_sh = NamedSharding(mesh, P("ex", None))
    bkeys = jax.device_put(bkeys_h, key_sh)
    bvals = jax.device_put(np.zeros((nb, 8), np.int32), row_sh)
    bnum = jax.device_put(np.full(n, bcap, np.int32), key_sh)
    pkeys = jax.device_put(pkeys_h, key_sh)
    pvals = jax.device_put(np.zeros((n * pcap, 16), np.int32), row_sh)
    pnum = jax.device_put(np.full(n, pcap, np.int32), key_sh)
    out = jax.block_until_ready(fn(bkeys, bvals, bnum, pkeys, pvals, pnum))
    recv_totals = np.asarray(out[4])  # (n, 2) true (build, probe) per shard
    assert (recv_totals[:, 0] <= spec.build_recv_capacity).all() and (
        recv_totals[:, 1] <= spec.probe_recv_capacity
    ).all(), (
        f"hash skew overflowed a receive buffer (max build "
        f"{recv_totals[:, 0].max()}/{spec.build_recv_capacity}, probe "
        f"{recv_totals[:, 1].max()}/{spec.probe_recv_capacity})"
    )
    counts = np.asarray(out[3])
    assert (counts <= spec.out_capacity).all(), (
        f"join output overflowed out_capacity ({counts.max()} > {spec.out_capacity})"
    )
    matches = int(counts.sum())
    assert matches == expect, (
        f"{join_type} join emitted {matches} rows, expected {expect}"
    )
    best = 0.0
    for it in range(iterations):
        t0 = time.perf_counter()
        for _ in range(outstanding):
            out = fn(bkeys, bvals, bnum, pkeys, pvals, pnum)
        jax.block_until_ready(out)
        np.asarray(out[0][:4])  # force completion through async tunnels
        dt = time.perf_counter() - t0
        rows = outstanding * n * pcap
        best = max(best, rows / dt / 1e6)
        if report is not None:
            report(it, dt, rows, fn.spec.impl)
    return best


def run_join(args) -> None:
    def report(it, dt, rows, impl):
        print(
            f"iter {it}: joined {rows} probe rows in {dt*1e3:.1f} ms = "
            f"{rows / dt / 1e6:.2f} M rows/s [impl={impl}]",
            flush=True,
        )

    measure_join(
        args.executors, args.num_blocks, args.build_rows, args.iterations,
        outstanding=args.outstanding, report=report, join_type=args.join_type,
    )


def run_columnar(args) -> None:
    width = max(1, parse_size(args.block_size) // 4)  # -s = row bytes

    def report(it, dt, tot, impl):
        print(
            f"iter {it}: {tot} bytes of {width * 4} B rows in {dt*1e3:.1f} ms = "
            f"{tot / dt / 1e9:.2f} GB/s [impl={impl}]",
            flush=True,
        )

    measure_columnar(
        args.executors, args.num_blocks, width, args.iterations,
        outstanding=args.outstanding, report=report,
    )


def run_sort(args) -> None:
    def report(it, dt, rows, impl):
        print(
            f"iter {it}: sorted {rows} x 100 B rows in {dt*1e3:.1f} ms = "
            f"{rows / dt / 1e6:.2f} M rows/s ({rows * 100 / dt / 1e9:.2f} GB/s) "
            f"[impl={impl}]",
            flush=True,
        )

    if args.sort_impl in ("radix", "single") and args.executors != 1:
        raise SystemExit(
            f"--sort-impl {args.sort_impl} needs --executors 1 (it is an n=1 "
            "local-sort lowering)"
        )
    if args.batches > 1:
        run_sort_external(args)
        return
    measure_sort(
        args.executors, args.num_blocks, args.iterations,
        report=report, outstanding=args.outstanding, sort_impl=args.sort_impl,
    )


def run_sort_external(args) -> None:
    """The --batches > 1 arm of the sort mode: out-of-core TeraSort through
    run_external_sort (device batches + stable host run-merge), timed
    end-to-end per iteration — one number covering device sorts, transfers,
    and the host merge, since that composite IS the out-of-core story."""
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort

    n = args.executors
    total = args.num_blocks
    cap = -(-total // (args.batches * n))
    spec = SortSpec(
        num_executors=n, capacity=cap, recv_capacity=2 * cap if n > 1 else cap,
        width=24, impl=args.sort_impl,
    )
    mesh = make_mesh(n)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint32)
    payload = np.zeros((total, 24), np.int32)
    actual_batches = -(-total // (n * cap))  # the driver's real batch count
    fns = {}  # compiled-sort cache shared across iterations: time data, not JIT
    sk, _ = run_external_sort(mesh, spec, keys, payload, fns=fns)  # warmup
    ok, _ = oracle_sort(keys, payload)
    assert np.array_equal(sk, ok), "external sort diverged from oracle"
    for it in range(args.iterations):
        t0 = time.perf_counter()
        run_external_sort(mesh, spec, keys, payload, fns=fns)
        dt = time.perf_counter() - t0
        print(
            f"iter {it}: external-sorted {total} x 100 B rows "
            f"({actual_batches} device batches) in {dt:.2f} s = "
            f"{total / dt / 1e6:.2f} M rows/s", flush=True,
        )


def main(argv=None) -> None:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.mode == "server":
        run_server(args)
    elif args.mode == "client":
        run_client(args)
    elif args.mode == "wire":
        run_wire(args)
    elif args.mode == "compress":
        run_compress(args)
    elif args.mode == "failover":
        run_failover(args)
    elif args.mode == "tenants":
        run_tenants(args)
    elif args.mode == "fanin":
        run_fanin(args)
    elif args.mode == "queries":
        run_queries(args)
    elif args.mode == "elastic":
        run_elastic(args)
    elif args.mode == "obs":
        run_obs(args)
    elif args.mode == "pipeline":
        run_pipeline(args)
    elif args.mode == "gather":
        run_gather(args)
    elif args.mode == "write":
        run_write(args)
    elif args.mode == "gray":
        run_gray(args)
    elif args.mode == "skew":
        run_skew(args)
    elif args.mode == "adaptive":
        run_adaptive(args)
    elif args.mode == "combine":
        run_combine(args)
    elif args.mode == "ici":
        run_ici(args)
    elif args.mode == "sort":
        run_sort(args)
    elif args.mode == "columnar":
        run_columnar(args)
    elif args.mode == "groupby":
        run_groupby(args)
    elif args.mode == "join":
        run_join(args)
    else:
        run_superstep(args)


if __name__ == "__main__":
    main()
