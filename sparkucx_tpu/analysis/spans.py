"""span-discipline: every tracer span opened is closed on all paths.

The ``with span(...)``/``TRACER.span(...)`` context managers are safe by
construction; the hazard is the *explicit* ``start_span``/``end_span`` pair
(pipelined paths that overlap windows can't keep spans on the thread-local
stack, so they hand ``SpanCtx`` objects around by value).  A span started
and never ended renders as an unterminated bar in Perfetto and — worse —
corrupts the ring's duration accounting silently.  Rules per
``start_span(...)`` call site:

* assigned to a name — the same function must call ``end_span(<name>)``
  inside a ``finally`` block (all-paths closure), OR return the name
  (handoff: the function's docstring must then say who ends it, via
  ``end_span`` / "ended by" / "closed by").
* returned directly — handoff: same docstring requirement.
* anything else (discarded, nested in an expression) — flagged: the
  ``SpanCtx`` is unreachable and the span can never be ended.

``instant(<literal>)`` event names are cross-checked against the trace
documentation (``TRACE_DOC``) when it is loaded: instants are the trace
vocabulary dashboards and postmortem tooling grep for, so an undocumented
name is a finding.  Modules in ``TRACE_IMPL_MODULES`` (the tracer itself)
are skipped.  Escape hatch: ``#: span-ok <reason>`` on the line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from sparkucx_tpu.analysis.base import (
    Finding,
    Program,
    callee_name,
    docstring_of,
    register_global,
)
from sparkucx_tpu.analysis.config import TRACE_DOC, TRACE_IMPL_MODULES

PASS = "span-discipline"
ESCAPE = "#: span-ok"

_HANDOFF_WORDS = ("end_span", "ended by", "closed by")

_FN_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _escaped(lines: List[str], lineno: int) -> bool:
    return 1 <= lineno <= len(lines) and ESCAPE in lines[lineno - 1]


def _walk_scope(fn: ast.AST):
    """Yield descendants of ``fn`` without crossing into nested function
    scopes (each nested def gets its own _check_function visit)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FN_TYPES):
            stack.extend(ast.iter_child_nodes(node))


def _finally_end_span_vars(fn: ast.AST) -> Set[str]:
    """Names passed to ``end_span(...)`` from inside any ``finally`` block
    of ``fn`` (nested statements included — the close usually sits under a
    ``with executor_scope`` inside the finally)."""
    out: Set[str] = set()
    for node in _walk_scope(fn):
        if not isinstance(node, ast.Try):
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if (
                    isinstance(sub, ast.Call)
                    and callee_name(sub) == "end_span"
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                ):
                    out.add(sub.args[0].id)
    return out


def _returned_vars(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in _walk_scope(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
    return out


def _check_function(fn: ast.AST, rel: str, lines: List[str],
                    findings: List[Finding]) -> None:
    closed = _finally_end_span_vars(fn)
    returned = _returned_vars(fn)
    doc = docstring_of(fn).lower()
    handoff_documented = any(w in doc for w in _HANDOFF_WORDS)

    # map each start_span call to the statement that anchors it
    for stmt in _walk_scope(fn):
        if isinstance(stmt, ast.Assign):
            call = stmt.value
            if isinstance(call, ast.Call) and callee_name(call) == "start_span":
                if _escaped(lines, call.lineno):
                    continue
                tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
                if isinstance(tgt, ast.Name):
                    if tgt.id in closed:
                        continue
                    if tgt.id in returned:
                        if handoff_documented:
                            continue
                        findings.append(Finding(rel, call.lineno, PASS, (
                            f"span handed off via return of '{tgt.id}' but "
                            f"'{getattr(fn, 'name', '<fn>')}' does not document "
                            f"its closer — say who calls end_span (docstring: "
                            f"'ended by ...') or close it in a finally")))
                        continue
                findings.append(Finding(rel, call.lineno, PASS, (
                    f"start_span result is never passed to end_span inside a "
                    f"finally block of '{getattr(fn, 'name', '<fn>')}' — a "
                    f"span must be closed on all paths (or returned with a "
                    f"documented closer)")))
        elif isinstance(stmt, ast.Return):
            call = stmt.value
            if isinstance(call, ast.Call) and callee_name(call) == "start_span":
                if _escaped(lines, call.lineno) or handoff_documented:
                    continue
                findings.append(Finding(rel, call.lineno, PASS, (
                    f"'{getattr(fn, 'name', '<fn>')}' returns a started span "
                    f"but its docstring never says who ends it — document the "
                    f"handoff ('ended by ...' / 'closed by ...')")))
        elif isinstance(stmt, ast.Expr):
            call = stmt.value
            if isinstance(call, ast.Call) and callee_name(call) == "start_span":
                if _escaped(lines, call.lineno):
                    continue
                findings.append(Finding(rel, call.lineno, PASS, (
                    "start_span result discarded — the SpanCtx is "
                    "unreachable, so the span can never be ended")))


def _check_instants(tree: ast.Module, rel: str, lines: List[str],
                    doc: Optional[str], findings: List[Finding]) -> None:
    if doc is None:
        return  # no trace doc loaded (installed-package run / bare fixture)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and callee_name(node) == "instant"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            name = node.args[0].value
            if name not in doc and not _escaped(lines, node.lineno):
                findings.append(Finding(rel, node.lineno, PASS, (
                    f"trace instant '{name}' is not documented in "
                    f"{TRACE_DOC} — instants are the grep vocabulary for "
                    f"dashboards and postmortems; add it to the trace-points "
                    f"table")))


@register_global(PASS)
def span_discipline_pass(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    doc = program.docs.get(TRACE_DOC)
    for rel, (tree, source) in sorted(program.modules.items()):
        if rel in TRACE_IMPL_MODULES:
            continue
        lines = source.splitlines()
        seen: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.lineno in seen:
                    continue
                seen.add(node.lineno)
                _check_function(node, rel, lines, findings)
        _check_instants(tree, rel, lines, doc, findings)
    return findings
