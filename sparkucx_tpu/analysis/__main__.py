"""CLI: ``python -m sparkucx_tpu.analysis [--ci]``.

Runs every registered pass over ``sparkucx_tpu/`` and exits non-zero on any
finding not covered by a reviewed allowlist entry (analysis/config.py).
Imports no jax/numpy — safe on a bare interpreter and cheap in CI.
"""

from __future__ import annotations

import argparse
import sys

from sparkucx_tpu.analysis import analyze_tree, registered_passes
from sparkucx_tpu.analysis.config import ALLOWLIST


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkucx_tpu.analysis",
        description="sparkucx_tpu shuffle invariant analyzer",
    )
    parser.add_argument("--ci", action="store_true",
                        help="quiet on success; non-zero exit on violations (same as default)")
    parser.add_argument("--root", default=None,
                        help="directory to analyze (default: the installed sparkucx_tpu/)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass subset (default: all)")
    parser.add_argument("--list-passes", action="store_true")
    parser.add_argument("--show-allowlisted", action="store_true",
                        help="also print findings suppressed by the allowlist")
    args = parser.parse_args(argv)

    if args.list_passes:
        for name in sorted(registered_passes()):
            print(name)
        return 0

    passes = args.passes.split(",") if args.passes else None
    if passes:
        unknown = sorted(set(passes) - set(registered_passes()))
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
            return 2

    violations, suppressed, num_files = analyze_tree(root=args.root, passes=passes)

    if args.show_allowlisted:
        for finding, entry in suppressed:
            print(f"{finding.render()}  [allowlisted: {entry}]")
    for finding in violations:
        print(finding.render())

    # an allowlist entry nothing matches is stale — surface it (warn, not fail)
    if passes is None and args.root is None:
        used = {entry for _, entry in suppressed}
        for entry in sorted(ALLOWLIST - used):
            print(f"warning: unused allowlist entry {entry}", file=sys.stderr)

    npass = len(passes) if passes else len(registered_passes())
    if violations:
        print(
            f"\n{len(violations)} violation(s) across {num_files} files "
            f"({npass} passes, {len(suppressed)} allowlisted)",
            file=sys.stderr,
        )
        return 1
    if not args.ci:
        print(
            f"analysis clean: {num_files} files, {npass} passes, "
            f"{len(suppressed)} allowlisted finding(s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
