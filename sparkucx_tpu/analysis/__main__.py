"""CLI: ``python -m sparkucx_tpu.analysis [--ci]``.

Runs every registered pass (module and whole-program) over
``sparkucx_tpu/`` and exits non-zero on any finding not covered by a
reviewed allowlist entry (analysis/config.py).  A full default run also
FAILS on stale configuration: an allowlist entry no finding matches, a
REQUIRED_SURFACE path that names no analyzed file, or a function-pinning
table entry (DONATING_BUILDERS / TUPLE_DONATING_BUILDERS /
HOST_SYNC_ROOTS) naming a function no longer defined anywhere — reviewed
exceptions that have rotted get pruned, not accumulated.  Imports no jax/numpy —
safe on a bare interpreter and cheap in CI.
"""

from __future__ import annotations

import argparse
import os
import sys

from sparkucx_tpu.analysis import all_pass_names, analyze_tree
from sparkucx_tpu.analysis.base import load_program, package_root
from sparkucx_tpu.analysis.config import (
    ALLOWLIST,
    DONATING_BUILDERS,
    HOST_SYNC_ROOTS,
    REQUIRED_SURFACE,
    TESTS_ALLOWLIST,
    TUPLE_DONATING_BUILDERS,
)


def _defined_function_names(root: str):
    """Every ``def <name>(`` in the package, by cheap regex sweep — enough
    to catch config tables pinning functions that a refactor deleted."""
    import re

    names = set()
    pat = re.compile(r"^\s*(?:async\s+)?def\s+(\w+)\s*\(", re.MULTILINE)
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if fname.endswith(".py"):
                with open(os.path.join(dirpath, fname)) as f:
                    names.update(pat.findall(f.read()))
    return names


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkucx_tpu.analysis",
        description="sparkucx_tpu shuffle invariant analyzer",
    )
    parser.add_argument("--ci", action="store_true",
                        help="quiet on success; non-zero exit on violations (same as default)")
    parser.add_argument("--root", default=None,
                        help="directory to analyze (default: the installed sparkucx_tpu/)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass subset (default: all)")
    parser.add_argument("--list-passes", action="store_true")
    parser.add_argument("--show-allowlisted", action="store_true",
                        help="also print findings suppressed by the allowlist")
    parser.add_argument("--allowlist", choices=("package", "tests"), default="package",
                        help="which reviewed-exception table applies: the package "
                             "ALLOWLIST (default) or TESTS_ALLOWLIST for runs "
                             "over the tests/ tree")
    parser.add_argument("--dump-lock-graph", action="store_true",
                        help="print the whole-program lock acquisition graph as "
                             "Graphviz DOT and exit")
    args = parser.parse_args(argv)

    if args.list_passes:
        for name in all_pass_names():
            print(name)
        return 0

    if args.dump_lock_graph:
        from sparkucx_tpu.analysis.lockorder import build_lock_graph, render_dot

        edges, _blocking = build_lock_graph(load_program(args.root))
        print(render_dot(edges))
        return 0

    passes = args.passes.split(",") if args.passes else None
    if passes:
        unknown = sorted(set(passes) - set(all_pass_names()))
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
            return 2

    allowlist = TESTS_ALLOWLIST if args.allowlist == "tests" else ALLOWLIST
    violations, suppressed, num_files = analyze_tree(
        root=args.root, passes=passes, allowlist=allowlist
    )

    if args.show_allowlisted:
        for finding, entry in suppressed:
            print(f"{finding.render()}  [allowlisted: {entry}]")
    for finding in violations:
        print(finding.render())

    # Stale reviewed-exception config is a FAILURE on the full default run:
    # an unused entry either outlived its construct (prune it) or quietly
    # stopped matching the message it was reviewed against (re-review it).
    stale = 0
    if passes is None and args.root is None and args.allowlist == "package":
        used = {entry for _, entry in suppressed}
        for entry in sorted(ALLOWLIST - used):
            stale += 1
            print(f"stale allowlist entry (matched no finding): {entry}",
                  file=sys.stderr)
        for path in sorted(REQUIRED_SURFACE):
            if not os.path.isfile(os.path.join(package_root(), path)):
                stale += 1
                print(f"stale REQUIRED_SURFACE path (no such file): {path}",
                      file=sys.stderr)
        # function-pinning tables rot the same way allowlist entries do: a
        # builder ladder removed by a refactor leaves its donation/host-sync
        # entries matching nothing (the PR 13 executor unification deleted
        # `_run_exchange_quota` and the per-variant `_assemble` ladder)
        defined = _defined_function_names(package_root())
        for table_name, table in (
            ("DONATING_BUILDERS", DONATING_BUILDERS),
            ("TUPLE_DONATING_BUILDERS", TUPLE_DONATING_BUILDERS),
            ("HOST_SYNC_ROOTS", dict.fromkeys(HOST_SYNC_ROOTS)),
        ):
            for fn_name in sorted(table):
                if fn_name not in defined:
                    stale += 1
                    print(
                        f"stale {table_name} entry (no `def {fn_name}` "
                        f"anywhere in the package): {fn_name}",
                        file=sys.stderr,
                    )

    npass = len(passes) if passes else len(all_pass_names())
    if violations or stale:
        print(
            f"\n{len(violations)} violation(s), {stale} stale config "
            f"entr(ies) across {num_files} files "
            f"({npass} passes, {len(suppressed)} allowlisted)",
            file=sys.stderr,
        )
        return 1
    if not args.ci:
        print(
            f"analysis clean: {num_files} files, {npass} passes, "
            f"{len(suppressed)} allowlisted finding(s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
