"""Pass: compile-cache hygiene (recompile-bomb detector).

Every compiled-exchange front-end in this codebase follows one discipline:
shape-ish arguments are bucketed to powers of two *before* they become part
of a compile-cache key (``bucket_send_rows`` in ``_exchange_fn``,
``bit_length`` rounding in ``_gather_fn``/``_scatter_fn``), so a shuffle
whose size wanders produces a handful of compiles instead of one per size —
a recompile per round is a multi-second stall on TPU.

The pass flags functions that (a) touch a compile cache — an attribute/name
containing a configured marker (``cache``, ``_fns``) used with ``.get(key)``
or ``[key] = ...``, or an ``@lru_cache`` decorator — AND (b) call a jit
builder (``build_*`` / ``jax.jit``), where (c) a *parameter* with a shape-ish
name (rows/size/count/blocks/…) appears raw in the cache key without having
been rebound through a bucketing call first.  ``@lru_cache`` builders key on
the raw arguments by construction, so every shape-ish parameter of one is
flagged — bucket at the call site or switch to an explicit keyed dict.

Only parameters are checked: locals derived inside the function are assumed
to have gone through whatever derivation the author chose (the
``bucketed = bucket_send_rows(...)`` idiom produces a fresh name, which is
the point — raw and bucketed values never share a spelling).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from sparkucx_tpu.analysis.base import Finding, callee_name, register
from sparkucx_tpu.analysis.config import (
    BUCKETING_MARKERS,
    BUILDER_NAMES,
    BUILDER_PREFIXES,
    CACHE_NAME_MARKERS,
)

PASS = "cache-hygiene"

_SHAPEY = re.compile(
    r"rows|size|count|blocks|capacity|depth|width|bytes|num_|_num|length", re.I
)


def _is_cache_name(name: Optional[str]) -> bool:
    return bool(name) and any(m in name for m in CACHE_NAME_MARKERS)


def _attr_or_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_builder_call(node: ast.Call) -> bool:
    name = callee_name(node)
    if name is None:
        return False
    return name in BUILDER_NAMES or any(name.startswith(p) for p in BUILDER_PREFIXES)


def _is_bucketing_expr(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            name = callee_name(sub)
            if name in BUCKETING_MARKERS:
                return True
    return False


def _params_of(fn) -> List[str]:
    args = fn.args
    names = [a.arg for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)]
    return [n for n in names if n not in ("self", "cls")]


def _lru_cached(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _attr_or_name(target) in ("lru_cache", "cache"):
            return True
    return False


class _CacheUse:
    """One ``<cache>.get(key)`` / ``<cache>[key] = ...`` site."""

    def __init__(self, cache_name: str, key: ast.AST, line: int) -> None:
        self.cache_name = cache_name
        self.key = key
        self.line = line


def _cache_uses(fn) -> List[_CacheUse]:
    uses: List[_CacheUse] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "setdefault")
                and _is_cache_name(_attr_or_name(f.value))
                and node.args
            ):
                uses.append(_CacheUse(_attr_or_name(f.value), node.args[0], node.lineno))
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and _is_cache_name(_attr_or_name(t.value)):
                    uses.append(_CacheUse(_attr_or_name(t.value), t.slice, node.lineno))
    return uses


def _key_names(fn, key: ast.AST) -> Set[str]:
    """Bare names participating in the key; a Name key resolves one level
    through a local ``key = (...)`` tuple assignment."""
    if isinstance(key, ast.Name):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == key.id for t in node.targets
            ):
                key = node.value
                break
    names: Set[str] = set()
    for sub in ast.walk(key):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            names.add(sub.id)
    return names


def _bucketed_params(fn) -> Set[str]:
    """Parameters rebound through a bucketing expression anywhere in the
    function (``send_rows = bucket_send_rows(send_rows, n)``)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_bucketing_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


@register(PASS)
def check(tree: ast.Module, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_builder = any(
            isinstance(n, ast.Call) and _is_builder_call(n) for n in ast.walk(fn)
        )
        # an @lru_cache'd build_* function IS the builder — its body need not
        # call another one for the cache key to matter
        is_builder_def = any(fn.name.startswith(p) for p in BUILDER_PREFIXES)
        if not has_builder and not (is_builder_def and _lru_cached(fn)):
            continue
        shapey_params = [p for p in _params_of(fn) if _SHAPEY.search(p)]
        if not shapey_params:
            continue
        if _lru_cached(fn):
            for p in shapey_params:
                findings.append(
                    Finding(
                        path,
                        fn.lineno,
                        PASS,
                        f"@lru_cache jit builder '{fn.name}' keys on raw shape "
                        f"argument '{p}' — recompile bomb; bucket at the call "
                        f"site (bucket_send_rows / pow2) or key an explicit dict",
                    )
                )
            continue
        uses = _cache_uses(fn)
        if not uses:
            continue
        bucketed = _bucketed_params(fn)
        seen: Set[str] = set()
        for use in uses:
            for p in _key_names(fn, use.key):
                if p in shapey_params and p not in bucketed and p not in seen:
                    seen.add(p)
                    findings.append(
                        Finding(
                            path,
                            use.line,
                            PASS,
                            f"shape argument '{p}' flows un-bucketed into "
                            f"compile cache '{use.cache_name}' in '{fn.name}' "
                            f"— recompile bomb (bucket_send_rows / pow2 first)",
                        )
                    )
    return findings
