"""metrics-naming: one exported-metric naming scheme, documented both ways.

Every exported row renders as ``<prefix>_<family>_<name>`` (obs/metrics.py),
and OBSERVABILITY.md carries a table row per family describing its source —
that table is the operator contract dashboards are built against.  This pass
pins the scheme statically:

* the ``PREFIX`` constant in ``OBS_METRICS_MODULE`` must equal the declared
  ``METRIC_PREFIX`` (rename drift breaks every scrape config at once);
* every ``sample(<family>, <name>, ...)`` literal: family matches
  ``[a-z][a-z0-9]*`` and name fragments match snake_case (f-string name
  templates are checked on their constant fragments);
* every ``counter_dict_provider(<family>, ...)`` literal family likewise
  (that adapter stamps the family onto a whole accessor's counters);
* families used in code ⊆ families documented in the OBSERVABILITY.md
  table (rows shaped ``| `fam` | ...``), and documented families ⊆ used —
  both directions, so the doc can neither lag nor advertise ghosts.

Doc cross-checks run only when the doc is loaded (bare fixtures and
installed-package runs skip them).  Escape: ``#: metric-ok <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from sparkucx_tpu.analysis.base import (
    Finding,
    Program,
    callee_name,
    register_global,
)
from sparkucx_tpu.analysis.config import (
    METRIC_PREFIX,
    OBS_METRICS_MODULE,
    TRACE_DOC,
)

PASS = "metrics-naming"
ESCAPE = "#: metric-ok"

_FAMILY_RE = re.compile(r"^[a-z][a-z0-9]*$")
_NAME_FRAGMENT_RE = re.compile(r"^[a-z0-9_]*$")
#: a family row in the OBSERVABILITY.md table: ``| `fam` | source |``
_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|", re.MULTILINE)


def _escaped(lines: List[str], lineno: int) -> bool:
    return 1 <= lineno <= len(lines) and ESCAPE in lines[lineno - 1]


def _str_arg(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _name_fragments(node: ast.AST) -> Optional[List[str]]:
    """Constant fragments of a metric-name argument: a literal yields
    itself, an f-string yields its constant pieces, anything else None
    (dynamic names come from accessor dict keys — not checkable here)."""
    lit = _str_arg(node)
    if lit is not None:
        return [lit]
    if isinstance(node, ast.JoinedStr):
        return [
            v.value
            for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        ]
    return None


@register_global(PASS)
def metrics_naming_pass(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    used_families: Dict[str, Tuple[str, int]] = {}  # family -> first use site

    for rel, (tree, source) in sorted(program.modules.items()):
        lines = source.splitlines()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = callee_name(node)
            if callee == "sample" and node.args:
                fam = _str_arg(node.args[0])
                if fam is not None:
                    used_families.setdefault(fam, (rel, node.lineno))
                    if not _FAMILY_RE.match(fam) and not _escaped(lines, node.lineno):
                        findings.append(Finding(rel, node.lineno, PASS, (
                            f"metric family '{fam}' breaks the "
                            f"{METRIC_PREFIX}_<family>_<name> scheme — "
                            f"families are [a-z][a-z0-9]*")))
                if len(node.args) > 1:
                    frags = _name_fragments(node.args[1])
                    if frags is not None:
                        bad = [f for f in frags if not _NAME_FRAGMENT_RE.match(f)]
                        if bad and not _escaped(lines, node.lineno):
                            findings.append(Finding(rel, node.lineno, PASS, (
                                f"metric name fragment {bad[0]!r} is not "
                                f"snake_case — exported rows must parse as "
                                f"{METRIC_PREFIX}_<family>_<name>")))
            elif callee == "counter_dict_provider" and node.args:
                fam = _str_arg(node.args[0])
                if fam is not None:
                    used_families.setdefault(fam, (rel, node.lineno))
                    if not _FAMILY_RE.match(fam) and not _escaped(lines, node.lineno):
                        findings.append(Finding(rel, node.lineno, PASS, (
                            f"metric family '{fam}' breaks the "
                            f"{METRIC_PREFIX}_<family>_<name> scheme — "
                            f"families are [a-z][a-z0-9]*")))

    # the PREFIX constant itself must match the declared scheme
    obs = program.module(OBS_METRICS_MODULE)
    if obs is not None:
        tree, _src = obs
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "PREFIX"
            ):
                val = _str_arg(node.value)
                if val != METRIC_PREFIX:
                    findings.append(Finding(OBS_METRICS_MODULE, node.lineno, PASS, (
                        f"PREFIX is {val!r} but the documented scheme is "
                        f"'{METRIC_PREFIX}_<family>_<name>' — update "
                        f"METRIC_PREFIX in analysis/config.py and "
                        f"OBSERVABILITY.md together")))

    doc = program.docs.get(TRACE_DOC)
    if doc is not None:
        documented: Set[str] = set(_DOC_ROW_RE.findall(doc))
        for fam, (rel, lineno) in sorted(used_families.items()):
            if fam not in documented:
                findings.append(Finding(rel, lineno, PASS, (
                    f"metric family '{fam}' has no row in the {TRACE_DOC} "
                    f"family table — every exported family is operator "
                    f"contract; document its source")))
        # reverse direction only when the program actually registers
        # families (a bare fixture module would otherwise flag every row)
        if used_families:
            for fam in sorted(documented - set(used_families)):
                findings.append(Finding(OBS_METRICS_MODULE, 1, PASS, (
                    f"{TRACE_DOC} documents metric family '{fam}' but no "
                    f"sample()/counter_dict_provider() site registers it — "
                    f"prune the stale row or restore the family")))

    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
