"""reactor-discipline: nothing blocking reachable from reactor callbacks.

The shared serving plane (service/reactor.py) multiplexes every idle
connection over ONE selector loop thread and a bounded worker pool.  Two
lanes, two contracts:

* ``add_listener(sock, on_accept)`` — ``on_accept`` runs ON the loop
  thread.  A blocking socket op, untimed wait, ``join``, ``time.sleep``,
  or a put into a full queue there stalls *every* connection the process
  serves.  The loop lane must stay non-blocking, full stop.
* ``add_connection(conn, serve_once, on_close=...)`` — callbacks run on
  the bounded worker pool.  Blocking frame *reads* are the documented
  design (the owner's serve code runs unchanged), but ``join``, untimed
  ``wait``/``wait_for``, and unbounded/untimed ``queue.put`` can deadlock
  the pool against itself once all workers block on each other.

The pass finds registration call sites in each module, resolves the
callback (method reference, function name, or a lambda whose body calls a
method), and walks the module-local call graph from those seeds — the
same reachability machinery as the host-sync pass, labelling findings
with the ``(via 'helper')`` chain.

Escape hatch: a ``#: reactor-ok`` comment on the flagged line, for calls
reviewed to be non-blocking in context (e.g. a nonblocking socket's
``recv`` used as a drain).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sparkucx_tpu.analysis.base import Finding, callee_name, dotted_name, register
from sparkucx_tpu.analysis.config import (
    REACTOR_LOOP_REGISTRARS,
    REACTOR_WORKER_REGISTRARS,
)

PASS = "reactor-discipline"

#: Blocking socket ops never allowed on the loop lane.
LOOP_BLOCKING = {"recv", "recv_into", "sendall", "sendmsg", "connect", "accept"}

ESCAPE_COMMENT = "#: reactor-ok"


def _index_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    fns: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
    return fns


def _own_nodes(fn: ast.AST):
    """Walk a function's own body, excluding nested defs AND lambdas — a
    lambda handed to a registrar runs on whatever lane the registrar puts
    it on (it is seeded there by ``_registration_seeds``), not on the lane
    of the function that happens to construct it."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _local_callees(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                base = dotted_name(f.value)
                if base in ("self", "cls"):
                    out.add(f.attr)
    return out


def _callback_names(node: ast.AST) -> List[str]:
    """Function names a callback expression resolves to, module-locally."""
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        if base in ("self", "cls"):
            return [node.attr]
        return []
    if isinstance(node, ast.Lambda):
        body = node.body
        if isinstance(body, ast.Call):
            return _callback_names(body.func)
        return []
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        out: Set[str] = set()
        for sub in _own_nodes(node):
            if isinstance(sub, ast.Call):
                out.update(_callback_names(sub.func))
        return sorted(out)
    return []


def _registration_seeds(tree: ast.Module) -> List[Tuple[str, str]]:
    """``(fn_name, lane)`` seeds from add_listener/add_connection sites."""
    seeds: List[Tuple[str, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name in REACTOR_LOOP_REGISTRARS:
            cb_args, lane = node.args[1:2], "loop"
        elif name in REACTOR_WORKER_REGISTRARS:
            cb_args, lane = list(node.args[1:2]), "worker"
            cb_args += [kw.value for kw in node.keywords if kw.arg == "on_close"]
        else:
            continue
        for arg in cb_args:
            for fn_name in _callback_names(arg):
                seeds.append((fn_name, lane))
    return seeds


def _line_escaped(source_lines: List[str], lineno: int) -> bool:
    if 1 <= lineno <= len(source_lines):
        return ESCAPE_COMMENT in source_lines[lineno - 1]
    return False


def _blocking_in(fn: ast.AST, lane: str, source_lines: List[str]):
    """``(label, line)`` blocking constructs in one function, per lane."""
    out: List[Tuple[str, int]] = []
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        if _line_escaped(source_lines, node.lineno):
            continue
        name = callee_name(node)
        label: Optional[str] = None
        if lane == "loop" and name in LOOP_BLOCKING:
            label = f"blocking socket op '{name}'"
        elif lane == "loop" and name == "sleep":
            label = "'time.sleep'"
        elif name == "join" and not node.args and not node.keywords:
            recv = node.func.value if isinstance(node.func, ast.Attribute) else None
            if isinstance(recv, ast.Constant):
                continue  # "sep".join(...)
            base = dotted_name(recv) if recv is not None else None
            if base is not None and base.split(".")[-1] in ("path", "sep"):
                continue
            label = "'join()' without timeout"
        elif name in ("wait", "wait_for"):
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
            has_timeout = has_timeout or len(node.args) >= (2 if name == "wait_for" else 1)
            if not has_timeout:
                label = f"'{name}()' without timeout"
        elif name == "put":
            bounded = any(
                kw.arg == "timeout"
                or (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False)
                for kw in node.keywords
            )
            if not bounded:
                label = "queue 'put' without timeout/block=False"
        if label is not None:
            out.append((label, node.lineno))
    return out


@register(PASS)
def reactor_discipline_pass(tree: ast.Module, source: str, rel_path: str) -> List[Finding]:
    seeds = _registration_seeds(tree)
    if not seeds:
        return []
    fns = _index_functions(tree)
    source_lines = source.splitlines()
    findings: List[Finding] = []
    reported: Set[Tuple[str, int, str]] = set()

    for seed, lane in sorted(set(seeds)):
        if seed not in fns:
            continue
        # BFS over the module-local call graph, tracking the via-chain.
        queue: List[Tuple[str, Tuple[str, ...]]] = [(seed, ())]
        visited: Set[str] = {seed}
        while queue:
            fn_name, chain = queue.pop(0)
            fn = fns[fn_name]
            via = f" (via '{chain[-1]}')" if chain else ""
            for label, line in _blocking_in(fn, lane, source_lines):
                key = (lane, line, label)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(Finding(rel_path, line, PASS,
                    f"{label} reachable from reactor {lane} callback "
                    f"'{seed}'{via}"))
            for callee in sorted(_local_callees(fn)):
                if callee in fns and callee not in visited:
                    visited.add(callee)
                    queue.append((callee, chain + (fn_name,)))
    return findings
