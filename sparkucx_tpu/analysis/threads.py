"""thread-lifecycle: every spawned thread is daemonized-or-joined; every
inter-thread queue is bounded.

The package spawns background threads in half a dozen places (replicator,
eviction epochs, server-group lane senders, reactor loop, drain workers).
Two ways such a thread is allowed to exist:

* ``daemon=True`` at construction — process exit never hangs on it (the
  thread must then tolerate dying mid-loop, which the package's daemon
  threads do by polling closed/broken flags), or
* the constructed thread is bound to a name that some code in the same
  module ``join``\\ s — the owner's ``close()`` path reaps it.

A non-daemon, never-joined thread is a shutdown hang waiting for its
first exception.  Separately, every ``queue.Queue()`` feeding such
threads must be constructed with a positive ``maxsize`` — an unbounded
queue turns a slow consumer into an unbounded-memory producer stall
(exactly the bug class the bounded server-group lanes were built to
avoid).  ``SimpleQueue`` has no bound at all and is flagged outright.

Escape hatch: a ``#: lifecycle: <reason>`` comment on the construction
line, for reviewed cases (e.g. a benchmark harness thread the harness
joins through a helper the pass cannot see).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from sparkucx_tpu.analysis.base import Finding, dotted_name, register

PASS = "thread-lifecycle"

ESCAPE_COMMENT = "#: lifecycle:"


def _call_named(node: ast.Call, names) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in names
    if isinstance(f, ast.Attribute):
        return f.attr in names
    return False


def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _joined_names(tree: ast.Module) -> Set[str]:
    """Final names of every receiver of a ``.join(...)`` call, plus every
    collection a ``for t in <name>: ... t.join()`` loop drains — the
    spawn-list-then-join-all idiom binds threads to a list, not a name."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                base = dotted_name(node.func.value)
                if base is not None:
                    out.add(base.split(".")[-1])
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.For)
            and isinstance(node.target, ast.Name)
            and isinstance(node.iter, ast.Name)
            and node.target.id in out
        ):
            out.add(node.iter.id)
    return out


def _bound_name(tree: ast.Module, call: ast.Call) -> Optional[str]:
    """Final name a constructor call's result is assigned to — directly or
    as an element of a comprehension/list the assignment builds."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and node.value is not None:
            if any(sub is call for sub in ast.walk(node.value)):
                for tgt in node.targets:
                    d = dotted_name(tgt)
                    if d is not None:
                        return d.split(".")[-1]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if any(sub is call for sub in ast.walk(node.value)):
                d = dotted_name(node.target)
                if d is not None:
                    return d.split(".")[-1]
    return None


def _line_escaped(source_lines: List[str], lineno: int) -> bool:
    if 1 <= lineno <= len(source_lines):
        return ESCAPE_COMMENT in source_lines[lineno - 1]
    return False


@register(PASS)
def thread_lifecycle_pass(tree: ast.Module, source: str, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []
    source_lines = source.splitlines()
    joined = _joined_names(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _line_escaped(source_lines, node.lineno):
            continue

        if _call_named(node, ("Thread",)):
            daemon = _kw(node, "daemon")
            if isinstance(daemon, ast.Constant) and daemon.value is True:
                continue
            bound = _bound_name(tree, node)
            if bound is not None and bound in joined:
                continue
            what = (
                f"thread bound to '{bound}' is never joined in this module"
                if bound is not None
                else "thread is neither bound to a joinable name nor daemonized"
            )
            findings.append(Finding(rel_path, node.lineno, PASS,
                f"Thread(...) without daemon=True: {what} — daemonize it or "
                f"join it on the owner's close() path"))

        elif _call_named(node, ("SimpleQueue",)):
            findings.append(Finding(rel_path, node.lineno, PASS,
                "SimpleQueue() has no maxsize — use a bounded queue.Queue "
                "so a slow consumer backpressures instead of buffering "
                "unboundedly"))

        elif _call_named(node, ("Queue", "LifoQueue", "PriorityQueue")):
            size = _kw(node, "maxsize")
            if size is None and node.args:
                size = node.args[0]
            unbounded = size is None or (
                isinstance(size, ast.Constant) and isinstance(size.value, int)
                and size.value <= 0
            )
            if unbounded:
                findings.append(Finding(rel_path, node.lineno, PASS,
                    "queue constructed without a positive maxsize — "
                    "unbounded queues turn a slow consumer into an "
                    "unbounded-memory stall"))
    return findings
