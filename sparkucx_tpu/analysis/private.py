"""Passes: private-access + required-surface (ex scripts/lint_private_access.py).

Folded into the analyzer so CI runs ONE gate; the old script remains as a
thin shim.  Semantics are unchanged:

* private-access — flags ``expr._name`` attribute access where ``expr`` is
  not ``self``/``cls`` (reaching into another object's internals rots) and
  ``from module import _name`` of private names across modules.  Allowed:
  ``self._x``, ``cls._x``, dunders, ``_``-prefixed locals/params themselves.
* required-surface — asserts the load-bearing public methods in
  config.REQUIRED_SURFACE still exist (AST only, no import), so a rename
  fails here before it fails at runtime in another layer.
"""

from __future__ import annotations

import ast
from typing import List

from sparkucx_tpu.analysis.base import Finding, register
from sparkucx_tpu.analysis.config import REQUIRED_SURFACE

PRIVATE_PASS = "private-access"
SURFACE_PASS = "required-surface"


@register(PRIVATE_PASS)
def check_private(tree: ast.Module, source: str, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            name = node.attr
            if not name.startswith("_") or name.startswith("__"):
                continue
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                continue
            # self.x._y is still private access on x's internals — flag unless
            # the full chain starts at self AND the private attr is on self
            out.append(Finding(path, node.lineno, PRIVATE_PASS,
                               f"private attribute access: .{name}"))
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name.startswith("_") and not alias.name.startswith("__"):
                    out.append(Finding(path, node.lineno, PRIVATE_PASS,
                                       f"private import: {alias.name} from {node.module}"))
    return out


@register(SURFACE_PASS)
def check_surface(tree: ast.Module, source: str, path: str) -> List[Finding]:
    want = None
    for sfx, classes in REQUIRED_SURFACE.items():
        if path.endswith(sfx):
            want = classes
    if want is None:
        return []
    methods = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods[node.name] = {
                n.name
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
    out: List[Finding] = []
    for cls, names in want.items():
        have = methods.get(cls)
        if have is None:
            out.append(Finding(path, 1, SURFACE_PASS,
                               f"required public surface: class {cls} missing"))
            continue
        for name in names:
            if name not in have:
                out.append(Finding(path, 1, SURFACE_PASS,
                                   f"required public surface: {cls}.{name} missing"))
    return out
