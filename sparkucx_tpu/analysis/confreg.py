"""conf-registry: every knob is real, documented, tested, and off by default.

Parses ``TpuShuffleConf`` out of config.py — the dataclass field defaults
and the ``from_spark_conf`` knob table (the ``(name, attr, conv)`` tuple
list, plus the bespoke ``get("...")`` special cases) — and enforces four
invariants per ``spark.shuffle.tpu.*`` knob:

* **real** — the attr the knob sets must be an actual conf field (a typo
  here is a knob that silently does nothing),
* **documented** — ``spark.shuffle.tpu.<name>`` must appear in
  docs/DEPLOYMENT.md (the operator-facing registry),
* **tested** — the knob name or its attr must be referenced somewhere in
  tests/ (an untested knob's parse/convert path rots invisibly),
* **off-path pinned** — for every field in ``OFF_PATH_DEFAULTS``
  (analysis/config.py), the dataclass default must equal the pinned
  byte-identical-off-path value.  Features added since the golden wire
  captures default OFF; flipping one requires editing the pin table,
  which is the review this pass forces.

Doc and test checks are skipped when the program carries no
DEPLOYMENT.md / tests text (installed package; fixtures may inject both
through ``run_source(docs=..., tests_text=...)``).  Escape hatch: the
standard allowlist, entry per knob, justification required.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, Program, register_global
from sparkucx_tpu.analysis.config import (
    CONF_DOC,
    CONF_KEY_PREFIX,
    CONF_MODULE,
    OFF_PATH_DEFAULTS,
    SPECIAL_CONF_KNOBS,
)

PASS = "conf-registry"


def _conf_class(tree: ast.Module) -> Optional[ast.ClassDef]:
    """The dataclass holding from_spark_conf (TpuShuffleConf in the real
    module; any class with that classmethod in fixtures)."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and item.name == "from_spark_conf":
                    return node
    return None


def extract_conf_fields(cls: ast.ClassDef) -> Dict[str, Tuple[object, int]]:
    """``{field: (default_literal, line)}``; non-constant defaults map to
    an ``...`` sentinel (factory/tuple defaults are not off-path pins)."""
    out: Dict[str, Tuple[object, int]] = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            default: object = Ellipsis
            if isinstance(stmt.value, ast.Constant):
                default = stmt.value.value
            out[stmt.target.id] = (default, stmt.lineno)
    return out


def extract_conf_knobs(cls: ast.ClassDef) -> List[Tuple[str, Optional[str], int]]:
    """``(knob_name, attr, line)`` from from_spark_conf: the tuple-table
    entries plus the bespoke ``get("...")`` constants (attr resolved
    through SPECIAL_CONF_KNOBS, None when unknown there)."""
    fn = next(
        item for item in cls.body
        if isinstance(item, ast.FunctionDef) and item.name == "from_spark_conf"
    )
    knobs: List[Tuple[str, Optional[str], int]] = []
    seen = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.List):
            for elt in node.elts:
                if (
                    isinstance(elt, ast.Tuple)
                    and len(elt.elts) >= 2
                    and isinstance(elt.elts[0], ast.Constant)
                    and isinstance(elt.elts[0].value, str)
                    and isinstance(elt.elts[1], ast.Constant)
                    and isinstance(elt.elts[1].value, str)
                ):
                    name, attr = elt.elts[0].value, elt.elts[1].value
                    if name not in seen:
                        seen.add(name)
                        knobs.append((name, attr, elt.lineno))
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "get"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            name = node.args[0].value
            if name not in seen:
                seen.add(name)
                knobs.append((name, SPECIAL_CONF_KNOBS.get(name), node.lineno))
    return knobs


def _find_conf_module(program: Program) -> Optional[Tuple[str, ast.Module]]:
    entry = program.module(CONF_MODULE)
    if entry is not None:
        return CONF_MODULE, entry[0]
    for rel, (tree, _source) in sorted(program.modules.items()):
        if _conf_class(tree) is not None:
            return rel, tree
    return None


@register_global(PASS)
def conf_registry_pass(program: Program) -> List[Finding]:
    located = _find_conf_module(program)
    if located is None:
        return []
    rel, tree = located
    cls = _conf_class(tree)
    if cls is None:
        return []
    fields = extract_conf_fields(cls)
    knobs = extract_conf_knobs(cls)
    doc = program.docs.get(CONF_DOC)
    tests = program.tests_text
    findings: List[Finding] = []

    for name, attr, line in knobs:
        key = f"{CONF_KEY_PREFIX}.{name}"
        if attr is not None and attr not in fields:
            findings.append(Finding(rel, line, PASS,
                f"knob '{key}' maps to unknown conf field '{attr}' — the "
                f"knob silently does nothing"))
        if doc is not None and key not in doc:
            findings.append(Finding(rel, line, PASS,
                f"knob '{key}' has no {CONF_DOC} row — every operator-facing "
                f"knob needs its registry entry"))
        if tests and name not in tests and (attr is None or attr not in tests):
            findings.append(Finding(rel, line, PASS,
                f"knob '{key}' (field '{attr}') is referenced by no test — "
                f"its parse/convert path is unguarded"))

    for attr, want in sorted(OFF_PATH_DEFAULTS.items()):
        if attr not in fields:
            # only the real conf module owes every pinned field; a fixture
            # class defining a knob subset is not a stale-pin signal
            if rel == CONF_MODULE:
                findings.append(Finding(rel, cls.lineno, PASS,
                    f"OFF_PATH_DEFAULTS pins unknown conf field '{attr}' — "
                    f"prune the stale pin"))
            continue
        got, line = fields[attr]
        if got is Ellipsis:
            continue  # non-literal default; nothing to compare statically
        if got != want or type(got) is not type(want):
            findings.append(Finding(rel, line, PASS,
                f"off-path default drift: '{attr}' defaults to {got!r} but "
                f"the byte-identical off-path pins {want!r} — flipping a "
                f"default requires re-capturing the golden frames and "
                f"editing OFF_PATH_DEFAULTS"))
    return findings
