"""wire-schema: the wire the code speaks is the wire the doc describes.

Parses the protocol *out of the source* — the ``AmId`` enum and every
module-level ``struct.Struct("...")`` header format in
core/definitions.py — and cross-checks it against docs/SHIM_PROTOCOL.md:

* AmId values must be contiguous from 0 with no duplicates (the wire
  carries the integer; a gap or collision is a silent protocol fork),
* every AmId must appear in the doc next to its pinned value (CamelCase
  name, e.g. ``REPLICA_PUT`` -> ``ReplicaPut``), so adding a frame type
  without documenting it fails CI,
* every header struct format string (``<IQQ>``, ``<iiiI>``, ...) must
  appear in the doc — header layout drift is exactly the silent breakage
  the golden captures exist to catch, and the doc is the reviewable copy.

The extraction half is exported (:func:`extract_am_ids`,
:func:`extract_structs`) and is also what tests/test_core.py uses to
auto-generate the AmId pin list, so the pin and the source cannot
diverge.  When the analyzed program has no SHIM_PROTOCOL.md (installed
package, fixture without injected docs) the doc cross-checks are skipped;
the enum-shape checks always run.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, Program, dotted_name, register_global
from sparkucx_tpu.analysis.config import WIRE_DEFS_MODULE, WIRE_DOC

PASS = "wire-schema"


def _am_class(tree: ast.Module) -> Optional[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "AmId":
            return node
    return None


def extract_am_ids(source: str) -> Dict[str, int]:
    """``{member_name: value}`` from the AmId enum, in definition order."""
    tree = ast.parse(source)
    cls = _am_class(tree)
    out: Dict[str, int] = {}
    if cls is None:
        return out
    for stmt in cls.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, int)
        ):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def extract_structs(source: str) -> Dict[str, str]:
    """``{name: format}`` for module-level ``NAME = struct.Struct("fmt")``."""
    tree = ast.parse(source)
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        val = stmt.value
        if (
            isinstance(tgt, ast.Name)
            and isinstance(val, ast.Call)
            and dotted_name(val.func) in ("struct.Struct", "Struct")
            and val.args
            and isinstance(val.args[0], ast.Constant)
            and isinstance(val.args[0].value, str)
        ):
            out[tgt.id] = val.args[0].value
    return out


def camel(name: str) -> str:
    """``REPLICA_PUT`` -> ``ReplicaPut`` (the doc's spelling)."""
    return "".join(part.capitalize() for part in name.split("_"))


def _find_defs_module(program: Program) -> Optional[Tuple[str, str]]:
    entry = program.module(WIRE_DEFS_MODULE)
    if entry is not None:
        return WIRE_DEFS_MODULE, entry[1]
    # fixture mode: any module defining an AmId enum
    for rel, (tree, source) in sorted(program.modules.items()):
        if _am_class(tree) is not None:
            return rel, source
    return None


@register_global(PASS)
def wire_schema_pass(program: Program) -> List[Finding]:
    located = _find_defs_module(program)
    if located is None:
        return []
    rel, source = located
    tree = ast.parse(source)
    cls = _am_class(tree)
    line_of = {
        stmt.targets[0].id: stmt.lineno
        for stmt in (cls.body if cls is not None else [])
        if isinstance(stmt, ast.Assign) and isinstance(stmt.targets[0], ast.Name)
    }
    am_ids = extract_am_ids(source)
    structs = extract_structs(source)
    struct_lines = {
        stmt.targets[0].id: stmt.lineno
        for stmt in tree.body
        if isinstance(stmt, ast.Assign)
        and len(stmt.targets) == 1
        and isinstance(stmt.targets[0], ast.Name)
    }
    findings: List[Finding] = []
    cls_line = cls.lineno if cls is not None else 1

    # -- enum shape -----------------------------------------------------
    values = list(am_ids.values())
    if len(set(values)) != len(values):
        dupes = sorted(v for v in set(values) if values.count(v) > 1)
        findings.append(Finding(rel, cls_line, PASS,
            f"AmId has duplicate values {dupes} — two frame types sharing "
            f"one wire id is a protocol fork"))
    elif values and sorted(values) != list(range(len(values))):
        findings.append(Finding(rel, cls_line, PASS,
            f"AmId values {sorted(values)} are not contiguous from 0 — a "
            f"gap means a reserved id nobody documented"))

    # -- doc cross-check ------------------------------------------------
    doc = program.docs.get(WIRE_DOC)
    if doc is not None:
        doc_lines = doc.splitlines()
        for name, value in am_ids.items():
            spelled = camel(name)
            pat = re.compile(rf"\b{value}\b")
            if not any(spelled in dl and pat.search(dl) for dl in doc_lines):
                findings.append(Finding(rel, line_of.get(name, cls_line), PASS,
                    f"AmId {name}={value} ('{spelled}') has no row in "
                    f"{WIRE_DOC} — every wire frame type must be documented "
                    f"next to its pinned id"))
        for sname, fmt in structs.items():
            if fmt not in doc:
                findings.append(Finding(rel, struct_lines.get(sname, cls_line), PASS,
                    f"header struct {sname} format '{fmt}' does not appear "
                    f"in {WIRE_DOC} — document the layout before the wire "
                    f"drifts from the doc"))
    return findings
