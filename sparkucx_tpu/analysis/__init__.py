"""Shuffle invariant analyzer (static passes + shared allowlist).

PR 1 and PR 2 made the hot path fast by adopting exactly the idioms that fail
*silently* when misused: donated jit buffers, zero-copy pooled memoryviews,
and a threaded round pipeline — the hazard classes SparkUCX manages by hand
around registered RDMA memory and its progress thread.  This package keeps
those invariants true mechanically as future PRs refactor freely.

Pure stdlib (``ast`` + ``re``): importing it never imports jax or numpy, so
the CLI (``python -m sparkucx_tpu.analysis``) runs on a bare interpreter and
the fixture tests in ``tests/test_analysis.py`` stay jax-free.

Passes (see docs/ANALYSIS.md for the conventions each one enforces):

==================  ========================================================
use-after-donate    reads of a local after it was passed into a donating jit
                    call (``build_exchange`` arg 0, ``build_block_scatter``
                    arg 4, literal ``donate_argnums``)
lock-discipline     fields annotated ``#: guarded by self._lock`` mutated
                    outside a ``with <lock>:`` block
host-sync           blocking host syncs (``block_until_ready``,
                    ``np.asarray`` on non-literals, ``jax.device_get``)
                    inside RoundPipeline submit/drain stages or code
                    reachable from ``_run_exchange``
cache-hygiene       raw shape/capacity parameters flowing into a compile
                    cache key without pow2 bucketing (recompile-bomb
                    detector)
private-access      cross-object ``expr._name`` access (ex
                    lint_private_access)
required-surface    load-bearing public methods must keep existing (ex lint)
lock-order          whole-program lock acquisition graph: cycles,
                    inversions, blocking calls under a lock
reactor-discipline  nothing blocking reachable from reactor loop/worker
                    callbacks (``add_listener`` / ``add_connection``)
thread-lifecycle    spawned threads daemonized-or-joined; inter-thread
                    queues bounded
resource-balance    CreditGate/tenant/pool acquire-release pairs balanced
                    on every exception path
wire-schema         AmId enum + header struct formats extracted from source
                    and cross-checked against docs/SHIM_PROTOCOL.md
conf-registry       every ``spark.shuffle.tpu.*`` knob is a real field,
                    has a DEPLOYMENT.md row, a test reference, and a
                    byte-identical off-path default
lockstep-taint      AST taint dataflow: local telemetry (PlanSignals,
                    metrics/health/breaker reads, clocks) must never reach
                    a collective-affecting ExchangePlan field or steer a
                    pre-collective SPMD branch; the COLLECTIVE/SERVE_PLANE
                    field split is cross-checked against the dataclass
span-discipline     explicit ``start_span`` results closed via ``end_span``
                    in a finally on all paths (or returned with a
                    documented closer); trace-instant names documented in
                    OBSERVABILITY.md
metrics-naming      ``sample``/``counter_dict_provider`` family and name
                    literals match ``sparkucx_tpu_<family>_<name>``; the
                    family set and the OBSERVABILITY.md table pin each
                    other both ways
error-taxonomy      TransportError subclasses classified retryable vs
                    fail-fast in ERROR_TAXONOMY, documented in API.md; the
                    reader's retry path statically barred from swallowing
                    fail-fast types
tier-vocabulary     plan tier strings (lowering, combine, codec, quantize
                    modes, planner/host-recv modes) compared, passed, and
                    documented only from the declared TIER_VOCAB
==================  ========================================================

The runtime half of PR 3 — the buffer sanitizer — lives in
``sparkucx_tpu/memory/sanitizer.py`` (``spark.shuffle.tpu.sanitize``).
"""

from sparkucx_tpu.analysis.base import (  # noqa: F401
    Finding,
    Program,
    all_pass_names,
    analyze_tree,
    is_allowlisted,
    registered_global_passes,
    registered_passes,
    run_source,
)

# Importing the pass modules registers them (base.register side effect).
from sparkucx_tpu.analysis import (  # noqa: F401,E402
    cache,
    confreg,
    donation,
    errors,
    hostsync,
    lockorder,
    locks,
    metricnames,
    private,
    protocol,
    reactor,
    resources,
    spans,
    taint,
    threads,
    tiers,
)
