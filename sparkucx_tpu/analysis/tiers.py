"""tier-vocabulary: plan tier strings are defined once and never drift.

The lossy tiers (int8/blockfloat quantization, dict/rle/delta codecs) made
vocabulary drift a silent-corruption risk, not a typo: a parse site that
accepts ``"bf16"`` where the planner only emits ``"blockfloat"`` routes
data through the wrong kernel, and nothing crashes.  ``TIER_VOCAB``
(analysis/config.py) is the single declared vocabulary per tier knob;
this pass cross-checks every site that mentions one:

* **comparisons** — ``x.lowering == "stock"``, ``impl in ("stock",
  "pallas")``, either operand order: when the non-literal side's terminal
  name is a vocab key, every compared literal must be in that key's
  vocabulary;
* **keywords** — ``f(lowering="dma")``: a keyword named like a vocab key
  with a literal string value must pass the same check;
* **assignments** — ``lowering = "stock"`` / ``self.combine: str =
  "off"``: a target named like a vocab key assigned a literal likewise;
* **docs** — for knobs in ``TIER_DOC_KEYS`` every vocabulary value must
  appear in DEPLOYMENT.md (the conf table is where operators learn the
  accepted spellings — a value missing there is unreachable in practice).

Dynamic values (conf reads, variables) are out of scope — the vocabulary
check bites exactly where a human typed a tier string.  Escape hatch:
``#: tier-ok <reason>`` on the line.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, Program, register_global
from sparkucx_tpu.analysis.config import CONF_DOC, TIER_DOC_KEYS, TIER_VOCAB

PASS = "tier-vocabulary"
ESCAPE = "#: tier-ok"


def _escaped(lines: List[str], lineno: int) -> bool:
    return 1 <= lineno <= len(lines) and ESCAPE in lines[lineno - 1]


def _terminal_name(node: ast.AST) -> Optional[str]:
    """``plan.lowering`` -> lowering, ``impl`` -> impl."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    """String literals on the comparison's literal side: a constant, or a
    tuple/list/set of constants.  None when any element is dynamic."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else None
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def _vocab_pairs(left: ast.AST, right: ast.AST) -> Optional[Tuple[str, List[str]]]:
    """(vocab key, compared literals) when one side is a vocab-named
    name/attribute and the other is all string literals."""
    for named, lit in ((left, right), (right, left)):
        key = _terminal_name(named)
        if key in TIER_VOCAB:
            lits = _literal_strs(lit)
            if lits:
                return key, lits
    return None


@register_global(PASS)
def tier_vocabulary_pass(program: Program) -> List[Finding]:
    findings: List[Finding] = []

    def flag(rel: str, lineno: int, key: str, value: str) -> None:
        vocab = ", ".join(TIER_VOCAB[key])
        findings.append(Finding(rel, lineno, PASS, (
            f"'{value}' is not in the declared '{key}' tier vocabulary "
            f"({vocab}) — tier strings are defined once in "
            f"analysis/config.py TIER_VOCAB; a drifted spelling routes "
            f"data through the wrong kernel silently")))

    for rel, (tree, source) in sorted(program.modules.items()):
        lines = source.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare) and len(node.comparators) == 1:
                pair = _vocab_pairs(node.left, node.comparators[0])
                if pair and not _escaped(lines, node.lineno):
                    key, lits = pair
                    for lit in lits:
                        if lit not in TIER_VOCAB[key]:
                            flag(rel, node.lineno, key, lit)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg in TIER_VOCAB
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and kw.value.value not in TIER_VOCAB[kw.arg]
                        and not _escaped(lines, node.lineno)
                    ):
                        flag(rel, node.lineno, kw.arg, kw.value.value)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                value = node.value
                if not (
                    isinstance(value, ast.Constant) and isinstance(value.value, str)
                ):
                    continue
                for tgt in targets:
                    key = _terminal_name(tgt)
                    if (
                        key in TIER_VOCAB
                        and value.value not in TIER_VOCAB[key]
                        and not _escaped(lines, node.lineno)
                    ):
                        flag(rel, node.lineno, key, value.value)

    doc = program.docs.get(CONF_DOC)
    if doc is not None:
        for key in TIER_DOC_KEYS:
            for value in TIER_VOCAB.get(key, ()):
                # backticked (the conf-table idiom) or a standalone word —
                # substring alone would vacuously pass short values ("off")
                if f"`{value}`" not in doc and not re.search(
                    rf"\b{re.escape(value)}\b", doc
                ):
                    findings.append(Finding("config.py", 1, PASS, (
                        f"tier value '{value}' of knob '{key}' is not "
                        f"documented in {CONF_DOC} — operators learn the "
                        f"accepted spellings from the conf table; enumerate "
                        f"the full vocabulary there")))

    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
