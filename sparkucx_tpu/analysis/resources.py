"""resource-balance: paired acquire/release must survive exceptions.

Credits, tenant quota bytes, and pooled buffers are refundable resources:
``CreditGate.acquire``/``release``, ``TenantRegistry.charge``/``release``,
the store's ``_charge_tenant``/``_release_tenant``, pooled-buffer
``checkout``/``release`` (table: ``RESOURCE_PAIRS`` in analysis/config.py).
A call that claims one and then raises without refunding leaks the
resource forever — the gate's budget shrinks, the tenant's quota fills,
and nothing ever gives it back.

The pass finds every acquire call and demands exception-path balance in
the acquiring function: either the acquire sits inside a ``try`` whose
``finally`` (or an ``except`` handler) calls the paired release on the
*same receiver*, or such a ``try`` is a subsequent sibling statement at
some enclosing block level (the ``gate.acquire(n)`` / ``try: ...
finally: gate.release(n)`` idiom all over the transport).  Receivers that
are synchronization primitives (``*lock*``/``*cond*``/``*sem*``) belong
to the lock passes and are skipped.

Escape hatches, for true ownership transfers:

* a ``#: balanced by <release>`` comment on the acquire line, naming the
  function that carries the refund duty (the ``#: guarded by`` idiom),
* the acquiring function's docstring declaring the transfer: "released
  by ...", "caller releases", or "ownership transfers".
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, docstring_of, dotted_name, register
from sparkucx_tpu.analysis.config import RESOURCE_PAIRS, RESOURCE_RECEIVER_SKIP

PASS = "resource-balance"

_BALANCED_BY = re.compile(r"#:\s*balanced by\s+([A-Za-z_][\w.]*)")

_TRANSFER_PHRASES = ("released by", "caller releases", "ownership transfers")

#: A frame is ``(block, index, try_ctx)``: the statement list containing
#: the (ancestor of the) acquire, its index there, and the enclosing Try
#: when the block is a ``try:`` body.
Frame = Tuple[List[ast.stmt], int, Optional[ast.Try]]


def _stmt_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Calls in one statement's own expressions — child statements are
    visited by the block walk, nested defs/lambdas run elsewhere."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            stack.append(child)


def _acquire_of(call: ast.Call) -> Optional[Tuple[str, str, str]]:
    """``(receiver, acquire_name, release_name)`` when the call is a
    tracked resource acquisition."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in RESOURCE_PAIRS:
        return None
    recv = dotted_name(f.value)
    if recv is None:
        return None
    final = recv.split(".")[-1].lower()
    if any(frag in final for frag in RESOURCE_RECEIVER_SKIP):
        return None
    return recv, f.attr, RESOURCE_PAIRS[f.attr]


def _releases(try_node: ast.Try, recv: str, release: str) -> bool:
    """Does the Try's finally or any except handler call recv.release?"""
    regions: List[ast.AST] = list(try_node.finalbody)
    for handler in try_node.handlers:
        regions.extend(handler.body)
    for region in regions:
        for node in ast.walk(region):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == release
                and dotted_name(node.func.value) == recv
            ):
                return True
    return False


def _protected(frames: List[Frame], recv: str, release: str) -> bool:
    for block, idx, try_ctx in frames:
        if try_ctx is not None and _releases(try_ctx, recv, release):
            return True
        for later in block[idx + 1:]:
            if isinstance(later, ast.Try) and _releases(later, recv, release):
                return True
    return False


def _walk_block(
    block: List[ast.stmt], frames: List[Frame], try_ctx: Optional[ast.Try], sink
) -> None:
    for i, stmt in enumerate(block):
        here = frames + [(block, i, try_ctx)]
        for call in _stmt_calls(stmt):
            sink(call, here)
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            _walk_block(stmt.body, here, None, sink)
            _walk_block(stmt.orelse, here, None, sink)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            _walk_block(stmt.body, here, None, sink)
        elif isinstance(stmt, ast.Try):
            _walk_block(stmt.body, here, stmt, sink)
            for handler in stmt.handlers:
                _walk_block(handler.body, here, None, sink)
            _walk_block(stmt.orelse, here, None, sink)
            _walk_block(stmt.finalbody, here, None, sink)


@register(PASS)
def resource_balance_pass(tree: ast.Module, source: str, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []
    source_lines = source.splitlines()

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        doc = docstring_of(fn).lower()
        transfer_ok = any(p in doc for p in _TRANSFER_PHRASES)

        def sink(call: ast.Call, frames: List[Frame]) -> None:
            acq = _acquire_of(call)
            if acq is None:
                return
            recv, name, release = acq
            if transfer_ok:
                return
            line = source_lines[call.lineno - 1] if call.lineno <= len(source_lines) else ""
            m = _BALANCED_BY.search(line)
            if m is not None and m.group(1).split(".")[-1] == release:
                return
            if _protected(frames, recv, release):
                return
            findings.append(Finding(rel_path, call.lineno, PASS,
                f"'{recv}.{name}(...)' is not balanced by '{recv}.{release}' "
                f"on exception paths (no enclosing/sibling try whose "
                f"finally/except releases it) — leaks the resource on error; "
                f"add the try/finally, or annotate '#: balanced by {release}' "
                f"/ document the ownership transfer in the docstring"))

        _walk_block(fn.body, [], None, sink)
    return findings
