"""Analyzer core: Finding, pass registry, tree walking, allowlist matching.

A *module pass* is a function ``(tree, source, rel_path) -> list[Finding]``
over one already-parsed module.  A *global pass* is a function
``(program) -> list[Finding]`` over a :class:`Program` — every parsed module
plus the docs and tests text the pass cross-references (lock graphs, wire
schema vs SHIM_PROTOCOL.md, conf knobs vs DEPLOYMENT.md).  Passes never
import the code under analysis — every check is AST + source-comment based,
so the analyzer runs without jax (and the fixture tests feed it snippets
that could never import).

Allowlisting: entries live in :mod:`sparkucx_tpu.analysis.config` as
``(file_suffix, pass_name, message_substring)`` triples, each with a reviewed
justification comment (the ``lint_private_access.py`` discipline, inherited).
A finding is allowlisted when the file matches the suffix, the pass matches
(or the entry names ``"*"``), and the substring occurs in the message — the
substring keeps entries narrow: they pin one construct, not a whole file.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One analyzer hit, printed as ``path:line: [pass] message``."""

    path: str  # package-relative, forward slashes (e.g. "transport/tpu.py")
    line: int
    pass_name: str
    message: str

    def render(self) -> str:
        return f"sparkucx_tpu/{self.path}:{self.line}: [{self.pass_name}] {self.message}"


PassFn = Callable[[ast.Module, str, str], List[Finding]]

_REGISTRY: Dict[str, PassFn] = {}


@dataclass
class Program:
    """Whole-program view handed to global passes.

    ``modules`` maps package-relative paths to ``(tree, source)``.  ``docs``
    maps doc basenames (``"SHIM_PROTOCOL.md"``, ``"DEPLOYMENT.md"``) to their
    text — empty when the repo checkout has no docs/ (installed-package runs
    skip doc cross-checks rather than failing).  ``tests_text`` is the
    concatenated source of the tests/ tree, used only for textual
    "is this knob referenced by a test" checks.
    """

    modules: Dict[str, Tuple[ast.Module, str]]
    docs: Dict[str, str]
    tests_text: str

    def module(self, rel_path: str) -> Optional[Tuple[ast.Module, str]]:
        return self.modules.get(rel_path)


GlobalPassFn = Callable[[Program], List[Finding]]

_GLOBAL_REGISTRY: Dict[str, GlobalPassFn] = {}


def register(name: str) -> Callable[[PassFn], PassFn]:
    """Decorator: add a module pass to the registry under ``name``."""

    def deco(fn: PassFn) -> PassFn:
        _REGISTRY[name] = fn
        return fn

    return deco


def register_global(name: str) -> Callable[[GlobalPassFn], GlobalPassFn]:
    """Decorator: add a whole-program pass to the registry under ``name``."""

    def deco(fn: GlobalPassFn) -> GlobalPassFn:
        _GLOBAL_REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> Dict[str, PassFn]:
    return dict(_REGISTRY)


def registered_global_passes() -> Dict[str, GlobalPassFn]:
    return dict(_GLOBAL_REGISTRY)


def all_pass_names() -> List[str]:
    return sorted(set(_REGISTRY) | set(_GLOBAL_REGISTRY))


# ----------------------------------------------------------------------
# allowlist


def is_allowlisted(
    finding: Finding, allowlist: Optional[Iterable[Tuple[str, str, str]]] = None
) -> Optional[Tuple[str, str, str]]:
    """Return the matching allowlist entry, or None."""
    if allowlist is None:
        from sparkucx_tpu.analysis.config import ALLOWLIST

        allowlist = ALLOWLIST
    for entry in allowlist:
        suffix, pass_name, match = entry
        if pass_name not in ("*", finding.pass_name):
            continue
        if suffix and not finding.path.endswith(suffix):
            continue
        if match in finding.message:
            return entry
    return None


# ----------------------------------------------------------------------
# drivers


def run_source(
    source: str,
    passes: Optional[Sequence[str]] = None,
    filename: str = "<fixture>",
    docs: Optional[Dict[str, str]] = None,
    tests_text: str = "",
) -> List[Finding]:
    """Run passes over one source string (the fixture-test entry point).

    Global passes see the string as a one-module :class:`Program` with the
    injected ``docs`` / ``tests_text``; they run only when named explicitly
    in ``passes`` (with no ``passes`` argument every *module* pass runs,
    matching the historical contract fixtures are written against).
    """
    tree = ast.parse(source, filename=filename)
    names = list(passes) if passes else sorted(_REGISTRY)
    out: List[Finding] = []
    program: Optional[Program] = None
    for name in names:
        if name in _REGISTRY:
            out.extend(_REGISTRY[name](tree, source, filename))
        elif name in _GLOBAL_REGISTRY:
            if program is None:
                program = Program(
                    modules={filename: (tree, source)},
                    docs=dict(docs or {}),
                    tests_text=tests_text,
                )
            out.extend(_GLOBAL_REGISTRY[name](program))
        else:
            raise KeyError(name)
    out.sort(key=lambda f: (f.path, f.line, f.pass_name))
    return out


def package_root() -> str:
    """The sparkucx_tpu/ directory this analyzer ships inside."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    """The checkout directory holding sparkucx_tpu/, docs/, and tests/."""
    return os.path.dirname(package_root())


#: Docs that global passes cross-reference, loaded by basename from
#: ``<repo>/docs`` when present.
PROGRAM_DOCS = ("SHIM_PROTOCOL.md", "DEPLOYMENT.md", "OBSERVABILITY.md", "API.md")


def _load_docs() -> Dict[str, str]:
    docs: Dict[str, str] = {}
    docs_dir = os.path.join(repo_root(), "docs")
    for name in PROGRAM_DOCS:
        path = os.path.join(docs_dir, name)
        if os.path.isfile(path):
            with open(path) as f:
                docs[name] = f.read()
    return docs


def _load_tests_text() -> str:
    chunks: List[str] = []
    tests_dir = os.path.join(repo_root(), "tests")
    for dirpath, dirs, files in os.walk(tests_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                with open(os.path.join(dirpath, fname)) as f:
                    chunks.append(f.read())
    return "\n".join(chunks)


def load_program(root: Optional[str] = None) -> Program:
    """Gather every .py under ``root`` plus docs/tests into a Program
    (also the ``--dump-lock-graph`` entry point)."""
    root = root or package_root()
    modules: Dict[str, Tuple[ast.Module, str]] = {}
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as f:
                source = f.read()
            modules[rel] = (ast.parse(source, filename=path), source)
    return Program(modules=modules, docs=_load_docs(), tests_text=_load_tests_text())


def analyze_tree(
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
    allowlist: Optional[Iterable[Tuple[str, str, str]]] = None,
) -> Tuple[List[Finding], List[Tuple[Finding, Tuple[str, str, str]]], int]:
    """Run passes over every .py under ``root``.

    Module passes run per file; global passes run once over the gathered
    :class:`Program`.  Returns ``(violations, allowlisted, num_files)`` where
    ``allowlisted`` pairs each suppressed finding with the entry that
    matched it.  ``allowlist`` defaults to the package ALLOWLIST (the
    tests-tree CI step passes TESTS_ALLOWLIST instead).
    """
    if allowlist is None:
        from sparkucx_tpu.analysis.config import ALLOWLIST

        allowlist = ALLOWLIST
    names = list(passes) if passes else all_pass_names()
    module_names = [n for n in names if n in _REGISTRY]
    global_names = [n for n in names if n in _GLOBAL_REGISTRY]
    violations: List[Finding] = []
    suppressed: List[Tuple[Finding, Tuple[str, str, str]]] = []

    def _sieve(finding: Finding) -> None:
        entry = is_allowlisted(finding, allowlist)
        if entry is not None:
            suppressed.append((finding, entry))
        else:
            violations.append(finding)

    program = load_program(root)
    for rel, (tree, source) in program.modules.items():
        for name in module_names:
            for finding in _REGISTRY[name](tree, source, rel):
                _sieve(finding)
    for name in global_names:
        for finding in _GLOBAL_REGISTRY[name](program):
            _sieve(finding)
    violations.sort(key=lambda f: (f.path, f.line, f.pass_name))
    suppressed.sort(key=lambda p: (p[0].path, p[0].line, p[0].pass_name))
    return violations, suppressed, len(program.modules)


# ----------------------------------------------------------------------
# small AST helpers shared by passes


def callee_name(call: ast.Call) -> Optional[str]:
    """Bare name of the called function: ``f(...)`` -> f, ``a.b.f(...)`` -> f."""
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` chains (Name/Attribute only) as a dotted string."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def docstring_of(fn: ast.AST) -> str:
    try:
        return ast.get_docstring(fn) or ""
    except TypeError:
        return ""
