"""Analyzer core: Finding, pass registry, tree walking, allowlist matching.

A *pass* is a function ``(tree, source, rel_path) -> list[Finding]`` over one
already-parsed module.  Passes never import the code under analysis — every
check is AST + source-comment based, so the analyzer runs without jax (and the
fixture tests feed it snippets that could never import).

Allowlisting: entries live in :mod:`sparkucx_tpu.analysis.config` as
``(file_suffix, pass_name, message_substring)`` triples, each with a reviewed
justification comment (the ``lint_private_access.py`` discipline, inherited).
A finding is allowlisted when the file matches the suffix, the pass matches
(or the entry names ``"*"``), and the substring occurs in the message — the
substring keeps entries narrow: they pin one construct, not a whole file.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One analyzer hit, printed as ``path:line: [pass] message``."""

    path: str  # package-relative, forward slashes (e.g. "transport/tpu.py")
    line: int
    pass_name: str
    message: str

    def render(self) -> str:
        return f"sparkucx_tpu/{self.path}:{self.line}: [{self.pass_name}] {self.message}"


PassFn = Callable[[ast.Module, str, str], List[Finding]]

_REGISTRY: Dict[str, PassFn] = {}


def register(name: str) -> Callable[[PassFn], PassFn]:
    """Decorator: add a pass to the registry under ``name``."""

    def deco(fn: PassFn) -> PassFn:
        _REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> Dict[str, PassFn]:
    return dict(_REGISTRY)


# ----------------------------------------------------------------------
# allowlist


def is_allowlisted(
    finding: Finding, allowlist: Optional[Iterable[Tuple[str, str, str]]] = None
) -> Optional[Tuple[str, str, str]]:
    """Return the matching allowlist entry, or None."""
    if allowlist is None:
        from sparkucx_tpu.analysis.config import ALLOWLIST

        allowlist = ALLOWLIST
    for entry in allowlist:
        suffix, pass_name, match = entry
        if pass_name not in ("*", finding.pass_name):
            continue
        if suffix and not finding.path.endswith(suffix):
            continue
        if match in finding.message:
            return entry
    return None


# ----------------------------------------------------------------------
# drivers


def run_source(
    source: str,
    passes: Optional[Sequence[str]] = None,
    filename: str = "<fixture>",
) -> List[Finding]:
    """Run passes over one source string (the fixture-test entry point)."""
    tree = ast.parse(source, filename=filename)
    names = list(passes) if passes else sorted(_REGISTRY)
    out: List[Finding] = []
    for name in names:
        out.extend(_REGISTRY[name](tree, source, filename))
    out.sort(key=lambda f: (f.path, f.line, f.pass_name))
    return out


def package_root() -> str:
    """The sparkucx_tpu/ directory this analyzer ships inside."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def analyze_tree(
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], List[Tuple[Finding, Tuple[str, str, str]]], int]:
    """Run passes over every .py under ``root``.

    Returns ``(violations, allowlisted, num_files)`` where ``allowlisted``
    pairs each suppressed finding with the entry that matched it.
    """
    from sparkucx_tpu.analysis.config import ALLOWLIST

    root = root or package_root()
    names = list(passes) if passes else sorted(_REGISTRY)
    violations: List[Finding] = []
    suppressed: List[Tuple[Finding, Tuple[str, str, str]]] = []
    num_files = 0
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            num_files += 1
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
            for name in names:
                for finding in _REGISTRY[name](tree, source, rel):
                    entry = is_allowlisted(finding, ALLOWLIST)
                    if entry is not None:
                        suppressed.append((finding, entry))
                    else:
                        violations.append(finding)
    violations.sort(key=lambda f: (f.path, f.line, f.pass_name))
    suppressed.sort(key=lambda p: (p[0].path, p[0].line, p[0].pass_name))
    return violations, suppressed, num_files


# ----------------------------------------------------------------------
# small AST helpers shared by passes


def callee_name(call: ast.Call) -> Optional[str]:
    """Bare name of the called function: ``f(...)`` -> f, ``a.b.f(...)`` -> f."""
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` chains (Name/Attribute only) as a dotted string."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def docstring_of(fn: ast.AST) -> str:
    try:
        return ast.get_docstring(fn) or ""
    except TypeError:
        return ""
