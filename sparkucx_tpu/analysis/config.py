"""Analyzer configuration: allowlist, required surface, and pass tables.

This is the ONE place reviewed exceptions live.  Every entry is
``(file_suffix, pass_name, message_substring)`` and carries a justification
comment above it; an entry without a justification does not get merged.  The
substring pins a single construct — prefer quoting the attribute/function
name from the finding message over blanket file-wide entries.
"""

from __future__ import annotations

#: Reviewed exceptions, grouped by pass.
#:
#: private-access (migrated verbatim from scripts/lint_private_access.py):
#: - hbm_store.py: MapWriter is a friend class defined in the SAME file as
#:   HbmBlockStore — allocation and epoch rollover must happen under the
#:   store's one lock, and exposing that lock publicly would invite misuse
#:   from outside the file.  Reviewed round 3; keep to same-file friends only.
#: - core/block.py: ``np.memmap`` exposes no public way to close its mapping —
#:   ``mm._mmap.close()`` is the canonical numpy idiom for releasing the fd
#:   eagerly (numpy/numpy#13510); guarded by try/except for numpy internals
#:   moving.
#: - daemon.py / peer.py ``._sendmsg_all``: the partial-send/IOV_MAX-safe
#:   vectored send loop lives as a ``BlockServer`` staticmethod; the store
#:   daemon's serve path and peer.py's own ``_ServerGroup`` lane senders
#:   (same file, but the pass keys on the attribute) reuse it so every wire
#:   writer handles short ``sendmsg`` returns identically.  It is a pure
#:   function of (socket, parts) — no BlockServer state — kept underscored
#:   because the iovec windowing is an implementation detail of the wire,
#:   not transport API.  Reviewed with the striped-wire PR.
#: - hbm_store.py ``._charge_tenant`` / ``._staging``: same-file friends
#:   again — MapWriter/DeviceMapWriter must run the tenant admission check
#:   inside the store-lock critical section that allocates the region (an
#:   over-quota write must fail typed with nothing allocated), and the tier
#:   probe ``_tier_of`` classifies a round by its ``_ShuffleState._staging``
#:   backing (memmap vs RAM).  Both stay underscored: admission and tier
#:   state are store internals, not writer/eviction API.  Reviewed with the
#:   multi-tenant service PR.
#: - service/tenants.py ``._gate``: ``Tenant`` is a same-file data holder of
#:   its ``TenantRegistry`` — the registry lazily creates the per-tenant
#:   CreditGate under its own lock; exposing the slot publicly would invite
#:   unlocked construction.  Reviewed with the multi-tenant service PR.
#:
#: host-sync:
#: - "drain stage": the drain lane IS the pipeline's sanctioned host-sync
#:   point.  Submit issues ``copy_to_host_async`` / device work and returns;
#:   drain runs on the one-worker drain executor and *observes* completion
#:   (``np.asarray`` / ``block_until_ready``) without stalling the submit
#:   lane — that overlap is the whole point of RoundPipeline.  Blocking in a
#:   SUBMIT stage is the real bug this pass exists to catch, and submit-stage
#:   findings are never allowlisted wholesale.
#: - spmd.py ``_submit``: ``np.asarray(payload)`` sits on the host-payload
#:   branch (the ``isinstance(payload, jax.Array)`` arm above it device_puts
#:   instead); asarray over an ndarray is a free view, not a device sync.
#:   (The retired per-variant engines' ``_assemble``/``_submit_quota``
#:   entries were pruned with PR 13 — the unified plan executor replaced
#:   them.)
#:
#: - tpu.py ``_recover_and_rerun``: the degraded-mode recovery path (elastic
#:   mesh, reached from ``_run_exchange`` only after an executor died).  It
#:   deliberately materializes restaged replica rounds and degraded-wave
#:   results host-side: recovery is an abort-and-rerun cold path measured in
#:   hundreds of ms, not a pipeline lane — blocking there is the design.
#:
#: - testing/faults.py ``kill_executor``: the chaos harness's whole job is to
#:   kill an executor the way SIGKILL would — yanking the live connection
#:   cache (``._conns``/``._zombies``) out from under the transport is the
#:   fault being injected, not an API to encourage.  Test-only module (no
#:   production import path reaches it with nothing armed); reviewed with the
#:   robustness PR.  ``._chaos_killed`` is the harness's own idempotency tag
#:   stamped onto the victim (second kill = no-op) — chaos bookkeeping, not
#:   transport state, so it stays the harness's private mark.
#:
#: cache-hygiene:
#: - hbm_store.py ``out_rows``: the scatter output shape IS the staging
#:   geometry — ``out_rows`` comes from ``staging_capacity_per_executor``
#:   (fixed per store), not from data, so distinct values are bounded by
#:   distinct configs.  Bucketing it would over-allocate the HBM staging
#:   array itself rather than a transient pad.
ALLOWLIST = {
    ("testing/faults.py", "private-access", "._conns"),
    ("testing/faults.py", "private-access", "._zombies"),
    ("testing/faults.py", "private-access", "._chaos_killed"),
    ("store/hbm_store.py", "private-access", "._lock"),
    ("store/hbm_store.py", "private-access", "._rollover"),  # also ._rollover_device
    ("store/hbm_store.py", "private-access", "._charge_tenant"),
    ("store/hbm_store.py", "private-access", "._staging"),
    ("service/tenants.py", "private-access", "._gate"),
    ("core/block.py", "private-access", "._mmap"),
    ("shuffle/daemon.py", "private-access", "._sendmsg_all"),
    ("transport/peer.py", "private-access", "._sendmsg_all"),
    ("transport/tpu.py", "host-sync", "(via '_recover_and_rerun')"),
    ("store/hbm_store.py", "cache-hygiene", "'out_rows'"),
}

#: Public-surface contract: these classes must keep these methods.  Transports,
#: writers, and the perf harness are wired to them by name across layers, and
#: the device-staging path (ISSUE 2) made several of them load-bearing surface
#: — a rename here fails the analyzer before it fails at runtime in another
#: layer.  (Migrated from scripts/lint_private_access.py.)
REQUIRED_SURFACE = {
    "store/hbm_store.py": {
        "HbmBlockStore": [
            "seal", "map_writer", "read_block", "block_staging_view",
            "region_bytes", "num_rounds", "host_staging_allocated",
        ],
        "MapWriter": ["write_partition", "write_partition_device", "commit"],
    },
    "shuffle/writer.py": {
        "DeviceMapWriter": ["write_partition", "commit"],
        "TpuShuffleMapOutputWriter": [
            "get_partition_writer", "write_partition_device", "commit_all_partitions",
        ],
    },
}

# ----------------------------------------------------------------------
# use-after-donate tables

#: Builders whose returned callable donates these positional args.  Donation
#: may be conditional at runtime (build_exchange only donates when
#: send_rows == recv_rows) — the pass treats may-donate as must-not-reuse,
#: which is exactly the contract callers must code to.
DONATING_BUILDERS = {
    "build_exchange": (0,),
    "build_hierarchical_exchange": (0,),
    "build_block_scatter": (4,),  # fn(starts, counts, outs, packed, dst): dst
    "build_ici_exchange": (0,),  # scheduled-ring exchange: same donation rule
    # fused send side fn(starts, counts, outs, packed, staging, sizes): staging
    "build_fused_ici_exchange": (4,),
    "build_quantized_exchange": (0,),  # tier-b twin of build_ici_exchange
    "build_quantized_fused_exchange": (4,),  # tier-b twin: staging donated
    # fused combine fn(data, sizes, accv, accc): the running accumulator is
    # consumed and re-emitted in place across quota sub-rounds
    "build_combine_exchange": (2, 3),
    "_exchange_fn": (0,),  # TpuShuffleCluster cache front-end for build_exchange
}

#: Builders returning ``(fn, ...)`` tuples where element 0 is the donating
#: callable (same positions convention).
TUPLE_DONATING_BUILDERS = {
    "_scatter_fn": (4,),  # HbmBlockStore cache front-end for build_block_scatter
}

# ----------------------------------------------------------------------
# host-sync tables

#: Root functions whose whole (module-local) call graph must stay free of
#: blocking host syncs, beyond RoundPipeline stages discovered per-module.
HOST_SYNC_ROOTS = ("_run_exchange",)

# ----------------------------------------------------------------------
# cache-hygiene tables

#: Attribute-name fragments that identify a compile cache.
CACHE_NAME_MARKERS = ("cache", "_fns")

#: Callee names that count as jit-compile builders (a cache keyed on raw
#: shapes in front of one of these is a recompile bomb).
BUILDER_PREFIXES = ("build_",)
BUILDER_NAMES = ("jit",)

#: Callee / method names that sanctify a shape value as bucketed.
#: quota_slot_rows / plan_exchange (ops/skew.py) pow2-round the quota-capped
#: slot — a plan's slot_rows is a bucket_send_rows fixed point, so shape
#: params flowing through the skew planner are bucketed by construction.
BUCKETING_MARKERS = (
    "bucket_send_rows",
    "round_up_to_next_power_of_two",
    "bit_length",
    "quota_slot_rows",
    "plan_exchange",
    "schedule_chunks",  # pow2 chunk-count clamp (ops/ici_exchange.py)
)

# ----------------------------------------------------------------------
# lock-order tables

#: Cross-object receiver resolution for the lock-order graph: a call through
#: ``self.<attr>.method(...)`` is resolved to a class when ``<attr>`` appears
#: here, so acquisitions inside that class's method become edges from every
#: lock held at the call site.  This is the wiring that actually exists in
#: the package (store/transport/service composition) — an attr missing here
#: just means the call contributes no edges, never a false cycle.
LOCK_ATTR_CLASSES = {
    "store": "HbmBlockStore",
    "_store": "HbmBlockStore",
    "tenants": "TenantRegistry",
    "eviction": "EvictionManager",
    "_eviction": "EvictionManager",
    "_credits": "CreditGate",
    "_gate": "CreditGate",
    "gate": "CreditGate",
    "_reactor": "Reactor",
    "server": "BlockServer",
    "membership": "ClusterMembership",
    # obs plane (PR 14): the registry lock is a leaf by design (providers run
    # OUTSIDE it — obs/metrics.py snapshot()); the recorder and tracer locks
    # guard only their own ring/bundle lists.  Wiring them here lets the
    # lock-order pass prove those claims instead of assuming them.
    "metrics": "MetricsRegistry",
    "_metrics": "MetricsRegistry",
    "recorder": "FlightRecorder",
    "tracer": "Tracer",
    # popularity-aware serving tier (PR 19): both locks are leaves by design
    # — the tracker computes EWMAs and the serve cache mutates its LRU map
    # with no calls out while held.  Wiring them here lets the lock-order
    # pass prove that instead of assuming it.
    "popularity": "BlockPopularity",
    "_popularity": "BlockPopularity",
    "serve_cache": "ServeCache",
    "_serve_cache": "ServeCache",
}

#: Locks that exist to SERIALIZE a blocking wire write and are therefore
#: exempt from the held-across-blocking-call check, keyed ``Class.lockname``
#: (``*`` wildcards the class).  Justifications:
#: - ``*.send_lock``: the per-connection frame-write serializer shared by a
#:   lane's serve thread and its _ServerGroup sender — control acks must
#:   interleave with chunk frames at frame granularity, so holding it across
#:   ``sendall``/``sendmsg`` IS the contract (transport/peer.py).
#: - ``_PeerConnection.lock``: the client-side twin — one frame on the wire
#:   at a time per connection; sendall under it is the serializer working.
#: - ``DaemonClient._lock``: the JVM-shim client is a synchronous
#:   request/response RPC over one socket — the lock holds the socket for
#:   the full send+recv round trip BY CONTRACT (two interleaved calls would
#:   cross-read each other's replies).  Blocking under it is the protocol.
LOCK_BLOCKING_EXEMPT = {
    "*.send_lock",
    "_PeerConnection.lock",
    "DaemonClient._lock",
}

# ----------------------------------------------------------------------
# reactor-discipline tables

#: Reactor registration methods and the lane the callback runs on.
#: ``add_listener(sock, on_accept)`` callbacks run ON the selector loop
#: thread — any block there stalls every connection the process serves.
#: ``add_connection(conn, serve_once, on_close=...)`` callbacks run on the
#: bounded worker pool — blocking frame reads are sanctioned there (the
#: reactor's documented design), but joins, untimed waits, and unbounded
#: queue puts can deadlock the pool against itself.
REACTOR_LOOP_REGISTRARS = ("add_listener",)
REACTOR_WORKER_REGISTRARS = ("add_connection",)

# ----------------------------------------------------------------------
# resource-balance tables

#: Paired acquire/release method names: a call to the key must be balanced
#: by a call to the value on every exception path (sibling try/finally or
#: except-reraise), unless the acquiring function documents an ownership
#: transfer ("released by ..." / "caller releases" / "ownership transfers"
#: in its docstring) or the call line carries a ``#: balanced by <name>``
#: annotation naming the releasing function.
RESOURCE_PAIRS = {
    "acquire": "release",        # CreditGate wire credits
    "try_acquire": "release",
    "charge": "release",         # TenantRegistry HBM quota bytes
    "_charge_tenant": "_release_tenant",  # store-side tenant admission
    "checkout": "release",       # pooled-buffer handles
}

#: Receivers whose final name contains one of these fragments are
#: synchronization primitives, not refundable resources — ``lock.acquire()``
#: is the lock-discipline passes' business, not this one's.
RESOURCE_RECEIVER_SKIP = ("lock", "cond", "sem")

# ----------------------------------------------------------------------
# wire-schema tables

#: Module defining the wire: the AmId enum and every frame/header struct.
WIRE_DEFS_MODULE = "core/definitions.py"
#: Doc the schema is cross-checked against (docs/ basename).
WIRE_DOC = "SHIM_PROTOCOL.md"

# ----------------------------------------------------------------------
# conf-knob registry tables

#: Module defining TpuShuffleConf + from_spark_conf, and the doc that must
#: carry a row per knob.
CONF_MODULE = "config.py"
CONF_DOC = "DEPLOYMENT.md"
CONF_KEY_PREFIX = "spark.shuffle.tpu"

#: Knobs handled outside the from_spark_conf (name, attr, conv) table —
#: parsed with bespoke code — mapped to the conf field they set.
SPECIAL_CONF_KNOBS = {
    "memory.preAllocateBuffers": "prealloc_buffers",
    "memory.minBufferSize": "min_buffer_size",
    "memory.minAllocationSize": "min_allocation_size",
    "listener.sockaddr": "listener_address",
}

#: The byte-identical off-path pin: every feature added since the golden
#: wire captures must DEFAULT to the value that leaves frames, store
#: behavior, and exchange results byte-for-byte identical to the
#: pre-feature build.  The conf-registry pass compares these against the
#: dataclass field defaults in config.py — flipping one here requires
#: re-capturing the golden frames, which is exactly the review this table
#: forces.
OFF_PATH_DEFAULTS = {
    "wire_streams": 1,
    "wire_checksum": False,
    "wire_compress_codec": "off",
    "quantize_mode": "off",
    "replication_factor": 0,
    "elastic": False,
    "membership_suspect_after_ms": 0,
    "replication_max_backlog_bytes": 0,
    "tenants_enabled": False,
    "tenant_hbm_quota_bytes": 0,
    "eviction_epoch_ms": 0,
    "server_workers": 0,
    "exchange_impl": "stock",
    "device_staging": False,
    "keep_device_recv": False,
    "use_shm_staging": False,
    "slot_quota_rows": 0,
    "planner_mode": "static",
    "planner_optimize": False,
    # adaptive-only thresholds: inert while planner_mode == "static" (the
    # off-path planner never reads them), so their defaults ARE the pinned
    # off-path values — all four planner.* knobs stay in one reviewed table
    "planner_target_padding": 0.5,
    "planner_min_quota_rows": 256,
    "host_recv_mode": "array",
    "sanitize": False,
    "fetch_hedge_ms": 0,
    "fetch_hedge_max_ms": 0,
    "breaker_failure_threshold": 0,
    "breaker_cooldown_ms": 1000,
    "store_soft_watermark": 0,
    "store_hard_watermark": 0,
    "server_accept_backlog": 0,
    "obs_trace_context": False,
    "obs_metrics_port": 0,
    "obs_ring_capacity": 8192,
    "obs_postmortem_dir": "",
    "exchange_fused_combine": False,
    # popularity-aware serving tier: threshold 0 = no tracker, no HotSetPull
    # frames, no widened replica pushes; serve_hot_replicas is hot-path-only
    # (inert while the threshold is 0) and serve_cache_bytes 0 = no decoded
    # cache, so serve behavior stays byte-identical.  compress_cache_bytes is
    # only consulted while compress.codec is on (itself pinned "off") — its
    # default preserves the historical 128 MiB pool cap.
    "serve_hot_threshold_fetches_per_sec": 0.0,
    "serve_hot_replicas": 4,
    "serve_cache_bytes": 0,
    "compress_cache_bytes": 128 << 20,
    # serve.holdersTtlMs is only consulted while serve.hotThresholdFetchesPerSec
    # is on (itself pinned 0.0 above); its default preserves the historical
    # hard-coded 250 ms advertisement TTL byte-for-byte.  The query-runner
    # knobs gate the lineage cache (sparkucx_tpu/query): off = every exchange
    # executes and is unregistered after its query, so wire/store behavior is
    # byte-identical to a cache-less runner; cacheMaxBytes is inert while the
    # cache is off.
    "serve_holders_ttl_ms": 250,
    "query_cache_enabled": False,
    "query_cache_max_bytes": 0,
}

# ----------------------------------------------------------------------
# lockstep-taint tables

#: The plan dataclass and the module defining it.  The taint pass parses the
#: dataclass fields and cross-checks the declared COLLECTIVE/SERVE_PLANE
#: split below against them, so the registry cannot drift from the code.
PLAN_MODULE = "ops/skew.py"
PLAN_CLASS = "ExchangePlan"

#: ExchangePlan fields that shape the COLLECTIVE schedule: in the SPMD
#: deployment every process compiles and submits collectives from these, so
#: they must be pure functions of conf + all-gathered geometry — a per-host
#: telemetry read steering one of them is a divergent compiled program and a
#: cluster-wide hang.  ``quantize_mode``/``quantize_block`` are here (not
#: serve-plane) because they select a DIFFERENT compiled collective
#: (``build_quantized_exchange``) — the lossy encode runs inside the kernel.
COLLECTIVE_FIELDS = (
    "slot_rows",
    "chunks_per_round",
    "single_shot",
    "round_order",
    "lowering",
    "quantize_mode",
    "quantize_block",
    "combine",
)

#: Fields local telemetry MAY steer: they shape how one host serves or
#: overlaps, never what any collective computes.  ``pipeline_depth`` is here
#: deliberately (ops/planner.py:36): depth changes WHEN stages overlap,
#: never the order collectives are submitted in, so it may vary per host.
SERVE_PLANE_FIELDS = (
    "pipeline_depth",
    "streams",
    "codec",
    "hedge_ms",
)

#: Modules the taint dataflow runs over (the plan-producing and
#: plan-consuming layers).  Fixture runs that contain none of these analyze
#: every module they were given instead.
TAINT_MODULES = (
    "ops/planner.py",
    "ops/skew.py",
    "transport/spmd.py",
    "transport/executor.py",
)

#: Callee names whose results are local telemetry (may differ per host):
#: metric registry snapshots, PlanSignals construction, health/wire/breaker
#: reads, and clocks.  Matched on the bare callee name, so both
#: ``registry.snapshot()`` and ``self.membership.snapshot()`` taint.
TAINT_SOURCE_CALLS = (
    "PlanSignals",
    "from_registry",
    "snapshot",
    "health_snapshot",
    "wire_lane_stats",
    "breaker_state",
    "breaker_allows",
    "eviction_stats",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
    "process_time",
    "time",
)

#: Attribute reads that (re-)introduce taint wherever they appear:
#: ``ctx.signals`` is THE sanctioned telemetry channel into a planner, and
#: reading it back out is where serve-plane-only discipline must hold.
TAINT_SOURCE_ATTRS = ("signals",)

#: Constructor/rewrite callees whose keywords are plan/context fields — the
#: taint sinks.  A tainted value bound to a COLLECTIVE_FIELDS keyword (or a
#: collective keyword written under a telemetry-tainted branch) is a
#: finding; taint bound to a serve-plane keyword (or the ``signals``
#: channel) is absorbed there by design.
PLAN_CONSTRUCTORS = ("ExchangePlan", "PlanContext", "replace")

#: Functions whose branch conditions run BEFORE collective submission in the
#: SPMD transport (matched by name in the analyzed modules): a tainted
#: condition there can diverge which collective each process enters.
#: Branches whose body ends in ``raise`` are exempt — failing fast before a
#: collective is the sanctioned response to local bad news (membership), a
#: divergent schedule is not.
SPMD_PRECOLLECTIVE_FUNCS = ("run_exchange",)

# ----------------------------------------------------------------------
# span-discipline / metrics-naming tables

#: Doc carrying the metric family registry and the trace-point table.
TRACE_DOC = "OBSERVABILITY.md"

#: The tracer implementation itself (opens/closes spans by definition) —
#: excluded from the span-discipline walk.
TRACE_IMPL_MODULES = ("utils/trace.py",)

#: The metrics module and the exposition prefix every family rides under
#: (``<prefix>_<family>_<name>``); the pass pins the PREFIX constant and
#: checks family/name literals against the scheme and the TRACE_DOC table.
OBS_METRICS_MODULE = "obs/metrics.py"
METRIC_PREFIX = "sparkucx_tpu"

# ----------------------------------------------------------------------
# error-taxonomy tables

#: Module defining the TransportError hierarchy, and the doc whose "Failure
#: semantics" section must name every classified type.
ERROR_MODULE = "core/operation.py"
ERROR_BASE = "TransportError"
ERROR_DOC = "API.md"

#: THE machine-checked retryable/fail-fast registry (API.md "Failure
#: semantics" points here).  Every TransportError subclass in the package
#: must appear exactly once; the pass fails on an unclassified subclass AND
#: on a stale entry naming a deleted class.
#: - retryable: transient per-block conditions — another attempt (or a
#:   replica) can succeed.
#: - retryable-backoff: the third arm — the server shed load; retry after a
#:   typed backoff hint, never instantly.
#: - fail-fast: deterministic rejections and no-recovery losses — every
#:   replica gives the same answer, so a retry only burns the budget and
#:   hides the real error.
ERROR_TAXONOMY = {
    "BlockNotFoundError": "retryable",
    "BlockCorruptError": "retryable",
    "ResourceExhaustedError": "retryable-backoff",
    "UnknownTenantError": "fail-fast",
    "TenantQuotaExceededError": "fail-fast",
    "ExecutorLostError": "fail-fast",
}

#: Reader retry/failover functions (matched by name): statically barred from
#: catching a fail-fast type, and a base-class ``except TransportError``
#: there must carry an isinstance re-raise guard covering EVERY fail-fast
#: class — anything less silently retries a deterministic rejection.
RETRY_PATH_FUNCS = ("_retry_fetch",)

# ----------------------------------------------------------------------
# tier-vocabulary tables

#: THE plan/conf tier vocabularies, defined once.  The pass cross-checks
#: every parse/validate/literal-comparison site against these: a string
#: compared to, assigned to, or passed as a keyword named after one of these
#: fields must be in its vocabulary — tier typos become findings instead of
#: silently-dead dispatch arms.  ``lowering`` carries the union of the plan
#: tier (stock|pallas|auto) and the kernel lowering it resolves to
#: (auto|dma|xla|interpret|tiled) because both ride the same field name.
#: The bare word ``impl`` is deliberately NOT pinned: every op module uses
#: it for its own local dispatch tiers (ragged|dense|radix|single|...), so
#: a global vocabulary for it would be fiction — the plan-level names
#: (``lowering``, ``exchange_impl``, ``gather_impl``) are the pinned ones.
TIER_VOCAB = {
    "lowering": ("stock", "pallas", "auto", "dma", "xla", "tiled", "interpret"),
    "exchange_impl": ("stock", "pallas", "auto"),
    "gather_impl": ("auto", "dma", "tiled", "xla"),
    "combine": ("off", "auto", "dense", "sorted"),
    "codec": ("off", "dict", "rle", "delta"),
    "wire_compress_codec": ("off", "dict", "rle", "delta"),
    "quantize_mode": ("off", "int8", "blockfloat"),
    "planner_mode": ("static", "adaptive"),
    "host_recv_mode": ("array", "memmap", "device"),
}

#: Conf-backed vocabulary keys whose every value must have a DEPLOYMENT.md
#: mention (operators pick these by name; an undocumented tier is
#: unreachable in practice and rots).
TIER_DOC_KEYS = (
    "exchange_impl",
    "gather_impl",
    "wire_compress_codec",
    "quantize_mode",
    "planner_mode",
    "host_recv_mode",
)

# ----------------------------------------------------------------------
# tests-tree run

#: Reviewed exceptions for analyzer runs over the tests/ tree (the CI step
#: runs the private-access pass there so tests cannot quietly couple to
#: internals either).  Same entry shape and review bar as ALLOWLIST.
#:
#: Policy: private ATTRIBUTE access is sanctioned wholesale — white-box
#: tests poke instance internals (store ``._state``, wire ``._inflight``,
#: fault-injection on ``._conns``) by design, and per-attribute entries
#: would just transcribe the test suite.  Private IMPORTS stay individually
#: reviewed: copying an internal symbol across a module boundary couples
#: the test to a name the package is free to rename, so each one must
#: justify why no public seam exists.
#: - ``_StripeRx`` (transport/peer.py): the stripe reassembly unit tests
#:   drive the receiver state machine directly — no public entry point
#:   exercises mid-stripe states deterministically.
#: - ``_read_frame`` (shuffle/daemon.py): the daemon protocol tests speak
#:   raw frames on a socket; the helper IS the framing contract under test.
#: - ``_estimate`` (shuffle/external.py): spill-size estimator unit tests;
#:   the public path only exposes it through end-to-end sort memory use.
#: - ``_ici_order`` (parallel/mesh.py): ring-order derivation pinned
#:   against the documented executor ordering.
#: - ``_free_port`` (tests' own test_spmd.py helper): test-to-test import,
#:   no package coupling at all.
TESTS_ALLOWLIST = {
    ("", "private-access", "private attribute access"),
    ("", "private-access", "private import: _StripeRx"),
    ("", "private-access", "private import: _read_frame"),
    ("", "private-access", "private import: _estimate"),
    ("", "private-access", "private import: _ici_order"),
    ("", "private-access", "private import: _free_port"),
}
