"""Analyzer configuration: allowlist, required surface, and pass tables.

This is the ONE place reviewed exceptions live.  Every entry is
``(file_suffix, pass_name, message_substring)`` and carries a justification
comment above it; an entry without a justification does not get merged.  The
substring pins a single construct — prefer quoting the attribute/function
name from the finding message over blanket file-wide entries.
"""

from __future__ import annotations

#: Reviewed exceptions, grouped by pass.
#:
#: private-access (migrated verbatim from scripts/lint_private_access.py):
#: - hbm_store.py: MapWriter is a friend class defined in the SAME file as
#:   HbmBlockStore — allocation and epoch rollover must happen under the
#:   store's one lock, and exposing that lock publicly would invite misuse
#:   from outside the file.  Reviewed round 3; keep to same-file friends only.
#: - core/block.py: ``np.memmap`` exposes no public way to close its mapping —
#:   ``mm._mmap.close()`` is the canonical numpy idiom for releasing the fd
#:   eagerly (numpy/numpy#13510); guarded by try/except for numpy internals
#:   moving.
#: - daemon.py / peer.py ``._sendmsg_all``: the partial-send/IOV_MAX-safe
#:   vectored send loop lives as a ``BlockServer`` staticmethod; the store
#:   daemon's serve path and peer.py's own ``_ServerGroup`` lane senders
#:   (same file, but the pass keys on the attribute) reuse it so every wire
#:   writer handles short ``sendmsg`` returns identically.  It is a pure
#:   function of (socket, parts) — no BlockServer state — kept underscored
#:   because the iovec windowing is an implementation detail of the wire,
#:   not transport API.  Reviewed with the striped-wire PR.
#: - hbm_store.py ``._charge_tenant`` / ``._staging``: same-file friends
#:   again — MapWriter/DeviceMapWriter must run the tenant admission check
#:   inside the store-lock critical section that allocates the region (an
#:   over-quota write must fail typed with nothing allocated), and the tier
#:   probe ``_tier_of`` classifies a round by its ``_ShuffleState._staging``
#:   backing (memmap vs RAM).  Both stay underscored: admission and tier
#:   state are store internals, not writer/eviction API.  Reviewed with the
#:   multi-tenant service PR.
#: - service/tenants.py ``._gate``: ``Tenant`` is a same-file data holder of
#:   its ``TenantRegistry`` — the registry lazily creates the per-tenant
#:   CreditGate under its own lock; exposing the slot publicly would invite
#:   unlocked construction.  Reviewed with the multi-tenant service PR.
#:
#: host-sync:
#: - "drain stage": the drain lane IS the pipeline's sanctioned host-sync
#:   point.  Submit issues ``copy_to_host_async`` / device work and returns;
#:   drain runs on the one-worker drain executor and *observes* completion
#:   (``np.asarray`` / ``block_until_ready``) without stalling the submit
#:   lane — that overlap is the whole point of RoundPipeline.  Blocking in a
#:   SUBMIT stage is the real bug this pass exists to catch, and submit-stage
#:   findings are never allowlisted wholesale.
#: - spmd.py ``_submit``: ``np.asarray(payload)`` sits on the host-payload
#:   branch (the ``isinstance(payload, jax.Array)`` arm above it device_puts
#:   instead); asarray over an ndarray is a free view, not a device sync.
#: - tpu.py ``_assemble``: the mixed host/device round fallback D2H-copies
#:   device payloads into the host assembly buffer.  That D2H is the
#:   documented cost of mixed-mode rounds (an executor sealed fewer device
#:   rounds than its peers), accepted until a device-side repack exists.
#: - tpu.py ``_submit_quota``: the quota engine's twin of ``_assemble`` — the
#:   np.asarray sits on the mixed host/device branch (the all-device arm above
#:   it slices on-device via jnp), guarded by ``isinstance(p, jax.Array)``;
#:   same documented mixed-mode D2H cost, same scope.
#:
#: - tpu.py ``_recover_and_rerun``: the degraded-mode recovery path (elastic
#:   mesh, reached from ``_run_exchange`` only after an executor died).  It
#:   deliberately materializes restaged replica rounds and degraded-wave
#:   results host-side: recovery is an abort-and-rerun cold path measured in
#:   hundreds of ms, not a pipeline lane — blocking there is the design.
#:
#: - testing/faults.py ``kill_executor``: the chaos harness's whole job is to
#:   kill an executor the way SIGKILL would — yanking the live connection
#:   cache (``._conns``/``._zombies``) out from under the transport is the
#:   fault being injected, not an API to encourage.  Test-only module (no
#:   production import path reaches it with nothing armed); reviewed with the
#:   robustness PR.
#:
#: cache-hygiene:
#: - hbm_store.py ``out_rows``: the scatter output shape IS the staging
#:   geometry — ``out_rows`` comes from ``staging_capacity_per_executor``
#:   (fixed per store), not from data, so distinct values are bounded by
#:   distinct configs.  Bucketing it would over-allocate the HBM staging
#:   array itself rather than a transient pad.
ALLOWLIST = {
    ("testing/faults.py", "private-access", "._conns"),
    ("testing/faults.py", "private-access", "._zombies"),
    ("store/hbm_store.py", "private-access", "._lock"),
    ("store/hbm_store.py", "private-access", "._rollover"),  # also ._rollover_device
    ("store/hbm_store.py", "private-access", "._charge_tenant"),
    ("store/hbm_store.py", "private-access", "._staging"),
    ("service/tenants.py", "private-access", "._gate"),
    ("core/block.py", "private-access", "._mmap"),
    ("shuffle/daemon.py", "private-access", "._sendmsg_all"),
    ("transport/peer.py", "private-access", "._sendmsg_all"),
    ("transport/tpu.py", "host-sync", "drain stage"),
    ("transport/spmd.py", "host-sync", "drain stage"),
    ("transport/spmd.py", "host-sync", "'np.asarray' in pipeline submit stage '_submit'"),
    ("transport/tpu.py", "host-sync", "'np.asarray' in pipeline submit stage '_submit' (via '_assemble')"),
    ("transport/tpu.py", "host-sync", "'np.asarray' in pipeline submit stage '_submit_quota'"),
    ("transport/tpu.py", "host-sync", "(via '_recover_and_rerun')"),
    ("store/hbm_store.py", "cache-hygiene", "'out_rows'"),
}

#: Public-surface contract: these classes must keep these methods.  Transports,
#: writers, and the perf harness are wired to them by name across layers, and
#: the device-staging path (ISSUE 2) made several of them load-bearing surface
#: — a rename here fails the analyzer before it fails at runtime in another
#: layer.  (Migrated from scripts/lint_private_access.py.)
REQUIRED_SURFACE = {
    "store/hbm_store.py": {
        "HbmBlockStore": [
            "seal", "map_writer", "read_block", "block_staging_view",
            "region_bytes", "num_rounds", "host_staging_allocated",
        ],
        "MapWriter": ["write_partition", "write_partition_device", "commit"],
    },
    "shuffle/writer.py": {
        "DeviceMapWriter": ["write_partition", "commit"],
        "TpuShuffleMapOutputWriter": [
            "get_partition_writer", "write_partition_device", "commit_all_partitions",
        ],
    },
}

# ----------------------------------------------------------------------
# use-after-donate tables

#: Builders whose returned callable donates these positional args.  Donation
#: may be conditional at runtime (build_exchange only donates when
#: send_rows == recv_rows) — the pass treats may-donate as must-not-reuse,
#: which is exactly the contract callers must code to.
DONATING_BUILDERS = {
    "build_exchange": (0,),
    "build_hierarchical_exchange": (0,),
    "build_block_scatter": (4,),  # fn(starts, counts, outs, packed, dst): dst
    "build_ici_exchange": (0,),  # scheduled-ring exchange: same donation rule
    # fused send side fn(starts, counts, outs, packed, staging, sizes): staging
    "build_fused_ici_exchange": (4,),
    "build_quantized_exchange": (0,),  # tier-b twin of build_ici_exchange
    "build_quantized_fused_exchange": (4,),  # tier-b twin: staging donated
    "_exchange_fn": (0,),  # TpuShuffleCluster cache front-end for build_exchange
}

#: Builders returning ``(fn, ...)`` tuples where element 0 is the donating
#: callable (same positions convention).
TUPLE_DONATING_BUILDERS = {
    "_scatter_fn": (4,),  # HbmBlockStore cache front-end for build_block_scatter
}

# ----------------------------------------------------------------------
# host-sync tables

#: Root functions whose whole (module-local) call graph must stay free of
#: blocking host syncs, beyond RoundPipeline stages discovered per-module.
HOST_SYNC_ROOTS = ("_run_exchange",)

# ----------------------------------------------------------------------
# cache-hygiene tables

#: Attribute-name fragments that identify a compile cache.
CACHE_NAME_MARKERS = ("cache", "_fns")

#: Callee names that count as jit-compile builders (a cache keyed on raw
#: shapes in front of one of these is a recompile bomb).
BUILDER_PREFIXES = ("build_",)
BUILDER_NAMES = ("jit",)

#: Callee / method names that sanctify a shape value as bucketed.
#: quota_slot_rows / plan_exchange (ops/skew.py) pow2-round the quota-capped
#: slot — a plan's slot_rows is a bucket_send_rows fixed point, so shape
#: params flowing through the skew planner are bucketed by construction.
BUCKETING_MARKERS = (
    "bucket_send_rows",
    "round_up_to_next_power_of_two",
    "bit_length",
    "quota_slot_rows",
    "plan_exchange",
    "schedule_chunks",  # pow2 chunk-count clamp (ops/ici_exchange.py)
)
