"""Pass: host-sync-in-pipeline.

RoundPipeline earns its overlap only if the submit lane never blocks on the
device: one stray ``np.asarray(device_array)`` in a submit callback serializes
the whole depth-d pipeline back to depth 1 — silently, with no failing test,
just a flat perf curve.  This pass walks the module-local call graph from

* every ``RoundPipeline(depth, submit, drain, ...)`` construction — the
  2nd/3rd positional (or ``submit=``/``drain=`` keyword) callbacks, and
* the configured roots (``_run_exchange``),

and flags blocking host syncs anywhere inside: ``block_until_ready`` (both
``jax.block_until_ready(x)`` and ``x.block_until_ready()``),
``jax.device_get``, and ``np.asarray``/``np.array`` whose first argument is a
variable (literal list/tuple arguments are host-born and skipped — the
static approximation of "on device values").

Findings carry the lane in the message (``submit stage '_submit'`` /
``drain stage '_drain' (via '_memmap_round')``) so the allowlist can bless
the drain lane — the pipeline's sanctioned host-sync point — while keeping
submit-lane findings hard errors.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, callee_name, dotted_name, register
from sparkucx_tpu.analysis.config import HOST_SYNC_ROOTS

PASS = "host-sync"

_LITERALS = (
    ast.Constant, ast.List, ast.Tuple, ast.Dict, ast.Set,
    ast.ListComp, ast.DictComp, ast.SetComp, ast.GeneratorExp,
)


def _blocking_call(node: ast.Call) -> Optional[str]:
    """Return a human name if this call is a blocking host sync."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "block_until_ready":
            return "block_until_ready"
        if func.attr == "device_get" and dotted_name(func) == "jax.device_get":
            return "jax.device_get"
        if func.attr in ("asarray", "array"):
            base = dotted_name(func.value)
            if base in ("np", "numpy"):
                if node.args and not isinstance(node.args[0], _LITERALS):
                    return f"np.{func.attr}"
    return None


def _index_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> FunctionDef for every def in the module (nested included).
    Shadowed names keep the first definition — good enough for a per-module
    reachability sketch."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _own_nodes(fn: ast.AST):
    """All descendant nodes EXCLUDING nested function bodies — those are
    separate graph nodes, labeled and scanned through their own call edges."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _local_callees(fn: ast.AST) -> List[str]:
    """Names this function calls that could resolve module-locally: bare
    ``f(...)`` and ``self.f(...)``, plus bare-name callback references."""
    out: List[str] = []
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.append(f.id)
            elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                    and f.value.id in ("self", "cls"):
                out.append(f.attr)
    return out


def _callback_name(node: ast.AST) -> Optional[str]:
    """A stage callback reference: bare ``_submit`` or bound ``self._submit``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    return None


def _pipeline_stages(tree: ast.Module) -> List[Tuple[str, str]]:
    """[(role, function_name)] for every RoundPipeline(...) construction."""
    stages: List[Tuple[str, str]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and callee_name(node) == "RoundPipeline"):
            continue
        # positional: RoundPipeline(depth, submit, drain, ...)
        for idx, role in ((1, "submit"), (2, "drain")):
            if idx < len(node.args):
                name = _callback_name(node.args[idx])
                if name is not None:
                    stages.append((role, name))
        for kw in node.keywords:
            if kw.arg in ("submit", "drain"):
                name = _callback_name(kw.value)
                if name is not None:
                    stages.append((kw.arg, name))
    return stages


@register(PASS)
def check(tree: ast.Module, source: str, path: str) -> List[Finding]:
    functions = _index_functions(tree)
    # label per function name: where it sits in the pipeline ("submit stage
    # '_submit'", "reachable from '_run_exchange'", possibly "(via 'helper')")
    labels: Dict[str, str] = {}
    queue: List[str] = []

    # Stages are seeded first so the stage label wins over plain reachability.
    for role, name in _pipeline_stages(tree):
        if name in functions and name not in labels:
            labels[name] = f"pipeline {role} stage '{name}'"
            queue.append(name)
    for root in HOST_SYNC_ROOTS:
        if root in functions and root not in labels:
            labels[root] = f"'{root}'"
            queue.append(root)

    while queue:
        name = queue.pop(0)
        base = labels[name]
        for callee in _local_callees(functions[name]):
            if callee in functions and callee not in labels:
                root_label = base.split(" (via ")[0]
                labels[callee] = f"{root_label} (via '{callee}')"
                queue.append(callee)

    findings: List[Finding] = []
    for name, label in labels.items():
        fn = functions[name]
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                what = _blocking_call(node)
                if what is not None:
                    findings.append(
                        Finding(
                            path,
                            node.lineno,
                            PASS,
                            f"blocking host sync '{what}' in {label} — "
                            f"stalls the pipeline lane",
                        )
                    )
    return findings
