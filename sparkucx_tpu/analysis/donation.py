"""Pass: use-after-donate.

A donated jit argument's buffer is invalid the moment the call returns — XLA
reused its memory for the output.  Reading the Python name afterwards returns
garbage (TPU) or works by accident (CPU backend ignores donation), which is
the worst kind of bug: green tests, corrupt shuffles in production.

The pass tracks, per function scope and in lexical order:

* names bound to donating callables — ``fn = build_exchange(...)`` (table in
  config.DONATING_BUILDERS), ``fn, b = self._scatter_fn(...)`` (tuple
  builders), and direct ``jax.jit(..., donate_argnums=<literal>)``;
* donation events — a call through such a name marks the ``ast.Name``
  arguments at the donating positions as dead;
* reads — a later ``Load`` of a dead name is a finding; a ``Store`` (or
  ``del``) revives it.  ``cur, _ = fn(cur, sizes)`` is the sanctioned idiom:
  the read happens before the donation, the rebind after.

Known limits (accepted — this is a linter, not an escape analysis): aliases
(``y = x``) are not tracked through the donation, loop back-edges are not
modeled (a donate-at-bottom/read-at-top loop escapes), and branches are
merged by union.  Conditional donation (build_exchange donates only when
send_rows == recv_rows) is treated as always-donating: may-donate means
must-not-reuse.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from sparkucx_tpu.analysis.base import Finding, callee_name, register
from sparkucx_tpu.analysis.config import DONATING_BUILDERS, TUPLE_DONATING_BUILDERS

PASS = "use-after-donate"

#: literal-ish nodes we refuse to treat as donated variables
_LITERALS = (ast.Constant, ast.List, ast.Tuple, ast.Dict, ast.Set)


def _jit_donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """``jax.jit(..., donate_argnums=<int or tuple literal>)`` -> positions."""
    if callee_name(call) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, ast.Tuple) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int) for e in v.elts
        ):
            return tuple(e.value for e in v.elts)
    return None


class _Scope:
    """Per-function donation state: builder bindings + dead names."""

    def __init__(self, donating: Optional[Dict[str, Tuple[int, ...]]] = None) -> None:
        # name -> donated positions of the callable bound to it
        self.donating: Dict[str, Tuple[int, ...]] = dict(donating or {})
        # name -> (line it was donated on, callable name that ate it)
        self.donated: Dict[str, Tuple[int, str]] = {}


class _Analyzer:
    def __init__(self, path: str) -> None:
        self.path = path
        self.findings: List[Finding] = []

    # -- expression handling (reads first, then donations) ---------------

    def _reads(self, expr: ast.AST, scope: _Scope) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                dead = scope.donated.get(sub.id)
                if dead is not None:
                    line, via = dead
                    self.findings.append(
                        Finding(
                            self.path,
                            sub.lineno,
                            PASS,
                            f"read of '{sub.id}' after it was donated to "
                            f"'{via}' at line {line} (buffer is dead post-call)",
                        )
                    )

    def _donations(self, expr: ast.AST, scope: _Scope) -> None:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call) or not isinstance(sub.func, ast.Name):
                continue
            positions = scope.donating.get(sub.func.id)
            if positions is None:
                continue
            for p in positions:
                if p < len(sub.args) and isinstance(sub.args[p], ast.Name):
                    name = sub.args[p].id
                    scope.donated[name] = (sub.lineno, sub.func.id)

    def _expr(self, expr: Optional[ast.AST], scope: _Scope) -> None:
        if expr is None:
            return
        self._reads(expr, scope)
        self._donations(expr, scope)

    # -- binding handling -------------------------------------------------

    def _store(self, target: ast.AST, scope: _Scope) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                scope.donated.pop(sub.id, None)
                scope.donating.pop(sub.id, None)

    def _bind_builders(self, targets: List[ast.AST], value: ast.AST, scope: _Scope) -> None:
        if not isinstance(value, ast.Call) or len(targets) != 1:
            return
        name = callee_name(value)
        target = targets[0]
        positions = DONATING_BUILDERS.get(name)
        if positions is None:
            positions = _jit_donated_positions(value)
        if positions is not None and isinstance(target, ast.Name):
            scope.donating[target.id] = positions
            return
        tuple_positions = TUPLE_DONATING_BUILDERS.get(name)
        if (
            tuple_positions is not None
            and isinstance(target, ast.Tuple)
            and target.elts
            and isinstance(target.elts[0], ast.Name)
        ):
            scope.donating[target.elts[0].id] = tuple_positions

    # -- statement walk ----------------------------------------------------

    def block(self, stmts: List[ast.stmt], scope: _Scope) -> None:
        for st in stmts:
            self.stmt(st, scope)

    def _branch(self, scope: _Scope, bodies: List[List[ast.stmt]]) -> None:
        """Exclusive branches: run each on a copy, merge by union (a donation
        in one arm must still poison reads after the join)."""
        merged_donated = dict(scope.donated)
        merged_donating = dict(scope.donating)
        for body in bodies:
            sub = _Scope(scope.donating)
            sub.donated = dict(scope.donated)
            self.block(body, sub)
            merged_donated.update(sub.donated)
            merged_donating.update(sub.donating)
        scope.donated = merged_donated
        scope.donating = merged_donating

    def stmt(self, st: ast.stmt, scope: _Scope) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _Scope(scope.donating)  # closures see outer builder bindings
            args = st.args
            for a in (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                inner.donating.pop(a.arg, None)
            self.block(st.body, inner)
        elif isinstance(st, ast.ClassDef):
            self.block(st.body, _Scope(scope.donating))
        elif isinstance(st, ast.Assign):
            self._expr(st.value, scope)
            for t in st.targets:
                self._store(t, scope)
            self._bind_builders(st.targets, st.value, scope)
        elif isinstance(st, ast.AnnAssign):
            self._expr(st.value, scope)
            self._store(st.target, scope)
            if st.value is not None:
                self._bind_builders([st.target], st.value, scope)
        elif isinstance(st, ast.AugAssign):
            self._expr(st.value, scope)
            self._expr(st.target, scope)  # augmented target is read too
            self._store(st.target, scope)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._store(t, scope)
        elif isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, scope)
            self._branch(scope, [st.body, st.orelse])
        elif isinstance(st, ast.For):
            self._expr(st.iter, scope)
            self._store(st.target, scope)
            self._branch(scope, [st.body, st.orelse])
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, scope)
                if item.optional_vars is not None:
                    self._store(item.optional_vars, scope)
            self.block(st.body, scope)
        elif isinstance(st, ast.Try):
            self.block(st.body, scope)
            for h in st.handlers:
                self.block(h.body, scope)
            self.block(st.orelse, scope)
            self.block(st.finalbody, scope)
        elif isinstance(st, (ast.Return, ast.Expr, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(st):
                self._expr(child, scope)
        else:
            # import / global / pass / break / continue — nothing to track,
            # but still scan any expressions for reads of dead names.
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, scope)


@register(PASS)
def check(tree: ast.Module, source: str, path: str) -> List[Finding]:
    analyzer = _Analyzer(path)
    analyzer.block(tree.body, _Scope())
    return analyzer.findings
