"""error-taxonomy: the retryable/fail-fast split is a registry, not folklore.

The gray-failure arc (health scoring, breakers, hedged fetches) made the
reader's retry/failover path load-bearing: it may retry *transient* faults
(missing/corrupt blocks, resource exhaustion) but must propagate
*fail-fast* faults (tenant errors, lost executors) immediately — retrying
those wastes the failover budget and masks cluster-state bugs.  API.md
documents the split in prose; ``ERROR_TAXONOMY`` (analysis/config.py) is
its machine-checked registry.  This pass pins three things:

* **completeness** — every ``TransportError`` subclass defined in
  ``ERROR_MODULE`` (transitively: subclasses of subclasses) is classified
  in ERROR_TAXONOMY, every registry entry names a class that still exists,
  and every classified class appears in the ``ERROR_DOC`` text;
* **retry-path hygiene** — functions named in ``RETRY_PATH_FUNCS`` (the
  reader's retry/failover machinery) must not name a fail-fast class in an
  ``except`` clause; and
* **broad-catch coverage** — when a retry-path function catches the base
  ``TransportError`` (broad by design: transport faults and socket errors
  share cleanup), the function must guard with ``isinstance`` + ``raise``
  covering *all* fail-fast classes, so fail-fast faults fall through the
  retry loop.  Guard classes are resolved through module-level tuple
  constants (``_FAIL_FAST = (A, B, C)``), so the fail-fast set lives in one
  assignment next to the imports.

Escape hatch: ``#: taxonomy-ok <reason>`` on the except/guard line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sparkucx_tpu.analysis.base import (
    Finding,
    Program,
    register_global,
)
from sparkucx_tpu.analysis.config import (
    ERROR_BASE,
    ERROR_DOC,
    ERROR_MODULE,
    ERROR_TAXONOMY,
    RETRY_PATH_FUNCS,
)

PASS = "error-taxonomy"
ESCAPE = "#: taxonomy-ok"

_FAIL_FAST = frozenset(
    name for name, kind in ERROR_TAXONOMY.items() if kind == "fail-fast"
)


def _escaped(lines: List[str], lineno: int) -> bool:
    return 1 <= lineno <= len(lines) and ESCAPE in lines[lineno - 1]


def _base_names(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for base in cls.bases:
        if isinstance(base, ast.Name):
            out.add(base.id)
        elif isinstance(base, ast.Attribute):
            out.add(base.attr)
    return out


def collect_error_classes(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    """Transitive subclasses of ERROR_BASE defined in this module."""
    classes = {
        node.name: node for node in tree.body if isinstance(node, ast.ClassDef)
    }
    family: Set[str] = {ERROR_BASE}
    changed = True
    while changed:
        changed = False
        for name, node in classes.items():
            if name not in family and _base_names(node) & family:
                family.add(name)
                changed = True
    return {n: classes[n] for n in family if n != ERROR_BASE and n in classes}


def _exc_names(node: ast.AST, module_consts: Dict[str, List[str]]) -> List[str]:
    """Class names an ``except`` clause or isinstance() second arg refers
    to — Names, Attributes, tuples of those, and module-level tuple
    constants resolved by name."""
    if node is None:
        return []
    if isinstance(node, ast.Name):
        if node.id in module_consts:
            return list(module_consts[node.id])
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_exc_names(elt, module_consts))
        return out
    return []


def _module_name_tuples(tree: ast.Module) -> Dict[str, List[str]]:
    """Module-level ``X = (A, B, C)`` assignments of bare names — the idiom
    for declaring a fail-fast guard set once."""
    out: Dict[str, List[str]] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Tuple)
        ):
            names = [
                elt.id for elt in node.value.elts if isinstance(elt, ast.Name)
            ]
            if names and len(names) == len(node.value.elts):
                out[node.targets[0].id] = names
    return out


def _guard_covered(fn: ast.AST, module_consts: Dict[str, List[str]]) -> Set[str]:
    """Class names covered by ``isinstance(x, C)`` tests inside ``fn``
    whose branch re-raises (the fail-fast escape from a broad catch)."""
    covered: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        calls = [test]
        # also accept `isinstance(...) or isinstance(...)` unions
        if isinstance(test, ast.BoolOp):
            calls = list(test.values)
        has_raise = any(isinstance(s, ast.Raise) for s in ast.walk(node))
        if not has_raise:
            continue
        for call in calls:
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == "isinstance"
                and len(call.args) == 2
            ):
                covered.update(_exc_names(call.args[1], module_consts))
    return covered


@register_global(PASS)
def error_taxonomy_pass(program: Program) -> List[Finding]:
    findings: List[Finding] = []

    entry = program.module(ERROR_MODULE)
    doc = program.docs.get(ERROR_DOC)
    if entry is not None:
        tree, source = entry
        lines = source.splitlines()
        defined = collect_error_classes(tree)
        for name, node in sorted(defined.items()):
            if name not in ERROR_TAXONOMY:
                if not _escaped(lines, node.lineno):
                    findings.append(Finding(ERROR_MODULE, node.lineno, PASS, (
                        f"{ERROR_BASE} subclass '{name}' is not classified in "
                        f"ERROR_TAXONOMY (analysis/config.py) — declare it "
                        f"retryable or fail-fast so the reader's failover "
                        f"path can be checked against it")))
            elif doc is not None and name not in doc:
                findings.append(Finding(ERROR_MODULE, node.lineno, PASS, (
                    f"error class '{name}' is classified "
                    f"'{ERROR_TAXONOMY[name]}' but undocumented in "
                    f"{ERROR_DOC} — the failure-semantics table is the "
                    f"caller contract; add it")))
        for name in sorted(set(ERROR_TAXONOMY) - set(defined)):
            findings.append(Finding(ERROR_MODULE, 1, PASS, (
                f"ERROR_TAXONOMY classifies '{name}' but no such "
                f"{ERROR_BASE} subclass is defined in {ERROR_MODULE} — "
                f"prune the stale registry entry")))

    # retry-path hygiene across the whole program
    for rel, (tree, source) in sorted(program.modules.items()):
        lines = source.splitlines()
        module_consts = _module_name_tuples(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in RETRY_PATH_FUNCS:
                continue
            for handler in ast.walk(node):
                if not isinstance(handler, ast.ExceptHandler):
                    continue
                caught = _exc_names(handler.type, module_consts)
                bad = sorted(set(caught) & _FAIL_FAST)
                if bad and not _escaped(lines, handler.lineno):
                    findings.append(Finding(rel, handler.lineno, PASS, (
                        f"retry path '{node.name}' catches fail-fast "
                        f"'{bad[0]}' — fail-fast faults must propagate, not "
                        f"burn failover budget (ERROR_TAXONOMY)")))
                if ERROR_BASE in caught:
                    covered = _guard_covered(node, module_consts)
                    missing = sorted(_FAIL_FAST - covered)
                    if missing and not _escaped(lines, handler.lineno):
                        findings.append(Finding(rel, handler.lineno, PASS, (
                            f"retry path '{node.name}' catches the broad "
                            f"{ERROR_BASE} without isinstance+raise guards "
                            f"covering fail-fast {', '.join(missing)} — "
                            f"those faults would be silently retried")))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
