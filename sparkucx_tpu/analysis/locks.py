"""Pass: lock-discipline.

Convention (docs/ANALYSIS.md): a field whose mutation must happen under a lock
carries the annotation

    self._shuffles = {}  #: guarded by self._lock

on the assignment line (or the annotation comment sits on the line directly
above — dataclass field style).  The pass then flags, module-wide, every
mutation of that field name — plain/aug/subscript assignment and mutator
method calls (``.append``/``.update``/...) — that is not lexically inside a
``with <...><lock>:`` block whose lock's final component matches the
annotated lock name.

Escapes, both deliberate conventions rather than holes:

* ``__init__`` bodies are exempt (construction happens-before sharing);
* a function whose docstring contains ``caller holds`` + the lock name is
  exempt — the documented private-helper contract already used by
  ``HbmBlockStore._rollover`` and friends.  The docstring is the contract;
  the analyzer makes writing it mandatory.

``ast`` drops comments, so annotations are collected with a line scan of the
source before the AST walk — which is also why the annotation syntax is a
comment, not a decorator: it works on dataclass fields and plain assignments
alike and costs nothing at runtime.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from sparkucx_tpu.analysis.base import Finding, docstring_of, register

PASS = "lock-discipline"

_GUARD_RE = re.compile(r"#:\s*guarded by\s+([A-Za-z_][\w.]*)")
_SELF_FIELD_RE = re.compile(r"(?:self|cls)\.(\w+)\s*(?::[^=]+)?=(?!=)")
_DATACLASS_FIELD_RE = re.compile(r"^\s*(\w+)\s*:")

#: method names that mutate their receiver in place
MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "popleft", "appendleft",
    "add", "remove", "discard", "clear", "update", "setdefault",
}


def collect_guards(source: str) -> Dict[str, str]:
    """Scan for ``#: guarded by <lock>`` annotations -> {field: lock_name}.

    The lock is remembered by its final dotted component (``self._tag_lock``
    -> ``_tag_lock``) so holding a *different* lock never satisfies it.
    """
    guards: Dict[str, str] = {}
    lines = source.splitlines()
    for i, line in enumerate(lines):
        m = _GUARD_RE.search(line)
        if m is None:
            continue
        lock = m.group(1).rsplit(".", 1)[-1]
        code = line[: m.start()]
        fm = _SELF_FIELD_RE.search(code) or _DATACLASS_FIELD_RE.match(code)
        if fm is None:
            # annotation-on-its-own-line style: field is on the next code line
            for j in range(i + 1, min(i + 4, len(lines))):
                nxt = lines[j]
                if not nxt.strip() or nxt.lstrip().startswith("#"):
                    continue
                fm = _SELF_FIELD_RE.search(nxt) or _DATACLASS_FIELD_RE.match(nxt)
                break
        if fm is not None:
            guards[fm.group(1)] = lock
    return guards


def _lock_names_in(expr: ast.AST) -> Set[str]:
    """Lock-ish identifiers in a ``with`` item (final components containing
    'lock'): ``with self._tag_lock:`` -> {'_tag_lock'}."""
    out: Set[str] = set()
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and "lock" in name.lower():
            out.add(name)
    return out


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, guards: Dict[str, str], path: str) -> None:
        self.guards = guards
        self.path = path
        self.findings: List[Finding] = []
        self.held: List[str] = []  # stack of held lock names
        self.exempt = 0  # __init__ / documented caller-holds depth

    # -- context tracking --------------------------------------------------

    def _visit_with(self, node) -> None:
        names: Set[str] = set()
        for item in node.items:
            names |= _lock_names_in(item.context_expr)
        self.held.extend(names)
        self.generic_visit(node)
        del self.held[len(self.held) - len(names):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _visit_func(self, node) -> None:
        doc = docstring_of(node).lower()
        exempt = node.name == "__init__" or ("caller holds" in doc and "lock" in doc)
        self.exempt += exempt
        self.generic_visit(node)
        self.exempt -= exempt

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- mutation sites ----------------------------------------------------

    def _flag(self, field: str, line: int, how: str) -> None:
        if self.exempt:
            return
        lock = self.guards[field]
        if lock in self.held:
            return
        self.findings.append(
            Finding(
                self.path,
                line,
                PASS,
                f"unguarded {how} of '{field}' (annotated '#: guarded by "
                f"{lock}'; held locks: {sorted(set(self.held)) or 'none'})",
            )
        )

    def _check_target(self, target: ast.AST, line: int) -> None:
        if isinstance(target, ast.Attribute) and target.attr in self.guards:
            self._flag(target.attr, line, "write")
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Attribute) and base.attr in self.guards:
                self._flag(base.attr, line, "item write")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_target(el, line)
        elif isinstance(target, ast.Starred):
            self._check_target(target.value, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
            base = func.value
            field: Optional[str] = None
            if isinstance(base, ast.Attribute) and base.attr in self.guards:
                field = base.attr
            elif isinstance(base, ast.Subscript):
                inner = base.value
                if isinstance(inner, ast.Attribute) and inner.attr in self.guards:
                    field = inner.attr
            if field is not None:
                self._flag(field, node.lineno, f"mutator call '.{func.attr}()'")
        self.generic_visit(node)


@register(PASS)
def check(tree: ast.Module, source: str, path: str) -> List[Finding]:
    guards = collect_guards(source)
    if not guards:
        return []
    visitor = _LockVisitor(guards, path)
    visitor.visit(tree)
    return visitor.findings
