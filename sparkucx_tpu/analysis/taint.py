"""lockstep-taint: local telemetry must never shape the collective schedule.

The SPMD deployment compiles and submits collectives on every process from
one :class:`ExchangePlan`; any plan field in ``COLLECTIVE_FIELDS``
(analysis/config.py) that depends on a per-host quantity — a metrics
snapshot, ``PlanSignals``, health/breaker state, a clock — is a divergent
compiled program and a cluster-wide hang at the next collective.  This pass
is an AST taint dataflow over the plan-producing and plan-consuming modules
(``TAINT_MODULES``):

* **sources** — calls named in ``TAINT_SOURCE_CALLS`` (registry
  ``snapshot()``, ``PlanSignals`` / ``from_registry``, ``health_snapshot``,
  ``wire_lane_stats``, breaker reads, clocks) and attribute reads named in
  ``TAINT_SOURCE_ATTRS`` (``ctx.signals`` — the sanctioned telemetry channel
  re-taints wherever it is read back out).
* **clean** — everything else, deliberately including conf fields, function
  parameters, and all-gather results: the invariant is about *telemetry*
  divergence, and unknown calls (``jax.jit``, cross-module planners) return
  clean unless fed taint.
* **propagation** — through names, attributes, operators, containers,
  comprehensions; through module-local calls (bare names, ``self.``/
  ``cls.`` methods, and *nested defs with their closure environment* — the
  transitive/helper case) by analyzing the callee under the caller's
  argument taint; through any other call when an argument is tainted.
* **sinks** — a ``COLLECTIVE_FIELDS`` keyword (or mapped positional) at an
  ``ExchangePlan`` / ``dataclasses.replace`` / ``PlanContext`` call, a
  ``plan.<collective_field> = ...`` assignment, either with a tainted value
  or lexically under a telemetry-tainted branch (implicit flow); and any
  tainted branch condition in ``SPMD_PRECOLLECTIVE_FUNCS`` (the SPMD
  transport's pre-collective orchestration) whose body does not end in
  ``raise`` — failing fast on local bad news is sanctioned, a divergent
  schedule is not.
* **absorption** — taint bound to a ``SERVE_PLANE_FIELDS`` keyword or the
  ``signals`` channel is absorbed: those fields are the declared serve
  plane, and the resulting plan/context object stays clean so one hedge
  tweak does not cascade false positives over the whole planner.

The declared COLLECTIVE/SERVE_PLANE split is cross-checked against the
``ExchangePlan`` dataclass itself (``PLAN_MODULE``): a plan field in
neither registry, in both, or a registry name with no field is a finding —
the registry cannot drift from the code.

Escape hatch: ``#: lockstep-ok <reason>`` on the sink/branch line, plus the
standard allowlist.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sparkucx_tpu.analysis.base import Finding, Program, register_global
from sparkucx_tpu.analysis.config import (
    COLLECTIVE_FIELDS,
    PLAN_CLASS,
    PLAN_CONSTRUCTORS,
    PLAN_MODULE,
    SERVE_PLANE_FIELDS,
    SPMD_PRECOLLECTIVE_FUNCS,
    TAINT_MODULES,
    TAINT_SOURCE_ATTRS,
    TAINT_SOURCE_CALLS,
)

PASS = "lockstep-taint"
ESCAPE = "#: lockstep-ok"

_COLLECTIVE = frozenset(COLLECTIVE_FIELDS)
_SERVE = frozenset(SERVE_PLANE_FIELDS)
#: keywords that absorb taint at a plan constructor (the declared serve
#: plane plus the sanctioned PlanContext telemetry channel)
_ABSORBING = _SERVE | {"signals"}


def plan_field_order(tree: ast.Module) -> List[str]:
    """Ordered field names of the PLAN_CLASS dataclass (for mapping
    positional constructor args to fields)."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == PLAN_CLASS:
            return [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
    return []


class _FnInfo:
    """One analyzable function: its AST plus the closure environment it was
    defined under (non-empty only for nested defs)."""

    __slots__ = ("node", "closure")

    def __init__(self, node: ast.AST, closure: Dict[str, bool]):
        self.node = node
        self.closure = closure


class _ModuleTaint:
    """Demand-driven per-module taint analysis."""

    def __init__(self, tree: ast.Module, source: str, rel: str,
                 plan_fields: List[str]):
        self.rel = rel
        self.lines = source.splitlines()
        self.plan_fields = plan_fields
        self.findings: Set[Tuple[int, str]] = set()
        # bare name -> FnInfos (module functions and every class's methods
        # share the namespace, like the host-sync pass's call-graph index)
        self.fns: Dict[str, List[_FnInfo]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fns.setdefault(node.name, []).append(_FnInfo(node, {}))
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.fns.setdefault(item.name, []).append(_FnInfo(item, {}))
        #: (fn id, frozenset tainted params) -> returns-tainted (memo +
        #: recursion guard: an in-flight entry reads as clean, analyzed twice)
        self._memo: Dict[Tuple[int, frozenset], bool] = {}
        self._active: Set[Tuple[int, frozenset]] = set()

    # -- driver ---------------------------------------------------------

    def run(self) -> List[Finding]:
        for infos in self.fns.values():
            for info in infos:
                self._analyze(info, frozenset())
        return [
            Finding(self.rel, line, PASS, msg)
            for line, msg in sorted(self.findings)
        ]

    # -- helpers --------------------------------------------------------

    def _escaped(self, lineno: int) -> bool:
        if 1 <= lineno <= len(self.lines):
            return ESCAPE in self.lines[lineno - 1]
        return False

    def _flag(self, node: ast.AST, msg: str) -> None:
        if not self._escaped(node.lineno):
            self.findings.add((node.lineno, msg))

    @staticmethod
    def _param_names(fn: ast.AST) -> List[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        if a.vararg:
            names.append(a.vararg.arg)
        names += [p.arg for p in a.kwonlyargs]
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    # -- function analysis ---------------------------------------------

    def _analyze(self, info: _FnInfo, tainted_params: frozenset) -> bool:
        key = (id(info.node), tainted_params)
        if key in self._memo:
            return self._memo[key]
        if key in self._active:
            return False  # recursion: assume clean on the back edge
        self._active.add(key)
        env: Dict[str, bool] = dict(info.closure)
        for name in self._param_names(info.node):
            env[name] = name in tainted_params
        local_fns: Dict[str, _FnInfo] = {}
        ret = [False]
        self._walk_body(info.node.body, env, local_fns, 0, info.node, ret)
        self._active.discard(key)
        self._memo[key] = ret[0]
        return ret[0]

    def _walk_body(self, body, env, local_fns, branch_taint, fn, ret) -> None:
        for stmt in body:
            self._walk_stmt(stmt, env, local_fns, branch_taint, fn, ret)

    def _walk_stmt(self, stmt, env, local_fns, branch_taint, fn, ret) -> None:
        E = lambda node: self._expr(node, env, local_fns, branch_taint)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: carries the defining scope's taint as its closure
            local_fns[stmt.name] = _FnInfo(stmt, dict(env))
        elif isinstance(stmt, ast.Assign):
            v = E(stmt.value)
            for tgt in stmt.targets:
                self._assign(tgt, v, env, branch_taint)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, E(stmt.value), env, branch_taint)
        elif isinstance(stmt, ast.AugAssign):
            v = E(stmt.value) or E(stmt.target)
            self._assign(stmt.target, v, env, branch_taint)
        elif isinstance(stmt, (ast.If, ast.While)):
            t = E(stmt.test)
            if t and fn.name in SPMD_PRECOLLECTIVE_FUNCS:
                if not self._raise_only(stmt.body) and not self._escaped(stmt.lineno):
                    self.findings.add((stmt.lineno, (
                        f"pre-collective branch in '{fn.name}' tested on local "
                        f"telemetry — every SPMD process must take the same "
                        f"path into the collective (raise-only fail-fast "
                        f"branches are exempt)")))
            inner = branch_taint + (1 if t else 0)
            self._walk_body(stmt.body, env, local_fns, inner, fn, ret)
            self._walk_body(stmt.orelse, env, local_fns, inner, fn, ret)
        elif isinstance(stmt, ast.For):
            v = E(stmt.iter)
            self._assign(stmt.target, v, env, branch_taint)
            # second pass catches loop-carried taint through the body
            for _ in range(2):
                self._walk_body(stmt.body, env, local_fns, branch_taint, fn, ret)
            self._walk_body(stmt.orelse, env, local_fns, branch_taint, fn, ret)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                v = E(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, v, env, branch_taint)
            self._walk_body(stmt.body, env, local_fns, branch_taint, fn, ret)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, env, local_fns, branch_taint, fn, ret)
            for handler in stmt.handlers:
                if handler.name:
                    env[handler.name] = False
                self._walk_body(handler.body, env, local_fns, branch_taint, fn, ret)
            self._walk_body(stmt.orelse, env, local_fns, branch_taint, fn, ret)
            self._walk_body(stmt.finalbody, env, local_fns, branch_taint, fn, ret)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None and E(stmt.value):
                ret[0] = True
        elif isinstance(stmt, ast.Expr):
            E(stmt.value)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                E(stmt.exc)
        # Pass/Break/Continue/Import/Global/Delete: nothing to track

    @staticmethod
    def _raise_only(body) -> bool:
        return bool(body) and isinstance(body[-1], ast.Raise)

    def _assign(self, tgt, tainted: bool, env, branch_taint) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = tainted
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._assign(elt, tainted, env, branch_taint)
        elif isinstance(tgt, ast.Starred):
            self._assign(tgt.value, tainted, env, branch_taint)
        elif isinstance(tgt, ast.Attribute) and tgt.attr in _COLLECTIVE:
            if tainted:
                self._flag(tgt, (
                    f"collective plan field '{tgt.attr}' assigned from local "
                    f"telemetry — collective-schedule fields must derive from "
                    f"conf + all-gathered geometry only (SPMD lockstep)"))
            elif branch_taint:
                self._flag(tgt, (
                    f"collective plan field '{tgt.attr}' assigned under a "
                    f"telemetry-tainted branch — the write itself diverges "
                    f"per host (SPMD lockstep)"))

    # -- expressions ----------------------------------------------------

    def _expr(self, node, env, local_fns, branch_taint) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return env.get(node.id, False)
        if isinstance(node, ast.Attribute):
            if node.attr in TAINT_SOURCE_ATTRS:
                return True
            return self._expr(node.value, env, local_fns, branch_taint)
        if isinstance(node, ast.Call):
            return self._call(node, env, local_fns, branch_taint)
        if isinstance(node, (ast.Lambda,)):
            # approximate: a lambda is tainted when its body reads taint from
            # the defining scope (params shadow to clean)
            inner = dict(env)
            for p in node.args.args:
                inner[p.arg] = False
            return self._expr(node.body, inner, local_fns, branch_taint)
        # generic: any tainted sub-expression taints the whole expression
        # (operators, comparisons, containers, subscripts, comprehensions,
        # f-strings, starred/keyword wrappers)
        out = False
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword, ast.comprehension)):
                out = self._expr_any(child, env, local_fns, branch_taint) or out
        return out

    def _expr_any(self, node, env, local_fns, branch_taint) -> bool:
        if isinstance(node, ast.keyword):
            return self._expr(node.value, env, local_fns, branch_taint)
        if isinstance(node, ast.comprehension):
            t = self._expr(node.iter, env, local_fns, branch_taint)
            self._assign(node.target, t, env, branch_taint)
            for cond in node.ifs:
                self._expr(cond, env, local_fns, branch_taint)
            return t
        return self._expr(node, env, local_fns, branch_taint)

    def _callee(self, node: ast.Call) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None

    def _resolve_local(self, node: ast.Call, local_fns) -> List[_FnInfo]:
        """Module-local / closure-local callees: bare names, nested defs,
        and ``self.``/``cls.``-qualified methods."""
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in local_fns:
                return [local_fns[func.id]]
            return self.fns.get(func.id, [])
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
        ):
            return self.fns.get(func.attr, [])
        return []

    def _call(self, node: ast.Call, env, local_fns, branch_taint) -> bool:
        name = self._callee(node)
        arg_taints = [
            self._expr(a.value if isinstance(a, ast.Starred) else a,
                       env, local_fns, branch_taint)
            for a in node.args
        ]
        kw_taints = {
            kw.arg: self._expr(kw.value, env, local_fns, branch_taint)
            for kw in node.keywords
        }

        if name in PLAN_CONSTRUCTORS or name == PLAN_CLASS:
            self._sink_check(node, name, arg_taints, kw_taints, branch_taint)

        # sources taint regardless of arguments
        if name in TAINT_SOURCE_CALLS:
            return True

        # module-local / closure calls: propagate argument taint through the
        # callee (the transitive/helper case)
        targets = self._resolve_local(node, local_fns)
        if targets:
            out = False
            for info in targets:
                params = self._param_names(info.node)
                # drop the bound receiver for self./cls. method calls
                offset = 0
                if (
                    isinstance(node.func, ast.Attribute)
                    and params
                    and params[0] in ("self", "cls")
                ):
                    offset = 1
                tainted = set()
                for i, t in enumerate(arg_taints):
                    if t and i + offset < len(params):
                        tainted.add(params[i + offset])
                for kw, t in kw_taints.items():
                    if t and kw in params:
                        tainted.add(kw)
                out = self._analyze(info, frozenset(tainted)) or out
            return out

        if name in PLAN_CONSTRUCTORS or name == PLAN_CLASS:
            # serve-plane keywords absorb their taint by design; the object
            # is tainted only through its base (replace arg 0) or a
            # non-absorbing field
            base = arg_taints[0] if (name == "replace" and arg_taints) else False
            field_taint = any(
                t for kw, t in kw_taints.items() if kw not in _ABSORBING
            )
            return base or field_taint

        # unknown call: clean unless fed taint
        return any(arg_taints) or any(kw_taints.values())

    def _sink_check(self, node, name, arg_taints, kw_taints, branch_taint) -> None:
        for kw in node.keywords:
            if kw.arg in _COLLECTIVE:
                if kw_taints.get(kw.arg):
                    self._flag(node, (
                        f"collective plan field '{kw.arg}' derives from local "
                        f"telemetry at this {name}(...) — collective-schedule "
                        f"fields must be pure functions of conf + all-gathered "
                        f"geometry (SPMD lockstep)"))
                elif branch_taint:
                    self._flag(node, (
                        f"collective plan field '{kw.arg}' written under a "
                        f"telemetry-tainted branch at this {name}(...) — the "
                        f"schedule rewrite itself diverges per host "
                        f"(SPMD lockstep)"))
        if name == PLAN_CLASS and self.plan_fields:
            for i, t in enumerate(arg_taints):
                if i < len(self.plan_fields) and self.plan_fields[i] in _COLLECTIVE:
                    field = self.plan_fields[i]
                    if t:
                        self._flag(node, (
                            f"collective plan field '{field}' derives from "
                            f"local telemetry at this {name}(...) — "
                            f"collective-schedule fields must be pure "
                            f"functions of conf + all-gathered geometry "
                            f"(SPMD lockstep)"))
                    elif branch_taint:
                        self._flag(node, (
                            f"collective plan field '{field}' written under a "
                            f"telemetry-tainted branch at this {name}(...) — "
                            f"the schedule rewrite itself diverges per host "
                            f"(SPMD lockstep)"))


@register_global(PASS)
def lockstep_taint_pass(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    plan_fields: List[str] = []

    plan_entry = program.module(PLAN_MODULE)
    if plan_entry is not None:
        tree, _source = plan_entry
        plan_fields = plan_field_order(tree)
        declared = set(COLLECTIVE_FIELDS) | set(SERVE_PLANE_FIELDS)
        both = set(COLLECTIVE_FIELDS) & set(SERVE_PLANE_FIELDS)
        fields = set(plan_fields)
        for name in sorted(both):
            findings.append(Finding(PLAN_MODULE, 1, PASS,
                f"plan field '{name}' is declared BOTH collective and "
                f"serve-plane — the split must partition the dataclass"))
        for name in sorted(fields - declared):
            findings.append(Finding(PLAN_MODULE, 1, PASS,
                f"{PLAN_CLASS} field '{name}' is in neither COLLECTIVE_FIELDS "
                f"nor SERVE_PLANE_FIELDS — classify it in analysis/config.py "
                f"before the analyzer can police it"))
        for name in sorted(declared - fields):
            findings.append(Finding(PLAN_MODULE, 1, PASS,
                f"registry names unknown plan field '{name}' — "
                f"COLLECTIVE_FIELDS/SERVE_PLANE_FIELDS drifted from the "
                f"{PLAN_CLASS} dataclass; prune the stale entry"))

    targets = [rel for rel in TAINT_MODULES if rel in program.modules]
    if not targets:
        targets = sorted(program.modules)  # fixture runs
    for rel in targets:
        tree, source = program.modules[rel]
        if not plan_fields:
            plan_fields = plan_field_order(tree)  # fixture-defined dataclass
        findings.extend(_ModuleTaint(tree, source, rel, plan_fields).run())
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
