"""lock-order: whole-program lock acquisition graph.

Builds the inter-lock acquisition graph across every module: a node is a
lock identified as ``Class.attr`` (``with self._lock`` inside a method of
``Class``), or ``*.name`` when the owner cannot be resolved statically (a
bare-name lock parameter, or an attribute chain not covered by
``LOCK_ATTR_CLASSES``).  An edge ``A -> B`` means some code path acquires
``B`` while holding ``A`` — directly (nested ``with``) or transitively
(a call made under ``A`` reaches a method that acquires ``B``, resolved
through the ``self.<attr>`` wiring table).

Findings:

* **cycles** — ``A -> B -> A`` (including 2-cycles, the classic lock-order
  inversion, and self-edges: re-acquiring a non-reentrant ``Lock`` the
  caller already holds).  Wildcard ``*.name`` nodes never participate in
  cycle detection: two ``send_lock`` instances on different connections are
  different locks, and proving them identical is beyond a static pass.
* **blocking calls under a lock** — ``sendall``/``recv``/``connect``/
  ``time.sleep``/untimed ``wait``/thread ``join`` lexically inside a
  ``with <lock>:`` body stalls every other acquirer for the call's
  duration.  Locks whose JOB is serializing a blocking wire write are
  exempted via ``LOCK_BLOCKING_EXEMPT`` (with justification, in
  analysis/config.py).

Known limits (documented, deliberate): explicit ``lock.acquire()`` calls
are not tracked (the package idiom is ``with``); the blocking check is
lexical per function (a blocking call inside a helper invoked under a lock
is not flagged — the edge it creates still is).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sparkucx_tpu.analysis.base import (
    Finding,
    Program,
    callee_name,
    dotted_name,
    register_global,
)
from sparkucx_tpu.analysis.config import LOCK_ATTR_CLASSES, LOCK_BLOCKING_EXEMPT

PASS = "lock-order"

#: Callee names treated as blocking when reached while holding a lock.
BLOCKING_CALLS = {"sendall", "sendmsg", "recv", "recv_into", "accept", "connect", "select", "sleep"}


def _lock_node(expr: ast.AST, cls_name: str) -> Optional[str]:
    """Map a ``with`` context expression to a lock node, or None."""
    d = dotted_name(expr)
    if d is None:
        return None
    parts = d.split(".")
    final = parts[-1]
    if "lock" not in final.lower():
        return None
    if parts[0] in ("self", "cls"):
        if len(parts) == 2:
            return f"{cls_name}.{final}"
        owner = LOCK_ATTR_CLASSES.get(parts[1])
        return f"{owner}.{final}" if owner else f"*.{final}"
    return f"*.{final}"


def _is_exempt(node: str) -> bool:
    name = node.split(".", 1)[1]
    return node in LOCK_BLOCKING_EXEMPT or f"*.{name}" in LOCK_BLOCKING_EXEMPT


def _blocking_label(call: ast.Call) -> Optional[str]:
    name = callee_name(call)
    if name in BLOCKING_CALLS:
        return name
    if name in ("wait", "wait_for"):
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        has_timeout = has_timeout or len(call.args) >= (2 if name == "wait_for" else 1)
        if not has_timeout:
            return f"{name}() without timeout"
    if name == "join" and not call.args and not call.keywords:
        recv = call.func.value if isinstance(call.func, ast.Attribute) else None
        if isinstance(recv, ast.Constant):
            return None  # "sep".join(...)
        base = dotted_name(recv) if recv is not None else None
        if base is not None and base.split(".")[-1] in ("path", "sep"):
            return None  # os.path.join
        return "join() without timeout"
    return None


def _resolve_callee(call: ast.Call, cls_name: str) -> Optional[Tuple[str, str]]:
    """``(class, method)`` for self./cross-object calls this pass can track."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    base = dotted_name(f.value)
    if base in ("self", "cls"):
        return (cls_name, f.attr)
    if base is not None and base.count(".") == 1 and base.startswith("self."):
        owner = LOCK_ATTR_CLASSES.get(base.split(".")[1])
        if owner:
            return (owner, f.attr)
    return None


class _MethodInfo:
    __slots__ = ("direct", "calls", "edges", "blocking")

    def __init__(self) -> None:
        self.direct: Set[str] = set()
        #: (callee key, held-locks snapshot, line)
        self.calls: List[Tuple[Tuple[str, str], Tuple[str, ...], int]] = []
        #: direct nested acquisitions: (held, acquired, line)
        self.edges: List[Tuple[str, str, int]] = []
        #: (lock, label, line)
        self.blocking: List[Tuple[str, str, int]] = []


class _MethodWalker(ast.NodeVisitor):
    def __init__(self, cls_name: str, info: _MethodInfo) -> None:
        self.cls = cls_name
        self.info = info
        self.held: List[str] = []

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            ln = _lock_node(item.context_expr, self.cls)
            if ln is not None:
                acquired.append(ln)
        for a in acquired:
            self.info.direct.add(a)
            for h in self.held:
                self.info.edges.append((h, a, node.lineno))
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.held[-len(acquired):]

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            label = _blocking_label(node)
            if label is not None:
                for h in self.held:
                    if not _is_exempt(h):
                        self.info.blocking.append((h, label, node.lineno))
        callee = _resolve_callee(node, self.cls)
        if callee is not None:
            self.info.calls.append((callee, tuple(self.held), node.lineno))
        self.generic_visit(node)

    # A nested def/lambda's body does not run under the enclosing locks —
    # and does not run *now* at all (closures fire later, on whatever
    # thread invokes them), so nothing inside contributes acquisitions,
    # edges, or blocking findings to the enclosing method.  Documented
    # limit: lock use inside closures is invisible to this pass.
    def _nested(self, node: ast.AST) -> None:
        del node

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._nested(node)


def _index_program(program: Program):
    """(cls, method) -> (_MethodInfo, rel_path) over every module."""
    methods: Dict[Tuple[str, str], Tuple[_MethodInfo, str]] = {}
    for rel, (tree, _source) in sorted(program.modules.items()):
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                key = (node.name, item.name)
                if key in methods:
                    continue  # first definition wins (same-name helper classes)
                info = _MethodInfo()
                walker = _MethodWalker(node.name, info)
                for stmt in item.body:
                    walker.visit(stmt)
                methods[key] = (info, rel)
    return methods


def build_lock_graph(program: Program):
    """``(edges, blocking)``: edges maps ``(held, acquired)`` to the site
    ``(rel_path, line, via)`` that first creates it; blocking is a list of
    ``(lock, label, rel_path, line)``."""
    methods = _index_program(program)

    # Transitive acquisition summaries, to fixpoint (call graph has cycles).
    acq: Dict[Tuple[str, str], Set[str]] = {
        key: set(info.direct) for key, (info, _rel) in methods.items()
    }
    changed = True
    while changed:
        changed = False
        for key, (info, _rel) in methods.items():
            for callee, _held, _line in info.calls:
                extra = acq.get(callee)
                if extra and not extra <= acq[key]:
                    acq[key] |= extra
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    blocking: List[Tuple[str, str, str, int]] = []
    for (cls, meth), (info, rel) in sorted(methods.items()):
        for held, acquired, line in info.edges:
            edges.setdefault((held, acquired), (rel, line, f"{cls}.{meth}"))
        for callee, held, line in info.calls:
            if not held:
                continue
            for acquired in sorted(acq.get(callee, ())):
                via = f"{cls}.{meth} via {callee[0]}.{callee[1]}"
                for h in held:
                    edges.setdefault((h, acquired), (rel, line, via))
        for lock, label, line in info.blocking:
            blocking.append((lock, label, rel, line))
    return edges, blocking


def render_dot(edges) -> str:
    """Graphviz DOT of the lock graph (``--dump-lock-graph``)."""
    lines = ["digraph lock_order {", "  rankdir=LR;"]
    for (a, b), (rel, line, via) in sorted(edges.items()):
        lines.append(f'  "{a}" -> "{b}" [label="{via} ({rel}:{line})"];')
    lines.append("}")
    return "\n".join(lines)


def _find_cycles(edges) -> List[Tuple[Tuple[str, ...], Tuple[str, str]]]:
    """Elementary cycles among resolvable nodes, canonicalized.  Returns
    ``(cycle_nodes, first_edge)`` pairs, one per distinct cycle."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        if a.startswith("*.") or b.startswith("*."):
            continue  # wildcard nodes: distinct instances, not provably one lock
        if a == b:
            continue  # self-edges are reported separately below
        graph.setdefault(a, []).append(b)

    seen: Set[Tuple[str, ...]] = set()
    out: List[Tuple[Tuple[str, ...], Tuple[str, str]]] = []

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cycle = tuple(path)
                i = cycle.index(min(cycle))
                canon = cycle[i:] + cycle[:i]
                if canon not in seen:
                    seen.add(canon)
                    out.append((canon, (path[0], path[1] if len(path) > 1 else path[0])))
            elif nxt not in path and nxt > start:
                # only explore nodes > start so each cycle is found once,
                # from its smallest node
                dfs(start, nxt, path + [nxt])

    for (a, b) in sorted(edges):
        if a == b and not a.startswith("*."):
            out.append(((a,), (a, a)))
    for start in sorted(graph):
        dfs(start, start, [start])
    return out


@register_global(PASS)
def lock_order_pass(program: Program) -> List[Finding]:
    edges, blocking = build_lock_graph(program)
    findings: List[Finding] = []
    for cycle, first_edge in _find_cycles(edges):
        if len(cycle) == 1:
            rel, line, via = edges[(cycle[0], cycle[0])]
            findings.append(Finding(rel, line, PASS,
                f"lock self-cycle: '{cycle[0]}' re-acquired while already held (in {via})"))
            continue
        arrows = " -> ".join(cycle + (cycle[0],))
        sites = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]} ({edges[(a, b)][2]})"
            for a, b in zip(cycle, cycle[1:] + (cycle[0],))
            if (a, b) in edges
        )
        rel, line, _via = edges.get((cycle[0], cycle[1]), ("", 0, ""))
        findings.append(Finding(rel, line, PASS,
            f"lock-order cycle: {arrows} [{sites}]"))
    for lock, label, rel, line in blocking:
        findings.append(Finding(rel, line, PASS,
            f"blocking call '{label}' while holding {lock}"))
    return findings
