#!/usr/bin/env python
"""TeraSort-style integration driver — the BASELINE.json TeraSort shape at
real scale (>=1M rows) on the daemon + separate OS-process topology.

Map side: each map task generates ROWS/MAPPERS random uint32 keys with a
payload (val = key ^ MIX, the integrity twin), range-partitions them over the
REDUCERS output ranges (partition = key * R >> 32, the TeraSort sampler's
equal-width analogue), and writes each partition block over the daemon wire
protocol.  Reduce side: each reducer fetches its partition's blocks from all
maps, sorts, and runs the TeraValidate checks: every key inside the
partition's range, payload integrity, and reports (count, min, max, checksum).
The driver verifies record preservation (count + checksum vs a regenerated
oracle) and cross-partition boundary ordering max(r) <= min(r+1).

Reference gate analogue: buildlib/test.sh:169-173 (the big workload);
BASELINE.json configs[1] (TeraSort, 4-executor single host).
Knobs via env: EXECUTORS, MAPPERS, REDUCERS, ROWS.
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXECUTORS = int(os.environ.get("EXECUTORS", "4"))
MAPPERS = int(os.environ.get("MAPPERS", "8"))
REDUCERS = int(os.environ.get("REDUCERS", "16"))
ROWS = int(os.environ.get("ROWS", "1000000"))
ROWS_PER_MAP = -(-ROWS // MAPPERS)
SHUFFLE_ID = 7
MIX = 0x9E3779B9  # payload = key ^ MIX; reducers verify the twin survived the wire

MAPPER_SCRIPT = """
import sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, N = int(sys.argv[4]), int(sys.argv[5])
client = DaemonClient((host, port))
for m in map_ids:
    rng = np.random.default_rng(7000 + m)  # deterministic per map (oracle twin)
    keys = rng.integers(0, 2**32, size=N, dtype=np.uint64).astype(np.uint32)
    vals = keys ^ np.uint32({mix})
    parts = ((keys.astype(np.uint64) * R) >> 32).astype(np.int64)
    w = client.open_map_writer({sid}, m)
    for r in np.unique(parts):
        sel = parts == r
        block = np.stack([keys[sel], vals[sel]], axis=1)  # (n, 2) uint32 rows
        client.write_partition(w, int(r), block.tobytes())
    client.commit_map(w)
client.close()
print("mapper done", map_ids)
"""

REDUCER_SCRIPT = """
import json, sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port = sys.argv[1], int(sys.argv[2])
partitions = [int(x) for x in sys.argv[3].split(",")]
M, R = int(sys.argv[4]), int(sys.argv[5])
client = DaemonClient((host, port))
out = {{}}
for r in partitions:
    blocks = client.fetch_blocks([ShuffleBlockId({sid}, m, r) for m in range(M)])
    rows = [np.frombuffer(b, dtype=np.uint32).reshape(-1, 2) for b in blocks if b]
    data = np.concatenate(rows) if rows else np.empty((0, 2), dtype=np.uint32)
    keys, vals = data[:, 0], data[:, 1]
    # TeraValidate: range membership + payload integrity, then sort
    lo = (r << 32) // R
    hi = ((r + 1) << 32) // R
    k64 = keys.astype(np.uint64)
    assert bool(np.all((k64 * R) >> 32 == r)), f"partition {{r}}: key outside range"
    assert bool(np.all(k64 >= lo)) and bool(np.all(k64 < hi))
    assert bool(np.all(vals == (keys ^ np.uint32({mix})))), f"partition {{r}}: payload corrupt"
    order = np.argsort(keys, kind="stable")
    skeys = keys[order]
    assert bool(np.all(skeys[1:] >= skeys[:-1]))
    out[r] = dict(
        count=int(keys.size),
        lo=int(skeys[0]) if keys.size else None,
        hi=int(skeys[-1]) if keys.size else None,
        checksum=int(k64.sum()),
    )
client.close()
print("REDUCER_RESULT " + json.dumps(out))
"""


def oracle():
    """Per-partition (count, checksum) from a regenerated key stream."""
    import numpy as np

    counts = [0] * REDUCERS
    checks = [0] * REDUCERS
    for m in range(MAPPERS):
        rng = np.random.default_rng(7000 + m)
        keys = rng.integers(0, 2**32, size=ROWS_PER_MAP, dtype=np.uint64).astype(np.uint32)
        parts = ((keys.astype(np.uint64) * REDUCERS) >> 32).astype(np.int64)
        for r in range(REDUCERS):
            sel = parts == r
            counts[r] += int(sel.sum())
            checks[r] += int(keys[sel].astype(np.uint64).sum())
    return counts, checks


def main() -> int:
    t0 = time.monotonic()
    env = dict(os.environ)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "sparkucx_tpu.shuffle.daemon", "--port", "0",
         "--executors", str(EXECUTORS)],
        stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
    )
    try:
        deadline = time.monotonic() + 120
        host = port = None
        while time.monotonic() < deadline:
            line = daemon.stdout.readline().strip()
            if "shuffle daemon on " in line:
                host, port = line.rsplit(" ", 1)[-1].split(":")
                break
        if host is None:
            print("[terasort] FAIL: daemon did not report its address")
            return 1
        print(f"[terasort] daemon on {host}:{port}")

        from sparkucx_tpu.shuffle.daemon import DaemonClient

        ctl = DaemonClient((host, int(port)))
        ctl.create_shuffle(SHUFFLE_ID, MAPPERS, REDUCERS)

        mappers = []
        for e in range(EXECUTORS):
            mine = [str(m) for m in range(MAPPERS) if m % EXECUTORS == e]
            if not mine:
                continue
            script = MAPPER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID, mix=MIX)
            mappers.append(subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine),
                 str(REDUCERS), str(ROWS_PER_MAP)],
                cwd=ROOT, env=env,
            ))
        for p in mappers:
            if p.wait(timeout=600) != 0:
                print("[terasort] FAIL: mapper exited nonzero")
                return 1

        ctl.run_exchange(SHUFFLE_ID)
        print("[terasort] exchange complete")

        per = -(-REDUCERS // EXECUTORS)
        reducers = []
        for e in range(EXECUTORS):
            mine = [str(r) for r in range(e * per, min((e + 1) * per, REDUCERS))]
            if not mine:
                continue
            script = REDUCER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID, mix=MIX)
            reducers.append(subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine),
                 str(MAPPERS), str(REDUCERS)],
                stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
            ))
        got = {}
        for p in reducers:
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                print("[terasort] FAIL: reducer exited nonzero")
                return 1
            for line in out.splitlines():
                if line.startswith("REDUCER_RESULT "):
                    for r, rec in json.loads(line[len("REDUCER_RESULT "):]).items():
                        got[int(r)] = rec

        counts, checks = oracle()
        total = 0
        prev_hi = -1
        for r in range(REDUCERS):
            rec = got.get(r)
            if rec is None:
                print(f"[terasort] FAIL: no result for partition {r}")
                return 1
            if rec["count"] != counts[r] or rec["checksum"] != checks[r]:
                print(f"[terasort] FAIL: partition {r} count/checksum mismatch "
                      f"({rec['count']} vs {counts[r]})")
                return 1
            if rec["count"]:
                if rec["lo"] <= prev_hi:
                    print(f"[terasort] FAIL: boundary disorder at partition {r}")
                    return 1
                prev_hi = rec["hi"]
            total += rec["count"]
        if total != ROWS_PER_MAP * MAPPERS:
            print(f"[terasort] FAIL: row loss ({total} vs {ROWS_PER_MAP * MAPPERS})")
            return 1
        print(f"[terasort] PASS: {total} rows sorted across {REDUCERS} ranges, "
              f"{MAPPERS} maps, {EXECUTORS} executor processes, "
              f"{time.monotonic() - t0:.1f}s wall")
        ctl.remove_shuffle(SHUFFLE_ID)
        ctl.shutdown()
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    sys.exit(main())
