#!/usr/bin/env python
"""TPC-H q18 integration driver — a Spark-SQL-shaped JOB through the daemon
(VERDICT r4 item 7): the SQL pipelines that run as device ops in
tests/test_tpch.py here run as a multi-process, two-stage, two-shuffle job
over the wire protocol, proving the L7 surface carries the BASELINE
configs[2] workloads, not only groupby/terasort.

q18 ("large volume customer") physical plan, mapped to shuffles:

    stage 1  lineitem --(shuffle A: hash by l_orderkey)--> SUM(l_quantity)
             GROUP BY l_orderkey HAVING sum > THRESHOLD          (HashAgg)
    stage 2  survivors --(shuffle B: re-keyed)--+
             orders    --(shuffle C: hash by o_orderkey)--+--> join on
             orderkey -> (c_custkey, o_totalprice, sum_qty) rows  (SHJ)

Mapper processes generate deterministic lineitem/orders shards and write
partition blocks over the daemon protocol; stage-1 reducer processes fetch,
aggregate, apply the HAVING filter, and act as stage-2 MAPPERS (writing the
survivors into shuffle B) — the classic multi-stage DAG where one stage's
reduce side is the next stage's map side.  Stage-2 reducers join shuffles B
and C per partition and emit the final q18 rows; the driver compares the
merged result against a full numpy oracle over the regenerated inputs.

Reference gate analogue: buildlib/test.sh:196's gate composition;
BASELINE.json configs[2] (TPC-H SF=10 plan shapes).
Knobs via env: EXECUTORS, MAPPERS, REDUCERS, ROWS (lineitem), ORDERS.
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXECUTORS = int(os.environ.get("EXECUTORS", "2"))
MAPPERS = int(os.environ.get("MAPPERS", "4"))
REDUCERS = int(os.environ.get("REDUCERS", "8"))
ROWS = int(os.environ.get("ROWS", "200000"))          # lineitem rows
ORDERS = int(os.environ.get("ORDERS", "10000"))       # orders rows (unique keys)
CUSTOMERS = max(ORDERS // 10, 1)
# HAVING SUM(l_quantity) > : with ROWS/ORDERS ~ 20 rows/order at mean qty
# 25.5, 650 qualifies ~1 order in 7 — the filter really filters (q18's HAVING
# is the plan's whole point)
THRESHOLD = int(os.environ.get("THRESHOLD", "650"))
ROWS_PER_MAP = -(-ROWS // MAPPERS)
SHUFFLE_LINEITEM, SHUFFLE_SURVIVORS, SHUFFLE_ORDERS = 18, 19, 20

# partitioner shared by every stage (and the oracle): hash(orderkey) % R
PARTITION = "lambda k, R: ((k.astype('uint64') * 2654435761) >> 16) % R"


LINEITEM_MAPPER = """
import sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, N, ORDERS = int(sys.argv[4]), int(sys.argv[5]), int(sys.argv[6])
part_of = {partition}
client = DaemonClient((host, port))
for m in map_ids:
    rng = np.random.default_rng(1800 + m)   # deterministic: the oracle's twin
    okey = rng.integers(0, ORDERS, size=N, dtype=np.uint64).astype(np.uint32)
    qty = rng.integers(1, 51, size=N, dtype=np.uint64).astype(np.uint32)
    parts = part_of(okey, R)
    w = client.open_map_writer({sid}, m)
    for r in np.unique(parts):
        sel = parts == r
        client.write_partition(w, int(r), np.stack([okey[sel], qty[sel]], axis=1).tobytes())
    client.commit_map(w)
client.close()
print("lineitem mapper done", map_ids)
"""


ORDERS_MAPPER = """
import sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, ORDERS, CUSTOMERS, M = int(sys.argv[4]), int(sys.argv[5]), int(sys.argv[6]), int(sys.argv[7])
part_of = {partition}
client = DaemonClient((host, port))
for m in map_ids:
    # orders table striped over mappers; attributes derive from the key so
    # any process (and the oracle) can regenerate them without coordination
    okey = np.arange(m, ORDERS, M, dtype=np.uint32)
    cust = (okey * np.uint32(2246822519)) % np.uint32(CUSTOMERS)
    price = (okey % np.uint32(9973)) + np.uint32(1)
    parts = part_of(okey, R)
    w = client.open_map_writer({sid}, m)
    for r in np.unique(parts):
        sel = parts == r
        client.write_partition(
            w, int(r), np.stack([okey[sel], cust[sel], price[sel]], axis=1).tobytes())
    client.commit_map(w)
client.close()
print("orders mapper done", map_ids)
"""


# Stage-1 reducer AND stage-2 mapper: aggregates its lineitem partitions,
# applies HAVING, re-publishes survivors into the survivors shuffle keyed by
# the same partitioner (map_id = partition id — the DAG edge).
STAGE1_SCRIPT = """
import sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port = sys.argv[1], int(sys.argv[2])
partitions = [int(x) for x in sys.argv[3].split(",")]
M, R, THRESHOLD = int(sys.argv[4]), int(sys.argv[5]), int(sys.argv[6])
part_of = {partition}
client = DaemonClient((host, port))
for r in partitions:
    blocks = client.fetch_blocks([ShuffleBlockId({sid_in}, m, r) for m in range(M)])
    rows = [np.frombuffer(b, dtype=np.uint32).reshape(-1, 2) for b in blocks if b]
    data = np.concatenate(rows) if rows else np.empty((0, 2), dtype=np.uint32)
    # HashAggregateExec: SUM(l_quantity) GROUP BY l_orderkey, then HAVING
    uniq, inv = np.unique(data[:, 0], return_inverse=True)
    sums = np.zeros(len(uniq), dtype=np.uint64)
    np.add.at(sums, inv, data[:, 1])
    keep = sums > THRESHOLD
    survivors = np.stack(
        [uniq[keep], sums[keep].astype(np.uint32)], axis=1
    ) if keep.any() else np.empty((0, 2), dtype=np.uint32)
    # stage-2 map side: survivors re-partitioned by the SAME partitioner
    # (hash partitioning is stable, so each survivor stays in partition r —
    # the degenerate exchange Spark's AQE would elide; written through the
    # wire anyway to exercise the full stage boundary)
    w = client.open_map_writer({sid_out}, r)
    parts = part_of(survivors[:, 0], R)
    for rr in np.unique(parts):
        sel = parts == rr
        client.write_partition(w, int(rr), survivors[sel].tobytes())
    client.commit_map(w)
client.close()
print("stage1 done", partitions)
"""


STAGE2_SCRIPT = """
import json, sys
sys.path.insert(0, {root!r})
import numpy as np
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient

host, port = sys.argv[1], int(sys.argv[2])
partitions = [int(x) for x in sys.argv[3].split(",")]
R, OM = int(sys.argv[4]), int(sys.argv[5])
client = DaemonClient((host, port))
out = []
for r in partitions:
    sblocks = client.fetch_blocks([ShuffleBlockId({sid_surv}, m, r) for m in range(R)])
    oblocks = client.fetch_blocks([ShuffleBlockId({sid_ord}, m, r) for m in range(OM)])
    srows = [np.frombuffer(b, dtype=np.uint32).reshape(-1, 2) for b in sblocks if b]
    orows = [np.frombuffer(b, dtype=np.uint32).reshape(-1, 3) for b in oblocks if b]
    surv = np.concatenate(srows) if srows else np.empty((0, 2), dtype=np.uint32)
    orders = np.concatenate(orows) if orows else np.empty((0, 3), dtype=np.uint32)
    # ShuffledHashJoin on orderkey: orders is the build side (PK), survivors
    # probe; both sides were hash-partitioned by the same key so the join is
    # partition-local.
    order_by_key = {{int(k): (int(c), int(p)) for k, c, p in orders}}
    for okey, sq in surv:
        cust, price = order_by_key[int(okey)]   # PK-FK: must always hit
        out.append((int(cust), int(okey), price, int(sq)))
client.close()
print("STAGE2_RESULT " + json.dumps(out))
"""


def oracle():
    """Full numpy q18 over the regenerated inputs."""
    import numpy as np

    okeys = []
    qtys = []
    for m in range(MAPPERS):
        rng = np.random.default_rng(1800 + m)
        okeys.append(rng.integers(0, ORDERS, size=ROWS_PER_MAP, dtype=np.uint64).astype(np.uint32))
        qtys.append(rng.integers(1, 51, size=ROWS_PER_MAP, dtype=np.uint64).astype(np.uint32))
    okey = np.concatenate(okeys)
    qty = np.concatenate(qtys)
    uniq, inv = np.unique(okey, return_inverse=True)
    sums = np.zeros(len(uniq), dtype=np.uint64)
    np.add.at(sums, inv, qty)
    keep = sums > THRESHOLD
    rows = []
    for k, s in zip(uniq[keep], sums[keep]):
        # uint32-wraparound twin of ORDERS_MAPPER's array arithmetic
        cust = ((int(k) * 2246822519) & 0xFFFFFFFF) % CUSTOMERS
        price = int(k) % 9973 + 1
        rows.append((cust, int(k), price, int(s)))
    return sorted(rows)


def main() -> int:
    t0 = time.monotonic()
    env = dict(os.environ)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "sparkucx_tpu.shuffle.daemon", "--port", "0",
         "--executors", str(EXECUTORS)],
        stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
    )
    try:
        deadline = time.monotonic() + 120
        host = port = None
        while time.monotonic() < deadline:
            line = daemon.stdout.readline().strip()
            if "shuffle daemon on " in line:
                host, port = line.rsplit(" ", 1)[-1].split(":")
                break
        if host is None:
            print("[tpch] FAIL: daemon did not report its address")
            return 1
        print(f"[tpch] daemon on {host}:{port}")

        from sparkucx_tpu.shuffle.daemon import DaemonClient

        ctl = DaemonClient((host, int(port)))
        ctl.create_shuffle(SHUFFLE_LINEITEM, MAPPERS, REDUCERS)
        ctl.create_shuffle(SHUFFLE_ORDERS, MAPPERS, REDUCERS)
        # survivors shuffle: stage-1 reducers are its mappers (one per partition)
        ctl.create_shuffle(SHUFFLE_SURVIVORS, REDUCERS, REDUCERS)

        def spawn_over_executors(script, ids, *extra):
            procs = []
            for e in range(EXECUTORS):
                mine = [str(i) for i in ids if i % EXECUTORS == e]
                if not mine:
                    continue
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", script, host, port, ",".join(mine), *extra],
                    stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
                ))
            return procs

        def wait_all(procs, label):
            outs = []
            for p in procs:
                out, _ = p.communicate(timeout=600)
                if p.returncode != 0:
                    raise RuntimeError(f"{label} exited nonzero")
                outs.append(out)
            return outs

        # stage 0: both base tables, concurrently
        li = spawn_over_executors(
            LINEITEM_MAPPER.format(root=ROOT, sid=SHUFFLE_LINEITEM, partition=PARTITION),
            range(MAPPERS), str(REDUCERS), str(ROWS_PER_MAP), str(ORDERS),
        )
        om = spawn_over_executors(
            ORDERS_MAPPER.format(root=ROOT, sid=SHUFFLE_ORDERS, partition=PARTITION),
            range(MAPPERS), str(REDUCERS), str(ORDERS), str(CUSTOMERS), str(MAPPERS),
        )
        wait_all(li, "lineitem mapper")
        wait_all(om, "orders mapper")
        ctl.run_exchange(SHUFFLE_LINEITEM)
        ctl.run_exchange(SHUFFLE_ORDERS)
        print(f"[tpch] stage-0 exchanges complete ({time.monotonic()-t0:.1f}s)")

        # stage 1: aggregate + HAVING; republish survivors (stage-2 map side)
        s1 = spawn_over_executors(
            STAGE1_SCRIPT.format(
                root=ROOT, sid_in=SHUFFLE_LINEITEM, sid_out=SHUFFLE_SURVIVORS,
                partition=PARTITION,
            ),
            range(REDUCERS), str(MAPPERS), str(REDUCERS), str(THRESHOLD),
        )
        wait_all(s1, "stage-1 reducer")
        ctl.run_exchange(SHUFFLE_SURVIVORS)
        print(f"[tpch] stage-1 exchange complete ({time.monotonic()-t0:.1f}s)")

        # stage 2: partition-local join + final rows
        s2 = spawn_over_executors(
            STAGE2_SCRIPT.format(root=ROOT, sid_surv=SHUFFLE_SURVIVORS, sid_ord=SHUFFLE_ORDERS),
            range(REDUCERS), str(REDUCERS), str(MAPPERS),
        )
        got = []
        for out in wait_all(s2, "stage-2 reducer"):
            for line in out.splitlines():
                if line.startswith("STAGE2_RESULT "):
                    got.extend(tuple(row) for row in json.loads(line[len("STAGE2_RESULT "):]))

        want = oracle()
        got = sorted(got)
        if got != want:
            print(f"[tpch] FAIL: result mismatch ({len(got)} rows vs {len(want)})")
            for g, w in list(zip(got, want))[:5]:
                if g != w:
                    print(f"  first diff: got {g} want {w}")
                    break
            return 1
        print(
            f"[tpch] PASS: q18 over {ROWS} lineitem x {ORDERS} orders -> "
            f"{len(got)} qualifying rows, 3 shuffles, 2 stages, "
            f"{EXECUTORS} executor processes, {time.monotonic()-t0:.1f}s wall"
        )
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    sys.exit(main())
