#!/usr/bin/env bash
# One-shot hardware measurement session — runs the full deferred on-chip
# queue the moment the axon tunnel answers, appending everything to a log so
# a brief tunnel window still captures a complete record.
#
# Queue (docs/PERF.md "Not yet measured on hardware"):
#   1. bench.py           — headline + groupby/partial/radix sub-metrics
#   2. profile_sort.py    — sort-lowering head-to-head incl. the radix kernel
#   3. benchmark sort --sort-impl radix   — the A/B at CLI scale
#   4. benchmark groupby [--partial] / join --join-type ... / sort --batches
#   5. tpu_smoke.py       — the 8 oracle drives on the real chip
#
# Usage:  bash scripts/hw_session.sh [logfile]   (default: hw_session_r5.log)
set -uo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-hw_session_r5.log}"

say() { echo "== $* ==" | tee -a "$LOG"; }
run() {  # run <timeout-s> <label> <cmd...>; failures are logged, not fatal
  local t="$1" label="$2"; shift 2
  say "$label ($(date -u +%H:%M:%SZ))"
  timeout "$t" "$@" >>"$LOG" 2>&1
  echo "-- rc=$? $label" | tee -a "$LOG"
}

say "probe"
if ! timeout 60 python -c "import jax; d=jax.devices(); print(d[0].platform, len(d))" >>"$LOG" 2>&1; then
  say "tunnel DOWN — nothing captured"
  exit 1
fi

run 900 "bench.py (headline + sub-metrics)" python bench.py
run 600 "profile_sort (incl. radix head-to-head)" python scripts/profile_sort.py
run 600 "sort radix A/B" python -m sparkucx_tpu.perf.benchmark sort \
  --executors 1 -n 2097152 -i 3 -o 8 --sort-impl radix
for tile in 4096 16384; do  # tile sweep: DMA segment size vs VMEM/search width
  run 600 "sort radix tile=$tile" env SPARKUCX_RADIX_TILE="$tile" \
    python -m sparkucx_tpu.perf.benchmark sort \
    --executors 1 -n 2097152 -i 2 -o 8 --sort-impl radix
done
run 600 "groupby" python -m sparkucx_tpu.perf.benchmark groupby \
  --executors 1 -n 2097152 -i 3 --keys 100
run 600 "groupby --partial" python -m sparkucx_tpu.perf.benchmark groupby \
  --executors 1 -n 2097152 -i 3 --keys 100 --partial
run 600 "join inner" python -m sparkucx_tpu.perf.benchmark join \
  --executors 1 -n 2097152 --build-rows 524288 -i 3
run 600 "join full_outer" python -m sparkucx_tpu.perf.benchmark join \
  --executors 1 -n 2097152 --build-rows 524288 -i 3 --join-type full_outer
run 900 "sort --batches 4 (out-of-core)" python -m sparkucx_tpu.perf.benchmark sort \
  --executors 1 -n 4194304 --batches 4 -i 2
run 600 "tpu_smoke (8 drives on chip)" python scripts/tpu_smoke.py
say "session complete — results in $LOG"
