#!/usr/bin/env python
"""Sort-lowering head-to-head: the compiled n=1 sort body vs its pieces.

Answers "where does the TeraSort step's time go, and what could beat it" with
one table (docs/PERF.md "Where the sort time actually goes").  Variants:

* the full jitted ``_sort_body_single`` (what ``bench.py`` measures),
* ``jnp.argsort`` alone, argsort + key gather, argsort + both gathers,
* keys-only ``jnp.sort`` (no index production) and batched argsort
  ([chunks, rows/chunk] — XLA's batched sort costs ~the keys-only sort,
  the basis for any two-level scheme),
* ``sort_key_val`` (what argsort lowers to).

Methodology per docs/PERF.md: best-of-3 chained windows with a tiny
device-sliced readback.  Data generated ON DEVICE (host->device through a
tunnel is ~10 MB/s).  Run on any backend; numbers only mean something on the
real chip:

    python scripts/profile_sort.py [-n ROWS] [-w WINDOW]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--rows", type=int, default=1 << 21)
    ap.add_argument("-w", "--window", type=int, default=8)
    args = ap.parse_args()

    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparkucx_tpu.ops.exchange import gather_rows, make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, build_distributed_sort

    N, W = args.rows, args.window
    print(f"backend: {jax.devices()[0].platform}, rows={N}, window={W}", flush=True)

    mesh = make_mesh(1)
    spec = SortSpec(num_executors=1, capacity=N, recv_capacity=N, width=24)
    full = build_distributed_sort(mesh, spec)

    @jax.jit
    def gen():
        k = jax.random.bits(jax.random.key(0), (N,), jnp.uint32)
        p = jax.lax.bitcast_convert_type(
            jax.random.bits(jax.random.key(1), (N, 24), jnp.uint32), jnp.int32
        )
        return k, p

    keys, pay = jax.block_until_ready(gen())
    nv = jax.device_put(np.full(1, N, np.int32))
    readback = jax.jit(lambda x: x.ravel()[:4])

    def timed(name, f, *fargs, rows=N):
        o = f(*fargs)
        jax.block_until_ready(o)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            outs = [f(*fargs) for _ in range(W)]
            jax.block_until_ready(outs)
            np.asarray(readback(jax.tree_util.tree_leaves(outs[-1])[0]))
            best = min(best, (time.perf_counter() - t0) / W)
        print(f"{name:44s} {best*1e3:8.2f} ms  {rows/best/1e6:7.1f} M rows/s", flush=True)
        return best

    timed("full sort body (impl=single)", full, keys, pay, nv)
    timed("argsort u32", jax.jit(lambda k: jnp.argsort(k)), keys)
    timed("argsort + key gather", jax.jit(lambda k: k[jnp.argsort(k)]), keys)

    def body_like(k, p):
        order = jnp.argsort(k)
        return k[order], gather_rows(p, order)

    timed("argsort + key gather + payload gather", jax.jit(body_like), keys, pay)
    timed("sort u32 keys only", jax.jit(lambda k: jnp.sort(k)), keys)
    chunks = 256
    nb = (N // chunks) * chunks  # round down so the variant always runs
    bkeys = keys if nb == N else jax.jit(lambda k: k[:nb])(keys)
    timed(
        f"argsort batched [{chunks},{nb // chunks}]"
        + ("" if nb == N else f" (first {nb} rows)"),
        jax.jit(lambda k: jnp.argsort(k.reshape(chunks, -1), axis=1)),
        bkeys,
        rows=nb,
    )
    timed(
        "sort_key_val (k, iota)",
        jax.jit(lambda k: jax.lax.sort_key_val(k, jnp.arange(N, dtype=jnp.int32))),
        keys,
    )

    # The round-5 contender: the Pallas LSD radix sort whose scatter moves
    # key+payload together by segment DMA (ops/radix.py; PERF.md brackets it
    # 35-70 M rows/s).  Mosaic-only — the interpreter path would measure the
    # emulator, so off-TPU this section just says so.
    if jax.devices()[0].platform == "tpu":
        from sparkucx_tpu.ops.radix import build_radix_sort

        fused = jax.jit(
            lambda k, p: jnp.concatenate(
                [jax.lax.bitcast_convert_type(k, jnp.int32)[:, None], p], axis=1
            )
        )
        rows_fused = jax.block_until_ready(fused(keys, pay))
        try:
            timed(
                "radix LSD 8x4bit, fused 100 B rows (Pallas)",
                build_radix_sort(N, 25), rows_fused,
            )
            rspec = SortSpec(
                num_executors=1, capacity=N, recv_capacity=N, width=24, impl="radix"
            )
            timed("full sort body (impl=radix)", build_distributed_sort(mesh, rspec), keys, pay, nv)
        except Exception as e:  # first hardware run of the kernel: report, don't die
            print(f"radix variant failed: {type(e).__name__}: {e}", flush=True)
    else:
        print("radix variants: skipped (Mosaic kernel; TPU only)", flush=True)


if __name__ == "__main__":
    main()
