#!/usr/bin/env python
"""GroupByTest-style integration driver — the ``buildlib/test.sh`` workload analogue.

The reference's integration gate runs stock Spark examples (GroupByTest, SparkTC)
on a real 2-executor standalone cluster (test.sh:163-179).  This driver runs the
same shape against the real process topology of this framework:

1. start the shuffle daemon (the TPU runtime process),
2. spawn EXECUTORS separate *mapper processes*, each writing its map tasks'
   partitioned (key, value) records over the daemon wire protocol,
3. run the collective exchange,
4. spawn separate *reducer processes* that fetch, aggregate, and report per-key
   sums,
5. verify the union of reducer outputs against a single-process oracle.

Exit code 0 = pass.  Knobs via env (test.sh style): EXECUTORS, MAPPERS,
REDUCERS, PAIRS_PER_MAP.
"""

import json
import os
import pickle
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXECUTORS = int(os.environ.get("EXECUTORS", "2"))
MAPPERS = int(os.environ.get("MAPPERS", "4"))
REDUCERS = int(os.environ.get("REDUCERS", "8"))
PAIRS = int(os.environ.get("PAIRS_PER_MAP", "5000"))
SHUFFLE_ID = 0

MAPPER_SCRIPT = """
import os, pickle, sys
sys.path.insert(0, {root!r})
from sparkucx_tpu.shuffle.daemon import DaemonClient
from sparkucx_tpu.shuffle.reader import serialize_records
import numpy as np

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, PAIRS = int(sys.argv[4]), int(sys.argv[5])
client = DaemonClient((host, port))
for m in map_ids:
    rng = np.random.default_rng(1000 + m)  # deterministic per map (oracle twin)
    keys = rng.integers(0, 100, size=PAIRS)
    parts = keys % R
    w = client.open_map_writer({sid}, m)
    for r in np.unique(parts):
        client.write_partition(
            w, int(r), serialize_records((int(k), 1) for k in keys[parts == r]))
    client.commit_map(w)
client.close()
print("mapper done", map_ids)
"""

REDUCER_SCRIPT = """
import json, os, pickle, sys
sys.path.insert(0, {root!r})
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient
from sparkucx_tpu.shuffle.reader import default_deserializer

host, port = sys.argv[1], int(sys.argv[2])
partitions = [int(x) for x in sys.argv[3].split(",")]
M = int(sys.argv[4])
client = DaemonClient((host, port))
counts = {{}}
for r in partitions:
    blocks = client.fetch_blocks([ShuffleBlockId({sid}, m, r) for m in range(M)])
    for blk in blocks:
        if not blk:
            continue
        for k, v in default_deserializer(blk):
            counts[k] = counts.get(k, 0) + v
client.close()
print("REDUCER_RESULT " + json.dumps(counts))
"""


def oracle():
    import numpy as np

    total = np.zeros(100, dtype=np.int64)
    for m in range(MAPPERS):
        rng = np.random.default_rng(1000 + m)
        total += np.bincount(rng.integers(0, 100, size=PAIRS), minlength=100)
    return {k: int(v) for k, v in enumerate(total) if v}


def main() -> int:
    t0 = time.monotonic()
    env = dict(os.environ)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "sparkucx_tpu.shuffle.daemon", "--port", "0",
         "--executors", str(EXECUTORS)],
        stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
    )
    try:
        deadline = time.monotonic() + 120
        host = port = None
        while time.monotonic() < deadline:
            line = daemon.stdout.readline().strip()
            if "shuffle daemon on " in line:
                host, port = line.rsplit(" ", 1)[-1].split(":")
                break
        if host is None:
            print("[integration] FAIL: daemon did not report its address")
            return 1
        print(f"[integration] daemon on {host}:{port}")

        from sparkucx_tpu.shuffle.daemon import DaemonClient

        ctl = DaemonClient((host, int(port)))
        ctl.create_shuffle(SHUFFLE_ID, MAPPERS, REDUCERS)

        # mapper processes (maps split round-robin over executor processes)
        mappers = []
        for e in range(EXECUTORS):
            mine = [str(m) for m in range(MAPPERS) if m % EXECUTORS == e]
            if not mine:
                continue
            script = MAPPER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID)
            mappers.append(subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine),
                 str(REDUCERS), str(PAIRS)],
                cwd=ROOT, env=env,
            ))
        for p in mappers:
            if p.wait(timeout=300) != 0:
                print("[integration] FAIL: mapper exited nonzero")
                return 1

        ctl.run_exchange(SHUFFLE_ID)
        print("[integration] exchange complete")

        # reducer processes (partitions split contiguously like peer ranges)
        per = -(-REDUCERS // EXECUTORS)
        reducers = []
        for e in range(EXECUTORS):
            mine = [str(r) for r in range(e * per, min((e + 1) * per, REDUCERS))]
            if not mine:
                continue
            script = REDUCER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID)
            reducers.append(subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine), str(MAPPERS)],
                stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
            ))
        got = {}
        for p in reducers:
            out, _ = p.communicate(timeout=300)
            if p.returncode != 0:
                print("[integration] FAIL: reducer exited nonzero")
                return 1
            for line in out.splitlines():
                if line.startswith("REDUCER_RESULT "):
                    for k, v in json.loads(line[len("REDUCER_RESULT "):]).items():
                        got[int(k)] = got.get(int(k), 0) + v

        expected = oracle()
        if got != expected:
            missing = {k: v for k, v in expected.items() if got.get(k) != v}
            print(f"[integration] FAIL: result mismatch ({len(missing)} keys differ)")
            return 1
        total = sum(got.values())
        print(f"[integration] PASS: {MAPPERS} maps x {PAIRS} pairs -> "
              f"{len(got)} keys, {total} records, {EXECUTORS} executor processes, "
              f"{time.monotonic() - t0:.1f}s wall")
        ctl.remove_shuffle(SHUFFLE_ID)
        ctl.shutdown()
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    sys.exit(main())
