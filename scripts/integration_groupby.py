#!/usr/bin/env python
"""GroupByTest-style integration driver — the ``buildlib/test.sh`` workload analogue.

The reference's integration gate runs stock Spark examples (GroupByTest, SparkTC)
on a real 2-executor standalone cluster (test.sh:163-179).  This driver runs the
same shape against the real process topology of this framework:

1. start the shuffle daemon (the TPU runtime process),
2. spawn EXECUTORS separate *mapper processes*, each writing its map tasks'
   partitioned (key, value) records over the daemon wire protocol,
3. run the collective exchange,
4. spawn separate *reducer processes* that fetch, aggregate, and report per-key
   sums,
5. verify the union of reducer outputs against a single-process oracle.

Exit code 0 = pass.  Knobs via env (test.sh style): EXECUTORS, MAPPERS,
REDUCERS, PAIRS_PER_MAP.

``FAULTS=1`` adds OS-process fault injection (recovery the reference never had
— SURVEY.md section 5.3: a failed UCX send just logs; no retry anywhere):

* executor 0's mapper is first run as a *crashing attempt*: it fully commits
  its first map task, half-writes the next one, and SIGKILLs itself
  mid-write.  The retry attempt then rewrites ALL its maps — with a poisoned
  record added to the already-committed map.  First-commit-wins over the wire
  (IndexShuffleBlockResolver.scala:161-217 semantics at the daemon boundary)
  means the poison must be discarded; it appearing in any reducer's output
  fails the oracle check.  The half-written map's bytes must vanish entirely
  (its partition stream never closed, so nothing was ever recorded).
* one reducer process is SIGKILLed after fetching a prefix of its partitions
  and a fresh process re-runs the same partitions — post-exchange fetches are
  idempotent reads of the daemon's received shards, so the retry must see
  exactly the same bytes.
"""

import json
import os
import pickle
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXECUTORS = int(os.environ.get("EXECUTORS", "2"))
MAPPERS = int(os.environ.get("MAPPERS", "4"))
REDUCERS = int(os.environ.get("REDUCERS", "8"))
PAIRS = int(os.environ.get("PAIRS_PER_MAP", "5000"))
FAULTS = os.environ.get("FAULTS", "") == "1"
SHUFFLE_ID = 0
POISON_KEY = 10**6  # far outside the 0..99 key space; must never surface

MAPPER_SCRIPT = """
import os, pickle, sys
sys.path.insert(0, {root!r})
from sparkucx_tpu.shuffle.daemon import DaemonClient
from sparkucx_tpu.shuffle.reader import serialize_records
import numpy as np

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, PAIRS = int(sys.argv[4]), int(sys.argv[5])
# maps whose writes this (retry) attempt poisons: if first-commit-wins fails
# to discard them over the wire, the poison key reaches a reducer and the
# driver's oracle check fails
poison = [int(x) for x in sys.argv[6].split(",") if x] if len(sys.argv) > 6 else []
client = DaemonClient((host, port))
for m in map_ids:
    rng = np.random.default_rng(1000 + m)  # deterministic per map (oracle twin)
    keys = rng.integers(0, 100, size=PAIRS)
    parts = keys % R
    w = client.open_map_writer({sid}, m)
    for r in np.unique(parts):
        recs = [(int(k), 1) for k in keys[parts == r]]
        if m in poison:
            recs.append(({poison_key}, 10**9))
        client.write_partition(w, int(r), serialize_records(recs))
    client.commit_map(w)
client.close()
print("mapper done", map_ids)
"""

CRASHING_MAPPER_SCRIPT = """
import os, signal, sys
sys.path.insert(0, {root!r})
from sparkucx_tpu.shuffle.daemon import DaemonClient
from sparkucx_tpu.shuffle.reader import serialize_records
import numpy as np

host, port, map_ids = sys.argv[1], int(sys.argv[2]), [int(x) for x in sys.argv[3].split(",")]
R, PAIRS = int(sys.argv[4]), int(sys.argv[5])
client = DaemonClient((host, port))
# 1. first map: full, committed — attempt 1 wins it
m = map_ids[0]
rng = np.random.default_rng(1000 + m)
keys = rng.integers(0, 100, size=PAIRS)
parts = keys % R
w = client.open_map_writer({sid}, m)
for r in np.unique(parts):
    client.write_partition(
        w, int(r), serialize_records((int(k), 1) for k in keys[parts == r]))
client.commit_map(w)
# 2. second map: half-write garbage into one partition stream, never close it,
#    then die hard mid-task (kill -9: no atexit, no socket shutdown handshake)
m2 = map_ids[1]
w2 = client.open_map_writer({sid}, m2)
client.write_partition(w2, 0, b"GARBAGE-HALF-WRITTEN" * 50)
print("crashing mapper: committed", m, "dying inside", m2, flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""

REDUCER_SCRIPT = """
import json, os, pickle, signal, sys
sys.path.insert(0, {root!r})
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient
from sparkucx_tpu.shuffle.reader import default_deserializer

host, port = sys.argv[1], int(sys.argv[2])
partitions = [int(x) for x in sys.argv[3].split(",")]
M = int(sys.argv[4])
# die hard after fetching this many partitions (fault injection; 0 = never)
fault_after = int(sys.argv[5]) if len(sys.argv) > 5 else 0
client = DaemonClient((host, port))
counts = {{}}
for i, r in enumerate(partitions):
    if fault_after and i >= fault_after:
        print("crashing reducer: dying after", i, "partitions", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    blocks = client.fetch_blocks([ShuffleBlockId({sid}, m, r) for m in range(M)])
    for blk in blocks:
        if not blk:
            continue
        for k, v in default_deserializer(blk):
            counts[k] = counts.get(k, 0) + v
client.close()
print("REDUCER_RESULT " + json.dumps(counts))
"""


def oracle():
    import numpy as np

    total = np.zeros(100, dtype=np.int64)
    for m in range(MAPPERS):
        rng = np.random.default_rng(1000 + m)
        total += np.bincount(rng.integers(0, 100, size=PAIRS), minlength=100)
    return {k: int(v) for k, v in enumerate(total) if v}


def main() -> int:
    t0 = time.monotonic()
    env = dict(os.environ)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "sparkucx_tpu.shuffle.daemon", "--port", "0",
         "--executors", str(EXECUTORS)],
        stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
    )
    try:
        deadline = time.monotonic() + 120
        host = port = None
        while time.monotonic() < deadline:
            line = daemon.stdout.readline().strip()
            if "shuffle daemon on " in line:
                host, port = line.rsplit(" ", 1)[-1].split(":")
                break
        if host is None:
            print("[integration] FAIL: daemon did not report its address")
            return 1
        print(f"[integration] daemon on {host}:{port}")

        from sparkucx_tpu.shuffle.daemon import DaemonClient

        ctl = DaemonClient((host, int(port)))
        ctl.create_shuffle(SHUFFLE_ID, MAPPERS, REDUCERS)

        # Fault phase A (FAULTS=1): executor 0's mapper crashes mid-task —
        # first map committed, second map half-written, then SIGKILL.
        if FAULTS:
            mine0 = [str(m) for m in range(MAPPERS) if m % EXECUTORS == 0]
            if len(mine0) < 2:
                print("[integration] FAIL: FAULTS=1 needs >= 2 maps on executor 0")
                return 1
            crash = subprocess.Popen(
                [sys.executable, "-c",
                 CRASHING_MAPPER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID),
                 host, port, ",".join(mine0), str(REDUCERS), str(PAIRS)],
                cwd=ROOT, env=env,
            )
            rc = crash.wait(timeout=300)
            if rc == 0:
                print("[integration] FAIL: crashing mapper did not crash")
                return 1
            print(f"[integration] fault A: mapper SIGKILLed mid-write (rc={rc}); retrying")

        # mapper processes (maps split round-robin over executor processes);
        # under FAULTS, executor 0 is the RETRY attempt and poisons the map the
        # crashed attempt already committed — first-commit-wins must discard it
        mappers = []
        for e in range(EXECUTORS):
            mine = [str(m) for m in range(MAPPERS) if m % EXECUTORS == e]
            if not mine:
                continue
            script = MAPPER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID, poison_key=POISON_KEY)
            argv = [sys.executable, "-c", script, host, port, ",".join(mine),
                    str(REDUCERS), str(PAIRS)]
            if FAULTS and e == 0:
                argv.append(mine[0])  # poison the committed map's retry writes
            mappers.append(subprocess.Popen(argv, cwd=ROOT, env=env))
        for p in mappers:
            if p.wait(timeout=300) != 0:
                print("[integration] FAIL: mapper exited nonzero")
                return 1

        ctl.run_exchange(SHUFFLE_ID)
        print("[integration] exchange complete")

        # Fault phase B (FAULTS=1): one reducer dies after fetching half its
        # partitions; a fresh process re-runs the SAME partitions — the
        # post-exchange fetch is an idempotent read, so the retry sees
        # identical bytes and the oracle check stays exact.
        script = REDUCER_SCRIPT.format(root=ROOT, sid=SHUFFLE_ID)
        per = -(-REDUCERS // EXECUTORS)
        if FAULTS:
            mine0 = [str(r) for r in range(0, min(per, REDUCERS))]
            if len(mine0) < 2:
                # fault_after=max(1, 0)=1 would let a 1-partition reducer
                # finish before the kill fires — a config artifact, not a pass
                print("[integration] FAIL: FAULTS=1 needs >= 2 reduce partitions "
                      "on the faulted reducer (raise REDUCERS or lower EXECUTORS)")
                return 1
            crash = subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine0),
                 str(MAPPERS), str(max(1, len(mine0) // 2))],
                stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
            )
            out, _ = crash.communicate(timeout=300)
            if crash.returncode == 0 or any(
                line.startswith("REDUCER_RESULT ") for line in out.splitlines()
            ):
                print("[integration] FAIL: crashing reducer did not crash")
                return 1
            print(f"[integration] fault B: reducer SIGKILLed mid-fetch "
                  f"(rc={crash.returncode}); re-running its partitions")

        # reducer processes (partitions split contiguously like peer ranges)
        reducers = []
        for e in range(EXECUTORS):
            mine = [str(r) for r in range(e * per, min((e + 1) * per, REDUCERS))]
            if not mine:
                continue
            reducers.append(subprocess.Popen(
                [sys.executable, "-c", script, host, port, ",".join(mine), str(MAPPERS)],
                stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env,
            ))
        got = {}
        for p in reducers:
            out, _ = p.communicate(timeout=300)
            if p.returncode != 0:
                print("[integration] FAIL: reducer exited nonzero")
                return 1
            for line in out.splitlines():
                if line.startswith("REDUCER_RESULT "):
                    for k, v in json.loads(line[len("REDUCER_RESULT "):]).items():
                        got[int(k)] = got.get(int(k), 0) + v

        expected = oracle()
        if FAULTS and POISON_KEY in got:
            print("[integration] FAIL: poisoned retry write of a committed map "
                  "surfaced — first-commit-wins discard broken over the wire")
            return 1
        if got != expected:
            missing = {k: v for k, v in expected.items() if got.get(k) != v}
            print(f"[integration] FAIL: result mismatch ({len(missing)} keys differ)")
            return 1
        total = sum(got.values())
        faults = " (+mapper/reducer fault injection)" if FAULTS else ""
        print(f"[integration] PASS: {MAPPERS} maps x {PAIRS} pairs -> "
              f"{len(got)} keys, {total} records, {EXECUTORS} executor processes, "
              f"{time.monotonic() - t0:.1f}s wall{faults}")
        ctl.remove_shuffle(SHUFFLE_ID)
        ctl.shutdown()
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    sys.exit(main())
