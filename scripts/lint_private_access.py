#!/usr/bin/env python
"""Lint: no cross-object private access inside sparkucx_tpu/ — COMPAT SHIM.

The real checks moved into the analyzer framework (PR 3): the
``private-access`` and ``required-surface`` passes of
``sparkucx_tpu/analysis/``, with the reviewed ALLOWLIST and REQUIRED_SURFACE
tables now in ``sparkucx_tpu/analysis/config.py``.  This shim keeps the old
entry point (and its exit-code contract) alive for muscle memory and any
external automation; new callers should run the full gate instead:

    python -m sparkucx_tpu.analysis --ci      # all six passes

Usage: python scripts/lint_private_access.py  (exit 1 on violations)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkucx_tpu.analysis.__main__ import main

if __name__ == "__main__":
    sys.exit(main(["--ci", "--passes", "private-access,required-surface"]))
