#!/usr/bin/env python
"""Lint: no cross-object private access inside sparkucx_tpu/.

Flags ``expr._name`` attribute access where ``expr`` is not ``self``/``cls``
(reaching into another object's internals rots — VERDICT round-1 weak item 6),
and ``from module import _name`` of private names across modules.  Allowed:
``self._x``, ``cls._x``, dunders, and ``_``-prefixed locals/params themselves.

Usage: python scripts/lint_private_access.py  (exit 1 on violations)
"""

import ast
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "sparkucx_tpu")

#: reviewed exceptions: (file suffix, attribute or imported name).
#: hbm_store.py: MapWriter is a friend class defined in the SAME file as
#: HbmBlockStore — allocation and epoch rollover must happen under the store's
#: one lock, and exposing that lock publicly would invite misuse from outside
#: the file.  Reviewed round 3; keep this list to same-file friends only.
#: core/block.py: ``np.memmap`` exposes no public way to close its mapping —
#: ``mm._mmap.close()`` is the canonical numpy idiom for releasing the fd
#: eagerly (numpy/numpy#13510); guarded by try/except for numpy internals
#: moving.
ALLOWLIST = {
    ("store/hbm_store.py", "._lock"),
    ("store/hbm_store.py", "._rollover"),  # also covers ._rollover_device
    ("core/block.py", "._mmap"),
}

#: Public-surface contract: these classes must keep these methods.  Transports,
#: writers, and the perf harness are wired to them by name across layers, and
#: the device-staging path (ISSUE 2) made several of them load-bearing surface
#: — a rename here fails the lint before it fails at runtime in another layer.
REQUIRED_SURFACE = {
    "store/hbm_store.py": {
        "HbmBlockStore": [
            "seal", "map_writer", "read_block", "block_staging_view",
            "region_bytes", "num_rounds", "host_staging_allocated",
        ],
        "MapWriter": ["write_partition", "write_partition_device", "commit"],
    },
    "shuffle/writer.py": {
        "DeviceMapWriter": ["write_partition", "commit"],
        "TpuShuffleMapOutputWriter": [
            "get_partition_writer", "write_partition_device", "commit_all_partitions",
        ],
    },
}


def check_surface(path: str, rel: str) -> list:
    """Assert the REQUIRED_SURFACE methods still exist (AST, no import)."""
    want = None
    for sfx, classes in REQUIRED_SURFACE.items():
        if rel.endswith(sfx):
            want = classes
    if want is None:
        return []
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    methods = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods[node.name] = {
                n.name
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
    out = []
    for cls, names in want.items():
        have = methods.get(cls)
        if have is None:
            out.append((1, f"required public surface: class {cls} missing"))
            continue
        for name in names:
            if name not in have:
                out.append((1, f"required public surface: {cls}.{name} missing"))
    return out


def check_file(path: str) -> list:
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            name = node.attr
            if not name.startswith("_") or name.startswith("__"):
                continue
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                continue
            # self.x._y is still private access on x's internals — flag unless
            # the full chain starts at self AND the private attr is on self
            out.append((node.lineno, f"private attribute access: .{name}"))
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name.startswith("_") and not alias.name.startswith("__"):
                    out.append((node.lineno, f"private import: {alias.name} from {node.module}"))
    return out


def main() -> int:
    failures = 0
    for dirpath, _dirs, files in os.walk(ROOT):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, os.path.dirname(ROOT))
            for lineno, msg in check_file(path):
                if any(rel.endswith(sfx) and key in msg for sfx, key in ALLOWLIST):
                    continue
                print(f"{rel}:{lineno}: {msg}")
                failures += 1
            for lineno, msg in check_surface(path, rel):
                print(f"{rel}:{lineno}: {msg}")
                failures += 1
    if failures:
        print(f"\n{failures} cross-module private access violation(s)", file=sys.stderr)
        return 1
    print("private-access lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
