#!/usr/bin/env python
"""Generate the golden wire-format fixtures for the JVM shim protocol.

Each fixture is the exact request frame the Java DaemonClient puts on the wire
(jvm/src/.../DaemonClient.java header builders; frame layout
docs/SHIM_PROTOCOL.md).  Three parties assert against these bytes:

* ``jvm/src/.../FixtureCheck.java`` re-encodes every frame with the Java
  builders and compares (run by CI after javac);
* ``tests/test_daemon.py`` regenerates them here (drift guard) and feeds the
  raw bytes to a live daemon (decode interop);
* a human diffing a protocol change sees exactly which bytes moved.

Java's String.format JSON headers and Python's ``json.dumps`` agree
byte-for-byte (same key order, ", "/": " separators) — that equality is the
drift guard's whole point.

Usage: python scripts/gen_shim_fixtures.py [--check]
"""

import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkucx_tpu.core.definitions import AmId, MAX_FRAME_BYTES  # noqa: E402
from sparkucx_tpu.shuffle.daemon import DaemonOp, _frame  # noqa: E402

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "jvm", "fixtures")

# Canonical parameters — FixtureCheck.java uses the same literals.
SHUFFLE_ID, NUM_MAPPERS, NUM_REDUCERS = 7, 4, 8
MAP_ID, WRITER, REDUCE_ID = 2, 3, 5
FETCH_TAG = 0x1122334455667788
FETCH_MAPS, FETCH_REDUCES = (0, 3), (5, 5)
WRITE_BODY = bytes(range(256))


#: 08: a Spark-3.x AQE partial-map read (startMapIndex=1, endMapIndex=3 over
#: one reduce partition).  Spark 2.4 (no AQE) always reads the full map range;
#: both generations land on the SAME wire shape — explicit (shuffle, mapIndex,
#: reduce) triples, the client enumerating its range — so the fixture pins
#: that the protocol is compat-generation-agnostic (jvm/README.md, "Spark 2.4
#: vs 3.x").
AQE_MAPS, AQE_REDUCES = (1, 2), (REDUCE_ID, REDUCE_ID)

#: 09: an AQE COALESCED read — one reducer task reading a coalesced range of
#: reduce partitions (5..6) across EVERY mapper (0..3), the
#: ShufflePartitionSpec shape AQE emits after coalescing small partitions.
#: Some of these (map, reduce) cells are legitimately empty in the behavioral
#: replay (tests/test_daemon.py) — the daemon must answer size 0, never -1.
COALESCE_MAPS = tuple(m for m in range(NUM_MAPPERS) for _ in (5, 6))
COALESCE_REDUCES = tuple(r for _ in range(NUM_MAPPERS) for r in (5, 6))

#: 10: an OVERSIZED frame header — op WritePartition claiming a body one byte
#: past MAX_FRAME_BYTES.  Negative fixture: both sides must REFUSE it
#: (FixtureCheck.java asserts the Java limit matches and rejects; the daemon
#: drops the connection and keeps serving — tests/test_daemon.py).
OVERSIZED_HEADER = struct.pack(
    "<IQQ", DaemonOp.WRITE_PARTITION, 0, MAX_FRAME_BYTES + 1
)


def fetch_frame(maps=FETCH_MAPS, reduces=FETCH_REDUCES) -> bytes:
    body = struct.pack("<QI", FETCH_TAG, len(maps))
    for m, r in zip(maps, reduces):
        body += struct.pack("<iii", SHUFFLE_ID, m, r)
    return struct.pack("<IQQ", int(AmId.FETCH_BLOCK_REQ), 0, len(body)) + body


def fixtures() -> dict:
    return {
        "01_create_shuffle.bin": _frame(
            DaemonOp.CREATE_SHUFFLE,
            {"shuffle_id": SHUFFLE_ID, "num_mappers": NUM_MAPPERS, "num_reducers": NUM_REDUCERS},
        ),
        "02_open_map_writer.bin": _frame(
            DaemonOp.OPEN_MAP_WRITER, {"shuffle_id": SHUFFLE_ID, "map_id": MAP_ID}
        ),
        "03_write_partition.bin": _frame(
            DaemonOp.WRITE_PARTITION, {"writer": WRITER, "reduce_id": REDUCE_ID}, WRITE_BODY
        ),
        "04_commit_map.bin": _frame(DaemonOp.COMMIT_MAP, {"writer": WRITER}),
        "05_run_exchange.bin": _frame(DaemonOp.RUN_EXCHANGE, {"shuffle_id": SHUFFLE_ID}),
        "06_fetch.bin": fetch_frame(),
        "07_remove_shuffle.bin": _frame(DaemonOp.REMOVE_SHUFFLE, {"shuffle_id": SHUFFLE_ID}),
        "08_fetch_aqe_maprange.bin": fetch_frame(AQE_MAPS, AQE_REDUCES),
        "09_fetch_coalesced_empty.bin": fetch_frame(COALESCE_MAPS, COALESCE_REDUCES),
        "10_oversized_frame.bin": OVERSIZED_HEADER,
    }


def main() -> int:
    check = "--check" in sys.argv
    os.makedirs(FIXTURE_DIR, exist_ok=True)
    ok = True
    for name, frame in fixtures().items():
        path = os.path.join(FIXTURE_DIR, name)
        if check:
            with open(path, "rb") as f:
                if f.read() != frame:
                    print(f"DRIFT: {name}", file=sys.stderr)
                    ok = False
        else:
            with open(path, "wb") as f:
                f.write(frame)
            print(f"wrote {path} ({len(frame)} B)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
