#!/usr/bin/env bash
# Integration harness — the buildlib/test.sh analogue.
#
# The reference boots a standalone Spark cluster and runs GroupByTest twice
# (small + big) plus SparkTC as the gate (test.sh:163-196).  Here the same
# gate shape runs against this framework's real process topology: a shuffle
# daemon + separate mapper/reducer processes over the wire protocol.
#
# Env knobs (test.sh style): EXECUTORS, MAPPERS, REDUCERS, PAIRS_PER_MAP.
set -euo pipefail
cd "$(dirname "$0")/.."

# Force the portable CPU mesh regardless of any backend the ambient env pins
# (set SPARKUCX_INTEG_PLATFORM to run against real hardware).
export JAX_PLATFORMS="${SPARKUCX_INTEG_PLATFORM:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

run_groupby_test() {  # test.sh:163-167 (GroupByTest 100 100)
  EXECUTORS=2 MAPPERS=4 REDUCERS=8 PAIRS_PER_MAP=5000 \
    python scripts/integration_groupby.py
}

run_big_test() {      # test.sh:169-173 (GroupByTest 200 5000 ...)
  EXECUTORS=4 MAPPERS=16 REDUCERS=32 PAIRS_PER_MAP=20000 \
    python scripts/integration_groupby.py
}

run_tc_test() {       # test.sh:175-179 (SparkTC; gate at :196)
  EXECUTORS=4 VERTICES=100 EDGES=200 python scripts/integration_tc.py
}

echo "== groupby test =="
run_groupby_test
echo "== big test =="
run_big_test
echo "== tc test =="
run_tc_test
echo "ALL INTEGRATION TESTS PASSED"
