#!/usr/bin/env bash
# Integration harness — the buildlib/test.sh analogue.
#
# The reference boots a standalone Spark cluster and runs GroupByTest twice
# (small + big) plus SparkTC as the gate (test.sh:163-196).  Here the same
# gate shape runs against this framework's real process topology: a shuffle
# daemon + separate mapper/reducer processes over the wire protocol — plus
# the BASELINE.json configs[0] 1M-row GroupByTest and a 1M-row TeraSort at
# stated scale, the private-access layering lint, and (when a JDK is on the
# PATH) the JVM shim compile + fixture + interop checks from ci.yml.
#
# Env knobs (test.sh style): EXECUTORS, MAPPERS, REDUCERS, PAIRS_PER_MAP, ROWS.
set -euo pipefail
cd "$(dirname "$0")/.."

# Force the portable CPU mesh regardless of any backend the ambient env pins
# (set SPARKUCX_INTEG_PLATFORM to run against real hardware).
export JAX_PLATFORMS="${SPARKUCX_INTEG_PLATFORM:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

run_lint() {          # layering gate (VERDICT r2 item 2)
  python scripts/lint_private_access.py
}

run_groupby_test() {  # test.sh:163-167 (GroupByTest 100 100)
  EXECUTORS=2 MAPPERS=4 REDUCERS=8 PAIRS_PER_MAP=5000 \
    python scripts/integration_groupby.py
}

run_big_test() {      # test.sh:169-173 (GroupByTest 200 5000 ...)
  EXECUTORS=4 MAPPERS=16 REDUCERS=32 PAIRS_PER_MAP=20000 \
    python scripts/integration_groupby.py
}

run_baseline_test() { # BASELINE.json configs[0]: 1M-row GroupByTest
  EXECUTORS=4 MAPPERS=16 REDUCERS=32 PAIRS_PER_MAP=62500 \
    python scripts/integration_groupby.py
}

run_terasort_test() { # BASELINE.json configs[1] shape at 1M rows
  EXECUTORS=4 MAPPERS=8 REDUCERS=16 ROWS=1000000 \
    python scripts/integration_terasort.py
}

run_tc_test() {       # test.sh:175-179 (SparkTC; gate at :196)
  EXECUTORS=4 VERTICES=100 EDGES=200 python scripts/integration_tc.py
}

run_tpch_test() {     # BASELINE.json configs[2]: TPC-H q18 as a 2-stage,
                      # 3-shuffle daemon job (SQL through the L7 surface)
  EXECUTORS=2 MAPPERS=4 REDUCERS=8 ROWS=200000 ORDERS=10000 \
    python scripts/integration_tpch.py
}

run_fault_test() {    # OS-process fault injection: mapper SIGKILL mid-write
  FAULTS=1 EXECUTORS=2 MAPPERS=4 REDUCERS=8 PAIRS_PER_MAP=5000 \
    python scripts/integration_groupby.py   # + reducer SIGKILL mid-fetch
}

run_jvm_shim_check() { # ci.yml jvm-shim job, runnable anywhere a JDK exists
  if ! command -v javac >/dev/null 2>&1; then
    echo "JVM SHIM CHECK: javac SKIPPED (no javac on PATH, none installable —"
    echo "  provisioning attempts + errors recorded in jvm/README.md)"
    echo "-- jvm shim: stub-fidelity lint (the no-JDK compile surrogate)"
    python scripts/check_stub_fidelity.py
    echo "-- jvm shim: fixture generator drift (Python side)"
    python scripts/gen_shim_fixtures.py --check
    return 0
  fi
  echo "-- jvm shim: compile against vendored SPI stubs"
  rm -rf jvm/target
  mkdir -p jvm/target/classes jvm/target/stub-classes
  javac -d jvm/target/stub-classes $(find jvm/stubs -name '*.java')
  javac -cp jvm/target/stub-classes -d jvm/target/classes \
    $(find jvm/src -name '*.java')
  echo "-- jvm shim: compile the Spark 2.4-signature leg (stubs24 shadows)"
  mkdir -p jvm/target/classes24 jvm/target/stub24-classes
  javac -cp jvm/target/stub-classes -d jvm/target/stub24-classes \
    $(find jvm/stubs24 -name '*.java')
  javac -cp jvm/target/stub24-classes:jvm/target/stub-classes:jvm/target/classes \
    -d jvm/target/classes24 $(find jvm/src24 -name '*.java')
  echo "-- jvm shim: golden wire fixtures (Java side)"
  java -cp jvm/target/classes:jvm/target/stub-classes \
    org.apache.spark.shuffle.tpu.FixtureCheck jvm/fixtures
  echo "-- jvm shim: fixture generator drift (Python side)"
  python scripts/gen_shim_fixtures.py --check
  echo "-- jvm shim: live Java<->Python interop cycle"
  python -m sparkucx_tpu.shuffle.daemon --port 13438 &
  local daemon_pid=$!
  trap "kill $daemon_pid 2>/dev/null || true" RETURN
  for _ in $(seq 1 50); do
    python -c "import socket; socket.create_connection(('127.0.0.1', 13438), 1)" \
      2>/dev/null && break
    sleep 0.2
  done
  java -cp jvm/target/classes:jvm/target/stub-classes \
    org.apache.spark.shuffle.tpu.InteropCheck 127.0.0.1 13438
  kill $daemon_pid 2>/dev/null || true
}

echo "== private-access lint =="
run_lint
echo "== groupby test =="
run_groupby_test
echo "== big test =="
run_big_test
echo "== baseline test (1M records) =="
run_baseline_test
echo "== terasort test (1M rows) =="
run_terasort_test
echo "== tc test =="
run_tc_test
echo "== tpch q18 test (2 stages, 3 shuffles) =="
run_tpch_test
echo "== fault-injection test =="
run_fault_test
echo "== jvm shim check =="
run_jvm_shim_check
echo "ALL INTEGRATION TESTS PASSED"
