#!/usr/bin/env python
"""Hardware acceptance smoke: every device-resident op vs its oracle, one command.

The reference validates hardware with live-cluster Spark jobs (buildlib/
test.sh); this is the TPU-native equivalent for a single chip (or any backend):
small-shape oracle drives of the exchange, the Pallas gather, the distributed
sort, the columnar shuffle, the hierarchical route, the full store →
commit → exchange → fetch stack, the relational operators (GROUP BY + hash
join), and the transitive closure.  Exit 0 = every drive passed.

Run on the real chip (default) or any backend:

    python scripts/tpu_smoke.py              # whatever jax.devices() offers
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/tpu_smoke.py          # the CI form (dense lowerings)

Each drive prints ``ok: <name> [impl=...] (<seconds>)``; failures raise with
the op's own diagnostics.  Kept fast (~2-4 min incl. first-compile on a
tunnelled chip) so it can gate deployments.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _drive(name):
    def deco(fn):
        fn._drive_name = name
        return fn
    return deco


@_drive("exchange vs oracle")
def drive_exchange():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import (
        ExchangeSpec, build_exchange, make_mesh, oracle_exchange,
        pack_chunks_slots, unpack_received,
    )

    n = min(4, len(jax.devices()))
    slot = 64
    spec = ExchangeSpec(num_executors=n, send_rows=n * slot, recv_rows=n * slot)
    mesh = make_mesh(n)
    fn = build_exchange(mesh, spec)
    rng = np.random.default_rng(0)
    per_dev = [
        [rng.integers(0, 256, size=int(rng.integers(0, slot * 256)), dtype=np.uint8).tobytes()
         for _ in range(n)]
        for _ in range(n)
    ]
    bufs, sizes = zip(*[
        pack_chunks_slots(chunks, slot, spec.row_bytes) for chunks in per_dev
    ])
    sh = NamedSharding(mesh, P("ex", None))
    recv, rs = fn(
        jax.device_put(np.concatenate(bufs), sh),
        jax.device_put(np.stack(sizes), sh),
    )
    recv_h = np.asarray(recv).reshape(n, -1)
    rs_h = np.asarray(rs)
    # the shared oracle concatenates raw chunks; the wire carries each chunk
    # row-padded, so compare per-sender chunks with padding stripped
    expect = oracle_exchange(per_dev)
    for j in range(n):
        parts = unpack_received(recv_h[j].view(np.uint8).tobytes(), rs_h[j], spec.row_bytes)
        got = b"".join(
            part[: len(chunk)] for part, chunk in
            zip(parts, (per_dev[i][j] for i in range(n)))
        )
        assert got == expect[j], f"receiver {j} diverged from oracle"
    return fn.spec.impl


@_drive("block gather vs oracle")
def drive_gather():
    import jax

    from sparkucx_tpu.ops.pallas_kernels import build_block_gather, pack_plan

    rng = np.random.default_rng(1)
    src = jax.device_put(rng.integers(-100, 100, size=(4096, 128), dtype=np.int32))
    plan = [(0, 512), (1536, 2048), (1024, 100), (3584, 512 * 97)]
    starts, counts, outs, total = pack_plan(plan, 512)
    fn = build_block_gather(len(plan), total)
    out = np.asarray(fn(*(jax.device_put(a) for a in (starts, counts, outs)), src))
    src_h = np.asarray(src)
    for (off, ln), s, c, o in zip(plan, starts, counts, outs):
        assert (out[o : o + c] == src_h[s : s + c]).all(), f"block at {off} diverged"
    return fn.impl


@_drive("distributed sort vs oracle")
def drive_sort():
    import jax

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort

    n = min(4, len(jax.devices()))
    cap = 512
    spec = SortSpec(num_executors=n, capacity=cap,
                    recv_capacity=cap if n == 1 else 2 * cap, width=24)
    rng = np.random.default_rng(2)
    total = n * cap - 13
    keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint64).astype(np.uint32)
    payload = rng.integers(-100, 100, size=(total, 24)).astype(np.int32)
    sk, sp = run_distributed_sort(make_mesh(n), spec, keys, payload)
    ek, ep = oracle_sort(keys, payload)
    assert (sk == ek).all() and (sp == ep).all(), "sort diverged from oracle"
    return spec.resolve_impl().impl


@_drive("columnar shuffle vs oracle")
def drive_columnar():
    import jax

    from sparkucx_tpu.ops.columnar import ColumnarSpec, run_columnar_shuffle
    from sparkucx_tpu.ops.exchange import make_mesh

    n = min(4, len(jax.devices()))
    cap = 256
    spec = ColumnarSpec(num_executors=n, capacity=cap,
                        recv_capacity=cap if n == 1 else 2 * cap, width=8)
    rng = np.random.default_rng(3)
    rows = rng.normal(size=(n * cap, 8)).astype(np.float32)
    owners = rng.integers(0, n, size=n * cap).astype(np.int32)
    mesh = make_mesh(n)
    recv, counts = run_columnar_shuffle(mesh, spec, rows, owners)
    counts_h = np.asarray(counts)
    assert int(counts_h.sum()) == n * cap, "columnar shuffle dropped rows"
    # every destination's shard holds exactly its rows (as a multiset)
    recv_h = np.asarray(recv).reshape(n, -1, 8)
    for j in range(n):
        mine = rows[owners == j]
        got = recv_h[j][: len(mine)]
        assert sorted(map(tuple, got.tolist())) == sorted(map(tuple, mine.tolist())), (
            f"destination {j} row multiset diverged"
        )
    return spec.resolve_impl().impl


@_drive("full store stack (stage→commit→exchange→fetch, incl. device batch fetch)")
def drive_stack():
    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
    from sparkucx_tpu.core.operation import OperationStatus
    from sparkucx_tpu.transport.tpu import TpuShuffleCluster

    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20, num_executors=1,
        keep_device_recv=True,  # so the device-side batch fetch can run
    )
    cluster = TpuShuffleCluster(conf, num_executors=1)
    M, R = 4, 8
    meta = cluster.create_shuffle(0, M, R)
    rng = np.random.default_rng(4)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=int(rng.integers(1, 2000)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(0)
    t = cluster.transport(0)
    for (m, r), expect in oracle.items():
        buf = MemoryBlock(np.zeros(4096, dtype=np.uint8), size=4096)
        [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(0, m, r)], [buf], [None])
        res = req.wait(30)
        assert res.status == OperationStatus.SUCCESS, str(res.error)
        assert buf.host_view()[: buf.size].tobytes() == expect, f"fetch ({m},{r}) diverged"
    # device-side batch fetch: the Pallas/XLA gather through the transport
    bids = [ShuffleBlockId(0, m, 0) for m in range(M)]
    packed, entries = t.fetch_blocks_device(bids)
    packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
    for (row_start, length), bid in zip(entries, bids):
        start = int(row_start) * cluster.row_bytes
        got = packed_bytes[start : start + int(length)].tobytes()
        assert got == oracle[(bid.map_id, bid.reduce_id)], f"device fetch {bid} diverged"
    cluster.remove_shuffle(0)
    return "auto"


@_drive("hierarchical 2-slice route vs oracle")
def drive_hierarchy():
    import jax
    from jax.sharding import Mesh

    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.transport.tpu import TpuShuffleCluster

    devs = jax.devices()
    if len(devs) < 4 or len(devs) % 2:
        return "skipped (needs >=4 even devices; single-chip backends exercise the flat route)"
    n = min(8, len(devs) - len(devs) % 2)
    mesh = Mesh(np.array(devs[:n]), ("ex",))
    conf = TpuShuffleConf(
        staging_capacity_per_executor=n * 4096, num_executors=n, num_slices=2
    )
    cluster = TpuShuffleCluster(conf, mesh=mesh)
    meta = cluster.create_shuffle(0, n, n)
    rng = np.random.default_rng(5)
    oracle = {}
    for m in range(n):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(n):
            payload = rng.integers(0, 256, size=int(rng.integers(1, 300)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(0)
    for (m, r), expect in oracle.items():
        view, ln = cluster.locate_received_block(meta.owner_of_reduce(r), 0, m, r)
        assert view.tobytes() == expect, f"hierarchical block ({m},{r}) diverged"
    cluster.remove_shuffle(0)
    return "two-phase"


@_drive("grouped aggregate + hash join vs oracle")
def drive_relational():
    import jax

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        AggregateSpec,
        oracle_aggregate,
        oracle_join,
        run_grouped_aggregate,
        run_hash_join,
    )

    n = min(4, len(jax.devices()))
    mesh = make_mesh(n)
    rng = np.random.default_rng(21)
    total = 6000
    keys = rng.integers(0, 64, size=total).astype(np.uint32)
    values = rng.integers(-1000, 1000, size=(total, 2)).astype(np.int32)
    spec = AggregateSpec(
        num_executors=n, capacity=-(-total // n), recv_capacity=4 * -(-total // n),
        aggs=("sum", "max"),
    )
    gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values)
    wk, wv, wc = oracle_aggregate(keys, values, spec.aggs)
    assert np.array_equal(gk, wk) and np.array_equal(gv, wv) and np.array_equal(gc, wc)

    # PK-FK join through the capacity-planning host driver (raises its own
    # precise diagnostics if the device placement diverges from the host plan)
    nb, nprobe = 512, 2048
    bkeys = rng.permutation(nb).astype(np.uint32)
    pkeys = bkeys[rng.integers(0, nb, size=nprobe)]
    bvals = rng.integers(-50, 50, size=(nb, 1)).astype(np.int32)
    pvals = rng.integers(-50, 50, size=(nprobe, 1)).astype(np.int32)
    jk, jb, jp = run_hash_join(mesh, bkeys, bvals, pkeys, pvals)
    got = sorted(zip(jk.tolist(), jb[:, 0].tolist(), jp[:, 0].tolist()))
    wk_, wb, wp = oracle_join(bkeys, bvals, pkeys, pvals)
    want = sorted(zip(wk_.tolist(), wb[:, 0].tolist(), wp[:, 0].tolist()))
    assert got == want, f"join rows diverged ({len(got)} vs {len(want)})"
    return spec.resolve_impl(mesh.devices.reshape(-1)[0].platform).impl


@_drive("transitive closure vs oracle")
def drive_tc():
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.tc import TcSpec, oracle_tc, run_transitive_closure

    import jax

    n = min(4, len(jax.devices()))
    mesh = make_mesh(n)
    rng = np.random.default_rng(22)
    edges = rng.integers(0, 48, size=(120, 2)).astype(np.uint32)
    want = oracle_tc(edges)
    cap = max(4096 // n, 512)
    spec = TcSpec(num_executors=n, edge_capacity=cap, tc_capacity=cap, join_capacity=4 * cap)
    pairs, rounds = run_transitive_closure(mesh, spec, edges)
    # the driver's contract is ascending-unique — compare directly, no
    # np.unique laundering of a dedup/order regression
    assert np.array_equal(pairs, want), "closure pairs diverged"
    return spec.resolve_impl(mesh.devices.reshape(-1)[0].platform).impl


DRIVES = [
    drive_exchange, drive_gather, drive_sort, drive_columnar, drive_stack,
    drive_hierarchy, drive_relational, drive_tc,
]


def main() -> int:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    import jax

    devs = jax.devices()
    print(f"backend: {devs[0].platform} x {len(devs)} ({devs[0].device_kind})", flush=True)
    failed = 0
    for drive in DRIVES:
        t0 = time.time()
        try:
            impl = drive()
            print(f"ok: {drive._drive_name} [impl={impl}] ({time.time() - t0:.1f}s)", flush=True)
        except Exception as e:
            failed += 1
            print(f"FAIL: {drive._drive_name}: {type(e).__name__}: {e}", flush=True)
    if failed:
        print(f"SMOKE: {failed}/{len(DRIVES)} drives FAILED")
        return 1
    print(f"SMOKE: all {len(DRIVES)} drives passed")  # skipped drives say so in their impl tag
    return 0


if __name__ == "__main__":
    sys.exit(main())
