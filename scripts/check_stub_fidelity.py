#!/usr/bin/env python
"""Stub-fidelity lint for the JVM shim — the no-JDK compile surrogate.

No JDK is installable on this harness (zero egress; see jvm/README.md for the
attempted provisioning commands and their errors), so ``javac`` cannot verify
that ``jvm/src`` and the vendored compile-only SPI stubs in ``jvm/stubs``
agree.  This script closes the gap the cheap way a linter can: it parses both
trees with a small Java-surface parser and asserts the contracts a compile
would enforce at the shim<->stub boundary:

1. every ``org.apache.spark.*`` / ``scala.*`` import in a shim source resolves
   to a stub file (nothing the shim needs is missing from ``jvm/stubs``);
2. every stub file declares the type its path promises (package dir == package
   statement, file name == type name) — the layout javac requires;
3. every shim class that ``implements``/``extends`` a stub type implements
   every abstract method of that stub, at matching arity (the "typo'd an SPI
   override" failure class — with real spark-core on the classpath this is a
   compile error);
4. every method the shim invokes on a receiver whose static type resolves to a
   stub type exists in that stub, at a matching arity (one level of call-chain
   return-type resolution included, e.g. ``dependency.rdd().getNumPartitions()``);
5. every constructor call ``new StubType(...)`` matches a declared (or
   implicit default) constructor arity.

This is NOT a javac replacement: receivers whose type cannot be resolved
statically (JDK types, locals of shim-declared types) are simply not checked.
It IS enough to catch every way the shim and the stubs can silently drift
apart — which is the risk a never-compiled source tree actually carries.

Exit 0 = all checks pass.  Run by scripts/run_integration.sh next to the
(skipped) javac gate.
"""

from __future__ import annotations

import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB_DIR = os.path.join(ROOT, "jvm", "stubs")
SRC_DIR = os.path.join(ROOT, "jvm", "src")
#: the Spark 2.4 compile leg: src24 shim sources checked against the shared
#: stubs with stubs24 OVERRIDING same-named types (the 2.4-signature
#: ShuffleManager) — mirrors the classpath order of the javac legs in
#: run_integration.sh / ci.yml
STUB24_DIR = os.path.join(ROOT, "jvm", "stubs24")
SRC24_DIR = os.path.join(ROOT, "jvm", "src24")

_KEYWORDS = {
    "if", "for", "while", "switch", "catch", "return", "new", "throw",
    "synchronized", "else", "do", "try", "assert", "super", "this",
}

_METHOD_RE = re.compile(
    r"(?:^|\n)\s*"
    r"(?P<mods>(?:(?:public|protected|private|static|final|abstract|default|synchronized|native|@\w+)\s+)*)"
    r"(?:<[^<>]*(?:<[^<>]*>)?[^<>]*>\s+)?"            # leading generic params
    r"(?P<ret>[\w$.]+(?:<[^()]*?>)?(?:\[\])*)\s+"     # return type
    r"(?P<name>[a-zA-Z_$][\w$]*)\s*"
    r"\((?P<params>[^()]*)\)"
)

_CTOR_RE = re.compile(
    r"(?:^|\n)\s*(?:(?:public|protected|private)\s+)?"
    r"(?P<name>[A-Z][\w$]*)\s*\((?P<params>[^()]*)\)\s*(?:throws [\w.,\s]+)?\{"
)


def _strip_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    src = re.sub(r"//[^\n]*", " ", src)
    src = re.sub(r'"(?:\\.|[^"\\])*"', '""', src)  # string literals hide parens
    return src


def _param_arity(params: str) -> Tuple[int, bool]:
    """(count, is_varargs) of a parameter list (generics flattened upstream)."""
    p = params.strip()
    if not p:
        return 0, False
    # flatten generic commas: <K, V> inside a param type is not a separator
    depth, count = 0, 1
    for ch in p:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        elif ch == "," and depth == 0:
            count += 1
    return count, "..." in p


@dataclass
class JavaType:
    name: str                      # simple name
    package: str
    kind: str                      # class | interface | enum
    methods: Dict[str, List[Tuple[int, bool, str]]] = field(default_factory=dict)
    #                 name -> [(arity, varargs, return_simple_type)]
    abstract_methods: Dict[str, List[int]] = field(default_factory=dict)
    ctor_arities: List[Tuple[int, bool]] = field(default_factory=list)
    extends: List[str] = field(default_factory=list)  # simple names


def parse_java(path: str) -> List[JavaType]:
    with open(path) as f:
        src = _strip_comments(f.read())
    pkg_m = re.search(r"\bpackage\s+([\w.]+)\s*;", src)
    package = pkg_m.group(1) if pkg_m else ""
    out: List[JavaType] = []
    for m in re.finditer(
        r"\b(?P<kind>class|interface|enum)\s+(?P<name>[\w$]+)"
        r"(?:<[^<>{]*>)?\s*(?P<heritage>[^{]*)\{",
        src,
    ):
        t = JavaType(m.group("name"), package, m.group("kind"))
        heritage = m.group("heritage")
        for h in re.findall(r"\b(?:extends|implements)\s+([\w.<>,\s$]+)", heritage):
            for sup in re.split(r",(?![^<]*>)", h):
                sup = re.sub(r"<[^>]*>", "", sup).strip().split(".")[-1]
                if sup:
                    t.extends.append(sup)
        out.append(t)
    if not out:
        return out
    # methods/ctors are attributed file-wide: good enough for the flat stub
    # files and for the shim (inner classes share the outer file's check scope)
    primary = out[0]
    is_interface = primary.kind == "interface"
    for mm in _METHOD_RE.finditer(src):
        name = mm.group("name")
        ret = re.sub(r"<[^>]*>", "", mm.group("ret")).split(".")[-1].replace("[]", "")
        if name in _KEYWORDS or ret in _KEYWORDS or ret in ("", "package"):
            continue
        arity, varargs = _param_arity(mm.group("params"))
        for t in out:
            t.methods.setdefault(name, []).append((arity, varargs, ret))
        mods = mm.group("mods")
        body_starts = src[mm.end():mm.end() + 3].lstrip()[:1]
        if (is_interface and "default" not in mods and "static" not in mods) or (
            "abstract" in mods
        ):
            if body_starts != "{":
                for t in out:
                    t.abstract_methods.setdefault(name, []).append(arity)
    for cm in _CTOR_RE.finditer(src):
        for t in out:
            if cm.group("name") == t.name:
                t.ctor_arities.append(_param_arity(cm.group("params")))
    return out


def load_stubs(stub_dir: Optional[str] = None) -> Dict[str, JavaType]:
    # resolve the default at CALL time: tests retarget the module globals
    # at alternate trees (tests/test_stub_fidelity.py run_on)
    stub_dir = stub_dir or STUB_DIR
    stubs: Dict[str, JavaType] = {}
    errors: List[str] = []
    for dirpath, _, files in os.walk(stub_dir):
        for fn in files:
            if not fn.endswith(".java"):
                continue
            path = os.path.join(dirpath, fn)
            types = parse_java(path)
            expect_pkg = os.path.relpath(dirpath, stub_dir).replace(os.sep, ".")
            expect_name = fn[:-5]
            if not types:
                errors.append(f"{path}: no type declaration found")
                continue
            # check 2: path <-> declaration agreement
            if types[0].package != expect_pkg:
                errors.append(
                    f"{path}: package {types[0].package!r} != directory {expect_pkg!r}"
                )
            declared = {t.name for t in types}
            if expect_name not in declared:
                errors.append(f"{path}: declares {declared}, file promises {expect_name}")
            for t in types:
                stubs[t.name] = t
    if errors:
        for e in errors:
            print(f"FIDELITY: {e}")
        sys.exit(1)
    return stubs


# -- shim-side checks --------------------------------------------------------


def _collect_var_types(src: str, known: Set[str]) -> Dict[str, str]:
    """Map identifier -> simple stub type from declarations, params, casts."""
    vars_: Dict[str, str] = {}
    # declarations & params: Type name  (generics stripped), incl. `Type name =`
    for m in re.finditer(
        r"\b([A-Z][\w$]*)(?:<[^<>;(){}]*>)?(?:\[\])?\s+([a-z_$][\w$]*)\s*[=;,)\:]",
        src,
    ):
        if m.group(1) in known:
            vars_.setdefault(m.group(2), m.group(1))
    # casts assigned: `X x = (Type) expr`
    for m in re.finditer(r"([a-z_$][\w$]*)\s*=\s*\(\s*([A-Z][\w$]*)[^)]*\)", src):
        if m.group(2) in known:
            vars_.setdefault(m.group(1), m.group(2))
    return vars_


def _resolve_method(
    stubs: Dict[str, JavaType], type_name: str, meth: str
) -> Optional[List[Tuple[int, bool, str]]]:
    """Find ``meth`` on ``type_name`` or its stub supertypes."""
    seen: Set[str] = set()
    frontier = [type_name]
    while frontier:
        tn = frontier.pop()
        if tn in seen or tn not in stubs:
            continue
        seen.add(tn)
        t = stubs[tn]
        if meth in t.methods:
            return t.methods[meth]
        frontier.extend(t.extends)
    return None


def _call_arity(src: str, open_paren: int) -> int:
    """Arity of the call whose '(' is at ``open_paren`` (paren matching)."""
    depth, count, any_arg = 0, 1, False
    for i in range(open_paren, min(len(src), open_paren + 2000)):
        ch = src[i]
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
            if depth == 0:
                return count if any_arg else 0
        elif ch == "," and depth == 1:
            count += 1
        elif not ch.isspace() and depth >= 1:
            any_arg = True
    return count if any_arg else 0


def check_shim_file(path: str, stubs: Dict[str, JavaType]) -> List[str]:
    errors: List[str] = []
    with open(path) as f:
        raw = f.read()
    src = _strip_comments(raw)

    # check 1: spark/scala imports must resolve to stubs
    for m in re.finditer(r"\bimport\s+((?:org\.apache\.spark|scala)\.[\w.]+)\s*;", src):
        fqcn = m.group(1)
        if fqcn.startswith("org.apache.spark.shuffle.tpu."):
            continue  # the shim's own package
        simple = fqcn.split(".")[-1]
        if simple not in stubs:
            errors.append(f"{path}: import {fqcn} has no stub")
        elif stubs[simple].package != fqcn.rsplit(".", 1)[0]:
            errors.append(
                f"{path}: import {fqcn} resolves to stub in package "
                f"{stubs[simple].package}"
            )

    shim_types = parse_java(path)
    shim_methods: Set[str] = set()
    for t in shim_types:
        shim_methods.update(t.methods)

    # check 3: SPI implementation completeness
    for t in shim_types:
        for sup in t.extends:
            if sup not in stubs:
                continue
            for meth, arities in stubs[sup].abstract_methods.items():
                impl = t.methods.get(meth)
                if impl is None:
                    errors.append(
                        f"{path}: {t.name} implements {sup} but lacks {meth}()"
                    )
                    continue
                impl_ar = {a for a, _, _ in impl}
                if not any(a in impl_ar for a in arities):
                    errors.append(
                        f"{path}: {t.name}.{meth} arity {sorted(impl_ar)} does not "
                        f"match {sup}.{meth} arity {sorted(set(arities))}"
                    )

    var_types = _collect_var_types(src, set(stubs))

    # check 4: resolved receiver calls, with one chain hop
    for m in re.finditer(r"\b([\w$]+)\s*\.\s*([\w$]+)\s*\(", src):
        recv, meth = m.group(1), m.group(2)
        tname = var_types.get(recv) or (recv if recv in stubs else None)
        if tname is None:
            continue
        overloads = _resolve_method(stubs, tname, meth)
        if overloads is None:
            errors.append(f"{path}: {tname}.{meth}() not declared by stub {tname}")
            continue
        arity = _call_arity(src, m.end() - 1)
        if not any(a == arity or (va and arity >= a - 1) for a, va, _ in overloads):
            errors.append(
                f"{path}: {tname}.{meth}() called with {arity} args; stub "
                f"declares {sorted({a for a, _, _ in overloads})}"
            )
            continue
        # chain hop: `recv.meth(...).next(`
        close = _find_close(src, m.end() - 1)
        if close is not None:
            chain = re.match(r"\s*\.\s*([\w$]+)\s*\(", src[close + 1 :])
            if chain:
                rets = {r for _, _, r in overloads}
                for ret in rets:
                    if ret in stubs:
                        nxt = chain.group(1)
                        if _resolve_method(stubs, ret, nxt) is None:
                            errors.append(
                                f"{path}: {tname}.{meth}().{nxt}() — {nxt} not "
                                f"declared by stub {ret}"
                            )

    # check 5: constructor arity on stub types
    shim_declared = {t.name for t in shim_types}
    for m in re.finditer(r"\bnew\s+([A-Z][\w$]*)(?:<[^<>()]*>)?\s*\(", src):
        tname = m.group(1)
        if tname not in stubs or tname in shim_declared:
            continue
        t = stubs[tname]
        if t.kind != "class":
            errors.append(f"{path}: new {tname}(...) but stub is an {t.kind}")
            continue
        arity = _call_arity(src, m.end() - 1)
        arities = t.ctor_arities or [(0, False)]  # implicit default ctor
        if not any(a == arity or (va and arity >= a - 1) for a, va in arities):
            errors.append(
                f"{path}: new {tname}() with {arity} args; stub declares "
                f"{sorted({a for a, _ in arities})}"
            )
    return errors


def _find_close(src: str, open_paren: int) -> Optional[int]:
    depth = 0
    for i in range(open_paren, min(len(src), open_paren + 2000)):
        if src[i] in "([{":
            depth += 1
        elif src[i] in ")]}":
            depth -= 1
            if depth == 0:
                return i
    return None


def main() -> int:
    stubs = load_stubs()
    # 2.4 leg: shared stubs with the stubs24 overrides shadowing same-named
    # types (the classpath order of the javac invocation); references from
    # src24 to the 3.x shim classes themselves are not stub-typed and are
    # skipped by the checker like any non-stub receiver
    overrides = load_stubs(STUB24_DIR)
    stubs24 = dict(stubs)
    stubs24.update(overrides)
    errors: List[str] = []
    n_files = 0
    legs = [(SRC_DIR, stubs), (SRC24_DIR, stubs24)]
    for src_dir, stub_set in legs:
        for dirpath, _, files in os.walk(src_dir):
            for fn in sorted(files):
                if fn.endswith(".java"):
                    n_files += 1
                    errors.extend(check_shim_file(os.path.join(dirpath, fn), stub_set))
    if errors:
        for e in errors:
            print(f"FIDELITY: {e}")
        print(f"STUB FIDELITY: FAIL ({len(errors)} problems)")
        return 1
    print(
        f"STUB FIDELITY: OK — {n_files} shim sources (incl. the 2.4-signature "
        f"leg) x {len(stubs)}+{len(overrides)} stub types: "
        "imports resolve, SPI overrides complete, resolved calls + ctors match "
        "stub signatures"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
