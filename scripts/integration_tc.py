#!/usr/bin/env python
"""Integration gate: transitive closure on the executor mesh vs the CPU oracle.

The ``run_tc_test`` analogue (buildlib/test.sh:175-179): the reference runs
Spark's SparkTC example through the plugin as half its CI gate; here the
device-resident closure (ops/tc.py) runs on a real multi-device mesh at
SparkTC's default shape (200 random edges over 100 vertices) and must match
the host oracle exactly.

Env knobs (test.sh style): EXECUTORS, VERTICES, EDGES, SEED.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from sparkucx_tpu.parallel.mesh import apply_platform_env  # noqa: E402

apply_platform_env()

from sparkucx_tpu.ops.exchange import make_mesh  # noqa: E402
from sparkucx_tpu.ops.tc import TcSpec, oracle_tc, run_transitive_closure  # noqa: E402


def main() -> int:
    n = int(os.environ.get("EXECUTORS", "4"))
    vertices = int(os.environ.get("VERTICES", "100"))
    num_edges = int(os.environ.get("EDGES", "200"))  # SparkTC defaults
    seed = int(os.environ.get("SEED", "0"))

    rng = np.random.default_rng(seed)
    edges = rng.integers(0, vertices, size=(num_edges, 2), dtype=np.uint32)

    # capacities: closure can approach vertices^2 pairs; hash-balanced shards
    per_shard = max(256, (2 * vertices * vertices) // n)
    spec = TcSpec(
        num_executors=n,
        edge_capacity=max(64, 2 * num_edges // n + num_edges % n),
        tc_capacity=per_shard,
        join_capacity=4 * per_shard,
    )
    mesh = make_mesh(n)
    t0 = time.perf_counter()
    got, rounds = run_transitive_closure(mesh, spec, edges, max_rounds=vertices)
    dt = time.perf_counter() - t0
    want = oracle_tc(edges)
    if not np.array_equal(got, want):
        print(f"FAIL: closure mismatch ({len(got)} pairs, want {len(want)})")
        return 1
    print(
        f"tc test OK: {num_edges} edges over {vertices} vertices -> "
        f"{len(got)} closure pairs in {rounds} rounds across {n} executors "
        f"({dt:.1f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
