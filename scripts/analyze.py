#!/usr/bin/env python3
"""Shuffle invariant analyzer CLI — thin wrapper over ``sparkucx_tpu.analysis``.

Equivalent to ``python -m sparkucx_tpu.analysis``; exists so the gate is
runnable from scripts/ like the rest of the repo tooling.  See
docs/ANALYSIS.md for the pass catalogue and the allowlist policy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkucx_tpu.analysis.__main__ import main

if __name__ == "__main__":
    sys.exit(main())
