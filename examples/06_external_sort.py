"""Out-of-core TeraSort: sorting a dataset larger than device capacity.

The single-chip HBM envelope is ~32M 100 B rows (docs/PERF.md); the
"TeraSort 10GB" workload (BASELINE configs[1]) exceeds it.  run_external_sort
chains full-capacity device sorts — one compiled function reused across
batches — and merges the sorted runs on the host, moving only (key, index)
pairs through the merge levels and placing each run's payload once.

Run: python examples/06_external_sort.py          (any backend; up to 4 executors)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort


def main() -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under vendor site hooks
    import jax

    n = min(4, len(jax.devices()))
    cap = 2_000                      # per-executor device capacity per batch
    total = 6 * n * cap + 123        # ~6 device batches, ragged tail
    rng = np.random.default_rng(17)
    keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint32)
    payload = rng.integers(-(2**31), 2**31, size=(total, 24), dtype=np.int32)

    spec = SortSpec(
        num_executors=n, capacity=cap,
        recv_capacity=cap if n == 1 else 2 * cap, width=24,
    )
    out_keys, out_payload = run_external_sort(make_mesh(n), spec, keys, payload)

    want_keys, want_payload = oracle_sort(keys, payload)
    assert np.array_equal(out_keys, want_keys)
    assert np.array_equal(out_payload, want_payload)  # stable across batch merges
    batches = -(-total // (n * cap))
    print(
        f"OK: {total} rows sorted through {batches} device batches of "
        f"{n * cap} rows + host merge, row-exact vs the oracle"
    )


if __name__ == "__main__":
    main()
