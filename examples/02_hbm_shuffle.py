"""The device data plane: stage map output in HBM, exchange, fetch.

The reference's full write→serve cycle is: map tasks write partitions through
NVKV to DPU NVMe, commit a MapperInfo offset table, and reducers fetch blocks
back over UCX active messages.  Here the store is TPU HBM, the commit is the
same offset-table idea, and ALL reducers' fetches are satisfied by ONE
collective superstep over the executor mesh (the ragged all_to_all) — after
which every fetch is a local HBM read.

Run: python examples/02_hbm_shuffle.py            (any backend; 2 executors)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


def main() -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under vendor site hooks
    import jax

    n = min(2, len(jax.devices()))
    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20,
        num_executors=n,
        keep_device_recv=True,  # keep received bytes in HBM for device-side fetch
    )
    cluster = TpuShuffleCluster(conf, num_executors=n)
    M, R = 4, 6  # 4 map tasks x 6 reduce partitions
    meta = cluster.create_shuffle(0, M, R)

    # Map side: each map task writes its R partition payloads through a
    # sequential-partition writer, then commits (the MapperInfo analogue).
    rng = np.random.default_rng(11)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=int(rng.integers(100, 3000)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())

    # The superstep: one collective moves every block to its reducer's owner.
    cluster.run_exchange(0)
    print("OK: exchange complete (one collective superstep)")

    # Reduce side, host path: batched fetch into caller buffers — now a local
    # HBM read on the owning executor.
    for eid in range(n):
        t = cluster.transport(eid)
        lo, hi = cluster.meta(0).peer_ranges[eid]
        for r in range(lo, hi):
            for m in range(M):
                buf = MemoryBlock(np.zeros(4096, dtype=np.uint8), size=4096)
                [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(0, m, r)], [buf], [None])
                res = req.wait(30)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                assert buf.host_view()[: buf.size].tobytes() == oracle[(m, r)]
    print(f"OK: all {M * R} blocks fetched byte-identical on their owners")

    # Reduce side, device path: pack many blocks into ONE device buffer without
    # the bytes visiting the host (Pallas DMA gather on TPU, XLA gather on CPU).
    t = cluster.transport(0)
    lo, _ = cluster.meta(0).peer_ranges[0]
    bids = [ShuffleBlockId(0, m, lo) for m in range(M)]
    packed, entries = t.fetch_blocks_device(bids)
    packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
    for (row_start, length), bid in zip(entries, bids):
        start = int(row_start) * cluster.row_bytes
        assert packed_bytes[start : start + int(length)].tobytes() == oracle[(bid.map_id, bid.reduce_id)]
    print("OK: device-side batch fetch packed the blocks in HBM")

    cluster.remove_shuffle(0)


if __name__ == "__main__":
    main()
