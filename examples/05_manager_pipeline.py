"""The ShuffleManager SPI pipeline — what a host engine (Spark) drives.

The reference plugs into Spark as a `ShuffleManager`: map tasks get a writer
(sequential partition streams), reduce tasks get a reader (windowed fetch +
deserialize -> aggregate -> sort).  This walkthrough drives the same SPI as a
word-count-style GroupByTest job would: partition records by key hash, write
through the writer, ONE collective exchange, then read each partition back
aggregated and key-ordered — checked against a host-side oracle.

Run: python examples/05_manager_pipeline.py        (any backend; 2 executors)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under vendor site hooks
    import jax

    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.shuffle.manager import TpuShuffleManager
    from sparkucx_tpu.shuffle.reader import serialize_records

    n = min(2, len(jax.devices()))
    manager = TpuShuffleManager(
        TpuShuffleConf(num_executors=n, staging_capacity_per_executor=1 << 20),
        num_executors=n,
    )
    M, R, SID = 4, 6, 0
    manager.register_shuffle(SID, num_mappers=M, num_reducers=R)

    # Map side: each map task hash-partitions its (word, count) records and
    # writes them through the sequential-partition SPI writer.
    rng = np.random.default_rng(13)
    oracle = {}
    for m in range(M):
        records = [
            (f"word-{int(rng.integers(0, 40))}", int(rng.integers(1, 100)))
            for _ in range(300)
        ]
        for k, v in records:
            oracle[k] = oracle.get(k, 0) + v
        writer = manager.get_writer(SID, m)
        by_part = {}
        for k, v in records:
            by_part.setdefault(hash(k) % R, []).append((k, v))
        for r in sorted(by_part):
            with writer.get_partition_writer(r).open_stream() as stream:
                stream.write(serialize_records(by_part[r]))
        writer.commit_all_partitions()

    # All maps committed -> one collective moves every block to its reducer.
    assert manager.exchange_ready(SID)
    manager.run_exchange(SID)
    print("OK: all maps committed, exchange complete")

    # Reduce side: each partition read back with combine + key ordering (the
    # deserialize -> aggregate -> sort pipeline the reference reader runs).
    got = {}
    records_read = 0
    for r in range(R):
        reader = manager.get_reader(
            SID, r, r + 1, aggregator=lambda a, b: a + b, key_ordering=True
        )
        out = list(reader.read())
        keys = [k for k, _ in out]
        assert keys == sorted(keys), "key_ordering must sort within the partition"
        for k, v in out:
            assert hash(k) % R == r, "record landed in the wrong partition"
            got[k] = v
        records_read += reader.metrics.records_read  # the Spark metric surface
    assert got == oracle, "aggregated counts diverged from the oracle"
    print(
        f"OK: {len(got)} words aggregated across {R} partitions, oracle-exact "
        f"({records_read} records through the read metrics)"
    )

    manager.unregister_shuffle(SID)
    manager.stop()


if __name__ == "__main__":
    main()
