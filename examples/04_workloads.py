"""The reference's gate workloads — GROUP BY, hash join, SparkTC — on device.

The reference validates itself by running stock Spark examples over its
transport (GroupByTest and SparkTC, buildlib/test.sh:163-179); its BASELINE
adds TPC-H-style joins.  Here the same logical plans run as device operators:
hash-partition exchange + segment reduction (GROUP BY), exchange of both
sides + sort-merge match (join), and an iterated join/union/distinct step
(transitive closure).  Every result is checked against a numpy oracle.

Run: python examples/04_workloads.py              (any backend; up to 4 executors)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    oracle_aggregate,
    run_grouped_aggregate,
    run_hash_join,
)
from sparkucx_tpu.ops.tc import TcSpec, oracle_tc, run_transitive_closure


def groupby(mesh, n: int) -> None:
    # GroupByTest's shape: random keys from a small keyspace, grouped; the
    # gate's pass criterion is the distinct-key count (test.sh:163-167).
    # Map-side partial aggregation (Spark's HashAggregateExec(partial)) is
    # taken from the conf toggle, on by default — each shard exchanges at
    # most one partial row per local distinct key instead of every raw row.
    from sparkucx_tpu.config import TpuShuffleConf

    total, num_keys = 20_000, 100
    conf = TpuShuffleConf()
    rng = np.random.default_rng(5)
    keys = rng.integers(0, num_keys, size=total).astype(np.uint32)
    values = rng.integers(0, 1000, size=(total, 2)).astype(np.int32)
    spec = AggregateSpec.from_conf(
        conf,
        num_executors=n, capacity=-(-total // n), recv_capacity=4 * -(-total // n),
        aggs=("sum", "max"),
    )
    partial = spec.partial
    gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values)
    wk, wv, wc = oracle_aggregate(keys, values, spec.aggs)
    assert np.array_equal(gk, wk) and np.array_equal(gv, wv) and np.array_equal(gc, wc)
    print(
        f"OK: GROUP BY over {total} rows -> {len(gk)} groups, oracle-exact "
        f"(partial aggregation {'on' if partial else 'off'})"
    )


def join(mesh, n: int) -> None:
    # PK-FK inner join (TPC-H's plan shape): unique dimension keys, fact rows
    # referencing them.  run_hash_join plans receive/output capacities from
    # the real placement hash and raises precise diagnostics on divergence —
    # use it instead of hand-sizing JoinSpec buffers.
    nb, np_rows = 1_000 * n, 4_000 * n
    rng = np.random.default_rng(6)
    bkeys = rng.permutation(nb).astype(np.uint32)
    pkeys = bkeys[rng.integers(0, nb, size=np_rows)]
    bvals = rng.integers(0, 100, size=(nb, 1)).astype(np.int32)
    # probe values derive from the key so the output check can verify the
    # probe side per-row (equal-key fact rows are otherwise interchangeable)
    pvals = (pkeys.astype(np.int64) * 3 + 1).astype(np.int32)[:, None]
    jk, jb, jp = run_hash_join(mesh, bkeys, bvals, pkeys, pvals)
    assert len(jk) == np_rows, f"PK-FK join must match every fact row ({len(jk)} != {np_rows})"
    # value alignment: every emitted (key, build, probe) triple must carry the
    # build table's value for that key AND the key-derived probe value
    build_of = dict(zip(bkeys.tolist(), bvals[:, 0].tolist()))
    for k, b, p in zip(jk.tolist(), jb[:, 0].tolist(), jp[:, 0].tolist()):
        assert build_of[k] == b
        assert p == k * 3 + 1
    print(f"OK: PK-FK join matched {len(jk)} fact rows, values aligned both sides")


def transitive_closure(mesh, n: int) -> None:
    # SparkTC: random sparse digraph, closure by iterated join until fixpoint.
    rng = np.random.default_rng(8)
    edges = rng.integers(0, 60, size=(150, 2)).astype(np.uint32)
    want = oracle_tc(edges)
    cap = max(4096 // n, 512)
    spec = TcSpec(
        num_executors=n, edge_capacity=cap, tc_capacity=cap, join_capacity=4 * cap
    )
    pairs, rounds = run_transitive_closure(mesh, spec, edges)
    assert np.array_equal(pairs, want)  # driver returns ascending-unique
    print(f"OK: transitive closure {len(want)} pairs in {rounds} rounds")


def main() -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under vendor site hooks
    import jax

    n = min(4, len(jax.devices()))
    mesh = make_mesh(n)
    groupby(mesh, n)
    join(mesh, n)
    transitive_closure(mesh, n)


if __name__ == "__main__":
    main()
