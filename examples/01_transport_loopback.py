"""The ShuffleTransport trait, end to end, with the in-process backend.

The reference documents its transport usage flow at ShuffleTransport.scala:95-109:
a server-side executor ``register``s blocks, a client calls
``fetch_blocks_by_block_ids`` and drives completion with explicit
``progress()`` polling.  That contract is preserved here; the loopback
fabric is the unit-test backend the reference never had (SURVEY.md §4).

Run: python examples/01_transport_loopback.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.loopback import LoopbackFabric, LoopbackTransport


def main() -> None:
    conf = TpuShuffleConf()
    fabric = LoopbackFabric()
    server = LoopbackTransport(conf, executor_id=0, fabric=fabric)
    client = LoopbackTransport(conf, executor_id=1, fabric=fabric)
    server_addr = server.init()
    client.init()
    client.add_executor(0, server_addr)  # the ExecutorAdded handshake

    # Server side: register three blocks of a shuffle (what the map-output
    # commit hook does after a map task finishes).
    rng = np.random.default_rng(7)
    payloads = {r: rng.integers(0, 256, size=1000 + r, dtype=np.uint8).tobytes() for r in range(3)}
    for r, data in payloads.items():
        server.register(ShuffleBlockId(shuffle_id=0, map_id=0, reduce_id=r), BytesBlock(data))

    # Client side: one batched fetch for all three blocks into caller-owned
    # receive buffers; requests complete under progress() (fetches are
    # deferred by design — poll, then wait).
    bids = [ShuffleBlockId(0, 0, r) for r in range(3)]
    bufs = [MemoryBlock(np.zeros(4096, dtype=np.uint8), size=4096) for _ in bids]
    reqs = client.fetch_blocks_by_block_ids(0, bids, bufs, [None] * len(bids))
    while not all(r.completed() for r in reqs):
        client.progress()
    for bid, buf, req in zip(bids, bufs, reqs):
        res = req.wait(5)
        assert res.status == OperationStatus.SUCCESS, res.error
        assert buf.host_view()[: buf.size].tobytes() == payloads[bid.reduce_id]
    print("OK: 3 blocks fetched byte-identical through the transport trait")

    # A fetch of an unregistered block is a FAILURE result, not an exception
    # (the contract fetch retry is built on).
    [req] = client.fetch_blocks_by_block_ids(
        0, [ShuffleBlockId(0, 9, 9)], [MemoryBlock(np.zeros(16, dtype=np.uint8), size=16)], [None]
    )
    while not req.completed():
        client.progress()
    assert req.wait(5).status == OperationStatus.FAILURE
    print("OK: missing block surfaces as a FAILURE result")

    client.close()
    server.close()


if __name__ == "__main__":
    main()
