"""TeraSort on device: the whole job, not just the shuffle transport.

The reference accelerates only the block-fetch layer under Spark's sortByKey;
here sampling, range partitioning, the all-to-all, and both local sorts run
as one jitted SPMD program over the executor mesh (ops/sort.py).  The host
driver handles the one data-dependent decision — splitter-skew overflow —
by re-running with doubled receive headroom.

Run: python examples/03_terasort.py               (any backend; up to 4 executors)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort


def main() -> None:
    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under vendor site hooks
    import jax

    n = min(4, len(jax.devices()))
    total = 40_000  # 100 B rows: uint32 key + 24 int32 payload lanes
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint32)
    payload = rng.integers(-(2**31), 2**31, size=(total, 24), dtype=np.int32)

    spec = SortSpec(
        num_executors=n,
        capacity=-(-total // n),
        recv_capacity=2 * -(-total // n),  # headroom over the balanced share
        width=24,
    )
    mesh = make_mesh(n)
    out_keys, out_payload = run_distributed_sort(mesh, spec, keys, payload)

    want_keys, want_payload = oracle_sort(keys, payload)
    assert np.array_equal(out_keys, want_keys)
    assert np.array_equal(out_payload, want_payload)  # stable: payloads row-exact
    print(f"OK: {total} rows sorted across {n} executors, row-exact vs the oracle")


if __name__ == "__main__":
    main()
