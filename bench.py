#!/usr/bin/env python
"""Headline benchmark: shuffle superstep throughput through the TPU transport.

Measures the data plane SparkUCX exists to accelerate — the reduce-side block
exchange (per-batch fetch bandwidth, UcxPerfBenchmark.scala:140-143; BASELINE.json
north star: shuffle-read GB/s vs TCP).

What is timed: the compiled shuffle superstep (ops/exchange.py — the ragged
all_to_all that replaces UCX active messages) moving realistically skewed block
payloads that are *resident in HBM*, exactly as in production where both the map
stage that produced them and the reduce stage that consumes them run on-TPU.
Supersteps are chained K deep before synchronizing so per-dispatch RPC latency is
amortized (the analogue of the reference benchmark's outstanding-request window,
UcxPerfBenchmark.scala:129-151).  Host<->device staging is deliberately excluded:
on this harness the chip sits behind a network tunnel whose D2H path (~10 MB/s) is
not representative of TPU-VM PCIe/DMA.

Baseline measured in the same run: the same byte volume served over a localhost TCP
socket into preallocated buffers (the stock Spark Netty-shuffle transport
analogue).  ``vs_baseline`` = tpu_gbps / tcp_gbps.

Sub-metrics (same JSON line): ``gather_gbps`` — the device-side ragged block
gather (ops/pallas_kernels.py), ``sort_mrows_s`` — the device-resident TeraSort
step (ops/sort.py), ``wire`` — the striped loopback peer wire (streams=1 vs 4,
perf/benchmark.py measure_wire; TPU-free, measured after the TCP baseline),
``failover`` — executor-loss robustness (perf/benchmark.py measure_failover;
TPU-free): steady loopback fetch GB/s vs GB/s with the primary executor killed
at t=50%, plus recovery time and p99 frame stall, ``gray`` — gray-failure
robustness (perf/benchmark.py measure_gray; TPU-free): the primary executor is
throttled to ~10% instead of killed, reporting fetch GB/s and p99 frame stall
with hedged fetches off vs on plus hedge-win counts and an off-the-clock
bit-equality check, ``tenants`` — the
multi-tenant serving plane (perf/benchmark.py measure_tenants; TPU-free): 8
concurrent apps fetching through the shared-selector reactor, reporting
aggregate GB/s, the min/max per-app fairness ratio, and p99 per-block fetch
latency, ``compress`` — wire payload
compression (perf/benchmark.py measure_compress; TPU-free): per-codec fetch
GB/s and compression ratio on a dictionary-heavy matrix vs incompressible
noise, plus an end-to-end compressed shuffle-read leg, ``obs`` — the
telemetry plane (perf/benchmark.py measure_obs; TPU-free): fetch GB/s with
tracing off vs ring-only (the always-on flight recorder's steady state) vs
full wire-context export, asserting the recorder's accounted overhead < 1%.

A small end-to-end shuffle (stage -> commit -> exchange -> fetch vs oracle) runs
untimed first as an integrity gate.

Robustness contract (the round-1 bench gate died with no output, BENCH_r01.json
rc=1/parsed=null): this script ALWAYS prints exactly one JSON line.  The TCP
baseline needs no TPU and runs first; the chip is probed in a bounded subprocess
(a dead tunnel makes in-process ``jax.devices()`` hang forever); a watchdog
force-emits whatever has been measured if the deadline passes.  When the chip is
unreachable the line carries ``"value": null``, ``"tpu": null`` (explicit
no-measurement marker) and an ``"error"`` field.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Preferred staging size first; if the chip can't fit it (shared-HBM pressure),
# fall back — 2M rows (1 GiB) measured ~7% faster than 1M on an idle v5e.
SEND_ROWS_CANDIDATES = [
    int(s) for s in os.environ.get("BENCH_SEND_ROWS", "2097152,1048576").split(",")
]
FILL = float(os.environ.get("BENCH_FILL", "0.9"))
# 256-deep: through the axon tunnel, enqueue latency still throttles the chip
# at 64-deep windows (x+0 copy measures 361 -> 565 GB/s r+w going 64 -> 256)
CHAIN = int(os.environ.get("BENCH_CHAIN", "256"))
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
TCP_BYTES = int(os.environ.get("BENCH_TCP_BYTES", str(256 << 20)))
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "30"))
# Probe until this much of the deadline budget remains (enough for the
# superstep + sub-metric measurements once the chip answers): the tunnel
# flaps for minutes-to-hours at a time, and a round whose gate records null
# is a round whose headline is unverifiable after the fact (BENCH_r02/r04).
PROBE_RESERVE = float(os.environ.get("BENCH_PROBE_RESERVE", "420"))
# optional hard cap on probe attempts (0 = keep going until the reserve);
# lets an operator fail fast without waiting out the deadline budget
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "0"))
# 900 s: ~8 min of probe retries before the measurement reserve — deep
# enough to ride out short tunnel flaps, conservative enough to emit the
# JSON line before any outer harness timeout could cut the process down
DEADLINE = float(os.environ.get("BENCH_DEADLINE", "900"))
SKIP_SUBMETRICS = os.environ.get("BENCH_SKIP_SUBMETRICS", "") == "1"

RESULT = {
    "metric": "shuffle_superstep_throughput",
    "value": None,
    "unit": "GB/s",
    "vs_baseline": None,
}
_EMITTED = threading.Lock()
_emitted = False


def emit_once() -> None:
    """Print the single JSON result line exactly once (main path or watchdog)."""
    global _emitted
    with _EMITTED:
        if _emitted:
            return
        _emitted = True
    sys.stdout.flush()
    print(json.dumps(RESULT), flush=True)


def _watchdog() -> None:
    time.sleep(DEADLINE)
    RESULT.setdefault("error", f"deadline {DEADLINE}s exceeded; partial results emitted")
    emit_once()
    os._exit(0)


def probe_tpu(budget_left) -> tuple:
    """Bounded out-of-process backend probe with deadline-aware retries.

    A dead chip tunnel makes ``jax.devices()`` block forever inside
    ``make_c_api_client`` (no Python-level timeout can interrupt it), so the
    first backend touch happens in a killable subprocess.  The tunnel flaps
    for long stretches, so a single failed probe must not write off the
    round: keep retrying with backoff until only ``PROBE_RESERVE`` seconds of
    deadline remain (the time the measurements themselves need).  Each failed
    attempt is logged to stderr so a null round shows its retry history.
    ``budget_left`` (required) returns the seconds of deadline remaining;
    ``BENCH_PROBE_ATTEMPTS`` > 0 additionally caps the attempt count.
    Returns ``(platform, error)`` — platform is None on failure.
    """
    # honor JAX_PLATFORMS even when a site hook pinned jax_platforms (the same
    # override parallel/mesh.apply_platform_env handles in-process)
    code = (
        "import os, jax\n"
        "w = os.environ.get('JAX_PLATFORMS')\n"
        "if w: jax.config.update('jax_platforms', w)\n"
        "d = jax.devices(); print(d[0].platform, len(d))\n"
    )
    last = "unknown"
    attempt = 0
    while True:
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if r.returncode == 0 and r.stdout.strip():
                platform = r.stdout.strip().split()[0]
                return platform, None
            last = (r.stderr or "").strip().splitlines()[-1:] or ["nonzero exit"]
            last = last[0][:300]
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {PROBE_TIMEOUT}s (tunnel down?)"
        if PROBE_ATTEMPTS and attempt >= PROBE_ATTEMPTS:
            print(
                f"# probe attempt {attempt} failed ({last}); attempt cap reached",
                file=sys.stderr,
            )
            return None, f"{last} [after {attempt} probe attempts]"
        remaining = budget_left()
        backoff = min(5.0 * attempt, 30.0)
        if remaining - backoff - PROBE_TIMEOUT <= PROBE_RESERVE:
            print(
                f"# probe attempt {attempt} failed ({last}); budget exhausted",
                file=sys.stderr,
            )
            return None, f"{last} [after {attempt} probe attempts]"
        print(
            f"# probe attempt {attempt} failed ({last}); retrying in {backoff:.0f}s "
            f"({remaining:.0f}s of deadline left)",
            file=sys.stderr,
        )
        time.sleep(backoff)


def tcp_shuffle_read_gbps(total_bytes: int, chunk: int = 1 << 20) -> float:
    """Serve ``total_bytes`` over a localhost socket and time the client reading
    all of it into preallocated buffers (what a TCP shuffle fetch does)."""
    payload = b"\xab" * total_bytes
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def server():
        conn, _ = srv.accept()
        with conn:
            conn.sendall(payload)

    th = threading.Thread(target=server, daemon=True)
    th.start()
    cli = socket.create_connection(("127.0.0.1", port))
    cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    dest = bytearray(total_bytes)
    view = memoryview(dest)
    t0 = time.perf_counter()
    got = 0
    while got < total_bytes:
        n = cli.recv_into(view[got:], min(chunk, total_bytes - got))
        if n == 0:
            break
        got += n
    dt = time.perf_counter() - t0
    cli.close()
    srv.close()
    th.join()
    assert got == total_bytes
    return got / dt / 1e9


def integrity_gate():
    """Tiny end-to-end shuffle vs oracle through the full stack (untimed)."""
    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
    from sparkucx_tpu.core.operation import OperationStatus
    from sparkucx_tpu.transport.tpu import TpuShuffleCluster

    conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20, num_executors=1)
    cluster = TpuShuffleCluster(conf, num_executors=1)
    M, R = 4, 8
    meta = cluster.create_shuffle(0, M, R)
    rng = np.random.default_rng(7)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=int(rng.integers(1, 2000)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(0)
    t = cluster.transport(0)
    for (m, r), expect in oracle.items():
        buf = MemoryBlock(np.zeros(4096, dtype=np.uint8), size=4096)
        [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(0, m, r)], [buf], [None])
        res = req.wait(30)
        assert res.status == OperationStatus.SUCCESS, str(res.error)
        assert buf.host_view()[: buf.size].tobytes() == expect, f"integrity fail at {(m, r)}"
    cluster.remove_shuffle(0)


def device_superstep_gbps(send_rows: int) -> tuple:
    """Chained shuffle supersteps over HBM-resident payloads.
    Returns (best GB/s, executed exchange impl)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh

    n = 1
    spec = ExchangeSpec(
        num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=128, impl="auto"
    )
    mesh = make_mesh(n)
    fn = build_exchange(mesh, spec)

    rng = np.random.default_rng(0)
    slot = spec.slot_rows
    sizes = np.minimum((rng.uniform(0.8, 1.0, size=(n, n)) * FILL * slot).astype(np.int32), slot)
    bytes_per_step = int(sizes.sum()) * spec.row_bytes

    data = jax.device_put(
        rng.integers(-(2**31), 2**31 - 1, size=(n * send_rows, spec.lane), dtype=np.int32),
        NamedSharding(mesh, P("ex", None)),
    )
    size_mat = jax.device_put(sizes, NamedSharding(mesh, P("ex", None)))

    out, _ = fn(data, size_mat)  # warmup/compile; donation consumed `data`
    jax.block_until_ready(out)

    best = 0.0
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        cur = out
        for _ in range(CHAIN):
            cur, _ = fn(cur, size_mat)
        jax.block_until_ready(cur)
        # block_until_ready alone under-blocks through remote-chip tunnels;
        # a tiny readback forces true completion so the window is honest
        np.asarray(cur[0, :4])
        dt = time.perf_counter() - t0
        out = cur
        best = max(best, CHAIN * bytes_per_step / dt / 1e9)
    return best, fn.spec.impl


def main():
    t_start = time.monotonic()
    budget_left = lambda: DEADLINE - (time.monotonic() - t_start)
    threading.Thread(target=_watchdog, daemon=True).start()

    # 1. TCP baseline — needs no TPU, always recorded.
    try:
        tcp = tcp_shuffle_read_gbps(TCP_BYTES)
        RESULT["tcp_gbps"] = round(tcp, 3)
    except Exception as e:
        tcp = None
        RESULT["tcp_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1b. Striped-wire sub-metric — also TPU-free (loopback peer wire), so it
    # runs before the chip probe and survives null rounds.  Measured AFTER the
    # TCP baseline so it cannot perturb tcp_gbps.  NOTE: this harness has one
    # CPU core, so loopback is core-bound (~2.4 GB/s aggregate); the striping
    # gain here comes from deeper kernel socket buffering, not parallel recv.
    try:
        from sparkucx_tpu.perf.benchmark import measure_wire

        w = measure_wire(streams_list=(1, 4), num_blocks=8, block_bytes=32 << 20,
                         iterations=4)
        RESULT["wire"] = {
            f"streams{s}_gbps": round(r["gbps"], 3) for s, r in w.items()
        }
        if w.get(1, {}).get("gbps") and w.get(4, {}).get("gbps"):
            RESULT["wire"]["stripe_speedup"] = round(w[4]["gbps"] / w[1]["gbps"], 3)
            RESULT["wire"]["syscalls_per_mb"] = round(w[4]["syscalls_per_mb"], 3)
    except Exception as e:
        RESULT["wire_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1c. Failover sub-metric — also TPU-free (3-executor loopback cluster
    # with replication.factor=1, testing/faults.kill_executor as the SIGKILL
    # stand-in): steady fetch GB/s vs GB/s with the primary killed at t=50%,
    # recovery time (kill -> first replica-served block), p99 frame stall.
    try:
        from sparkucx_tpu.perf.benchmark import measure_failover

        fo = measure_failover(num_blocks=8, block_bytes=8 << 20, iterations=3)
        RESULT["failover"] = {
            "steady_gbps": round(fo["steady_gbps"], 3),
            "killed_gbps": round(fo["killed_gbps"], 3),
            "recovery_ms": round(fo["recovery_ms"], 1),
            "failovers": fo["failovers"],
            "rx_stall_p99_ms": round(fo["rx_stall_p99_ms"], 2),
        }
    except Exception as e:
        RESULT["failover_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1c2. Gray-failure sub-metric — also TPU-free (the failover cluster
    # shape, but the primary is throttled to ~10% of the healthy rate
    # instead of killed): GB/s and p99 frame stall with hedging off vs on
    # (fetch.hedgeMs), hedge win counts, bit-equality asserted outside the
    # clock (perf/benchmark.py measure_gray).
    try:
        from sparkucx_tpu.perf.benchmark import measure_gray

        gr = measure_gray(num_blocks=8, block_bytes=8 << 20, iterations=3)
        RESULT["gray"] = {
            "healthy_gbps": round(gr["healthy_gbps"], 3),
            "degraded_gbps": round(gr["degraded_gbps"], 3),
            "hedged_gbps": round(gr["hedged_gbps"], 3),
            "degraded_p99_ms": round(gr["degraded_p99_ms"], 2),
            "hedged_p99_ms": round(gr["hedged_p99_ms"], 2),
            "hedge_wins": gr["hedge_wins"],
            "fetch_timeouts": gr["fetch_timeouts"],
            "bit_identical": gr["bit_identical"],
        }
    except Exception as e:
        RESULT["gray_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1d. Multi-tenant serving-plane sub-metric — also TPU-free (one
    # tenants-enabled loopback server on the shared-selector reactor plane,
    # N concurrent apps each fetching through its own tenant namespace):
    # aggregate GB/s, the min/max per-app fairness ratio, and p99 per-block
    # fetch latency under concurrent fan-in (perf/benchmark.py
    # measure_tenants).
    try:
        from sparkucx_tpu.perf.benchmark import measure_tenants

        tn = measure_tenants(
            num_apps=8, num_blocks=8, block_bytes=1 << 20, iterations=2
        )
        RESULT["tenants"] = {
            "apps": tn["apps"],
            "agg_gbps": round(tn["agg_gbps"], 3),
            "fairness": round(tn["fairness"], 3),
            "p99_fetch_ms": round(tn["p99_fetch_ms"], 2),
        }
    except Exception as e:
        RESULT["tenants_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1d2. Popularity-aware fan-in sub-metric — also TPU-free (per
    # replica-set width, single-worker loopback servers under a fixed
    # per-request service stall; 8 concurrent readers fan in on ONE hot
    # block promoted past serve.hotThresholdFetchesPerSec and spread across
    # the HOT_SET_PULL-advertised holders): aggregate GB/s + pooled p99 per
    # width, and the width-4/width-1 speedup (perf/benchmark.py
    # measure_fanin; bit-identical from every holder off the clock).
    try:
        from sparkucx_tpu.perf.benchmark import measure_fanin

        fn = measure_fanin(
            num_readers=8, block_bytes=256 << 10, iterations=2,
            fetches_per_reader=3,
        )
        RESULT["fanin"] = {
            "per_width": {
                str(w): {
                    "agg_gbps": round(m["agg_gbps"], 3),
                    "p99_fetch_ms": round(m["p99_fetch_ms"], 2),
                }
                for w, m in fn["per_width"].items()
            },
            "speedup": round(fn["speedup"], 3),
        }
    except Exception as e:
        RESULT["fanin_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1e. Compression sub-metric — also TPU-free (loopback peer wire with the
    # tier-(a) chunk codecs).  Reports ratio x effective GB/s, never ratio
    # alone: a codec only counts if DECODED bytes per wall-second go up.
    # Small sizes here (the recorded headline run lives in docs/PERF.md);
    # every iteration is bit-compared against the source outside the clock.
    try:
        from sparkucx_tpu.perf.benchmark import measure_compress

        comp = measure_compress(
            num_blocks=4, block_bytes=4 << 20, iterations=3, e2e=True
        )
        RESULT["compress"] = {
            name: {
                codec: {
                    k: round(cell[k], 3)
                    for k in ("gbps", "ratio", "speedup_vs_off", "e2e_gbps")
                    if k in cell
                }
                for codec, cell in cells.items()
            }
            for name, cells in comp.items()
        }
    except Exception as e:
        RESULT["compress_error"] = f"{type(e).__name__}: {e}"[:300]

    # 1f. Observability sub-metric — also TPU-free (2-executor loopback
    # fetch): GB/s with tracing off / ring-only (the always-on flight
    # recorder's steady state) / full wire-context export.  measure_obs
    # asserts the recorder's accounted overhead (events/pass x ns/record)
    # < 1%; the disabled-span() fast path is the docs/PERF.md number.
    try:
        from sparkucx_tpu.perf.benchmark import measure_obs

        ob = measure_obs(num_blocks=8, block_bytes=4 << 20, iterations=3)
        RESULT["obs"] = {
            "off_gbps": round(ob["off_gbps"], 3),
            "ring_gbps": round(ob["ring_gbps"], 3),
            "full_gbps": round(ob["full_gbps"], 3),
            "ring_overhead_pct": round(ob["ring_overhead_pct"], 3),
            "span_disabled_ns": round(ob["span_disabled_ns"], 1),
            "span_record_ns": round(ob["span_record_ns"], 1),
            "merged_events": ob["merged_events"],
            "export_ms": round(ob["export_ms"], 1),
        }
    except Exception as e:
        RESULT["obs_error"] = f"{type(e).__name__}: {e}"[:300]

    # 2. Bounded chip probe — never touch the backend in-process before this.
    platform, probe_err = probe_tpu(budget_left)
    if platform is None:
        RESULT["tpu"] = None
        RESULT["error"] = f"backend unreachable: {probe_err}"
        # honest provenance for a null round: point at the measurement log
        # rather than baking numbers into this string (they go stale the
        # moment the harness changes — see ADVICE r4)
        RESULT["note"] = (
            "chip tunnel down for the whole probe window; the most recent "
            "in-session hardware captures, with their configs, dates, and "
            "commits, are recorded in docs/PERF.md (measured-results table)"
        )
        emit_once()
        return
    RESULT["platform"] = platform

    # 3. Measured path; any failure still emits what we have.
    try:
        from sparkucx_tpu.parallel.mesh import apply_platform_env

        apply_platform_env()
        integrity_gate()
        RESULT["integrity"] = "pass"
        tpu = None
        for i, send_rows in enumerate(SEND_ROWS_CANDIDATES):
            try:
                tpu, RESULT["superstep_impl"] = device_superstep_gbps(send_rows)
                RESULT["send_rows"] = send_rows
                RESULT["superstep_window"] = CHAIN
                break
            except Exception as e:
                if i + 1 == len(SEND_ROWS_CANDIDATES):
                    raise
                print(
                    f"# {send_rows} rows failed ({type(e).__name__}); retrying smaller",
                    file=sys.stderr,
                )
        RESULT["value"] = round(tpu, 3)
        if tcp:
            RESULT["vs_baseline"] = round(tpu / tcp, 3)
    except Exception as e:
        RESULT["error"] = f"{type(e).__name__}: {e}"[:300]

    if not SKIP_SUBMETRICS and RESULT["value"] is not None:
        from sparkucx_tpu.perf.benchmark import (
            measure_gather,
            measure_groupby,
            measure_sort,
        )

        # Gather: the documented config (256 x 2 MiB blocks — docs/PERF.md) with
        # the Pallas DMA lowering REQUESTED EXPLICITLY and the executed lowering
        # recorded, plus the XLA fallback side by side — so this gate can never
        # silently benchmark the fallback and call it the kernel.  A Mosaic
        # lowering failure lands in gather_error, not in a wrong number.
        # Window 64 amortizes tunnel dispatch (~2-18 ms/call here); deeper
        # windows keep climbing (see PERF.md), this is the gate's time budget.
        impls = []
        rep = lambda it, dt, tot, impl: impls.append(impl)
        gather_window = 64
        try:
            RESULT["gather_gbps"] = round(
                measure_gather(
                    256, 2 << 20, REPEATS, outstanding=gather_window, impl="dma",
                    report=rep,
                ), 3,
            )
            RESULT["gather_impl"] = impls[-1]
            RESULT["gather_window"] = gather_window
        except Exception as e:
            RESULT["gather_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            RESULT["gather_xla_gbps"] = round(
                measure_gather(256, 2 << 20, REPEATS, outstanding=8, impl="xla"), 3
            )
        except Exception as e:
            RESULT["gather_xla_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            sort_impls = []
            RESULT["sort_mrows_s"] = round(
                measure_sort(
                    1, 1 << 21, REPEATS,
                    report=lambda it, dt, rows, impl: sort_impls.append(impl),
                ), 3,
            )
            if sort_impls:  # report never fires when BENCH_REPEATS=0
                RESULT["sort_impl"] = sort_impls[-1]
        except Exception as e:
            RESULT["sort_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # The Pallas LSD radix sort (ops/radix.py) head-to-head against
            # the argsort floor above — first hardware execution of the
            # kernel happens HERE, so a Mosaic compile failure lands in
            # sort_radix_error while the argsort number stands.
            if budget_left() < 120:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            if platform != "tpu":
                # ops/sort.py falls back to the Pallas interpreter off-TPU
                # (fine for the unit suite's tiny shapes, hours at 2M rows) —
                # an honest skip beats the watchdog truncating every
                # sub-metric queued behind this one
                raise RuntimeError(f"skipped: radix interprets on {platform}")
            RESULT["sort_radix_mrows_s"] = round(
                measure_sort(1, 1 << 21, REPEATS, sort_impl="radix"), 3
            )
        except Exception as e:
            RESULT["sort_radix_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # GROUP BY — the reference's gate workload (GroupByTest,
            # buildlib/test.sh:163-173) as one on-device hash-exchange +
            # segment-reduce step; 2M x 100 B rows, 100-key keyspace like the
            # small gate's.  Last sub-metric: runs only if enough deadline
            # budget remains for its compile (~60-90 s on the tunnelled chip)
            # — better an honest skip note than the watchdog truncating it.
            if budget_left() < 150:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            gb_impls = []
            wire = []
            RESULT["groupby_mrows_s"] = round(
                measure_groupby(
                    1, 1 << 21, REPEATS,
                    report=lambda it, dt, rows, impl: gb_impls.append(impl),
                    wire_rows=wire,
                ), 3,
            )
            if gb_impls:
                RESULT["groupby_impl"] = gb_impls[-1]
            if wire:
                RESULT["groupby_wire_rows"] = wire[0]
        except Exception as e:
            RESULT["groupby_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Same workload with map-side partial aggregation below the
            # exchange (conf partialAggregation, on by default for jobs):
            # wire rows collapse from ~2M to ~n_senders * 100 keys.
            if budget_left() < 150:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            wire_p = []
            gb_rows = 1 << 21
            RESULT["groupby_partial_mrows_s"] = round(
                measure_groupby(1, gb_rows, REPEATS, partial=True, wire_rows=wire_p),
                3,
            )
            if wire_p and wire_p[0]:
                RESULT["groupby_partial_wire_rows"] = wire_p[0]
                RESULT["groupby_wire_reduction"] = round(gb_rows / wire_p[0], 1)
        except Exception as e:
            RESULT["groupby_partial_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Multi-round (spilled) shuffle with host staging in the loop, at
            # pipeline depths 1/2/3 (transport/pipeline.py): depth 1 is the
            # serial engine, deeper rings overlap H2D staging, the collective,
            # and the D2H drain.  Through the chip tunnel the D2H leg
            # dominates, which is exactly the latency the ring hides — the
            # depth-2/depth-1 ratio is the tentpole's headline.
            if budget_left() < 120:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_pipeline

            pl = measure_pipeline(1, 8 << 20, 6, REPEATS)
            RESULT["pipeline"] = {f"depth{d}": round(v, 3) for d, v in pl.items()}
            if pl.get(1) and pl.get(2):
                RESULT["pipeline_overlap_speedup"] = round(pl[2] / pl[1], 3)
        except Exception as e:
            RESULT["pipeline_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Elastic recovery: full-mesh exchange GB/s vs one pass with an
            # executor killed mid-superstep — the cluster shrinks to the
            # surviving pow2 bucket, restages the dead executor's rounds from
            # ring-successor replicas, and re-runs in degraded waves (output
            # asserted bit-identical inside the measurement).  The headline is
            # recovery_ms and the degraded/steady throughput ratio.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            import jax

            n_el = min(4, jax.device_count())
            if n_el < 2:
                raise RuntimeError("skipped: elastic recovery needs >= 2 devices")
            from sparkucx_tpu.perf.benchmark import measure_elastic

            el = measure_elastic(n_el, 8 << 10, REPEATS)
            RESULT["elastic"] = {
                "steady_gbps": round(el["steady_gbps"], 3),
                "degraded_gbps": round(el["degraded_gbps"], 3),
                "recovery_ms": round(el["recovery_ms"], 1),
                "mesh": f"{n_el}->{el['degraded_mesh']}",
            }
        except Exception as e:
            RESULT["elastic_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Map-output staging: host byte path (memcpy into host staging +
            # seal's H2D) vs the device staging path (write_partition_device +
            # block-scatter kernel, seal returns the HBM payload directly).
            # On real TPUs the device path skips the PCIe round trip entirely;
            # through the CPU tunnel it mainly measures kernel overhead.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_write

            wr = measure_write(8, 1 << 20, REPEATS)
            RESULT["write"] = {impl: round(v, 3) for impl, v in wr.items()}
            if wr.get("host") and wr.get("device"):
                RESULT["write_device_speedup"] = round(wr["device"] / wr["host"], 3)
        except Exception as e:
            RESULT["write_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Skew-aware exchange planning (ops/skew.py): quota-capped chunked
            # plan vs the max-sized single-shot bucket on a Zipf-skewed size
            # matrix.  40000 rows sits just past the 32768 pow2 boundary, the
            # case where single-shot doubles its staging bucket but chunking
            # pays only extra sub-rounds; quota 8192 forces 5 chunks.  Bit
            # equality of the two plans is asserted inside measure_skew.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_skew

            sk = measure_skew(1, 40000, REPEATS, quota_rows=8192)
            RESULT["skew"] = {
                "quota_gbps": round(sk["quota"]["gbps"], 3),
                "max_gbps": round(sk["max"]["gbps"], 3),
                "subrounds": sk["subrounds"],
                "quota_padding": round(sk["quota"]["padding_fraction"], 4),
                "max_padding": round(sk["max"]["padding_fraction"], 4),
                "staged_rows_cut": round(
                    sk["max"]["staged_rows"] / max(sk["quota"]["staged_rows"], 1), 3
                ),
            }
        except Exception as e:
            RESULT["skew_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # Adaptive exchange planning (ops/planner.py): the telemetry-fed
            # AdaptivePlanner re-planning per cell of a skew x payload-entropy
            # x fault matrix vs every static (quota, codec) config held fixed
            # across it.  The exchange leg is measured, the serve-plane legs
            # are modeled from measured inputs (encode time/bytes, hedge vs a
            # gray straggler); bit-equality of every chunked schedule against
            # the single-shot reference is asserted inside measure_adaptive.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            import jax

            n_ad = min(8, jax.device_count())
            from sparkucx_tpu.perf.benchmark import measure_adaptive

            ad = measure_adaptive(n_ad, 512, max(2, REPEATS))
            worst = max(ad["cells"], key=lambda c: c["distance_from_oracle"])
            RESULT["adaptive"] = {
                "executors": n_ad,
                "cells": len(ad["cells"]),
                "aggregate_adaptive_gbps": ad["aggregate_adaptive_gbps"],
                "best_static": ad["best_static"],
                "best_static_gbps": ad["best_static_gbps"],
                "beats_every_static": ad["adaptive_beats_every_static"],
                "worst_cell_distance": ad["worst_cell_distance"],
                "worst_cell": f"alpha={worst['alpha']} entropy={worst['entropy']} "
                              f"fault={worst['fault']}",
                "bit_identical": all(c["bit_identical"] for c in ad["cells"]),
            }
        except Exception as e:
            RESULT["adaptive_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # FAST-scheduled ring exchange (ops/ici_exchange.py) vs the stock
            # collective at the widest mesh this backend exposes, plus the
            # fused send side's single-launch check.  Bit equality between the
            # impls is asserted inside measure_ici; through a one-chip tunnel
            # only n=1 exists and the honest skip lands in ici_error.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_ici

            ic = measure_ici((2, 4, 8), 1024, 128, iterations=REPEATS)
            widest = max(ic["per_n"])
            p = ic["per_n"][widest]
            RESULT["ici"] = {
                "executors": widest,
                "stock_gbps": round(p["stock_gbps"], 3),
                "pallas_gbps": round(p["pallas_gbps"], 3),
                "pallas_per_link_gbps": round(p["pallas_per_link_gbps"], 4),
                "supersteps": p["supersteps"],
                "chunks": p["chunks"],
                "lowering": p["lowering"],
                "fused_single_launch": ic["fused"]["launches"] == 1,
            }
        except Exception as e:
            RESULT["ici_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # compute-in-exchange: the receive-side fused combine vs the
            # unfused exchange-then-fold reference.  Bit equality is asserted
            # inside measure_combine; the drain ratio is the O(rows) landed
            # grid over the O(groups) accumulator each device drains instead.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_combine

            cb = measure_combine(8, 1024, 128, iterations=REPEATS)
            RESULT["combine"] = {
                "executors": cb["executors"],
                "fused_gbps": round(cb["fused_gbps"], 3),
                "unfused_gbps": round(cb["unfused_gbps"], 3),
                "drain_ratio": round(cb["drain"]["ratio"], 1),
                "lowering": cb["lowering"],
                "fused_single_launch": cb["launches"] == 1,
            }
        except Exception as e:
            RESULT["combine_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            # End-to-end query DAGs with lineage-keyed cross-query shuffle
            # reuse (sparkucx_tpu/query): M concurrent tenant DAGs repeat a
            # GroupByTest-shaped pipeline; the cached pass serves repeated
            # exchanges from the sealed store tiers instead of re-executing.
            # Cached-hit results are asserted bit-identical to the cold pass
            # inside measure_queries; the headline is the warm/cold
            # queries-per-second ratio at the measured hit rate.
            if budget_left() < 90:
                raise TimeoutError(f"skipped: {budget_left():.0f}s of deadline left")
            from sparkucx_tpu.perf.benchmark import measure_queries

            qr = measure_queries(
                num_apps=4, queries_per_app=4, rows_per_query=2000,
            )
            RESULT["queries"] = {
                "apps": qr["apps"],
                "cold_qps": round(qr["cold_qps"], 2),
                "warm_qps": round(qr["warm_qps"], 2),
                "speedup": round(qr["speedup"], 3),
                "hit_rate": round(qr["hit_rate"], 3),
                "p99_stage_ms": round(qr["p99_stage_ms"], 2),
                "bit_identical": qr["bit_identical"],
            }
        except Exception as e:
            RESULT["queries_error"] = f"{type(e).__name__}: {e}"[:200]

    emit_once()


if __name__ == "__main__":
    main()
