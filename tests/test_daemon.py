"""Tests for the shuffle daemon + client — the JVM-shim protocol surface."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient, DaemonOp, ShuffleDaemon


@pytest.fixture(scope="module")
def daemon():
    d = ShuffleDaemon(
        TpuShuffleConf(staging_capacity_per_executor=1 << 20, num_executors=2),
        num_executors=2,
    )
    yield d
    d.close()


@pytest.fixture
def client(daemon):
    c = DaemonClient(daemon.address)
    yield c
    c.close()


class TestDaemonFlow:
    def test_full_shuffle_through_wire(self, client, rng):
        M, R, SID = 3, 4, 0
        client.create_shuffle(SID, M, R)
        oracle = {}
        for m in range(M):
            w = client.open_map_writer(SID, m)
            for r in range(R):
                payload = rng.integers(0, 256, size=int(rng.integers(1, 3000)), dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                # stream in two chunks to exercise repeated WritePartition
                client.write_partition(w, r, payload[: len(payload) // 2])
                client.write_partition(w, r, payload[len(payload) // 2 :])
            lengths = client.commit_map(w)
            assert lengths.tolist() == [len(oracle[(m, r)]) for r in range(R)]
        stats = client.stats(SID)
        assert stats["num_mappers"] == M and not stats["exchanged"]
        client.run_exchange(SID)
        assert client.stats(SID)["exchanged"]

        bids = [ShuffleBlockId(SID, m, r) for m in range(M) for r in range(R)]
        blocks = client.fetch_blocks(bids)
        for bid, blk in zip(bids, blocks):
            assert blk == oracle[(bid.map_id, bid.reduce_id)]
        client.remove_shuffle(SID)

    def test_error_propagation(self, client):
        with pytest.raises(RuntimeError, match="unknown shuffle|KeyError"):
            client.run_exchange(777)

    def test_fetch_miss_returns_none(self, client):
        client.create_shuffle(1, 1, 1)
        w = client.open_map_writer(1, 0)
        client.write_partition(w, 0, b"only")
        client.commit_map(w)
        client.run_exchange(1)
        [hit, miss] = client.fetch_blocks([ShuffleBlockId(1, 0, 0), ShuffleBlockId(1, 0, 99)])
        assert hit == b"only"
        assert miss is None
        client.remove_shuffle(1)

    def test_two_clients_one_daemon(self, daemon, rng):
        # two executor connections writing different maps of one shuffle
        c1, c2 = DaemonClient(daemon.address), DaemonClient(daemon.address)
        try:
            c1.create_shuffle(2, 2, 2)
            w1 = c1.open_map_writer(2, 0)
            c1.write_partition(w1, 0, b"from-c1")
            c1.commit_map(w1)
            w2 = c2.open_map_writer(2, 1)
            c2.write_partition(w2, 1, b"from-c2")
            c2.commit_map(w2)
            c1.run_exchange(2)
            [a] = c2.fetch_blocks([ShuffleBlockId(2, 0, 0)])
            [b] = c1.fetch_blocks([ShuffleBlockId(2, 1, 1)])
            assert a == b"from-c1" and b == b"from-c2"
            c1.remove_shuffle(2)
        finally:
            c1.close()
            c2.close()

    def test_unknown_op_acks_error(self, daemon):
        import socket
        import struct

        s = socket.create_connection(daemon.address)
        s.sendall(struct.pack("<IQQ", 99, 2, 0) + b"{}")
        hdr = b""
        while len(hdr) < 20:
            hdr += s.recv(20 - len(hdr))
        op, hlen, blen = struct.unpack("<IQQ", hdr)
        payload = b""
        while len(payload) < hlen:
            payload += s.recv(hlen - len(payload))
        assert b'"ok": false' in payload
        s.close()

    def test_hostile_frames_cannot_take_the_daemon_down(self, daemon):
        """Protocol fuzz at the Spark-facing boundary: oversized length
        claims, truncated frames, garbage headers, and random byte storms
        each cost at most their own connection — the daemon keeps serving
        well-formed clients afterwards (the endpoint-eviction policy,
        UcxWorkerWrapper.scala:248-253)."""
        import socket
        import struct

        rng = np.random.default_rng(0)
        hostile = [
            # oversized header+body claim (the _MAX_FRAME guard): must be
            # dropped without streaming terabytes
            struct.pack("<IQQ", DaemonOp.CREATE_SHUFFLE, 1 << 60, 1 << 60),
            # truncated: header promises more bytes than ever arrive
            struct.pack("<IQQ", DaemonOp.CREATE_SHUFFLE, 64, 0) + b"{\"x\"",
            # valid frame layout, unparseable JSON header
            struct.pack("<IQQ", DaemonOp.CREATE_SHUFFLE, 7, 0) + b"not-js}",
            # random byte storm (may parse as a huge claim or garbage op)
            rng.integers(0, 256, size=333, dtype=np.uint8).tobytes(),
            # shorter than one frame header
            b"\x01\x02\x03",
        ]
        for i, frame in enumerate(hostile):
            s = socket.create_connection(daemon.address, timeout=5)
            try:
                s.settimeout(5)
                # the daemon may RST mid-send/shutdown when it drops the
                # connection — that reset IS the expected eviction behavior
                try:
                    s.sendall(frame)
                    s.shutdown(socket.SHUT_WR)
                    while s.recv(4096):  # drain any reply, bounded
                        pass
                except (socket.timeout, OSError):
                    pass
            finally:
                s.close()
            # after each hostile connection, a fresh well-formed client works
            probe = DaemonClient(daemon.address)
            try:
                sid = 900 + i
                probe.create_shuffle(sid, 1, 1)
                w = probe.open_map_writer(sid, 0)
                probe.write_partition(w, 0, b"still-alive")
                probe.commit_map(w)
                probe.run_exchange(sid)
                [blk] = probe.fetch_blocks([ShuffleBlockId(sid, 0, 0)])
                assert blk == b"still-alive"
                probe.remove_shuffle(sid)
            finally:
                probe.close()


class TestGoldenWireFixtures:
    """The jvm/fixtures/*.bin frames are the EXACT bytes the Java shim's
    DaemonClient encodes (FixtureCheck.java re-encodes them in CI).  Here the
    Python side holds up its half of the contract: the generator reproduces
    the committed files bit-for-bit (drift guard), and a live daemon driven by
    the raw fixture bytes executes a full write -> exchange -> fetch cycle."""

    def _gen(self):
        import importlib
        import os
        import sys

        scripts = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
        sys.path.insert(0, scripts)
        try:
            mod = importlib.import_module("gen_shim_fixtures")
            return importlib.reload(mod)
        finally:
            sys.path.remove(scripts)

    def test_fixture_files_match_generator(self):
        import os

        gen = self._gen()
        for name, frame in gen.fixtures().items():
            path = os.path.join(gen.FIXTURE_DIR, name)
            with open(path, "rb") as f:
                assert f.read() == frame, f"fixture {name} drifted — regen + sync FixtureCheck.java"

    def test_daemon_decodes_java_frames_end_to_end(self):
        import os
        import socket
        import struct

        from sparkucx_tpu.shuffle.daemon import _read_frame

        gen = self._gen()
        fx = {n: open(os.path.join(gen.FIXTURE_DIR, n), "rb").read() for n in gen.fixtures()}
        d = ShuffleDaemon(
            TpuShuffleConf(staging_capacity_per_executor=1 << 20, num_executors=1),
            num_executors=1,
        )
        client = DaemonClient(d.address)  # side channel for the non-fixture maps
        raw = socket.create_connection(d.address)

        def send_fixture(name, expect_ok=True):
            raw.sendall(fx[name])
            frame = _read_frame(raw)
            assert frame is not None
            op, meta, body = frame
            if expect_ok:
                assert meta.get("ok") is True, f"{name}: {meta}"
            return meta, body

        try:
            send_fixture("01_create_shuffle.bin")  # shuffle 7: 4 maps x 8 reduces

            # burn writer handles 0-2 so the fixture writer lands on handle 3
            # (the handle baked into 03/04), and give the fetch fixture's maps
            # (0 and 3) real payloads
            burn = [client.open_map_writer(gen.SHUFFLE_ID, m) for m in (0, 1, 3)]
            assert burn == [0, 1, 2]
            payload_m0 = b"\xaa" * 100
            payload_m3 = b"\xbb" * 300
            payload_m1r6 = b"\xcc" * 77  # fixture 09's only reduce-6 block
            client.write_partition(burn[0], gen.REDUCE_ID, payload_m0)
            client.write_partition(burn[1], 6, payload_m1r6)
            client.write_partition(burn[2], gen.REDUCE_ID, payload_m3)

            meta, _ = send_fixture("02_open_map_writer.bin")  # map 2 -> handle 3
            assert meta["writer"] == gen.WRITER

            send_fixture("03_write_partition.bin")  # 256 bytes to reduce 5
            _, commit_body = send_fixture("04_commit_map.bin")
            lengths = np.frombuffer(commit_body, dtype="<i8")
            assert lengths[gen.REDUCE_ID] == len(gen.WRITE_BODY)

            for w in burn:
                client.commit_map(w)

            send_fixture("05_run_exchange.bin")

            def raw_fetch(name):
                raw.sendall(fx[name])
                hdr = b""
                while len(hdr) < 20:
                    hdr += raw.recv(20 - len(hdr))
                _, hlen, blen = struct.unpack("<IQQ", hdr)
                reply_hdr = b""
                while len(reply_hdr) < hlen:
                    reply_hdr += raw.recv(hlen - len(reply_hdr))
                body = b""
                while len(body) < blen:
                    body += raw.recv(blen - len(body))
                tag, count = struct.unpack_from("<QI", reply_hdr)
                sizes = [
                    struct.unpack_from("<q", reply_hdr, 12 + 8 * i)[0] for i in range(count)
                ]
                return tag, count, sizes, body

            # batched fetch exactly as the Java client frames it
            tag, count, sizes, body = raw_fetch("06_fetch.bin")
            assert tag == gen.FETCH_TAG and count == len(gen.FETCH_MAPS)
            assert sizes == [len(payload_m0), len(payload_m3)]
            assert body[: sizes[0]] == payload_m0
            assert body[sizes[0] :] == payload_m3

            # the AQE partial-map read (Spark 3.x startMapIndex/endMapIndex):
            # maps [1, 3) x reduce 5 — map 1 committed nothing there (empty
            # block, size 0), map 2 holds the fixture's 256-byte write
            tag, count, sizes, body = raw_fetch("08_fetch_aqe_maprange.bin")
            assert tag == gen.FETCH_TAG and count == len(gen.AQE_MAPS)
            assert sizes == [0, len(gen.WRITE_BODY)]
            assert body == gen.WRITE_BODY

            # the AQE COALESCED read (09): reduce range 5..6 across EVERY
            # mapper — present and empty cells mixed; empties must answer
            # size 0 (a real committed-empty block), never -1 (a miss)
            tag, count, sizes, body = raw_fetch("09_fetch_coalesced_empty.bin")
            assert tag == gen.FETCH_TAG and count == len(gen.COALESCE_MAPS)
            assert sizes == [
                len(payload_m0), 0,              # map 0: r5 block, r6 empty
                0, len(payload_m1r6),            # map 1: r5 empty, r6 block
                len(gen.WRITE_BODY), 0,          # map 2: the fixture write
                len(payload_m3), 0,              # map 3
            ]
            assert body == payload_m0 + payload_m1r6 + gen.WRITE_BODY + payload_m3

            send_fixture("07_remove_shuffle.bin")
            with pytest.raises(RuntimeError):
                client.stats(gen.SHUFFLE_ID)
        finally:
            raw.close()
            client.close()
            d.close()


class TestErrorEdges:
    """The error/edge wire paths the first eight fixtures skipped
    (VERDICT r4 item 6): oversized-frame rejection and daemon restart
    mid-job."""

    def test_oversized_frame_drops_connection_daemon_survives(self):
        import socket

        gen = TestGoldenWireFixtures._gen(self)
        import os

        oversized = open(
            os.path.join(gen.FIXTURE_DIR, "10_oversized_frame.bin"), "rb"
        ).read()
        d = ShuffleDaemon(
            TpuShuffleConf(staging_capacity_per_executor=1 << 18, num_executors=1),
            num_executors=1,
        )
        try:
            raw = socket.create_connection(d.address)
            raw.sendall(oversized)
            raw.settimeout(10)
            # the daemon must refuse BEFORE reading/allocating the 2 GiB body:
            # this connection is dropped (endpoint-eviction policy)
            assert raw.recv(1) == b"", "daemon accepted an oversized frame"
            raw.close()
            # ...and keeps serving new connections
            c = DaemonClient(d.address)
            c.create_shuffle(55, 1, 1)
            w = c.open_map_writer(55, 0)
            c.write_partition(w, 0, b"alive")
            c.commit_map(w)
            c.run_exchange(55)
            [blk] = c.fetch_blocks([ShuffleBlockId(55, 0, 0)])
            assert blk == b"alive"
            c.close()
        finally:
            d.close()

    def test_daemon_restart_mid_job(self, rng):
        """Kill the daemon after a partial map stage; a fresh daemon on a new
        port serves the re-run job from clean state — the task-retry
        discipline the reference never had (SURVEY §5.3: it only logs)."""
        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18, num_executors=1)
        d1 = ShuffleDaemon(conf, num_executors=1)
        c1 = DaemonClient(d1.address)
        c1.create_shuffle(9, 2, 2)
        w = c1.open_map_writer(9, 0)
        c1.write_partition(w, 0, b"lost-on-restart")
        c1.commit_map(w)  # map 0 committed; map 1 never runs
        d1.close()  # daemon dies mid-job
        c1.close()

        # driver-side retry: fresh daemon, SAME shuffle id, full re-run
        d2 = ShuffleDaemon(conf, num_executors=1)
        c2 = DaemonClient(d2.address)
        try:
            c2.create_shuffle(9, 2, 2)  # no stale state: re-create succeeds
            oracle = {}
            for m in range(2):
                w = c2.open_map_writer(9, m)
                for r in range(2):
                    payload = rng.integers(0, 256, size=200, dtype=np.uint8).tobytes()
                    oracle[(m, r)] = payload
                    c2.write_partition(w, r, payload)
                c2.commit_map(w)
            c2.run_exchange(9)
            bids = [ShuffleBlockId(9, m, r) for m in range(2) for r in range(2)]
            for bid, blk in zip(bids, c2.fetch_blocks(bids)):
                assert blk == oracle[(bid.map_id, bid.reduce_id)]
        finally:
            c2.close()
            d2.close()
