"""Tests for the shuffle daemon + client — the JVM-shim protocol surface."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.shuffle.daemon import DaemonClient, ShuffleDaemon


@pytest.fixture(scope="module")
def daemon():
    d = ShuffleDaemon(
        TpuShuffleConf(staging_capacity_per_executor=1 << 20, num_executors=2),
        num_executors=2,
    )
    yield d
    d.close()


@pytest.fixture
def client(daemon):
    c = DaemonClient(daemon.address)
    yield c
    c.close()


class TestDaemonFlow:
    def test_full_shuffle_through_wire(self, client, rng):
        M, R, SID = 3, 4, 0
        client.create_shuffle(SID, M, R)
        oracle = {}
        for m in range(M):
            w = client.open_map_writer(SID, m)
            for r in range(R):
                payload = rng.integers(0, 256, size=int(rng.integers(1, 3000)), dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                # stream in two chunks to exercise repeated WritePartition
                client.write_partition(w, r, payload[: len(payload) // 2])
                client.write_partition(w, r, payload[len(payload) // 2 :])
            lengths = client.commit_map(w)
            assert lengths.tolist() == [len(oracle[(m, r)]) for r in range(R)]
        stats = client.stats(SID)
        assert stats["num_mappers"] == M and not stats["exchanged"]
        client.run_exchange(SID)
        assert client.stats(SID)["exchanged"]

        bids = [ShuffleBlockId(SID, m, r) for m in range(M) for r in range(R)]
        blocks = client.fetch_blocks(bids)
        for bid, blk in zip(bids, blocks):
            assert blk == oracle[(bid.map_id, bid.reduce_id)]
        client.remove_shuffle(SID)

    def test_error_propagation(self, client):
        with pytest.raises(RuntimeError, match="unknown shuffle|KeyError"):
            client.run_exchange(777)

    def test_fetch_miss_returns_none(self, client):
        client.create_shuffle(1, 1, 1)
        w = client.open_map_writer(1, 0)
        client.write_partition(w, 0, b"only")
        client.commit_map(w)
        client.run_exchange(1)
        [hit, miss] = client.fetch_blocks([ShuffleBlockId(1, 0, 0), ShuffleBlockId(1, 0, 99)])
        assert hit == b"only"
        assert miss is None
        client.remove_shuffle(1)

    def test_two_clients_one_daemon(self, daemon, rng):
        # two executor connections writing different maps of one shuffle
        c1, c2 = DaemonClient(daemon.address), DaemonClient(daemon.address)
        try:
            c1.create_shuffle(2, 2, 2)
            w1 = c1.open_map_writer(2, 0)
            c1.write_partition(w1, 0, b"from-c1")
            c1.commit_map(w1)
            w2 = c2.open_map_writer(2, 1)
            c2.write_partition(w2, 1, b"from-c2")
            c2.commit_map(w2)
            c1.run_exchange(2)
            [a] = c2.fetch_blocks([ShuffleBlockId(2, 0, 0)])
            [b] = c1.fetch_blocks([ShuffleBlockId(2, 1, 1)])
            assert a == b"from-c1" and b == b"from-c2"
            c1.remove_shuffle(2)
        finally:
            c1.close()
            c2.close()

    def test_unknown_op_acks_error(self, daemon):
        import socket
        import struct

        s = socket.create_connection(daemon.address)
        s.sendall(struct.pack("<IQQ", 99, 2, 0) + b"{}")
        hdr = b""
        while len(hdr) < 20:
            hdr += s.recv(20 - len(hdr))
        op, hlen, blen = struct.unpack("<IQQ", hdr)
        payload = b""
        while len(payload) < hlen:
            payload += s.recv(hlen - len(payload))
        assert b'"ok": false' in payload
        s.close()
