"""Tests for L0 contracts: block ids, memory blocks, operations, wire frames, config."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf, parse_size
from sparkucx_tpu.core.block import (
    Block,
    BytesBlock,
    FileBackedBlock,
    MemoryBlock,
    ShuffleBlockId,
)
from sparkucx_tpu.core.definitions import (
    FRAME_HEADER_SIZE,
    AmId,
    MapperInfo,
    pack_fetch_req,
    pack_frame,
    unpack_fetch_req,
    unpack_frame_header,
)
from sparkucx_tpu.core.operation import (
    OperationResult,
    OperationStats,
    OperationStatus,
    Request,
)


class TestShuffleBlockId:
    def test_roundtrip(self):
        bid = ShuffleBlockId(3, 17, 42)
        data = bid.serialize()
        assert len(data) == bid.serialized_size() == 12
        assert ShuffleBlockId.deserialize(data) == bid

    def test_ordering_and_hash(self):
        a, b = ShuffleBlockId(0, 1, 2), ShuffleBlockId(0, 1, 3)
        assert a < b
        assert len({a, b, ShuffleBlockId(0, 1, 2)}) == 2

    def test_negative_ids_roundtrip(self):
        bid = ShuffleBlockId(-1, 0, 5)
        assert ShuffleBlockId.deserialize(bid.serialize()) == bid


class TestMemoryBlock:
    def test_host_view_and_close_hook(self):
        closed = []
        mb = MemoryBlock(np.arange(16, dtype=np.uint8), size=10, _on_close=closed.append)
        assert mb.host_view().tolist() == list(range(10))
        mb.close()
        mb.close()  # idempotent
        assert len(closed) == 1

    def test_to_bytes(self):
        mb = MemoryBlock(np.arange(8, dtype=np.uint8), size=4)
        assert mb.to_bytes() == bytes([0, 1, 2, 3])


class TestBlocks:
    def test_bytes_block(self):
        blk = BytesBlock(b"hello world")
        out = np.zeros(blk.get_size(), dtype=np.uint8)
        blk.get_block(out)
        assert out.tobytes() == b"hello world"

    def test_get_memory_block_default(self):
        # The reference stubs this as ??? (ShuffleTransport.scala:43); ours works.
        mb = BytesBlock(b"abc").get_memory_block()
        assert mb.to_bytes() == b"abc"

    def test_file_backed_block(self, tmp_path):
        p = tmp_path / "data.bin"
        p.write_bytes(b"0123456789")
        blk = FileBackedBlock(str(p), offset=2, length=5)
        out = np.zeros(5, dtype=np.uint8)
        blk.get_block(out)
        assert out.tobytes() == b"23456"
        # zero-copy serving view: a read-only mmap of just the segment,
        # created once (the peer server sends straight from the page cache)
        view = blk.memory_view()
        assert view.tobytes() == b"23456" and not view.flags.writeable
        assert blk.memory_view() is view  # cached, not re-mapped per fetch

    def test_file_backed_block_close_releases_mapping(self, tmp_path):
        """close() must release the cached mmap's fd NOW (unregistration used
        to just drop the registry entry, leaking one fd per served spill
        segment for the life of the process) and stay reusable after."""
        p = tmp_path / "data.bin"
        p.write_bytes(b"0123456789")
        blk = FileBackedBlock(str(p), offset=0, length=10)
        view = blk.memory_view()
        mapping = view._mmap  # the mmap.mmap owning the fd
        assert not mapping.closed
        del view
        blk.close()
        assert mapping.closed, "close() left the mapping (and its fd) open"
        blk.close()  # idempotent
        # the block is still servable: a fresh mapping is created on demand
        assert blk.memory_view().tobytes() == b"0123456789"
        # with an exported view alive, close() defers to GC instead of raising
        survivor = blk.memory_view()
        blk.close()
        assert survivor.tobytes() == b"0123456789"

    def test_unregister_closes_file_backed_blocks(self, tmp_path):
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.transport.tpu import TpuShuffleCluster

        cluster = TpuShuffleCluster(TpuShuffleConf(num_executors=1), num_executors=1)
        t = cluster.transport(0)
        p = tmp_path / "seg.bin"
        p.write_bytes(b"x" * 64)
        blk = FileBackedBlock(str(p), offset=0, length=64)
        bid = ShuffleBlockId(7, 0, 0)
        t.register(bid, blk)
        view = blk.memory_view()
        mapping = view._mmap
        del view
        t.unregister(bid)
        assert mapping.closed, "unregister left the block's mmap open"

    def test_file_backed_block_arbitrary_offset_and_empty(self, tmp_path):
        p = tmp_path / "odd.bin"
        payload = bytes(range(256)) * 40
        p.write_bytes(payload)
        # offsets far from any page boundary must still map correctly
        blk = FileBackedBlock(str(p), offset=4097, length=300)
        assert blk.memory_view().tobytes() == payload[4097 : 4097 + 300]
        empty = FileBackedBlock(str(p), offset=8, length=0)
        assert empty.memory_view().size == 0


class TestRequest:
    def test_complete_and_wait(self):
        req = Request()
        req.complete(OperationResult(OperationStatus.SUCCESS))
        assert req.completed()
        assert req.wait(timeout=1).status == OperationStatus.SUCCESS

    def test_poll_drives_completion(self):
        req = Request()
        state = {"calls": 0}

        def poll():
            state["calls"] += 1
            if state["calls"] >= 3:
                req.complete(OperationResult(OperationStatus.SUCCESS))
                return True
            return False

        req.attach_poll(poll)
        assert not req.completed()
        assert not req.completed()
        assert req.completed()
        assert state["calls"] == 3

    def test_cancel(self):
        req = Request()
        req.cancel()
        assert req.is_cancelled()
        assert req.wait().status == OperationStatus.CANCELED

    def test_stats_elapsed(self):
        stats = OperationStats()
        stats.mark_done(recv_size=128)
        assert stats.recv_size == 128
        assert stats.elapsed_ns() >= 0


class TestWireFrames:
    def test_frame_roundtrip(self):
        frame = pack_frame(AmId.FETCH_BLOCK_REQ, b"hdr", b"body!")
        am, hlen, blen = unpack_frame_header(frame)
        assert am == AmId.FETCH_BLOCK_REQ
        assert frame[FRAME_HEADER_SIZE : FRAME_HEADER_SIZE + hlen] == b"hdr"
        assert frame[FRAME_HEADER_SIZE + hlen :] == b"body!"
        assert blen == 5

    def test_fetch_req_roundtrip(self):
        assert unpack_fetch_req(pack_fetch_req(1, 2, 3)) == (1, 2, 3)

    def test_mapper_info_roundtrip(self):
        mi = MapperInfo(shuffle_id=7, map_id=3, partitions=((0, 100), (128, 50), (256, 0)))
        assert MapperInfo.unpack(mi.pack()) == mi

    def test_am_ids_match_reference(self):
        # 0-4: Definitions.scala:22-29 verbatim.  5-6: striped-wire extensions
        # (FetchBlockChunk / WireHello, docs/SHIM_PROTOCOL.md), 7-8:
        # replication extensions (ReplicaPut / ReplicaAck), 9-10: membership
        # gossip (MemberSuspect / MemberRejoin), 11-12: observability pulls
        # (TracePull / MetricsPull), 13: accept-backlog shed (ServerBusy),
        # 14: hot-holder advertisement (HotSetPull) — peer plane only, never
        # emitted at wire.streams=1 / replication.factor=0 / elastic off /
        # server.acceptBacklog=0 / serve.hotThresholdFetchesPerSec=0 with no
        # export/scrape call, so reference parity holds for every frame a
        # stock deployment sees.
        #
        # The pin list is generated from the SOURCE of core/definitions.py by
        # the analyzer's wire-schema extractor, then cross-checked against the
        # runtime enum: a new AmId cannot land without showing up here AND in
        # SHIM_PROTOCOL.md (the wire-schema pass gates the doc side in CI).
        import inspect

        from sparkucx_tpu.analysis.protocol import extract_am_ids
        from sparkucx_tpu.core import definitions

        extracted = extract_am_ids(inspect.getsource(definitions))
        assert extracted == {a.name: int(a) for a in AmId}
        assert sorted(extracted.values()) == list(range(15))
        assert AmId.FETCH_BLOCK_CHUNK == 5 and AmId.WIRE_HELLO == 6
        assert AmId.REPLICA_PUT == 7 and AmId.REPLICA_ACK == 8
        assert AmId.MEMBER_SUSPECT == 9 and AmId.MEMBER_REJOIN == 10
        assert AmId.TRACE_PULL == 11 and AmId.METRICS_PULL == 12
        assert AmId.SERVER_BUSY == 13
        assert AmId.HOT_SET_PULL == 14


class TestConf:
    def test_parse_size(self):
        assert parse_size("4k") == 4096
        assert parse_size("1m") == 1 << 20
        assert parse_size("30MB") == 30 << 20
        assert parse_size(512) == 512
        with pytest.raises(ValueError):
            parse_size("nope")

    def test_defaults_match_reference(self):
        c = TpuShuffleConf()
        assert c.min_buffer_size == 4096  # UcxShuffleConf.scala:33-39
        assert c.min_allocation_size == 1 << 20  # :41-48
        assert c.max_blocks_per_request == 50  # :88-93
        assert c.num_io_threads == 1  # :66-71
        assert c.use_wakeup is True  # :58-64
        assert c.store_port == 1338  # CommonUcxShuffleManager.scala:84-89
        assert c.serve_from_store is True  # UcxShuffleBlockResolver.scala:86

    def test_from_spark_conf(self):
        c = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.memory.preAllocateBuffers": "4k:16,1m:4",
                "spark.shuffle.tpu.memory.minBufferSize": "8k",
                "spark.shuffle.tpu.listener.sockaddr": "127.0.0.1:4242",
                "spark.shuffle.tpu.maxBlocksPerRequest": "10",
                "spark.shuffle.tpu.numExecutors": "8",
                "spark.executor.cores": "4",
            }
        )
        assert c.prealloc_buffers == {4096: 16, 1 << 20: 4}
        assert c.min_buffer_size == 8192
        assert c.listener_address == ("127.0.0.1", 4242)
        assert c.max_blocks_per_request == 10
        assert c.num_executors == 8
        assert c.num_client_workers == 4  # falls back to spark.executor.cores

    def test_from_spark_conf_sizes_and_service_knobs(self):
        # Parse/convert coverage for every knob the conf-registry analyzer
        # pass tracks that the round-trip test above doesn't touch: size
        # suffixes, ms durations, and the service-plane integers.
        c = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.numListenerThreads": "5",
                "spark.shuffle.tpu.wire.creditBytes": "32m",
                "spark.shuffle.tpu.wire.sockBufBytes": "8m",
                "spark.shuffle.tpu.membership.suspectAfterMs": "250",
                "spark.shuffle.tpu.tenants.hbmQuotaBytes": "16m",
                "spark.shuffle.tpu.eviction.epochMs": "1000",
            }
        )
        assert c.num_listener_threads == 5
        assert c.wire_credit_bytes == 32 << 20
        assert c.wire_sock_buf_bytes == 8 << 20
        assert c.membership_suspect_after_ms == 250
        assert c.tenant_hbm_quota_bytes == 16 << 20
        assert c.eviction_epoch_ms == 1000

    def test_validation(self):
        with pytest.raises(ValueError):
            TpuShuffleConf(block_alignment=100).validate()
        with pytest.raises(ValueError):
            TpuShuffleConf().replace(max_blocks_per_request=0)
