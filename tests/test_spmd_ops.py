"""Multi-controller device ops: two OS processes (2 CPU devices each) run the
distributed sort as one SPMD program over the 4-device global mesh.

test_spmd.py proves the byte shuffle is multi-controller; this proves the
device-resident *workloads* (ops/sort.py and, by the same construction,
columnar/relational/tc) are too — the jitted step is plain SPMD over a global
mesh, so the only multi-host-specific code is array construction from
process-local shards."""

import os
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {root!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    pid = int(sys.argv[1]); coord = sys.argv[2]
    from sparkucx_tpu.ops._compat import enable_cpu_cross_process_collectives
    enable_cpu_cross_process_collectives()
    jax.distributed.initialize(coord, num_processes=2, process_id=pid)
    assert len(jax.devices()) == 4, jax.devices()

    from sparkucx_tpu.ops.sort import SortSpec, build_distributed_sort

    N_EXEC, CAP = 4, 512
    mesh = Mesh(np.array(jax.devices()), ("ex",))
    spec = SortSpec(
        num_executors=N_EXEC, capacity=CAP, recv_capacity=2 * CAP, width=2,
        impl="dense",
    )
    fn = build_distributed_sort(mesh, spec)

    # both processes generate the SAME global input; each contributes only its
    # process-local shards
    rng = np.random.default_rng(99)
    keys = rng.integers(0, 1 << 31, size=N_EXEC * CAP, dtype=np.uint32)
    payload = rng.integers(-100, 100, size=(N_EXEC * CAP, 2), dtype=np.int32)
    nv = np.full(N_EXEC, CAP, np.int32)

    key_sh = NamedSharding(mesh, P("ex"))
    row_sh = NamedSharding(mesh, P("ex", None))
    gkeys = jax.make_array_from_process_local_data(key_sh, keys[pid * 2 * CAP : (pid + 1) * 2 * CAP])
    gpay = jax.make_array_from_process_local_data(row_sh, payload[pid * 2 * CAP : (pid + 1) * 2 * CAP])
    gnv = jax.make_array_from_process_local_data(key_sh, nv[pid * 2 : (pid + 1) * 2])

    out_keys, out_pay, counts = fn(gkeys, gpay, gnv)

    from jax.experimental import multihost_utils
    all_counts = np.asarray(multihost_utils.process_allgather(counts, tiled=True))
    assert all_counts.sum() == N_EXEC * CAP, all_counts
    bounds = np.concatenate([[0], np.cumsum(all_counts)])
    oracle_keys = np.sort(keys)

    # each process verifies ITS local output shards against the oracle range
    checked = 0
    for shard in out_keys.addressable_shards:
        j = shard.index[0].start // (2 * CAP)  # global executor of this shard
        got = np.asarray(shard.data)[: all_counts[j]]
        want = oracle_keys[bounds[j] : bounds[j + 1]]
        assert np.array_equal(got, want), f"shard {{j}} keys mismatch"
        checked += 1
    assert checked == 2, checked
    print(f"CHILD_PASS pid={{pid}} shards={{checked}}", flush=True)
    """
)


CHILD_COMBINE = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {root!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from dataclasses import replace
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    pid = int(sys.argv[1]); coord = sys.argv[2]
    from sparkucx_tpu.ops._compat import enable_cpu_cross_process_collectives
    enable_cpu_cross_process_collectives()
    jax.distributed.initialize(coord, num_processes=2, process_id=pid)
    assert len(jax.devices()) == 4, jax.devices()

    from sparkucx_tpu.ops.relational import AggregateSpec, build_grouped_aggregate

    N_EXEC, CAP = 4, 256
    mesh = Mesh(np.array(jax.devices()), ("ex",))
    spec = AggregateSpec(
        num_executors=N_EXEC, capacity=CAP, recv_capacity=CAP,
        aggs=("sum", "min", "max"), partial=True,
        combine="dense", combine_groups=64,
    )
    # both planes must derive the SAME plan/tier in lockstep: the spec is
    # static and identical in every process, the bodies are pure SPMD
    fused = build_grouped_aggregate(mesh, spec)
    unfused = build_grouped_aggregate(mesh, replace(spec, combine="off"))

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 60, size=N_EXEC * CAP).astype(np.uint32)
    vals = rng.integers(-100, 100, size=(N_EXEC * CAP, 3)).astype(np.int32)
    nv = np.full(N_EXEC, CAP, np.int32)

    key_sh = NamedSharding(mesh, P("ex"))
    row_sh = NamedSharding(mesh, P("ex", None))
    lo, hi = pid * 2 * CAP, (pid + 1) * 2 * CAP
    args = (
        jax.make_array_from_process_local_data(key_sh, keys[lo:hi]),
        jax.make_array_from_process_local_data(row_sh, vals[lo:hi]),
        jax.make_array_from_process_local_data(key_sh, nv[pid * 2 : (pid + 1) * 2]),
    )

    from jax.experimental import multihost_utils
    got = [
        np.asarray(multihost_utils.process_allgather(o, tiled=True))
        for o in fused(*args)
    ]
    ref = [
        np.asarray(multihost_utils.process_allgather(o, tiled=True))
        for o in unfused(*args)
    ]
    for a, b in zip(ref, got):
        assert a.tobytes() == b.tobytes(), "fused != unfused over 2 processes"
    assert got[3].sum() == 60, got[3]  # 60 distinct keys across all shards
    print(f"CHILD_PASS pid={{pid}} groups={{int(got[3].sum())}}", flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_spmd_sort():
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_two_process_spmd_fused_combine():
    """The compute-in-exchange aggregate over TWO OS PROCESSES: the fused
    ring fold runs as lockstep SPMD collectives (same static spec -> same
    tier in every process) and reproduces the unfused bytes exactly."""
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    script = CHILD_COMBINE.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
