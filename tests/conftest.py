"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-executor sharding/collectives are
exercised without TPU hardware (the env vars must be set before jax imports).  This
is the unit-test scaffolding the reference never had (SURVEY.md section 4: "There are
no unit tests"); the loopback transport plays the role its ShuffleTransport trait was
designed for ("standalone testing purpose", ShuffleTransport.scala:124-128).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# A sitecustomize hook may have pinned jax_platforms to a hardware backend at
# interpreter start (overriding the env var); force the CPU mesh for tests.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
