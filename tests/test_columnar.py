"""Tests for the device-resident columnar shuffle (GpuColumnarExchange analogue)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.columnar import (
    ColumnarSpec,
    build_columnar_shuffle,
    owners_from_partitions,
)
from sparkucx_tpu.ops.exchange import make_mesh

N = 8
CAP = 64
W = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


@pytest.fixture(scope="module")
def fn(mesh):
    spec = ColumnarSpec(
        num_executors=N, capacity=CAP, recv_capacity=N * CAP, width=W,
        dtype=np.dtype(np.float32), impl="dense",
    )
    return build_columnar_shuffle(mesh, spec)


def _place(mesh, rows, owners):
    return (
        jax.device_put(rows, NamedSharding(mesh, P("ex", None))),
        jax.device_put(owners, NamedSharding(mesh, P("ex"))),
    )


def _oracle(rows, owners, n, cap):
    """Receiver j's rows: sender-major, each sender's rows in original order."""
    out = {j: [] for j in range(n)}
    for i in range(n):
        for k in range(cap):
            dest = owners[i * cap + k]
            if 0 <= dest < n:
                out[dest].append(rows[i * cap + k])
    return out


class TestColumnarShuffle:
    def test_random_vs_oracle(self, mesh, fn, rng):
        rows = rng.normal(size=(N * CAP, W)).astype(np.float32)
        owners = rng.integers(0, N, size=N * CAP).astype(np.int32)
        recv, counts = fn(*_place(mesh, rows, owners))
        recv, counts = np.asarray(recv), np.asarray(counts)
        expected = _oracle(rows, owners, N, CAP)
        for j in range(N):
            total = int(counts[j].sum())
            got = recv[j * fn.spec.recv_capacity : j * fn.spec.recv_capacity + total]
            want = np.stack(expected[j]) if expected[j] else np.zeros((0, W), np.float32)
            assert got.shape == want.shape
            assert np.array_equal(got, want), f"receiver {j}"

    def test_padding_rows_not_sent(self, mesh, fn, rng):
        rows = rng.normal(size=(N * CAP, W)).astype(np.float32)
        owners = np.full(N * CAP, N, dtype=np.int32)  # all padding
        owners[5] = 3
        recv, counts = fn(*_place(mesh, rows, owners))
        counts = np.asarray(counts)
        assert counts.sum() == 1
        got = np.asarray(recv)[3 * fn.spec.recv_capacity]
        assert np.array_equal(got, rows[5])

    def test_skew_all_to_one(self, mesh, fn, rng):
        rows = rng.normal(size=(N * CAP, W)).astype(np.float32)
        owners = np.zeros(N * CAP, dtype=np.int32)  # everything to executor 0
        recv, counts = fn(*_place(mesh, rows, owners))
        counts = np.asarray(counts)
        assert counts[0].sum() == N * CAP
        got = np.asarray(recv)[: N * CAP]
        expected = _oracle(rows, owners, N, CAP)[0]
        assert np.array_equal(got, np.stack(expected))

    def test_jit_reuse_no_retrace(self, mesh, fn, rng):
        for _ in range(3):
            rows = rng.normal(size=(N * CAP, W)).astype(np.float32)
            owners = rng.integers(0, N, size=N * CAP).astype(np.int32)
            recv, counts = fn(*_place(mesh, rows, owners))
            assert int(np.asarray(counts).sum()) == N * CAP

    def test_ragged_lowering(self, mesh):
        from sparkucx_tpu.ops._compat import HAS_RAGGED_ALL_TO_ALL

        if not HAS_RAGGED_ALL_TO_ALL:
            pytest.skip("jax.lax.ragged_all_to_all absent on this JAX (< 0.5)")
        spec = ColumnarSpec(
            num_executors=N, capacity=CAP, recv_capacity=N * CAP, width=W, impl="ragged"
        )
        f = build_columnar_shuffle(mesh, spec)
        rows = jax.ShapeDtypeStruct((N * CAP, W), np.float32)
        owners = jax.ShapeDtypeStruct((N * CAP,), np.int32)
        text = f.lower(rows, owners).as_text()
        assert "ragged_all_to_all" in text or "ragged-all-to-all" in text


class TestOwnersFromPartitions:
    def test_contiguous_ranges_match_store(self):
        from sparkucx_tpu.store.hbm_store import default_peer_ranges

        R, n = 10, 4
        ranges = default_peer_ranges(R, n)
        pids = jnp.arange(R, dtype=jnp.int32)
        owners = np.asarray(owners_from_partitions(pids, R, n))
        for p, (s, e) in enumerate(ranges):
            for r in range(s, e):
                assert owners[r] == p

    def test_padding_maps_to_n(self):
        pids = jnp.array([-1, 0, 5, 99], dtype=jnp.int32)
        owners = np.asarray(owners_from_partitions(pids, 6, 3))
        assert owners[0] == 3 and owners[3] == 3
        assert 0 <= owners[1] < 3 and 0 <= owners[2] < 3


class TestRunColumnarShuffle:
    """Overflow-retry wrapper for device-resident repartitioning."""

    def test_skewed_destinations_trigger_retry(self, rng):
        from sparkucx_tpu.ops.columnar import ColumnarSpec, run_columnar_shuffle
        from sparkucx_tpu.ops.exchange import make_mesh

        n, cap = 4, 256
        mesh = make_mesh(n)
        rows = rng.normal(size=(n * cap, 4)).astype(np.float32)
        owners = np.zeros(n * cap, np.int32)  # everything to executor 0
        spec = ColumnarSpec(
            num_executors=n, capacity=cap, recv_capacity=cap, width=4, impl="dense"
        )
        recv, counts = run_columnar_shuffle(mesh, spec, rows, owners)
        per_dest = np.asarray(counts).sum(axis=1)
        assert per_dest[0] == n * cap and per_dest[1:].sum() == 0
        got = np.asarray(recv)[: n * cap]
        assert sorted(map(tuple, got)) == sorted(map(tuple, rows))

    def test_no_retry_when_balanced(self, rng):
        from sparkucx_tpu.ops.columnar import ColumnarSpec, run_columnar_shuffle
        from sparkucx_tpu.ops.exchange import make_mesh

        n, cap = 4, 64
        mesh = make_mesh(n)
        rows = rng.normal(size=(n * cap, 2)).astype(np.float32)
        owners = (np.arange(n * cap) % n).astype(np.int32)
        spec = ColumnarSpec(
            num_executors=n, capacity=cap, recv_capacity=2 * cap, width=2, impl="dense"
        )
        recv, counts = run_columnar_shuffle(mesh, spec, rows, owners)
        assert int(np.asarray(counts).sum()) == n * cap


class TestGatherRowsBandChunking:
    """gather_rows chunks lane widths in the empirically slow XLA band
    (25..32 on v5e) into <=24-lane gathers; results must be bit-identical to
    the plain gather at every width."""

    def test_equivalence_across_widths(self):
        from sparkucx_tpu.ops.exchange import SLOW_GATHER_LANES, gather_rows

        rng = np.random.default_rng(0)
        idx = rng.permutation(257).astype(np.int32)
        for w in (1, 8, 24, 25, 31, 32, 33, 100):
            rows = rng.normal(size=(257, w)).astype(np.float32)
            got = np.asarray(jax.jit(gather_rows)(rows, idx))
            np.testing.assert_array_equal(got, rows[idx], err_msg=f"width {w}")
        lo, hi = SLOW_GATHER_LANES
        assert lo <= 32 <= hi  # the measured-slow width stays covered
