"""Device staging rounds (conf.device_staging): map output written as device
arrays, placed into HBM staging by the block-scatter kernel at seal, with no
host round trip.

The core check is bit-identity against the host-path oracle: the SAME payload
stream written via ``write_partition_device`` and via the host ``MapWriter``
must produce identical MapperInfo offset tables and identical post-exchange
bytes, for every host_recv_mode and for 1- and 8-executor meshes.  Alongside:
the no-host-round-trip guarantee (the host staging buffer is never allocated
for device rounds), uneven multi-round D2H rollover, the writer-layer conf
gate, the sealed-round geometry validation, and the reader's zero-copy block
views that the device path's consumers rely on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.shuffle.reader import (
    BlockFetchResult,
    TpuShuffleReader,
    serialize_records,
)
from sparkucx_tpu.shuffle.writer import DeviceMapWriter, TpuShuffleMapOutputWriter
from sparkucx_tpu.store.hbm_store import HbmBlockStore
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

ALIGN = 128
LANE = ALIGN // 4


def _rows_for(payload: bytes):
    """Bytes -> the device write unit: a (rows, lane) int32 array, one row per
    ``ALIGN`` bytes, zero-padded tail."""
    padded = -(-len(payload) // ALIGN) * ALIGN
    buf = np.zeros(padded, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    return jnp.asarray(buf.view(np.int32).reshape(-1, LANE))


def _conf(device: bool, n: int, cap: int, mode: str = "array") -> TpuShuffleConf:
    return TpuShuffleConf(
        staging_capacity_per_executor=cap,
        block_alignment=ALIGN,
        num_executors=n,
        device_staging=device,
        gather_impl="xla",
        host_recv_mode=mode,
        keep_device_recv=(mode == "device"),
    )


def _exchange(device: bool, n: int, M: int, R: int, cap: int, mode: str = "array"):
    """Write rng(7) payloads (0-3000 bytes, uneven) through the chosen path,
    commit, exchange.  Same seed both paths -> byte-identical input stream."""
    cluster = TpuShuffleCluster(_conf(device, n, cap, mode), num_executors=n)
    meta = cluster.create_shuffle(0, M, R)
    rng = np.random.default_rng(7)
    oracle, infos = {}, {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(
                0, 256, size=int(rng.integers(0, 3000)), dtype=np.uint8
            ).tobytes()
            oracle[(m, r)] = payload
            if device:
                w.write_partition_device(r, _rows_for(payload), length=len(payload))
            else:
                w.write_partition(r, payload)
        info = w.commit()
        infos[m] = info
        t.commit_block(info.pack())
    cluster.run_exchange(0)
    return cluster, meta, oracle, infos


class TestDeviceWriteBitIdentity:
    """Device writes vs the host MapWriter oracle: same blocks, same MapperInfo
    offsets, same post-exchange bytes."""

    @pytest.mark.parametrize(
        "mode,n",
        [("array", 1), ("array", 8), ("memmap", 4), ("device", 4)],
    )
    def test_post_exchange_bytes_match_host_path(self, mode, n):
        M = R = 8
        host_c, host_meta, oracle, host_infos = _exchange(False, n, M, R, 1 << 20, mode)
        dev_c, dev_meta, _, dev_infos = _exchange(True, n, M, R, 1 << 20, mode)
        for m in range(M):
            assert dev_infos[m].partitions == host_infos[m].partitions, m
        for m in range(M):
            for r in range(R):
                consumer = dev_meta.owner_of_reduce(r)
                h_view, h_len = host_c.locate_received_block(consumer, 0, m, r)
                d_view, d_len = dev_c.locate_received_block(consumer, 0, m, r)
                assert d_len == h_len == len(oracle[(m, r)])
                assert bytes(d_view) == bytes(h_view) == oracle[(m, r)]

    @pytest.mark.parametrize("n", [1, 8])
    def test_host_staging_never_allocated(self, n):
        dev_c, dev_meta, *_ = _exchange(True, n, 8, 8, 1 << 20)
        for e in range(n):
            assert not dev_c.transport(e).store.host_staging_allocated(0)
        host_c, host_meta, *_ = _exchange(False, n, 8, 8, 1 << 20)
        writers = {host_meta.map_owner[m] for m in range(8)}
        assert all(host_c.transport(e).store.host_staging_allocated(0) for e in writers)

    def test_uneven_multi_round_rollover(self):
        # cap=16384 with ~12KB of uneven payloads per mapper forces D2H
        # rollovers mid-write; rounds must reassemble bit-identically and the
        # host staging buffer must STILL never be allocated (rollover snapshots
        # are standalone D2H copies, not the staging buffer)
        n, M, R, cap = 2, 4, 4, 8192
        host_c, _, oracle, host_infos = _exchange(False, n, M, R, cap)
        dev_c, dev_meta, _, dev_infos = _exchange(True, n, M, R, cap)
        assert dev_c.transport(0).store.num_rounds(0) >= 2
        for m in range(M):
            assert dev_infos[m].partitions == host_infos[m].partitions
        for m in range(M):
            for r in range(R):
                consumer = dev_meta.owner_of_reduce(r)
                d_view, d_len = dev_c.locate_received_block(consumer, 0, m, r)
                assert bytes(d_view) == oracle[(m, r)]
        for e in range(n):
            assert not dev_c.transport(e).store.host_staging_allocated(0)


def _standalone_store(device_staging: bool = True) -> HbmBlockStore:
    store = HbmBlockStore(_conf(device_staging, 1, 1 << 20), device=jax.devices()[0])
    store.create_shuffle(0, 1, 4)
    return store


class TestSealPayloads:
    def test_seal_returns_device_arrays_no_host_round_trip(self):
        store = _standalone_store()
        w = store.map_writer(0, 0)
        w.write_partition_device(0, _rows_for(b"x" * 777), length=777)
        w.write_partition_device(1, _rows_for(b"y" * 130), length=130)
        w.commit()
        rounds = store.seal(0)
        assert rounds, "seal returned no rounds"
        for payload, sizes in rounds:
            assert isinstance(payload, jax.Array), type(payload)
        assert not store.host_staging_allocated(0)
        stats = store.stats(0)
        assert stats["host_staging_allocated"] is False
        assert stats["device_mode"] is True

    def test_read_block_serves_device_round(self):
        store = _standalone_store()
        w = store.map_writer(0, 0)
        w.write_partition_device(0, _rows_for(b"z" * 300), length=300)
        w.commit()
        assert store.read_block(0, 0, 0) == b"z" * 300


class TestGuards:
    def _store(self, device=True):
        return _standalone_store(device_staging=device)

    def test_host_then_device_write_rejected(self):
        w = self._store().map_writer(0, 0)
        w.write_partition(0, b"a" * 10)
        with pytest.raises(TransportError, match="cannot mix"):
            w.write_partition_device(1, _rows_for(b"b" * 10), length=10)

    def test_device_then_host_write_rejected(self):
        w = self._store().map_writer(0, 0)
        w.write_partition_device(0, _rows_for(b"a" * 10), length=10)
        with pytest.raises(TransportError, match="cannot mix"):
            w.write_partition(1, b"b" * 10)

    def test_wrong_lane_shape_rejected(self):
        w = self._store().map_writer(0, 0)
        with pytest.raises(TransportError, match="must be"):
            w.write_partition_device(0, jnp.zeros((4, LANE + 1), jnp.int32))

    def test_out_of_order_reduce_rejected(self):
        w = self._store().map_writer(0, 0)
        w.write_partition_device(3, _rows_for(b"a" * 10), length=10)
        with pytest.raises(TransportError, match="increasing"):
            w.write_partition_device(1, _rows_for(b"b" * 10), length=10)

    def test_device_map_writer_conf_gate(self):
        store = self._store(device=False)
        with pytest.raises(TransportError, match="deviceStaging"):
            DeviceMapWriter(store, 0, 0)

    def test_map_output_writer_conf_gate(self):
        store = self._store(device=False)
        mow = TpuShuffleMapOutputWriter(store, transport=None, shuffle_id=0, map_id=0, num_partitions=2)
        with pytest.raises(TransportError, match="deviceStaging"):
            mow.write_partition_device(0, _rows_for(b"a" * 10))

    def test_divergent_executor_geometry_is_named(self):
        # satellite: sealed-round shape validation must name the offending
        # executor instead of failing deep inside the collective
        n = 2
        cluster = TpuShuffleCluster(_conf(False, n, 1 << 20), num_executors=n)
        meta = cluster.create_shuffle(0, 2, 2)
        for m in range(2):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(2):
                w.write_partition(r, b"q" * 200)
            t.commit_block(w.commit().pack())
        bad_store = cluster.transport(1).store
        real_seal = bad_store.seal
        bad_store.seal = lambda sid: [
            (np.pad(p, ((0, 4), (0, 0))), sizes) for p, sizes in real_seal(sid)
        ]
        with pytest.raises(TransportError, match="executor 1 sealed round 0"):
            cluster.run_exchange(0)


class TestWriterLayer:
    def test_device_map_writer_roundtrip(self):
        store = _standalone_store()
        w = DeviceMapWriter(store, 0, 0)
        w.write_partition(0, _rows_for(b"m" * 513), length=513)
        w.write_partition(2, _rows_for(b"n" * 64), length=64)
        info = w.commit()
        assert info.partitions[0][1] == 513
        assert store.read_block(0, 0, 0) == b"m" * 513
        assert store.read_block(0, 0, 2) == b"n" * 64


class TestWriteBenchmark:
    def test_measure_write_reports_both_impls(self):
        from sparkucx_tpu.perf.benchmark import measure_write

        res = measure_write(2, 4096, iterations=1)
        assert set(res) == {"host", "device"}
        assert all(v > 0 for v in res.values())


class TestReaderZeroCopy:
    """The fetch iterator serves read-only memoryviews of the fetch buffer
    (shuffle/reader.py): no per-block copy on the pool-less path, copy only
    when a pooled buffer is about to be recycled."""

    def _shuffled(self):
        n = 2
        cluster = TpuShuffleCluster(_conf(False, n, 1 << 20), num_executors=n)
        meta = cluster.create_shuffle(0, 2, 2)
        payloads = {}
        for m in range(2):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(2):
                data = serialize_records([(f"k{m}{r}", m * 10 + r)])
                payloads[(m, r)] = data
                w.write_partition(r, data)
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        return cluster, meta, payloads

    def _reader(self, cluster, meta, payloads, r):
        consumer = meta.owner_of_reduce(r)
        return TpuShuffleReader(
            cluster.transport(consumer), consumer, 0, r, r + 1, 2,
            block_sizes=lambda m, rr: len(payloads[(m, rr)]),
            sender_of=lambda m: meta.map_owner[m],
        )

    def test_pool_less_fetch_serves_readonly_views(self):
        cluster, meta, payloads = self._shuffled()
        blocks = list(self._reader(cluster, meta, payloads, 0).fetch_blocks())
        assert blocks
        for blk in blocks:
            assert isinstance(blk.data, memoryview)
            assert blk.data.readonly
            # pool-less: data stays valid after the iterator detached it
            assert bytes(blk.data) == payloads[(blk.block_id.map_id, 0)]

    def test_read_streams_records(self):
        cluster, meta, payloads = self._shuffled()
        r = 1
        got = sorted(self._reader(cluster, meta, payloads, r).read())
        assert got == sorted([(f"k{m}{r}", m * 10 + r) for m in range(2)])

    def test_pooled_detach_copies_and_release_drops(self):
        class _Buf:
            closed = 0

            def close(self):
                self.closed += 1

        view = memoryview(b"payload")
        pooled = BlockFetchResult(ShuffleBlockId(0, 0, 0), view, _Buf(), pooled=True)
        pooled.detach()
        assert isinstance(pooled.data, bytes) and pooled.data == b"payload"
        pooled.detach()  # idempotent
        assert pooled._buf is None

        buf = _Buf()
        dropped = BlockFetchResult(ShuffleBlockId(0, 0, 0), view, buf, pooled=True)
        dropped.release()
        assert dropped.data == b"" and buf.closed == 1

    def test_unpooled_detach_keeps_view_without_copy(self):
        class _Buf:
            def close(self):
                pass

        view = memoryview(b"payload")
        blk = BlockFetchResult(ShuffleBlockId(0, 0, 0), view, _Buf(), pooled=False)
        blk.detach()
        assert blk.data is view
