"""TPC-DS-style query pipelines (BASELINE.md: "TPC-DS SF=100 full suite") —
the star-schema plan shapes at mini scale through the device operators, like
tests/test_tpch.py does for TPC-H.  TPC-DS plans are dimension⋈fact joins
feeding grouped aggregation; q3 and q42 are the canonical two-stage shapes."""

import numpy as np
import pytest

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    JoinSpec,
    build_grouped_aggregate,
    build_hash_join,
    run_grouped_aggregate,
)

N = 8
CAP = 128


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _pad_table(keys, values, cap_per_shard):
    width = values.shape[1]
    k = np.zeros(N * cap_per_shard, np.uint32)
    v = np.zeros((N * cap_per_shard, width), np.int32)
    nvalid = np.zeros(N, np.int32)
    for i, (ki, vi) in enumerate(zip(keys, values)):
        j = i % N
        assert nvalid[j] < cap_per_shard
        k[j * cap_per_shard + nvalid[j]] = ki
        v[j * cap_per_shard + nvalid[j]] = vi
        nvalid[j] += 1
    return k, v, nvalid


def _shard(mesh, k, v, n):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (
        jax.device_put(k, NamedSharding(mesh, P("ex"))),
        jax.device_put(v, NamedSharding(mesh, P("ex", None))),
        jax.device_put(n, NamedSharding(mesh, P("ex"))),
    )


def test_q3_brand_revenue_by_year(mesh, rng):
    """q3 shape: date_dim (filtered to one month) ⋈ store_sales on date key,
    then GROUP BY brand with SUM(price) — dimension-filter join into agg."""
    n_dates, n_sales, n_brands = 60, 400, 12
    # build side: the dates surviving the moy=11 filter, value = year index
    nov_dates = np.sort(rng.choice(n_dates, size=n_dates // 3, replace=False)).astype(np.uint32)
    date_vals = (nov_dates % 3).astype(np.int32)[:, None]  # 3 "years"
    # probe side: sales keyed by sold_date, value = (brand, price)
    s_date = rng.integers(0, n_dates, size=n_sales).astype(np.uint32)
    s_brand = rng.integers(0, n_brands, size=n_sales).astype(np.int32)
    s_price = rng.integers(1, 500, size=n_sales).astype(np.int32)

    jspec = JoinSpec(
        num_executors=N,
        build_capacity=CAP, build_recv_capacity=2 * CAP, build_width=1,
        probe_capacity=CAP, probe_recv_capacity=2 * CAP, probe_width=2,
        out_capacity=2 * CAP,
    )
    jfn = build_hash_join(mesh, jspec)
    bk, bv, bn = _pad_table(nov_dates, date_vals, CAP)
    pk, pv, pn = _pad_table(s_date, np.stack([s_brand, s_price], axis=1), CAP)
    ok, ob, op, cnt, rt = jfn(*_shard(mesh, bk, bv, bn), *_shard(mesh, pk, pv, pn))

    okh = np.asarray(ok).reshape(N, -1)
    obh = np.asarray(ob).reshape(N, okh.shape[1], -1)
    oph = np.asarray(op).reshape(N, okh.shape[1], -1)
    cnth = np.asarray(cnt)
    assert np.all(cnth <= 2 * CAP)
    joined_brand = np.concatenate([oph[j, : cnth[j], 0] for j in range(N)])
    joined_price = np.concatenate([oph[j, : cnth[j], 1] for j in range(N)])
    joined_year = np.concatenate([obh[j, : cnth[j], 0] for j in range(N)])

    # stage 2: GROUP BY (year, brand) — composite key in one uint32
    gkeys = (joined_year.astype(np.uint32) << 8) | joined_brand.astype(np.uint32)
    spec = AggregateSpec(
        num_executors=N, capacity=2 * CAP, recv_capacity=4 * CAP, aggs=("sum",)
    )
    out_k, out_v, out_c = run_grouped_aggregate(
        make_mesh(N), spec, gkeys, joined_price[:, None].astype(np.int32)
    )

    # oracle
    in_nov = np.isin(s_date, nov_dates)
    year_of = {int(d): int(y) for d, y in zip(nov_dates, date_vals[:, 0])}
    expect = {}
    for d, b, p in zip(s_date[in_nov], s_brand[in_nov], s_price[in_nov]):
        key = (year_of[int(d)] << 8) | int(b)
        expect[key] = expect.get(key, 0) + int(p)
    got = {int(k): int(v[0]) for k, v in zip(out_k, out_v)}
    assert got == expect


def test_q42_category_sum_pure_agg(mesh, rng):
    """q42 degenerates to the grouped-aggregation shape after the dimension
    filter: SUM(price) by category over pre-joined rows — run at a size that
    forces real multi-shard hash routing."""
    rows, cats = 2000, 25
    keys = rng.integers(0, cats, size=rows).astype(np.uint32)
    price = rng.integers(1, 300, size=rows).astype(np.int32)
    spec = AggregateSpec(
        num_executors=N, capacity=512, recv_capacity=1024, aggs=("sum",)
    )
    out_k, out_v, out_c = run_grouped_aggregate(mesh, spec, keys, price[:, None])
    for i, k in enumerate(out_k):
        m = keys == k
        assert out_v[i, 0] == price[m].sum()
        assert out_c[i] == m.sum()
    assert set(out_k.tolist()) == set(np.unique(keys).tolist())


def test_q16_exclusion_anti_join(mesh, rng):
    """q16/q93 shape: catalog sales EXCLUDING orders that appear in returns —
    a NOT EXISTS anti join feeding an aggregate, the TPC-DS exclusion idiom."""
    from sparkucx_tpu.ops.relational import run_grouped_aggregate, run_hash_join

    num_orders, returns = 600, 150
    cs_order = rng.integers(0, num_orders, size=1500, dtype=np.uint64).astype(np.uint32)
    cs_price = rng.integers(1, 200, size=(1500, 1)).astype(np.int32)
    cr_order = rng.choice(num_orders, size=returns, replace=False).astype(np.uint32)

    jk, jb, jp = run_hash_join(
        mesh,
        cr_order, np.zeros((returns, 1), np.int32),  # build = returned orders
        cs_order, cs_price,                           # probe = catalog sales
        impl="dense", join_type="left_anti",
    )
    assert (jb == 0).all()
    # aggregate net sales over the surviving rows: one global group
    spec = AggregateSpec(
        num_executors=N, capacity=-(-max(len(jk), 1) // N),
        recv_capacity=4 * -(-max(len(jk), 1) // N), aggs=("sum",),
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, spec, np.zeros(len(jk), np.uint32), jp[:, 0][:, None]
    )
    keep = ~np.isin(cs_order, cr_order)
    assert gc[0] == keep.sum()
    assert gv[0, 0] == cs_price[keep, 0].sum()
