"""TPC-DS-style query pipelines (BASELINE.md: "TPC-DS SF=100 full suite") —
the star-schema plan shapes at mini scale through the device operators, like
tests/test_tpch.py does for TPC-H.  TPC-DS plans are dimension⋈fact joins
feeding grouped aggregation; q3 and q42 are the canonical two-stage shapes."""

import numpy as np
import pytest

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    JoinSpec,
    build_grouped_aggregate,
    build_hash_join,
    run_grouped_aggregate,
)

N = 8
CAP = 128


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _pad_table(keys, values, cap_per_shard):
    width = values.shape[1]
    k = np.zeros(N * cap_per_shard, np.uint32)
    v = np.zeros((N * cap_per_shard, width), np.int32)
    nvalid = np.zeros(N, np.int32)
    for i, (ki, vi) in enumerate(zip(keys, values)):
        j = i % N
        assert nvalid[j] < cap_per_shard
        k[j * cap_per_shard + nvalid[j]] = ki
        v[j * cap_per_shard + nvalid[j]] = vi
        nvalid[j] += 1
    return k, v, nvalid


def _shard(mesh, k, v, n):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (
        jax.device_put(k, NamedSharding(mesh, P("ex"))),
        jax.device_put(v, NamedSharding(mesh, P("ex", None))),
        jax.device_put(n, NamedSharding(mesh, P("ex"))),
    )


def test_q3_brand_revenue_by_year(mesh, rng):
    """q3 shape: date_dim (filtered to one month) ⋈ store_sales on date key,
    then GROUP BY brand with SUM(price) — dimension-filter join into agg."""
    n_dates, n_sales, n_brands = 60, 400, 12
    # build side: the dates surviving the moy=11 filter, value = year index
    nov_dates = np.sort(rng.choice(n_dates, size=n_dates // 3, replace=False)).astype(np.uint32)
    date_vals = (nov_dates % 3).astype(np.int32)[:, None]  # 3 "years"
    # probe side: sales keyed by sold_date, value = (brand, price)
    s_date = rng.integers(0, n_dates, size=n_sales).astype(np.uint32)
    s_brand = rng.integers(0, n_brands, size=n_sales).astype(np.int32)
    s_price = rng.integers(1, 500, size=n_sales).astype(np.int32)

    jspec = JoinSpec(
        num_executors=N,
        build_capacity=CAP, build_recv_capacity=2 * CAP, build_width=1,
        probe_capacity=CAP, probe_recv_capacity=2 * CAP, probe_width=2,
        out_capacity=2 * CAP,
    )
    jfn = build_hash_join(mesh, jspec)
    bk, bv, bn = _pad_table(nov_dates, date_vals, CAP)
    pk, pv, pn = _pad_table(s_date, np.stack([s_brand, s_price], axis=1), CAP)
    ok, ob, op, cnt, rt = jfn(*_shard(mesh, bk, bv, bn), *_shard(mesh, pk, pv, pn))

    okh = np.asarray(ok).reshape(N, -1)
    obh = np.asarray(ob).reshape(N, okh.shape[1], -1)
    oph = np.asarray(op).reshape(N, okh.shape[1], -1)
    cnth = np.asarray(cnt)
    assert np.all(cnth <= 2 * CAP)
    joined_brand = np.concatenate([oph[j, : cnth[j], 0] for j in range(N)])
    joined_price = np.concatenate([oph[j, : cnth[j], 1] for j in range(N)])
    joined_year = np.concatenate([obh[j, : cnth[j], 0] for j in range(N)])

    # stage 2: GROUP BY (year, brand) — composite key in one uint32
    gkeys = (joined_year.astype(np.uint32) << 8) | joined_brand.astype(np.uint32)
    spec = AggregateSpec(
        num_executors=N, capacity=2 * CAP, recv_capacity=4 * CAP, aggs=("sum",)
    )
    out_k, out_v, out_c = run_grouped_aggregate(
        make_mesh(N), spec, gkeys, joined_price[:, None].astype(np.int32)
    )

    # oracle
    in_nov = np.isin(s_date, nov_dates)
    year_of = {int(d): int(y) for d, y in zip(nov_dates, date_vals[:, 0])}
    expect = {}
    for d, b, p in zip(s_date[in_nov], s_brand[in_nov], s_price[in_nov]):
        key = (year_of[int(d)] << 8) | int(b)
        expect[key] = expect.get(key, 0) + int(p)
    got = {int(k): int(v[0]) for k, v in zip(out_k, out_v)}
    assert got == expect


def test_q42_category_sum_pure_agg(mesh, rng):
    """q42 degenerates to the grouped-aggregation shape after the dimension
    filter: SUM(price) by category over pre-joined rows — run at a size that
    forces real multi-shard hash routing."""
    rows, cats = 2000, 25
    keys = rng.integers(0, cats, size=rows).astype(np.uint32)
    price = rng.integers(1, 300, size=rows).astype(np.int32)
    spec = AggregateSpec(
        num_executors=N, capacity=512, recv_capacity=1024, aggs=("sum",)
    )
    out_k, out_v, out_c = run_grouped_aggregate(mesh, spec, keys, price[:, None])
    for i, k in enumerate(out_k):
        m = keys == k
        assert out_v[i, 0] == price[m].sum()
        assert out_c[i] == m.sum()
    assert set(out_k.tolist()) == set(np.unique(keys).tolist())


def test_q97_channel_overlap_full_outer(mesh, rng):
    """q97 shape: store_sales FULL OUTER JOIN catalog_sales on customer —
    count customers buying from store only / catalog only / both.  The
    canonical FULL OUTER consumer in TPC-DS; both sides contribute
    null-extended rows and the matched flag + indicator lanes classify them."""
    from sparkucx_tpu.ops.relational import run_hash_join

    store_cust = rng.choice(200, size=60, replace=False).astype(np.uint32)
    catalog_cust = rng.choice(200, size=80, replace=False).astype(np.uint32)
    ones_s = np.ones((60, 1), np.int32)   # store indicator lane
    ones_c = np.ones((80, 1), np.int32)   # catalog indicator lane

    jk, jb, jp, jm = run_hash_join(
        mesh, store_cust, ones_s, catalog_cust, ones_c,
        impl="dense", join_type="full_outer",
    )
    both = int(((jb[:, 0] == 1) & (jp[:, 0] == 1)).sum())
    store_only = int(((jb[:, 0] == 1) & (jp[:, 0] == 0)).sum())
    catalog_only = int(((jb[:, 0] == 0) & (jp[:, 0] == 1)).sum())
    overlap = np.isin(store_cust, catalog_cust)
    assert both == overlap.sum()
    assert store_only == (~overlap).sum()
    assert catalog_only == (~np.isin(catalog_cust, store_cust)).sum()
    assert both + store_only + catalog_only == len(jk)
    assert (jm == ((jb[:, 0] == 1) & (jp[:, 0] == 1))).all()


def test_q80_net_profit_right_outer(mesh, rng):
    """q80 shape: store_sales ⟕ store_returns — every sale preserved, returns
    subtracted where present.  Expressed with the FACT side as the build
    (hash-table) input via RIGHT OUTER: build=sales is preserved, probe=
    returns null-extends, so net = price - refund with refund 0 for
    unreturned sales."""
    from sparkucx_tpu.ops.relational import run_hash_join

    n_sales = 300
    sale_id = rng.permutation(n_sales).astype(np.uint32)  # unique ticket ids
    price = rng.integers(10, 400, size=(n_sales, 1)).astype(np.int32)
    returned = rng.choice(n_sales, size=70, replace=False).astype(np.uint32)
    refund = rng.integers(1, 9, size=(70, 1)).astype(np.int32)

    jk, jb, jp, jm = run_hash_join(
        mesh, sale_id, price, returned, refund,
        impl="dense", join_type="right_outer",
    )
    assert len(jk) == n_sales  # every sale exactly once (PK join + preserved)
    price_of = {int(k): int(v) for k, v in zip(sale_id, price[:, 0])}
    refund_of = {int(k): int(v) for k, v in zip(returned, refund[:, 0])}
    for k, b, p, m in zip(jk, jb[:, 0], jp[:, 0], jm):
        assert int(b) == price_of[int(k)]
        assert int(p) == refund_of.get(int(k), 0)
        assert bool(m) == (int(k) in refund_of)
    net = (jb[:, 0] - jp[:, 0]).sum()
    assert net == price.sum() - refund.sum()


def test_q7_avg_by_item_with_filter(mesh, rng):
    """q7 shape: AVG(quantity), AVG(sales_price) GROUP BY item over rows
    surviving the demographics filter — fused sum+count avg under a WHERE
    pushdown mask, divided exactly on the host."""
    from sparkucx_tpu.ops.relational import oracle_aggregate, run_grouped_aggregate

    rows, items = 2400, 30
    item = rng.integers(0, items, size=rows).astype(np.uint32)
    qty = rng.integers(1, 20, size=rows).astype(np.int32)
    sp = rng.integers(5, 500, size=rows).astype(np.int32)
    demo_ok = rng.random(rows) < 0.35  # the cd_gender/cd_marital filter

    spec = AggregateSpec(
        num_executors=N, capacity=512, recv_capacity=1024,
        aggs=("avg", "avg"), with_filter=True,
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, spec, item, np.stack([qty, sp], axis=1), mask=demo_ok
    )
    wk, wv, wc = oracle_aggregate(
        item[demo_ok], np.stack([qty, sp], axis=1)[demo_ok], spec.aggs
    )
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gv, wv)  # float64, exact int/int division
    np.testing.assert_array_equal(gc, wc)


def test_q38_distinct_customers_by_month(mesh, rng):
    """q38 shape: COUNT(DISTINCT customer) per month — repeat purchases by
    the same customer in a month must count once (the device lexsort
    dedup), alongside a plain COUNT(*) of visits."""
    from sparkucx_tpu.ops.relational import oracle_aggregate, run_grouped_aggregate

    visits, months, customers = 3000, 12, 90
    month = rng.integers(0, months, size=visits).astype(np.uint32)
    cust = rng.integers(0, customers, size=visits).astype(np.int32)

    spec = AggregateSpec(
        num_executors=N, capacity=512, recv_capacity=1024,
        aggs=("count_distinct",),
    )
    gk, gv, gc = run_grouped_aggregate(mesh, spec, month, cust[:, None])
    wk, wv, wc = oracle_aggregate(month, cust[:, None], spec.aggs)
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gv, wv)
    np.testing.assert_array_equal(gc, wc)
    # sanity vs a direct host computation of the headline number
    for k, v in zip(gk, gv[:, 0]):
        assert v == len(np.unique(cust[month == k]))


def test_q16_exclusion_anti_join(mesh, rng):
    """q16/q93 shape: catalog sales EXCLUDING orders that appear in returns —
    a NOT EXISTS anti join feeding an aggregate, the TPC-DS exclusion idiom."""
    from sparkucx_tpu.ops.relational import run_grouped_aggregate, run_hash_join

    num_orders, returns = 600, 150
    cs_order = rng.integers(0, num_orders, size=1500, dtype=np.uint64).astype(np.uint32)
    cs_price = rng.integers(1, 200, size=(1500, 1)).astype(np.int32)
    cr_order = rng.choice(num_orders, size=returns, replace=False).astype(np.uint32)

    jk, jb, jp = run_hash_join(
        mesh,
        cr_order, np.zeros((returns, 1), np.int32),  # build = returned orders
        cs_order, cs_price,                           # probe = catalog sales
        impl="dense", join_type="left_anti",
    )
    assert (jb == 0).all()
    # aggregate net sales over the surviving rows: one global group
    spec = AggregateSpec(
        num_executors=N, capacity=-(-max(len(jk), 1) // N),
        recv_capacity=4 * -(-max(len(jk), 1) // N), aggs=("sum",),
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, spec, np.zeros(len(jk), np.uint32), jp[:, 0][:, None]
    )
    keep = ~np.isin(cs_order, cr_order)
    assert gc[0] == keep.sum()
    assert gv[0, 0] == cs_price[keep, 0].sum()
