"""Hierarchical (ICI+DCN) exchange vs the flat lowering and the CPU oracle —
bit-identical contract on a factored (2 slices x 4 chips) CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    make_mesh,
    oracle_exchange,
    pack_chunks_slots,
    unpack_received,
)
from sparkucx_tpu.ops.hierarchy import build_hierarchical_exchange, make_hierarchical_mesh

S, C = 2, 4
N = S * C
SLOT = 16
LANE = 32  # 128-byte rows keep the test light


def _spec():
    return ExchangeSpec(
        num_executors=N, send_rows=N * SLOT, recv_rows=N * SLOT, lane=LANE, impl="dense"
    )


def _random_inputs(rng):
    spec = _spec()
    data = rng.integers(-(2**31), 2**31 - 1, size=(N * spec.send_rows, LANE), dtype=np.int32)
    sizes = rng.integers(0, SLOT + 1, size=(N, N), dtype=np.int32)
    return spec, data, sizes


class TestHierarchicalExchange:
    def test_bit_identical_to_flat(self, rng):
        spec, data, sizes = _random_inputs(rng)

        flat_mesh = make_mesh(N)
        flat = build_exchange(flat_mesh, spec)
        sh = NamedSharding(flat_mesh, P("ex", None))
        f_recv, f_sizes = flat(jax.device_put(data, sh), jax.device_put(sizes, sh))

        hmesh = make_hierarchical_mesh(S, C)
        hier = build_hierarchical_exchange(hmesh, spec)
        hsh = NamedSharding(hmesh, P(("dcn", "ici"), None))
        h_recv, h_sizes = hier(jax.device_put(data, hsh), jax.device_put(sizes, hsh))

        assert np.array_equal(np.asarray(f_sizes), np.asarray(h_sizes))
        assert np.array_equal(np.asarray(f_recv), np.asarray(h_recv))

    def test_bytes_vs_oracle(self, rng):
        spec = _spec()
        row_bytes = LANE * 4
        chunks = [
            [
                rng.integers(0, 256, size=int(rng.integers(0, SLOT * row_bytes)), dtype=np.uint8).tobytes()
                for _ in range(N)
            ]
            for _ in range(N)
        ]
        bufs, size_rows = zip(
            *[pack_chunks_slots(chunks[i], SLOT, row_bytes) for i in range(N)]
        )
        data = np.concatenate(bufs)
        sizes = np.stack(size_rows)

        hmesh = make_hierarchical_mesh(S, C)
        hier = build_hierarchical_exchange(hmesh, spec)
        hsh = NamedSharding(hmesh, P(("dcn", "ici"), None))
        recv, recv_sizes = hier(jax.device_put(data, hsh), jax.device_put(sizes, hsh))

        recv_np = np.asarray(recv).reshape(N, spec.recv_rows * LANE).view(np.uint8)
        sizes_np = np.asarray(recv_sizes)
        want = oracle_exchange([[_pad(c, row_bytes) for c in row] for row in chunks])
        for j in range(N):
            got = b"".join(unpack_received(recv_np[j].tobytes(), sizes_np[j], row_bytes))
            assert got == want[j], f"receiver {j} mismatch"

    def test_mesh_shape_validation(self):
        spec = _spec()
        with pytest.raises(ValueError, match="mesh axes"):
            build_hierarchical_exchange(make_mesh(N), spec)
        hmesh = make_hierarchical_mesh(S, C)
        bad = ExchangeSpec(num_executors=4, send_rows=4 * SLOT, recv_rows=4 * SLOT, lane=LANE)
        with pytest.raises(ValueError, match="mesh"):
            build_hierarchical_exchange(hmesh, bad)

    def test_other_factorization(self, rng):
        # 4 slices x 2 chips over the same 8 devices
        spec, data, sizes = _random_inputs(rng)
        flat = build_exchange(make_mesh(N), spec)
        sh = NamedSharding(make_mesh(N), P("ex", None))
        f_recv, _ = flat(jax.device_put(data, sh), jax.device_put(sizes, sh))

        hmesh = make_hierarchical_mesh(4, 2)
        hier = build_hierarchical_exchange(hmesh, spec)
        hsh = NamedSharding(hmesh, P(("dcn", "ici"), None))
        h_recv, _ = hier(jax.device_put(data, hsh), jax.device_put(sizes, hsh))
        assert np.array_equal(np.asarray(f_recv), np.asarray(h_recv))


def _pad(chunk: bytes, row_bytes: int) -> bytes:
    rows = -(-len(chunk) // row_bytes)
    return chunk + b"\0" * (rows * row_bytes - len(chunk))
