"""Randomized differential tests: arbitrary shuffle shapes through the full
cluster path vs the host oracle — the safety net over dimension/padding edge
cases (empty blocks, empty maps, odd M/R vs executor counts, tiny alignments,
multi-round spill) that targeted tests enumerate one at a time."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


def _run_case(seed: int) -> None:
    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 3, 4, 8]))
    M = int(rng.integers(1, 12))
    R = int(rng.integers(1, 12))
    alignment = int(rng.choice([128, 256, 512]))
    # capacity small enough that some seeds force multi-round spill
    capacity = int(rng.choice([1 << 14, 1 << 16, 1 << 20]))
    num_slices = int(rng.choice([1, 2])) if n % 2 == 0 and n >= 4 else 1
    # a single partition must fit one peer region (the store's documented
    # contract — larger blocks are a config error it raises on)
    region = (capacity // n) // alignment * alignment
    max_block = min(int(rng.choice([0, 17, 300, 4000])), region)

    conf = TpuShuffleConf(
        staging_capacity_per_executor=capacity,
        block_alignment=alignment,
        num_executors=n,
        num_slices=num_slices,
    )
    cluster = TpuShuffleCluster(conf, num_executors=n)
    meta = cluster.create_shuffle(0, M, R)
    oracle = {}
    try:
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(R):
                size = int(rng.integers(0, max_block + 1)) if max_block else 0
                payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            t = cluster.transport(consumer)
            bids = [ShuffleBlockId(0, m, r) for m in range(M)]
            bufs = [
                MemoryBlock(np.zeros(max_block + 1, np.uint8), size=max_block + 1)
                for _ in range(M)
            ]
            reqs = t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
            for m, (req, buf) in enumerate(zip(reqs, bufs)):
                res = req.wait(5)
                assert res.status == OperationStatus.SUCCESS, (
                    f"seed={seed} n={n} M={M} R={R} align={alignment} "
                    f"slices={num_slices}: {res.error}"
                )
                got = buf.host_view()[: buf.size].tobytes()
                assert got == oracle[(m, r)], (
                    f"seed={seed} n={n} M={M} R={R} align={alignment} cap={capacity} "
                    f"slices={num_slices} block=({m},{r}): "
                    f"{len(got)}B != {len(oracle[(m, r)])}B"
                )
    finally:
        cluster.remove_shuffle(0)


@pytest.mark.parametrize("seed", range(20))
def test_random_shuffle_shapes(seed):
    _run_case(seed)


def _run_sort_case(seed: int) -> None:
    """Differential fuzz for the distributed sort: random executor counts,
    fills, widths (crossing the 25-32-lane gather band), and key skew (down
    to single-valued keys, which exercises the recv_capacity doubling retry
    in run_distributed_sort)."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    cap = int(rng.integers(8, 200))
    width = int(rng.choice([1, 4, 24, 25, 32]))
    total = int(rng.integers(1, n * cap + 1))
    distinct = int(rng.choice([1, 2, 50, 1 << 32]))
    spec = SortSpec(
        num_executors=n,
        capacity=cap,
        recv_capacity=int(cap * rng.choice([1, 2, 3])) if n == 1 else 2 * cap,
        width=width,
        samples_per_shard=max(n, int(rng.choice([8, 64]))),
    )
    keys = rng.integers(0, distinct, size=total, dtype=np.uint64).astype(np.uint32)
    payload = rng.integers(-100, 100, size=(total, width)).astype(np.int32)
    mesh = make_mesh(n)
    sk, sp = run_distributed_sort(mesh, spec, keys, payload, max_attempts=6)
    ek, ep = oracle_sort(keys, payload)
    assert (sk == ek).all(), f"seed={seed} n={n} cap={cap} w={width} distinct={distinct}"
    assert (sp == ep).all(), f"seed={seed} payload rows diverged"


@pytest.mark.parametrize("seed", range(12))
def test_random_sort_shapes(seed):
    _run_sort_case(seed)


def _run_join_case(seed: int) -> None:
    """Differential fuzz for the hash join through its host driver
    (run_hash_join: exact capacity planning from the placement hash, raises
    on host/device placement divergence): random executor counts, fills,
    widths, duplicate keys on BOTH sides (many-to-many expansion), and
    one-sided/empty tables — results compared to the numpy oracle as
    multisets."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import oracle_join, run_hash_join

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    bcap = int(rng.integers(1, 60))
    pcap = int(rng.integers(1, 60))
    bw = int(rng.choice([1, 3, 8]))
    pw = int(rng.choice([1, 2, 16]))
    distinct = int(rng.choice([1, 3, 20, 1000]))
    btotal = int(rng.integers(0, n * bcap + 1))
    ptotal = int(rng.integers(0, n * pcap + 1))

    bkeys = rng.integers(0, distinct, size=btotal, dtype=np.uint64).astype(np.uint32)
    pkeys = rng.integers(0, distinct, size=ptotal, dtype=np.uint64).astype(np.uint32)
    bvals = rng.integers(-50, 50, size=(btotal, bw)).astype(np.int32)
    pvals = rng.integers(-50, 50, size=(ptotal, pw)).astype(np.int32)

    mesh = make_mesh(n)
    join_type = [
        "inner", "left_outer", "left_semi", "left_anti",
        "right_outer", "full_outer",
    ][seed % 6]
    # over-provisioned input capacities (bcap/pcap >= fill) keep the
    # padding/validity-mask paths under fuzz, not just the tight auto-sizing
    out = run_hash_join(
        mesh, bkeys, bvals, pkeys, pvals, impl="dense",
        build_capacity=bcap, probe_capacity=pcap, join_type=join_type,
    )
    want = oracle_join(bkeys, bvals, pkeys, pvals, join_type=join_type)
    if join_type in ("left_outer", "right_outer", "full_outer"):
        got_rows = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(*out)
        )
        want_rows = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(*want)
        )
    else:
        got_rows = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist())) for k, b, p in zip(*out)
        )
        want_rows = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist())) for k, b, p in zip(*want)
        )
    assert got_rows == want_rows, (
        f"seed={seed} n={n} bcap={bcap} pcap={pcap} distinct={distinct} "
        f"{join_type}: {len(got_rows)} rows != oracle {len(want_rows)}"
    )


@pytest.mark.parametrize("seed", range(12))
def test_random_join_shapes(seed):
    _run_join_case(seed)


def _run_groupby_case(seed: int) -> None:
    """Differential fuzz for the grouped aggregation: random executor counts,
    fills, agg mixes, and key skew (single-key through all-distinct) vs the
    numpy oracle, through the retry-on-skew host driver."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        AggregateSpec,
        oracle_aggregate,
        run_grouped_aggregate,
    )

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    cap = int(rng.integers(4, 120))
    total = int(rng.integers(0, n * cap + 1))
    # full uint32 range (a drawn KEY_MAX remains unlikely at these sizes — the
    # deterministic sentinel case lives in test_relational.py::
    # test_sentinel_key_is_a_real_group)
    distinct = int(rng.choice([1, 2, 16, 1 << 32]))
    n_aggs = int(rng.integers(0, 4))
    aggs = tuple(
        rng.choice(["sum", "min", "max", "avg", "count_distinct"])
        for _ in range(n_aggs)
    )
    # map-side partial aggregation fuzzes alongside the unfused path; it
    # rejects count_distinct by contract (partials don't compose)
    partial = bool(rng.integers(0, 2)) and "count_distinct" not in aggs
    spec = AggregateSpec(
        num_executors=n, capacity=cap,
        recv_capacity=max(8, 2 * cap), aggs=aggs, impl="dense", partial=partial,
    )
    keys = rng.integers(0, distinct, size=total, dtype=np.uint64).astype(np.uint32)
    values = rng.integers(-1000, 1000, size=(total, n_aggs)).astype(np.int32)
    mesh = make_mesh(n)
    gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values, max_attempts=6)
    wk, wv, wc = oracle_aggregate(keys, values, aggs)
    assert np.array_equal(gk, wk), f"seed={seed} n={n} cap={cap} distinct={distinct}"
    assert np.array_equal(gv, wv), f"seed={seed} aggregated columns diverged"
    assert np.array_equal(gc, wc), f"seed={seed} group counts diverged"


@pytest.mark.parametrize("seed", range(12))
def test_random_groupby_shapes(seed):
    _run_groupby_case(seed)


def _run_external_sort_case(seed: int) -> None:
    """Differential fuzz for the out-of-core sort: random batch counts (1-7
    runs incl. ragged tails), widths, and key duplication — the device-batch +
    host-merge composite must stay stable and oracle-exact."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4]))
    cap = int(rng.integers(8, 80))
    width = int(rng.choice([1, 4, 24]))
    total = int(rng.integers(1, 7 * n * cap + 1))
    distinct = int(rng.choice([1, 4, 1 << 32]))
    spec = SortSpec(
        num_executors=n, capacity=cap,
        recv_capacity=cap if n == 1 else 2 * cap, width=width, impl="dense",
    )
    keys = rng.integers(0, distinct, size=total, dtype=np.uint64).astype(np.uint32)
    payload = rng.integers(-100, 100, size=(total, width)).astype(np.int32)
    mesh = make_mesh(n)
    sk, sp = run_external_sort(mesh, spec, keys, payload, max_attempts=6)
    ek, ep = oracle_sort(keys, payload)
    assert np.array_equal(sk, ek), f"seed={seed} n={n} cap={cap} total={total}"
    assert np.array_equal(sp, ep), f"seed={seed} payload rows diverged (stability)"


@pytest.mark.parametrize("seed", range(10))
def test_random_external_sort_shapes(seed):
    _run_external_sort_case(seed)
