"""Randomized differential tests: arbitrary shuffle shapes through the full
cluster path vs the host oracle — the safety net over dimension/padding edge
cases (empty blocks, empty maps, odd M/R vs executor counts, tiny alignments,
multi-round spill) that targeted tests enumerate one at a time."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


def _run_case(seed: int) -> None:
    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 3, 4, 8]))
    M = int(rng.integers(1, 12))
    R = int(rng.integers(1, 12))
    alignment = int(rng.choice([128, 256, 512]))
    # capacity small enough that some seeds force multi-round spill
    capacity = int(rng.choice([1 << 14, 1 << 16, 1 << 20]))
    num_slices = int(rng.choice([1, 2])) if n % 2 == 0 and n >= 4 else 1
    # a single partition must fit one peer region (the store's documented
    # contract — larger blocks are a config error it raises on)
    region = (capacity // n) // alignment * alignment
    max_block = min(int(rng.choice([0, 17, 300, 4000])), region)

    conf = TpuShuffleConf(
        staging_capacity_per_executor=capacity,
        block_alignment=alignment,
        num_executors=n,
        num_slices=num_slices,
    )
    cluster = TpuShuffleCluster(conf, num_executors=n)
    meta = cluster.create_shuffle(0, M, R)
    oracle = {}
    try:
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(R):
                size = int(rng.integers(0, max_block + 1)) if max_block else 0
                payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            t = cluster.transport(consumer)
            bids = [ShuffleBlockId(0, m, r) for m in range(M)]
            bufs = [
                MemoryBlock(np.zeros(max_block + 1, np.uint8), size=max_block + 1)
                for _ in range(M)
            ]
            reqs = t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
            for m, (req, buf) in enumerate(zip(reqs, bufs)):
                res = req.wait(5)
                assert res.status == OperationStatus.SUCCESS, (
                    f"seed={seed} n={n} M={M} R={R} align={alignment} "
                    f"slices={num_slices}: {res.error}"
                )
                got = buf.host_view()[: buf.size].tobytes()
                assert got == oracle[(m, r)], (
                    f"seed={seed} n={n} M={M} R={R} align={alignment} cap={capacity} "
                    f"slices={num_slices} block=({m},{r}): "
                    f"{len(got)}B != {len(oracle[(m, r)])}B"
                )
    finally:
        cluster.remove_shuffle(0)


@pytest.mark.parametrize("seed", range(20))
def test_random_shuffle_shapes(seed):
    _run_case(seed)


def _run_sort_case(seed: int) -> None:
    """Differential fuzz for the distributed sort: random executor counts,
    fills, widths (crossing the 25-32-lane gather band), and key skew (down
    to single-valued keys, which exercises the recv_capacity doubling retry
    in run_distributed_sort)."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    cap = int(rng.integers(8, 200))
    width = int(rng.choice([1, 4, 24, 25, 32]))
    total = int(rng.integers(1, n * cap + 1))
    distinct = int(rng.choice([1, 2, 50, 1 << 32]))
    spec = SortSpec(
        num_executors=n,
        capacity=cap,
        recv_capacity=int(cap * rng.choice([1, 2, 3])) if n == 1 else 2 * cap,
        width=width,
        samples_per_shard=max(n, int(rng.choice([8, 64]))),
    )
    keys = rng.integers(0, distinct, size=total, dtype=np.uint64).astype(np.uint32)
    payload = rng.integers(-100, 100, size=(total, width)).astype(np.int32)
    mesh = make_mesh(n)
    sk, sp = run_distributed_sort(mesh, spec, keys, payload, max_attempts=6)
    ek, ep = oracle_sort(keys, payload)
    assert (sk == ek).all(), f"seed={seed} n={n} cap={cap} w={width} distinct={distinct}"
    assert (sp == ep).all(), f"seed={seed} payload rows diverged"


@pytest.mark.parametrize("seed", range(12))
def test_random_sort_shapes(seed):
    _run_sort_case(seed)


def _run_join_case(seed: int) -> None:
    """Differential fuzz for the hash join: random executor counts, fills,
    widths, duplicate keys on BOTH sides (many-to-many expansion), and
    one-sided/empty tables, with receive/output capacities planned from the
    real placement hash — results compared to the numpy oracle as multisets."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        JoinSpec,
        build_hash_join,
        hash_owners_host,
        oracle_join,
    )

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    bcap = int(rng.integers(1, 60))
    pcap = int(rng.integers(1, 60))
    bw = int(rng.choice([1, 3, 8]))
    pw = int(rng.choice([1, 2, 16]))
    distinct = int(rng.choice([1, 3, 20, 1000]))
    btotal = int(rng.integers(0, n * bcap + 1))
    ptotal = int(rng.integers(0, n * pcap + 1))

    bkeys = rng.integers(0, distinct, size=btotal, dtype=np.uint64).astype(np.uint32)
    pkeys = rng.integers(0, distinct, size=ptotal, dtype=np.uint64).astype(np.uint32)
    bvals = rng.integers(-50, 50, size=(btotal, bw)).astype(np.int32)
    pvals = rng.integers(-50, 50, size=(ptotal, pw)).astype(np.int32)

    # exact capacity planning from the host twin of the device hash: matches
    # for key k land on k's owner shard, bcount(k) * pcount(k) of them
    brecv = max(1, int(np.bincount(hash_owners_host(bkeys, n), minlength=n).max()))
    precv = max(1, int(np.bincount(hash_owners_host(pkeys, n), minlength=n).max()))
    uk, bc = np.unique(bkeys, return_counts=True)
    pc = np.array([(pkeys == k).sum() for k in uk], np.int64)
    per_shard_matches = np.zeros(n, np.int64)
    np.add.at(per_shard_matches, hash_owners_host(uk, n), bc * pc)
    out_cap = max(1, int(per_shard_matches.max()))

    spec = JoinSpec(
        num_executors=n,
        build_capacity=bcap, build_recv_capacity=brecv, build_width=bw,
        probe_capacity=pcap, probe_recv_capacity=precv, probe_width=pw,
        out_capacity=out_cap,
        impl="dense",
    )
    mesh = make_mesh(n)
    fn = build_hash_join(mesh, spec)

    from sparkucx_tpu.ops.columnar import shard_rows_host

    bk, bv, bn = shard_rows_host(bkeys, bvals, n, bcap)
    pk, pv, pn = shard_rows_host(pkeys, pvals, n, pcap)
    key_sh = NamedSharding(mesh, P("ex"))
    row_sh = NamedSharding(mesh, P("ex", None))
    ok, ob, op_, oc, rt = fn(
        jax.device_put(bk, key_sh), jax.device_put(bv, row_sh), jax.device_put(bn, key_sh),
        jax.device_put(pk, key_sh), jax.device_put(pv, row_sh), jax.device_put(pn, key_sh),
    )
    rt = np.asarray(rt)
    assert (rt[:, 0] <= brecv).all() and (rt[:, 1] <= precv).all(), (
        f"seed={seed}: host capacity plan diverged from device placement"
    )
    oc = np.asarray(oc)
    assert (oc <= out_cap).all(), f"seed={seed}: output overflowed the exact plan"
    ok, ob, op_ = np.asarray(ok), np.asarray(ob), np.asarray(op_)
    got = []
    for shard in range(n):
        base = shard * out_cap
        for i in range(base, base + int(oc[shard])):
            got.append((int(ok[i]), tuple(ob[i].tolist()), tuple(op_[i].tolist())))
    want_k, want_b, want_p = oracle_join(bkeys, bvals, pkeys, pvals)
    want = [
        (int(k), tuple(b.tolist()), tuple(p.tolist()))
        for k, b, p in zip(want_k, want_b, want_p)
    ]
    assert sorted(got) == sorted(want), (
        f"seed={seed} n={n} bcap={bcap} pcap={pcap} distinct={distinct}: "
        f"{len(got)} rows != oracle {len(want)}"
    )


@pytest.mark.parametrize("seed", range(12))
def test_random_join_shapes(seed):
    _run_join_case(seed)


def _run_groupby_case(seed: int) -> None:
    """Differential fuzz for the grouped aggregation: random executor counts,
    fills, agg mixes, and key skew (single-key through all-distinct) vs the
    numpy oracle, through the retry-on-skew host driver."""
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.relational import (
        AggregateSpec,
        oracle_aggregate,
        run_grouped_aggregate,
    )

    rng = np.random.default_rng(seed)
    n = int(rng.choice([1, 2, 4, 8]))
    cap = int(rng.integers(4, 120))
    total = int(rng.integers(0, n * cap + 1))
    # full uint32 range (a drawn KEY_MAX remains unlikely at these sizes — the
    # deterministic sentinel case lives in test_relational.py::
    # test_sentinel_key_is_a_real_group)
    distinct = int(rng.choice([1, 2, 16, 1 << 32]))
    n_aggs = int(rng.integers(0, 4))
    aggs = tuple(rng.choice(["sum", "min", "max"]) for _ in range(n_aggs))
    spec = AggregateSpec(
        num_executors=n, capacity=cap,
        recv_capacity=max(8, 2 * cap), aggs=aggs, impl="dense",
    )
    keys = rng.integers(0, distinct, size=total, dtype=np.uint64).astype(np.uint32)
    values = rng.integers(-1000, 1000, size=(total, n_aggs)).astype(np.int32)
    mesh = make_mesh(n)
    gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values, max_attempts=6)
    wk, wv, wc = oracle_aggregate(keys, values, aggs)
    assert np.array_equal(gk, wk), f"seed={seed} n={n} cap={cap} distinct={distinct}"
    assert np.array_equal(gv, wv), f"seed={seed} aggregated columns diverged"
    assert np.array_equal(gc, wc), f"seed={seed} group counts diverged"


@pytest.mark.parametrize("seed", range(12))
def test_random_groupby_shapes(seed):
    _run_groupby_case(seed)
