"""Tests for the L2 control plane (driver/executor address exchange) and mesh
topology helpers."""

import time

import pytest

from sparkucx_tpu.parallel.bootstrap import DriverEndpoint, ExecutorEndpoint
from sparkucx_tpu.parallel.mesh import (
    discover_topology,
    executor_mesh,
    executor_for_device,
)
from sparkucx_tpu.transport.loopback import LoopbackFabric, LoopbackTransport


def _wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


class TestBootstrap:
    def test_three_executors_converge(self):
        driver = DriverEndpoint()
        fabric = LoopbackFabric()
        endpoints = []
        try:
            for eid in (1, 2, 3):
                t = LoopbackTransport(executor_id=eid, fabric=fabric)
                addr = t.init()
                ep = ExecutorEndpoint(driver.address, eid, t)
                ep.register(addr)
                endpoints.append((ep, t))
            # Every executor learns every *other* executor (driver replies with
            # pre-existing members; broadcasts cover the rest).
            assert _wait_until(
                lambda: all(
                    set(ep.known) == {1, 2, 3} - {ep.executor_id} for ep, _ in endpoints
                )
            ), [set(ep.known) for ep, _ in endpoints]
            # transports got add_executor for each peer
            for ep, t in endpoints:
                for other_ep, _ in endpoints:
                    if other_ep.executor_id != ep.executor_id:
                        assert other_ep.executor_id in t._peers
            assert set(driver.members) == {1, 2, 3}
        finally:
            for ep, t in endpoints:
                ep.close()
                t.close()
            driver.close()

    def test_late_joiner_broadcast(self):
        driver = DriverEndpoint()
        fabric = LoopbackFabric()
        t1 = LoopbackTransport(executor_id=1, fabric=fabric)
        ep1 = ExecutorEndpoint(driver.address, 1, t1)
        try:
            ep1.register(t1.init())
            assert ep1.known == {}
            t2 = LoopbackTransport(executor_id=2, fabric=fabric)
            ep2 = ExecutorEndpoint(driver.address, 2, t2)
            ep2.register(t2.init())
            try:
                assert _wait_until(lambda: 2 in ep1.known)  # pushed, not polled
                assert _wait_until(lambda: 1 in ep2.known)
            finally:
                ep2.close()
                t2.close()
        finally:
            ep1.close()
            t1.close()
            driver.close()

    def test_member_callback_fires(self):
        driver = DriverEndpoint()
        fabric = LoopbackFabric()
        seen = []
        t1 = LoopbackTransport(executor_id=1, fabric=fabric)
        t2 = LoopbackTransport(executor_id=2, fabric=fabric)
        ep1 = ExecutorEndpoint(driver.address, 1, t1, on_member=lambda e, a: seen.append(e))
        ep2 = ExecutorEndpoint(driver.address, 2, t2)
        try:
            ep1.register(t1.init())
            ep2.register(t2.init())
            assert _wait_until(lambda: seen == [2])
        finally:
            ep1.close(); ep2.close(); t1.close(); t2.close(); driver.close()

    def test_register_timeout_without_driver_reply(self):
        import socket

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        fabric = LoopbackFabric()
        t = LoopbackTransport(executor_id=1, fabric=fabric)
        ep = ExecutorEndpoint(srv.getsockname(), 1, t)
        try:
            with pytest.raises(TimeoutError):
                ep.register(t.init(), timeout=0.3)
        finally:
            ep.close(); t.close(); srv.close()


class TestTopology:
    def test_discover_topology(self):
        topo = discover_topology()
        assert topo.num_devices >= 8  # the forced CPU mesh
        assert topo.process_count == 1
        assert not topo.multi_host

    def test_executor_mesh(self):
        mesh = executor_mesh(8)
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("ex",)
        dev = mesh.devices.reshape(-1)[3]
        assert executor_for_device(mesh, dev) == 3

    def test_executor_mesh_too_many(self):
        with pytest.raises(ValueError, match="need"):
            executor_mesh(10_000)

    def test_ici_order_with_coords(self):
        # Fake devices exposing coords: snake order should sort (z, y, x-snaked).
        class FakeDev:
            def __init__(self, x, y, z):
                self.coords = (x, y, z)
                self.core_on_chip = 0

            def __repr__(self):
                return f"D{self.coords}"

        from sparkucx_tpu.parallel.mesh import _ici_order

        devs = [FakeDev(x, y, 0) for y in range(2) for x in range(2)]
        ordered = _ici_order(devs[::-1])
        coords = [d.coords for d in ordered]
        assert coords == [(0, 0, 0), (1, 0, 0), (1, 1, 0), (0, 1, 0)]  # snake
