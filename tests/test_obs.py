"""Distributed telemetry plane: wire traces, metrics registry, flight recorder.

Pins the obs PR's contracts end to end:

* the tracer's bounded ring (capacity, dropped counter, tail) and the
  disabled-span fast path (shared no-op singleton, no allocation),
* trace-context wire extensions on FetchBlockReq / ReplicaPut — golden frames
  byte-identical with everything off, composition with the tenant app-id /
  checksum / compression extensions, old receivers ignoring the unknown ext,
* the `MetricsRegistry`: provider registration, executor labels, error
  counting, deterministic Prometheus text, the stock adapters, the optional
  HTTP scrape endpoint (`obs.metricsPort`),
* the always-on `FlightRecorder`: bounded bundles, light capture on
  `TransportError` construction and chaos faults, file dumps, re-entrancy,
* the TRACE_PULL / METRICS_PULL Active Messages over the loopback peer wire,
* the headline acceptance scenario: chaos-killed primary mid-read, the
  reducer fails over, and ONE merged Perfetto trace shows the `read.window`
  span with `server.serve` children from TWO different executors, metrics
  carry wire/replica/elastic/eviction families from every executor, and a
  postmortem bundle was auto-dumped.
"""

import json
import struct
import urllib.request

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.core.definitions import (
    REPLICA_TRACE_EXT_SIZE,
    TRACE_EXT_SIZE,
    AmId,
    pack_replica_trace_ext,
    pack_trace_ext,
    unpack_replica_trace_ext,
    unpack_trace_ext,
)
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.obs.metrics import (
    MetricsRegistry,
    close_http_server,
    counter_dict_provider,
    sample,
    start_http_server,
    stats_aggregator_provider,
    tracer_provider,
    wire_lane_provider,
)
from sparkucx_tpu.obs.recorder import MAX_BUNDLES, FlightRecorder
from sparkucx_tpu.parallel.membership import ClusterMembership
from sparkucx_tpu.service.eviction import EvictionManager
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.shuffle.resolver import ring_neighbors
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.peer import (
    PeerTransport,
    pack_batch_fetch_req,
    split_fetch_req_trace,
    unpack_batch_fetch_req,
    unpack_fetch_req_app_id,
)
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import TRACER, Tracer, merge_events, span


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """The process-wide TRACER is shared across the suite (and the recorder
    flips ``recording`` on); save/restore switches and empty the ring so
    every test sees a clean plane."""
    prev_enabled, prev_recording = TRACER.enabled, TRACER.recording
    TRACER.clear()
    faults.reset()
    yield
    TRACER.enabled, TRACER.recording = prev_enabled, prev_recording
    TRACER.clear()
    faults.reset()


# ---------------------------------------------------------------------------
# tracer: bounded ring + fast path
# ---------------------------------------------------------------------------


class TestTracerRing:
    def test_capacity_bounds_and_counts_drops(self):
        t = Tracer(enabled=True, capacity=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        assert len(t.events) == 4
        assert t.dropped == 6
        assert [e["name"] for e in t.events] == ["s6", "s7", "s8", "s9"]

    def test_set_capacity_keeps_newest(self):
        t = Tracer(enabled=True, capacity=8)
        for i in range(8):
            with t.span(f"s{i}"):
                pass
        t.set_capacity(2)
        assert [e["name"] for e in t.events] == ["s6", "s7"]

    def test_tail_returns_newest_in_order(self):
        t = Tracer(enabled=True, capacity=16)
        for i in range(6):
            with t.span(f"s{i}"):
                pass
        assert [e["name"] for e in t.tail(3)] == ["s3", "s4", "s5"]
        assert len(t.tail(100)) == 6  # n past the ring = the whole ring

    def test_recording_without_enabled_fills_ring(self):
        t = Tracer(enabled=False, recording=True)
        with t.span("warm"):
            pass
        assert t.active and [e["name"] for e in t.events] == ["warm"]

    def test_clear_resets_drop_counter(self):
        t = Tracer(enabled=True, capacity=1)
        with t.span("a"):
            pass
        with t.span("b"):
            pass
        assert t.dropped == 1
        t.clear()
        assert t.dropped == 0 and t.events == []


class TestDisabledFastPath:
    def test_module_span_is_shared_noop_singleton(self):
        TRACER.enabled = TRACER.recording = False
        s1, s2 = span("a", key="v"), span("b")
        assert s1 is s2  # one shared object: no allocation on the hot path
        with s1:
            pass
        assert TRACER.events == []

    def test_enabled_records_real_span(self):
        TRACER.enabled = True
        with span("real", shuffle_id=3):
            pass
        (ev,) = TRACER.events
        assert ev["name"] == "real" and ev["args"]["shuffle_id"] == 3
        assert ev["trace_id"] and ev["span_id"] and ev["parent_id"] == 0

    def test_nested_spans_parent(self):
        TRACER.enabled = True
        with TRACER.span("outer") as octx:
            with TRACER.span("inner"):
                pass
        inner, outer = TRACER.events
        assert inner["parent_id"] == octx.span_id
        assert inner["trace_id"] == outer["trace_id"]

    def test_remote_context_reparents(self):
        TRACER.enabled = True
        remote = Tracer.remote_context(trace_id=77, span_id=88)
        with TRACER.activate(remote):
            with TRACER.span("served"):
                pass
        (ev,) = TRACER.events
        assert ev["trace_id"] == 77 and ev["parent_id"] == 88

    def test_executor_scope_stamps_eid_and_merge_rewrites_pid(self):
        TRACER.enabled = True
        with TRACER.executor_scope(5):
            with TRACER.span("on5"):
                pass
        merged = merge_events([TRACER.events, TRACER.events])  # overlap dedups
        assert len(merged) == 1
        assert merged[0]["pid"] == 5  # executor id IS the Perfetto process


# ---------------------------------------------------------------------------
# trace-context wire extensions
# ---------------------------------------------------------------------------

_IDS = [ShuffleBlockId(1, 2, 3), ShuffleBlockId(1, 4, 5)]


def _bare_header(tag, ids):
    out = struct.pack("<Q", tag) + struct.pack("<I", len(ids))
    for b in ids:
        out += struct.pack("<iii", b.shuffle_id, b.map_id, b.reduce_id)
    return out


class TestTraceExtCodec:
    def test_fetch_ext_roundtrip(self):
        ext = pack_trace_ext(0xDEAD, 0xBEEF)
        assert len(ext) == TRACE_EXT_SIZE
        assert unpack_trace_ext(b"xxxx" + ext) == (0xDEAD, 0xBEEF)
        assert unpack_trace_ext(b"\x00" * 40) is None  # no magic

    def test_replica_ext_roundtrip(self):
        ext = pack_replica_trace_ext(11, 22)
        assert len(ext) == REPLICA_TRACE_EXT_SIZE
        assert unpack_replica_trace_ext(b"hdr" + ext) == (11, 22)
        assert unpack_replica_trace_ext(b"\x00" * 30) is None

    def test_split_plain_header_untouched(self):
        h = pack_batch_fetch_req(9, _IDS)
        assert split_fetch_req_trace(h) == (None, h)

    def test_split_strips_trailing_ext(self):
        h = pack_batch_fetch_req(9, _IDS, trace=(123, 456))
        ctx, stripped = split_fetch_req_trace(h)
        assert ctx == (123, 456)
        assert stripped == pack_batch_fetch_req(9, _IDS)

    def test_split_with_app_ext_between(self):
        h = pack_batch_fetch_req(9, _IDS, app_id="app-007", trace=(1, 2))
        ctx, stripped = split_fetch_req_trace(h)
        assert ctx == (1, 2)
        assert unpack_fetch_req_app_id(stripped, len(_IDS)) == "app-007"

    def test_adversarial_app_id_containing_magic_not_missplit(self):
        """An app id whose utf-8 tail embeds the trace magic + 16 junk bytes
        must NOT be mis-split: structural consistency rejects it."""
        evil = "x" + pack_trace_ext(7, 8).decode("latin-1")
        h = pack_batch_fetch_req(9, _IDS, app_id=evil)
        ctx, stripped = split_fetch_req_trace(h)
        assert ctx is None and stripped == h
        # and the tenant ext still decodes to the evil app id untouched
        assert unpack_fetch_req_app_id(h, len(_IDS)) == evil


class TestGoldenFramesUnchanged:
    """All obs knobs off => historical bytes exactly (the golden-frame pin)."""

    def test_fetch_req_bytes_identical_without_trace(self):
        assert pack_batch_fetch_req(42, _IDS) == _bare_header(42, _IDS)

    def test_obs_knobs_default_off(self):
        conf = TpuShuffleConf()
        assert conf.obs_trace_context is False
        assert conf.obs_metrics_port == 0
        assert conf.obs_ring_capacity == 8192
        assert conf.obs_postmortem_dir == ""

    def test_knob_parsing_from_spark_conf(self):
        conf = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.obs.traceContext": "true",
                "spark.shuffle.tpu.obs.metricsPort": "9091",
                "spark.shuffle.tpu.obs.ringCapacity": "1024",
                "spark.shuffle.tpu.obs.postmortemDir": "/tmp/pm",
            }
        )
        assert conf.obs_trace_context is True
        assert conf.obs_metrics_port == 9091
        assert conf.obs_ring_capacity == 1024
        assert conf.obs_postmortem_dir == "/tmp/pm"

    def test_knob_validation_bounds(self):
        with pytest.raises(ValueError, match="obs_metrics_port"):
            TpuShuffleConf(obs_metrics_port=70000).validate()
        with pytest.raises(ValueError, match="obs_ring_capacity"):
            TpuShuffleConf(obs_ring_capacity=0).validate()


class TestOldReceiversIgnoreExt:
    def test_old_server_parses_triples_despite_trailing_ext(self):
        """A pre-obs server reads tag + count triples and never looks past
        them — the trailing ext must not corrupt the parse."""
        h = pack_batch_fetch_req(42, _IDS, trace=(9, 10))
        tag, bids = unpack_batch_fetch_req(h)
        assert tag == 42 and bids == _IDS

    def test_old_tenant_server_sees_no_app_in_bare_trace_ext(self):
        """The tenant-ext reader on a header that carries ONLY a trace ext
        reads an absurd length and bails to None (single-tenant semantics) —
        never a garbage app id."""
        h = pack_batch_fetch_req(42, _IDS, trace=(9, 10))
        assert unpack_fetch_req_app_id(h, len(_IDS)) is None

    def test_old_tenant_server_still_reads_app_under_trace_ext(self):
        h = pack_batch_fetch_req(42, _IDS, app_id="tenant-a", trace=(9, 10))
        assert unpack_fetch_req_app_id(h, len(_IDS)) == "tenant-a"


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_register_snapshot_prometheus(self):
        reg = MetricsRegistry(executor_id=3)
        reg.register("wire", lambda: [sample("wire", "tx_bytes_total", 128, {"lane": 0}, kind="counter")])
        text = reg.prometheus_text()
        assert "# TYPE sparkucx_tpu_wire_tx_bytes_total counter" in text
        assert 'sparkucx_tpu_wire_tx_bytes_total{executor="3",lane="0"} 128' in text

    def test_reregister_replaces_not_duplicates(self):
        reg = MetricsRegistry()
        reg.register("a", lambda: [sample("f", "x", 1)])
        reg.register("a", lambda: [sample("f", "x", 2)])
        rows = [s for s in reg.snapshot() if s.name == "x"]
        assert len(rows) == 1 and rows[0].value == 2

    def test_provider_error_counted_not_fatal(self):
        reg = MetricsRegistry()
        reg.register("bad", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        reg.register("good", lambda: [sample("f", "ok", 1)])
        text = reg.prometheus_text()
        assert "sparkucx_tpu_f_ok 1" in text
        assert "sparkucx_tpu_obs_provider_errors_total 1" in text
        # the error count accumulates across snapshots
        assert "provider_errors_total 2" in reg.prometheus_text()

    def test_counter_dict_provider_skips_non_numeric(self):
        p = counter_dict_provider("elastic", lambda: {"epoch": 4, "mesh": "[0,1]", "degraded": True})
        rows = {s.name: s.value for s in p()}
        assert rows == {"epoch": 4.0, "degraded": 1.0}  # string skipped, bool coerced

    def test_wire_lane_provider_labels(self):
        lanes = [{"executor": 1, "slot": 0, "lane": 2, "tx_bytes": 10, "rx_stall_p99_ns": 5}]
        rows = {s.full_name: s for s in wire_lane_provider(lambda: lanes)()}
        tx = rows["sparkucx_tpu_wire_tx_bytes_total"]
        assert tx.kind == "counter" and dict(tx.labels) == {"peer": "1", "slot": "0", "lane": "2"}
        assert rows["sparkucx_tpu_wire_rx_stall_p99_ns"].kind == "gauge"

    def test_stats_aggregator_provider(self):
        agg = StatsAggregator()
        agg.record_counters("read", failovers=2, blocks_retried=1)
        rows = {(s.name, dict(s.labels).get("kind")): s.value for s in stats_aggregator_provider(agg)()}
        assert rows[("failovers_total", "read")] == 2
        assert rows[("blocks_retried_total", "read")] == 1
        assert ("count_total", "read") in rows  # counter-only kinds still listed

    def test_tracer_provider(self):
        t = Tracer(enabled=True, capacity=2)
        with t.span("a"):
            pass
        rows = {s.name: s.value for s in tracer_provider(t)()}
        assert rows["trace_events"] == 1 and rows["trace_dropped_total"] == 0


class TestHttpScrape:
    def test_get_metrics_and_404(self):
        reg = MetricsRegistry(executor_id=0)
        reg.register("f", lambda: [sample("f", "up", 1)])
        server = start_http_server(reg, port=0)  # test-only: conf 0 means OFF
        try:
            host, port = server.server_address[:2]
            body = urllib.request.urlopen(f"http://{host}:{port}/metrics").read().decode()
            assert 'sparkucx_tpu_f_up{executor="0"} 1' in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{host}:{port}/nope")
        finally:
            close_http_server(server)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_capture_full_bundle(self):
        reg = MetricsRegistry()
        reg.register("f", lambda: [sample("f", "x", 7)])
        rec = FlightRecorder(Tracer(enabled=True), executor_id=2)
        rec.attach_registry(reg)
        rec.attach_membership(lambda: {"epoch": 3, "alive": [0, 1], "dead": {}})
        with rec.tracer.span("before-the-fault"):
            pass
        b = rec.capture("unit", detail="ctx")
        assert b["reason"] == "unit" and b["executor"] == 2
        assert b["context"] == {"detail": "ctx"}
        assert [e["name"] for e in b["trace_tail"]] == ["before-the-fault"]
        assert "sparkucx_tpu_f_x 7" in b["metrics"]
        assert b["membership"]["epoch"] == 3
        assert rec.last_postmortem is b or rec.last_postmortem == b

    def test_bundles_bounded(self):
        rec = FlightRecorder(Tracer())
        for i in range(MAX_BUNDLES + 5):
            rec.capture(f"r{i}")
        assert len(rec.postmortems) == MAX_BUNDLES
        assert rec.captures == MAX_BUNDLES + 5
        assert rec.last_postmortem["reason"] == f"r{MAX_BUNDLES + 4}"

    def test_transport_error_triggers_light_capture(self):
        rec = FlightRecorder(Tracer())
        reg = MetricsRegistry()
        rec.attach_registry(reg)
        rec.install()
        try:
            TransportError("synthetic wire failure")
        finally:
            rec.close()
        b = rec.last_postmortem
        assert b["reason"] == "transport_error"
        assert "synthetic wire failure" in b["context"]["error"]
        assert b["metrics"] is None  # light: no provider walk under unknown locks

    def test_close_unhooks(self):
        rec = FlightRecorder(Tracer())
        rec.install()
        rec.close()
        TransportError("after close")
        assert rec.last_postmortem is None

    def test_chaos_fault_observer(self):
        rec = FlightRecorder(Tracer())
        rec.install()
        try:
            faults.arm("some.point", faults.stall(0))
            faults.check("some.point", lane=1)
        finally:
            rec.close()
            faults.reset()
        b = rec.last_postmortem
        assert b["reason"] == "fault:some.point"
        assert b["context"]["lane"] == 1

    def test_postmortem_dir_dumps_file(self, tmp_path):
        rec = FlightRecorder(Tracer(), executor_id=1, postmortem_dir=str(tmp_path))
        b = rec.capture("diskdump")
        assert b["path"].endswith("postmortem-e1-0001-diskdump.json")
        on_disk = json.loads((tmp_path / "postmortem-e1-0001-diskdump.json").read_text())
        assert on_disk["reason"] == "diskdump"

    def test_reentrant_capture_dropped(self):
        rec = FlightRecorder(Tracer())
        reg = MetricsRegistry()
        # a provider that itself triggers a capture: must not recurse
        reg.register("evil", lambda: [sample("f", "n", len(rec.postmortems) if rec.capture("inner") is None else -1)])
        rec.attach_registry(reg)
        b = rec.capture("outer")
        assert b is not None and rec.captures == 1  # inner was dropped

    def test_ring_capacity_applied(self):
        t = Tracer(enabled=True)
        FlightRecorder(t, ring_capacity=3)
        for i in range(5):
            with t.span(f"s{i}"):
                pass
        assert len(t.events) == 3


# ---------------------------------------------------------------------------
# pull AMs over the loopback peer wire
# ---------------------------------------------------------------------------


def _mesh(n, **conf_kw):
    conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
    conf = TpuShuffleConf(**conf_kw)
    ts = [PeerTransport(conf, executor_id=i) for i in range(n)]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    return ts


def _close_all(ts):
    for t in ts:
        t.close()


def _stage(t, shuffle_id, num_mappers, num_reducers, seed=0):
    rng = np.random.default_rng(seed)
    t.store.create_shuffle(shuffle_id, num_mappers, num_reducers)
    payloads = {}
    for m in range(num_mappers):
        w = t.store.map_writer(shuffle_id, m)
        for r in range(num_reducers):
            data = rng.integers(0, 256, size=200 + 37 * (m + r), dtype=np.uint8).tobytes()
            payloads[(m, r)] = data
            w.write_partition(r, data)
        w.commit()
    return payloads


class TestPullAms:
    def test_trace_pull_returns_peer_scoped_events(self):
        TRACER.enabled = True
        ts = _mesh(2)
        try:
            with TRACER.executor_scope(1):
                with TRACER.span("on-executor-1"):
                    pass
            with TRACER.executor_scope(0):
                with TRACER.span("on-executor-0"):
                    pass
            buf = ts[0].pull_trace(1)
            assert buf["executor"] == 1
            assert [e["name"] for e in buf["events"]] == ["on-executor-1"]
            assert buf["dropped"] == 0
        finally:
            _close_all(ts)

    def test_metrics_pull_returns_prometheus_text(self):
        ts = _mesh(2)
        try:
            text = ts[0].pull_metrics(1)
            assert 'executor="1"' in text
            assert "sparkucx_tpu_replica_" in text
            assert "sparkucx_tpu_obs_trace_events" in text
        finally:
            _close_all(ts)

    def test_pull_from_dead_peer_times_out_typed(self):
        ts = _mesh(2, wire_timeout_ms=1000)
        try:
            faults.kill_executor(ts[1])
            with pytest.raises((TransportError, OSError)):
                ts[0].pull_trace(1, timeout=2.0)
        finally:
            _close_all(ts)

    def test_http_scrape_disabled_by_default(self):
        ts = _mesh(1)
        try:
            assert ts[0]._metrics_http is None
        finally:
            _close_all(ts)

    def test_http_scrape_enabled_by_conf(self):
        import socket

        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        ts = _mesh(1, obs_metrics_port=port)
        try:
            assert ts[0]._metrics_http is not None
            body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
            assert "sparkucx_tpu_obs_trace_events" in body
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# trace propagation through a live fetch (wire composition lanes)
# ---------------------------------------------------------------------------


def _reader(transport, payloads, num_mappers, num_reducers, executors, **kw):
    kw.setdefault("fetch_retries", 2)
    kw.setdefault("fetch_deadline_ms", 2000)
    kw.setdefault("fetch_backoff_ms", 10)
    return TpuShuffleReader(
        transport,
        executor_id=transport.executor_id,
        shuffle_id=0,
        start_partition=0,
        end_partition=num_reducers,
        num_mappers=num_mappers,
        block_sizes=lambda m, r: len(payloads[(m, r)]),
        max_blocks_per_request=1,
        sender_of=lambda m: 1,
        replica_of=lambda primary: ring_neighbors(primary, executors, 1),
        **kw,
    )


def _drain(reader):
    got = {}
    for blk in reader.fetch_blocks():
        got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
        blk.release()
    return got


class TestTracePropagation:
    @pytest.mark.parametrize(
        "lanes",
        [
            {},
            {"wire_checksum": True, "wire_compress_codec": "dict"},
            {"wire_streams": 2},
        ],
        ids=["plain", "crc+codec", "striped"],
    )
    def test_serve_span_parents_under_read_window(self, lanes):
        TRACER.enabled = True
        ts = _mesh(2, obs_trace_context=True, **lanes)
        try:
            payloads = _stage(ts[1], 0, 2, 2)
            ts[1].store.seal(0)
            got = _drain(_reader(ts[0], payloads, 2, 2, executors=[0, 1]))
            assert got == payloads  # bit-identical with tracing on
            events = TRACER.events
            windows = {e["span_id"] for e in events if e["name"] == "read.window"}
            serves = [e for e in events if e["name"] == "server.serve"]
            assert windows and serves
            assert all(s["parent_id"] in windows for s in serves)
            assert {s["eid"] for s in serves} == {1}
        finally:
            _close_all(ts)

    def test_obs_off_emits_no_ext_no_spans(self):
        ts = _mesh(2)  # obs_trace_context defaults False
        try:
            TRACER.enabled = TRACER.recording = False
            payloads = _stage(ts[1], 0, 1, 2)
            ts[1].store.seal(0)
            got = _drain(_reader(ts[0], payloads, 1, 2, executors=[0, 1]))
            assert got == payloads
            assert TRACER.events == []  # nothing recorded anywhere
        finally:
            _close_all(ts)

    def test_replica_push_span_parents_apply(self):
        TRACER.enabled = True
        ts = _mesh(2, obs_trace_context=True, replication_factor=1)
        try:
            _stage(ts[0], 5, 1, 2)
            ts[0].store.seal(5)
            assert ts[0].replication_wait(5, timeout=10.0)
            events = TRACER.events
            pushes = {e["span_id"] for e in events if e["name"] == "replica.push"}
            applies = [e for e in events if e["name"] == "server.replica_apply"]
            assert pushes and applies
            assert all(a["parent_id"] in pushes for a in applies)
            assert {a["eid"] for a in applies} == {1}
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# the headline acceptance scenario
# ---------------------------------------------------------------------------


class TestAcceptanceChaos:
    def test_failover_trace_metrics_postmortem(self, tmp_path):
        """Kill the primary mid-read with the full obs plane on: the merged
        Perfetto trace must show a read.window span served by TWO different
        executors (primary then replica), the Prometheus snapshot must carry
        wire/replica/elastic/eviction families from every executor, and a
        postmortem bundle must have been auto-dumped."""
        TRACER.enabled = True
        ts = _mesh(
            3,
            replication_factor=1,
            wire_timeout_ms=5000,
            obs_trace_context=True,
            obs_postmortem_dir=str(tmp_path),
        )
        try:
            for t in ts:
                t.membership = ClusterMembership(range(3))
                t.store.eviction = EvictionManager(t.store)
            payloads = _stage(ts[1], 0, 2, 3, seed=42)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)

            reader = _reader(ts[0], payloads, 2, 3, executors=[0, 1, 2])
            got = {}
            it = reader.fetch_blocks()
            first = next(it)
            got[(first.block_id.map_id, first.block_id.reduce_id)] = bytes(first.data)
            first.release()
            faults.kill_executor(ts[1])  # chaos: primary dies mid-stream
            for blk in it:
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # failover stayed bit-identical

            # -- leg 1: ONE merged Perfetto trace, two serving executors ----
            path = tmp_path / "merged.json"
            buffers = [TRACER.events, ts[0].pull_trace(2)["events"]]
            merged = merge_events(buffers)
            path.write_text(json.dumps({"traceEvents": merged, "displayTimeUnit": "ms"}))
            doc = json.loads(path.read_text())["traceEvents"]
            windows = {e["span_id"] for e in doc if e["name"] == "read.window"}
            serve_eids = {
                e["pid"]
                for e in doc
                if e["name"] == "server.serve" and e["parent_id"] in windows
            }
            assert len(serve_eids) >= 2  # primary AND replica served windows
            assert 2 in serve_eids  # the replica holder really answered

            # -- leg 2: metrics families from every executor ----------------
            texts = {0: ts[0].metrics.prometheus_text(), 2: ts[0].pull_metrics(2)}
            texts[1] = ts[1].metrics.prometheus_text()  # dead peer: local read
            for eid, text in texts.items():
                for family in ("replica", "elastic", "eviction", "obs"):
                    assert f"sparkucx_tpu_{family}_" in text, (eid, family)
                assert f'executor="{eid}"' in text
            # the reader's failover counters surfaced through the registry
            assert "sparkucx_tpu_ops_failovers_total" in texts[0]
            # wire lanes existed on the fetching side
            assert "sparkucx_tpu_wire_rx_bytes_total" in texts[0]
            # elastic view noticed the death
            assert 'sparkucx_tpu_elastic_dead{executor="0"} 1' in texts[0]

            # -- leg 3: postmortem bundles auto-dumped ----------------------
            dumped = list(tmp_path.glob("postmortem-*.json"))
            assert dumped  # TransportError/chaos captures hit the dir
            # in-memory rings hold the newest 16 (transport_error flood from
            # the failover evicts older bundles); the dir holds everything
            reasons = {json.loads(p.read_text())["reason"] for p in dumped}
            assert "chaos_kill" in reasons  # kill_executor's full bundle
            assert "transport_error" in reasons
            kill_bundle = json.loads(
                next(p for p in dumped if "chaos_kill" in p.name).read_text()
            )
            assert kill_bundle["metrics"] is not None  # full capture pre-kill
            assert kill_bundle["executor"] == 1
        finally:
            _close_all(ts)
