"""Tests for the distributed sample sort (device-resident TeraSort core)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.sort import KEY_MAX, SortSpec, build_distributed_sort, oracle_sort

N = 8
CAP = 256
W = 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


@pytest.fixture(scope="module")
def fn(mesh):
    spec = SortSpec(
        num_executors=N,
        capacity=CAP,
        recv_capacity=2 * CAP,
        width=W,
        samples_per_shard=64,
        impl="dense",
    )
    return build_distributed_sort(mesh, spec)


def _place(mesh, keys, payload, nvalid):
    return (
        jax.device_put(keys, NamedSharding(mesh, P("ex"))),
        jax.device_put(payload, NamedSharding(mesh, P("ex", None))),
        jax.device_put(nvalid, NamedSharding(mesh, P("ex"))),
    )


def _collect(fn, mesh, keys, payload, nvalid):
    ko, po, cnt = fn(*_place(mesh, keys, payload, nvalid))
    ko = np.asarray(ko).reshape(N, -1)
    po = np.asarray(po).reshape(N, ko.shape[1], -1)
    cnt = np.asarray(cnt)
    got_k = np.concatenate([ko[j, : cnt[j]] for j in range(N)])
    got_p = np.concatenate([po[j, : cnt[j]] for j in range(N)])
    return got_k, got_p, cnt


class TestDistributedSort:
    def test_full_shards_unique_keys(self, fn, mesh, rng):
        keys = rng.permutation(N * CAP).astype(np.uint32)
        payload = keys[:, None].astype(np.int32) * np.arange(1, W + 1, dtype=np.int32)
        nvalid = np.full(N, CAP, np.int32)
        got_k, got_p, cnt = _collect(fn, mesh, keys, payload, nvalid)
        want_k, want_p = oracle_sort(keys, payload)
        assert cnt.sum() == N * CAP
        np.testing.assert_array_equal(got_k, want_k)
        np.testing.assert_array_equal(got_p, want_p)

    def test_ragged_shards_with_padding(self, fn, mesh, rng):
        nvalid = rng.integers(0, CAP + 1, size=N).astype(np.int32)
        nvalid[3] = 0  # empty shard
        keys = np.full(N * CAP, KEY_MAX, dtype=np.uint32)
        payload = np.zeros((N * CAP, W), np.int32)
        real = []
        for j in range(N):
            ks = rng.integers(0, 2**32 - 1, size=nvalid[j], dtype=np.uint64).astype(np.uint32)
            keys[j * CAP : j * CAP + nvalid[j]] = ks
            payload[j * CAP : j * CAP + nvalid[j], 0] = np.arange(nvalid[j])
            real.append(ks)
        got_k, _, cnt = _collect(fn, mesh, keys, payload, nvalid)
        want = np.sort(np.concatenate(real))
        assert cnt.sum() == nvalid.sum()
        np.testing.assert_array_equal(got_k, want)

    def test_duplicate_keys_multiset_preserved(self, fn, mesh, rng):
        keys = rng.integers(0, 7, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        payload = rng.integers(0, 2**31 - 1, size=(N * CAP, W), dtype=np.int64).astype(np.int32)
        nvalid = np.full(N, CAP, np.int32)
        got_k, got_p, cnt = _collect(fn, mesh, keys, payload, nvalid)
        assert cnt.sum() == N * CAP
        np.testing.assert_array_equal(got_k, np.sort(keys))
        # payload rows survive as a multiset, attached to the right key
        want_rows = sorted(map(tuple, np.concatenate([keys[:, None].astype(np.int64), payload], axis=1)))
        got_rows = sorted(map(tuple, np.concatenate([got_k[:, None].astype(np.int64), got_p], axis=1)))
        assert got_rows == want_rows

    def test_shards_are_contiguous_ranges(self, fn, mesh, rng):
        keys = rng.integers(0, 2**32 - 1, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        payload = np.zeros((N * CAP, W), np.int32)
        nvalid = np.full(N, CAP, np.int32)
        ko, _, cnt = fn(*_place(mesh, keys, payload, nvalid))
        ko = np.asarray(ko).reshape(N, -1)
        cnt = np.asarray(cnt)
        hi = np.uint64(0)
        for j in range(N):
            shard = ko[j, : cnt[j]]
            if len(shard) == 0:
                continue
            assert np.all(np.diff(shard.astype(np.int64)) >= 0)  # sorted within shard
            assert np.uint64(shard[0]) >= hi  # ranges ascend across shards
            hi = np.uint64(shard[-1])

    def test_skewed_keys_balanced_by_sampling(self, fn, mesh, rng):
        # all keys in a narrow band: splitters adapt, nothing overflows 2x headroom
        keys = rng.integers(1000, 1100, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        payload = np.zeros((N * CAP, W), np.int32)
        nvalid = np.full(N, CAP, np.int32)
        got_k, _, cnt = _collect(fn, mesh, keys, payload, nvalid)
        assert np.all(cnt <= 2 * CAP)
        np.testing.assert_array_equal(got_k, np.sort(keys))

    def test_valid_rows_with_sentinel_key(self, fn, mesh, rng):
        # Valid rows whose key equals KEY_MAX must survive: they are
        # distinguished from padding only by stable sort + prefix layout.
        keys = rng.integers(0, 1000, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        sent = rng.choice(N * CAP, size=17, replace=False)
        keys[sent] = KEY_MAX
        payload = np.arange(N * CAP, dtype=np.int32)[:, None] * np.ones(W, np.int32)
        nvalid = np.full(N, CAP, np.int32)
        got_k, got_p, cnt = _collect(fn, mesh, keys, payload, nvalid)
        assert cnt.sum() == N * CAP
        np.testing.assert_array_equal(got_k, np.sort(keys))
        # every sentinel-keyed payload row made it through
        assert sorted(got_p[got_k == KEY_MAX][:, 0]) == sorted(np.arange(N * CAP)[sent])

    def test_imbalanced_shards_stay_balanced(self, mesh, rng):
        # One full shard of uniform keys + 7 near-empty shards pinned at key 0:
        # fill-weighted sampling must keep the big shard's rows spread out
        # instead of letting the tiny shards' keys dominate the splitters.
        spec = SortSpec(
            num_executors=N, capacity=CAP, recv_capacity=CAP, width=1,
            samples_per_shard=64, impl="dense",
        )
        f = build_distributed_sort(make_mesh(N), spec)
        keys = np.full(N * CAP, KEY_MAX, dtype=np.uint32)
        nvalid = np.zeros(N, np.int32)
        nvalid[0] = CAP
        keys[:CAP] = rng.integers(0, 2**32 - 1, size=CAP, dtype=np.uint64).astype(np.uint32)
        for j in range(1, N):
            nvalid[j] = 1
            keys[j * CAP] = 0
        payload = np.zeros((N * CAP, 1), np.int32)
        ko, _, cnt = f(*_place(make_mesh(N), keys, payload, nvalid))
        cnt = np.asarray(cnt)
        assert cnt.sum() == nvalid.sum()
        # receive stays within the (deliberately tight) 1x capacity everywhere
        assert np.all(cnt <= CAP), cnt
        got = np.concatenate(
            [np.asarray(ko).reshape(N, -1)[j, : cnt[j]] for j in range(N)]
        )
        valid_keys = np.concatenate([keys[j * CAP : j * CAP + nvalid[j]] for j in range(N)])
        np.testing.assert_array_equal(got, np.sort(valid_keys))

    def test_single_executor_mesh(self):
        mesh1 = make_mesh(1)
        spec = SortSpec(num_executors=1, capacity=64, recv_capacity=64, width=1, impl="dense")
        f = build_distributed_sort(mesh1, spec)
        rng = np.random.default_rng(0)
        keys = rng.permutation(64).astype(np.uint32)
        ko, po, cnt = f(
            jax.device_put(keys, NamedSharding(mesh1, P("ex"))),
            jax.device_put(keys[:, None].astype(np.int32), NamedSharding(mesh1, P("ex", None))),
            jax.device_put(np.array([64], np.int32), NamedSharding(mesh1, P("ex"))),
        )
        np.testing.assert_array_equal(np.asarray(ko), np.arange(64, dtype=np.uint32))
        np.testing.assert_array_equal(np.asarray(po)[:, 0], np.arange(64, dtype=np.int32))
        assert int(np.asarray(cnt)[0]) == 64

    def test_single_lowering_auto_resolution(self):
        # n=1 resolves to 'single' on ANY platform (pure XLA: no collective)
        spec = SortSpec(num_executors=1, capacity=64, recv_capacity=64, width=1)
        assert spec.resolve_impl(platform="cpu").impl == "single"
        assert spec.resolve_impl(platform="tpu").impl == "single"
        multi = SortSpec(num_executors=2, capacity=64, recv_capacity=128, width=1)
        assert multi.resolve_impl(platform="cpu").impl == "dense"
        # single demands n=1 and recv headroom >= capacity
        bad = SortSpec(num_executors=2, capacity=64, recv_capacity=128, width=1, impl="single")
        with pytest.raises(ValueError, match="single"):
            bad.validate()

    def test_single_lowering_vs_oracle_with_padding(self):
        """impl='single' (what n=1 'auto' now runs, incl. the PERF headline):
        nv < capacity padding, a VALID KEY_MAX key, and the recv_capacity >
        capacity pad branch — output must match the other lowerings' contract
        (sorted prefix, zeroed payload tail, KEY_MAX key tail)."""
        mesh1 = make_mesh(1)
        CAP, RECV, NV = 64, 96, 40
        spec = SortSpec(num_executors=1, capacity=CAP, recv_capacity=RECV, width=2, impl="auto")
        f = build_distributed_sort(mesh1, spec)
        assert f.spec.impl == "single"
        rng = np.random.default_rng(7)
        keys = np.full(CAP, 12345, np.uint32)  # padding region deliberately NOT KEY_MAX
        keys[:NV] = rng.integers(0, 1 << 32, size=NV, dtype=np.uint64).astype(np.uint32)
        keys[3] = KEY_MAX  # a genuinely valid max-key row must survive
        payload = np.full((CAP, 2), -7, np.int32)  # garbage padding payload
        payload[:NV] = rng.integers(-100, 100, size=(NV, 2)).astype(np.int32)
        ko, po, cnt = f(
            jax.device_put(keys, NamedSharding(mesh1, P("ex"))),
            jax.device_put(payload, NamedSharding(mesh1, P("ex", None))),
            jax.device_put(np.array([NV], np.int32), NamedSharding(mesh1, P("ex"))),
        )
        ko, po, cnt = np.asarray(ko), np.asarray(po), np.asarray(cnt)
        assert cnt.tolist() == [NV]
        ek, ep = oracle_sort(keys[:NV], payload[:NV])
        np.testing.assert_array_equal(ko[:NV], ek)
        np.testing.assert_array_equal(po[:NV], ep)
        # contract parity with the collective lowerings: zero payload tail,
        # KEY_MAX key tail — caller padding must NOT leak through
        np.testing.assert_array_equal(ko[NV:], np.full(RECV - NV, KEY_MAX, np.uint32))
        np.testing.assert_array_equal(po[NV:], np.zeros((RECV - NV, 2), np.int32))

    def test_spec_validation(self, mesh):
        with pytest.raises(ValueError, match="mesh size"):
            build_distributed_sort(mesh, SortSpec(num_executors=4, capacity=8, recv_capacity=8))
        with pytest.raises(ValueError, match="32-bit"):
            SortSpec(
                num_executors=N, capacity=8, recv_capacity=8,
                dtype=np.dtype(np.float64), impl="dense",
            ).validate()
        with pytest.raises(ValueError, match="samples_per_shard"):
            SortSpec(
                num_executors=N, capacity=8, recv_capacity=8,
                samples_per_shard=2, impl="dense",
            ).validate()


class TestRunDistributedSort:
    """Host driver with automatic skew retry (run_distributed_sort)."""

    def test_uniform_keys_roundtrip(self, rng):
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort
        from sparkucx_tpu.ops.exchange import make_mesh

        n, total = 4, 3000
        keys = rng.integers(0, 1 << 31, size=total, dtype=np.uint32)
        payload = rng.integers(-99, 99, size=(total, 3), dtype=np.int32)
        spec = SortSpec(
            num_executors=n, capacity=1024, recv_capacity=1536, width=3, impl="dense"
        )
        sk, sp = run_distributed_sort(make_mesh(n), spec, keys, payload)
        ok, op = oracle_sort(keys, payload)
        assert np.array_equal(sk, ok)
        # payload rows must travel with their keys (same multiset per key)
        assert sorted(map(tuple, sp)) == sorted(map(tuple, op))

    def test_skewed_keys_trigger_retry(self, rng):
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort
        from sparkucx_tpu.ops.exchange import make_mesh

        n, total = 4, 2000
        # 90% of keys identical: one range gets almost everything, so the
        # balanced recv_capacity must overflow and the driver must double it
        keys = np.where(
            rng.uniform(size=total) < 0.9,
            np.uint32(7),
            rng.integers(0, 1 << 31, size=total).astype(np.uint32),
        )
        payload = rng.integers(-99, 99, size=(total, 1), dtype=np.int32)
        spec = SortSpec(
            num_executors=n, capacity=512, recv_capacity=600, width=1, impl="dense"
        )
        sk, sp = run_distributed_sort(make_mesh(n), spec, keys, payload)
        ok, _ = oracle_sort(keys, payload)
        assert np.array_equal(sk, ok)

    def test_pathological_skew_raises(self, rng):
        from sparkucx_tpu.ops.sort import SortSpec, run_distributed_sort
        from sparkucx_tpu.ops.exchange import make_mesh

        n, total = 4, 2000
        keys = np.full(total, 7, np.uint32)  # every key identical
        payload = np.zeros((total, 1), np.int32)
        spec = SortSpec(
            num_executors=n, capacity=512, recv_capacity=520, width=1, impl="dense"
        )
        with pytest.raises(RuntimeError, match="skewed"):
            run_distributed_sort(make_mesh(n), spec, keys, payload, max_attempts=1)


class TestExternalSort:
    """Out-of-core driver: device-batch sorts + stable host merge."""

    def test_multi_batch_vs_oracle(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort

        n, cap = 4, 200
        total = 5 * n * cap + 37  # 6 runs, ragged tail
        keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint64).astype(np.uint32)
        payload = rng.integers(-99, 99, size=(total, 3), dtype=np.int32)
        spec = SortSpec(
            num_executors=n, capacity=cap, recv_capacity=2 * cap, width=3, impl="dense"
        )
        sk, sp = run_external_sort(make_mesh(n), spec, keys, payload)
        ok, op = oracle_sort(keys, payload)
        assert np.array_equal(sk, ok)
        assert np.array_equal(sp, op)

    def test_stability_under_heavy_duplication(self, rng):
        # payload carries the input row index; the stable oracle's permutation
        # must be reproduced row-exact even with only 3 distinct keys spread
        # across many runs
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort

        n, cap = 2, 64
        total = 7 * n * cap + 11
        keys = rng.integers(0, 3, size=total, dtype=np.uint64).astype(np.uint32)
        payload = np.arange(total, dtype=np.int32)[:, None]
        spec = SortSpec(
            num_executors=n, capacity=cap, recv_capacity=2 * cap, width=1, impl="dense"
        )
        sk, sp = run_external_sort(make_mesh(n), spec, keys, payload)
        ok, op = oracle_sort(keys, payload)
        assert np.array_equal(sk, ok)
        assert np.array_equal(sp, op)

    def test_single_batch_delegates(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_external_sort

        n, cap = 4, 256
        total = n * cap  # exactly one batch
        keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint64).astype(np.uint32)
        payload = rng.integers(-99, 99, size=(total, 1), dtype=np.int32)
        spec = SortSpec(
            num_executors=n, capacity=cap, recv_capacity=2 * cap, width=1, impl="dense"
        )
        sk, _ = run_external_sort(make_mesh(n), spec, keys, payload)
        ok, _ = oracle_sort(keys, payload)
        assert np.array_equal(sk, ok)

    def test_merge_sorted_runs_edges(self):
        from sparkucx_tpu.ops.sort import merge_sorted_runs

        # odd run count, empty run, all-equal keys
        k1 = np.array([1, 3, 5], np.uint32)
        k2 = np.array([], np.uint32)
        k3 = np.array([2, 3, 3], np.uint32)
        p = lambda k, base: (np.arange(len(k), dtype=np.int32) + base)[:, None]
        mk, mp = merge_sorted_runs([k1, k2, k3], [p(k1, 0), p(k2, 10), p(k3, 20)])
        assert mk.tolist() == [1, 2, 3, 3, 3, 5]
        # stability: run1's key-3 row (payload 1) precedes run3's (21, 22)
        assert mp[:, 0].tolist() == [0, 20, 1, 21, 22, 2]
        with pytest.raises(ValueError):
            merge_sorted_runs([], [])
