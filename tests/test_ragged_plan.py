"""Standalone verification of the ragged exchange's offset/size formulas.

XLA:CPU has no ragged-all-to-all kernel, so the multi-device CPU mesh only ever
executes the dense lowering — the exact offset math that would corrupt data on a
real pod (``ragged_params``, the layout contract of the reference's reply
packing, UcxWorkerWrapper.scala:397-448) is verified here instead by:

1. simulating ``jax.lax.ragged_all_to_all`` semantics in numpy, parameterized
   by the SAME ``ragged_params`` expressions the jitted collective traces, and
   property-testing the simulated receive buffers against ``oracle_exchange``
   for random n x n size matrices (n up to 8);
2. differentially comparing the simulation against the dense lowering actually
   executed on the 8-device CPU mesh (both must produce bit-identical tight
   sender-major receive buffers);
3. lowering the ragged impl on the CPU mesh (compile-time trace check).

A regression in any input/output offset formula fails 1 and 2.
"""

import numpy as np
import pytest

from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    make_mesh,
    oracle_exchange,
    pack_chunks_slots,
    ragged_params,
    unpack_received,
)

ROW = 512
LANE = ROW // 4


def simulate_ragged_exchange(staged, sizes, slot_rows, recv_rows):
    """Numpy model of ``jax.lax.ragged_all_to_all`` over the executor axis.

    ``staged[i]`` is executor i's (n*slot_rows, lane) staging buffer; the
    update rule mirrors the documented semantics: sender i's rows
    ``[input_offsets[j], +send_sizes[j])`` land in receiver j's output at
    ``[output_offsets[j], +send_sizes[j])`` — with every parameter produced by
    ``ragged_params`` (xp=np), the same expressions the TPU path traces.
    """
    n = sizes.shape[0]
    outs = [np.zeros((recv_rows, staged[i].shape[1]), dtype=staged[i].dtype) for i in range(n)]
    for i in range(n):
        input_offsets, send_sizes, output_offsets, _recv_sizes = ragged_params(
            sizes, i, slot_rows, xp=np
        )
        for j in range(n):
            s = int(send_sizes[j])
            src = staged[i][int(input_offsets[j]) : int(input_offsets[j]) + s]
            outs[j][int(output_offsets[j]) : int(output_offsets[j]) + s] = src
    return outs


def random_chunks(rng, n, slot_rows, full=False):
    """Per-(sender, receiver) random byte chunks fitting the slot layout."""
    chunks = []
    for i in range(n):
        row = []
        for j in range(n):
            if full:
                nbytes = slot_rows * ROW
            else:
                rows = int(rng.integers(0, slot_rows + 1))
                nbytes = 0 if rows == 0 else int(rng.integers((rows - 1) * ROW + 1, rows * ROW + 1))
            row.append(rng.integers(0, 256, size=nbytes, dtype=np.uint8).tobytes())
        chunks.append(row)
    return chunks


def row_padded(chunk):
    pad = (-len(chunk)) % ROW
    return chunk + b"\x00" * pad


class TestRaggedParamsProperties:
    @pytest.mark.parametrize("trial", range(20))
    def test_simulated_ragged_matches_oracle(self, trial):
        rng = np.random.default_rng(1000 + trial)
        n = int(rng.integers(1, 9))
        slot_rows = int(rng.integers(1, 17))
        chunks = random_chunks(rng, n, slot_rows)
        staged, size_rows = zip(
            *(pack_chunks_slots(chunks[i], slot_rows, ROW) for i in range(n))
        )
        sizes = np.stack(size_rows)
        recv_rows = n * slot_rows
        outs = simulate_ragged_exchange(list(staged), sizes, slot_rows, recv_rows)
        expected = oracle_exchange(
            [[row_padded(c) for c in sender] for sender in chunks]
        )
        for j in range(n):
            got = np.asarray(outs[j]).reshape(-1).view(np.uint8)
            total = int(sizes[:, j].sum()) * ROW
            assert got[:total].tobytes() == expected[j], f"receiver {j} corrupted (n={n})"
            # per-sender split must also line up (unpack_received contract)
            parts = unpack_received(got[:total].tobytes(), sizes[:, j], ROW)
            for i in range(n):
                assert parts[i] == row_padded(chunks[i][j])

    @pytest.mark.parametrize("n", [1, 2, 5, 8])
    def test_full_slots(self, n):
        # every chunk exactly fills its slot: offsets are pure slot arithmetic
        rng = np.random.default_rng(n)
        slot_rows = 4
        chunks = random_chunks(rng, n, slot_rows, full=True)
        staged, size_rows = zip(
            *(pack_chunks_slots(chunks[i], slot_rows, ROW) for i in range(n))
        )
        sizes = np.stack(size_rows)
        outs = simulate_ragged_exchange(list(staged), sizes, slot_rows, n * slot_rows)
        expected = oracle_exchange(chunks)
        for j in range(n):
            got = np.asarray(outs[j]).reshape(-1).view(np.uint8)
            assert got.tobytes() == expected[j]

    def test_empty_and_skewed(self):
        # adversarial skew: one hot receiver, several empty senders
        n, slot_rows = 6, 8
        chunks = [[b""] * n for _ in range(n)]
        rng = np.random.default_rng(7)
        for i in range(n):
            chunks[i][3] = rng.integers(0, 256, size=slot_rows * ROW, dtype=np.uint8).tobytes()
        staged, size_rows = zip(
            *(pack_chunks_slots(chunks[i], slot_rows, ROW) for i in range(n))
        )
        sizes = np.stack(size_rows)
        outs = simulate_ragged_exchange(list(staged), sizes, slot_rows, n * slot_rows)
        expected = oracle_exchange(chunks)
        for j in range(n):
            got = np.asarray(outs[j]).reshape(-1).view(np.uint8)
            total = int(sizes[:, j].sum()) * ROW
            assert got[:total].tobytes() == expected[j]


class TestCompactLayoutParams:
    """The compact-input-layout variant (``slot_rows=None``) — the parameter
    set the columnar shuffle and distributed sort pass to ragged_all_to_all
    (ops/columnar.py size_matrix_from_owners / columnar_shard_ragged)."""

    @pytest.mark.parametrize("trial", range(10))
    def test_compact_simulation_matches_sender_major_contract(self, trial):
        rng = np.random.default_rng(2000 + trial)
        n = int(rng.integers(1, 9))
        sizes = rng.integers(0, 6, size=(n, n)).astype(np.int32)
        width = 4

        def tag(i, j, k):  # distinguishable row content
            return np.full(width, i * 10000 + j * 100 + k, dtype=np.int32)

        # sender i's compact payload: chunks for j = 0..n-1 back to back
        payloads = []
        for i in range(n):
            rows = [tag(i, j, k) for j in range(n) for k in range(sizes[i, j])]
            buf = np.stack(rows) if rows else np.zeros((0, width), np.int32)
            payloads.append(buf)

        recv_cap = max(1, int(sizes.sum(axis=0).max()))
        outs = [np.zeros((recv_cap, width), np.int32) for _ in range(n)]
        for i in range(n):
            input_offsets, send_sizes, output_offsets, _ = ragged_params(
                sizes, i, None, xp=np
            )
            for j in range(n):
                s = int(send_sizes[j])
                src = payloads[i][int(input_offsets[j]) : int(input_offsets[j]) + s]
                outs[j][int(output_offsets[j]) : int(output_offsets[j]) + s] = src

        for j in range(n):
            expected = [tag(i, j, k) for i in range(n) for k in range(sizes[i, j])]
            total = len(expected)
            if total:
                assert np.array_equal(outs[j][:total], np.stack(expected)), (
                    f"receiver {j} sender-major layout corrupted (n={n})"
                )


class TestRaggedVsDenseDifferential:
    """The dense lowering executes on the CPU mesh; the ragged simulation uses
    the traced formulas — both must land every byte identically."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_dense_execution_matches_ragged_simulation(self, seed):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.default_rng(seed)
        n = 8
        slot_rows = int(rng.integers(2, 9))
        chunks = random_chunks(rng, n, slot_rows)
        staged, size_rows = zip(
            *(pack_chunks_slots(chunks[i], slot_rows, ROW) for i in range(n))
        )
        sizes = np.stack(size_rows)

        sim = simulate_ragged_exchange(list(staged), sizes, slot_rows, n * slot_rows)

        spec = ExchangeSpec(
            num_executors=n,
            send_rows=n * slot_rows,
            recv_rows=n * slot_rows,
            lane=LANE,
            impl="dense",
        )
        mesh = make_mesh(n)
        fn = build_exchange(mesh, spec)
        data = jax.device_put(
            np.concatenate(staged), NamedSharding(mesh, P("ex", None))
        )
        size_mat = jax.device_put(sizes, NamedSharding(mesh, P("ex", None)))
        recv, recv_sizes = fn(data, size_mat)
        recv = np.asarray(recv)
        recv_sizes = np.asarray(recv_sizes)
        for j in range(n):
            total = int(sizes[:, j].sum())
            shard = recv[j * n * slot_rows : (j + 1) * n * slot_rows]
            assert np.array_equal(recv_sizes[j], sizes[:, j])
            assert np.array_equal(
                shard[:total], sim[j][:total]
            ), f"dense execution != ragged simulation at receiver {j}"


class TestRaggedOnTpu:
    def test_ragged_n1_roundtrip_real_chip(self):
        """On real TPU hardware: execute the ragged lowering (n=1 degenerate
        self-exchange) over several non-trivially sized payloads and assert
        against pack_chunks_slots + oracle.  Skipped where ragged can't run."""
        import jax

        if jax.devices()[0].platform != "tpu":
            pytest.skip("ragged_all_to_all executes only on TPU")
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.default_rng(5)
        slot_rows = 64
        for nbytes in (1, ROW - 1, 17 * ROW + 13, slot_rows * ROW):
            chunk = rng.integers(0, 256, size=nbytes, dtype=np.uint8).tobytes()
            staged, sizes = pack_chunks_slots([chunk], slot_rows, ROW)
            spec = ExchangeSpec(
                num_executors=1, send_rows=slot_rows, recv_rows=slot_rows,
                lane=LANE, impl="ragged",
            )
            mesh = make_mesh(1)
            fn = build_exchange(mesh, spec)
            recv, recv_sizes = fn(
                jax.device_put(staged, NamedSharding(mesh, P("ex", None))),
                jax.device_put(sizes[None, :], NamedSharding(mesh, P("ex", None))),
            )
            got = np.asarray(recv).reshape(-1).view(np.uint8)
            total = int(np.asarray(recv_sizes)[0, 0]) * ROW
            assert got[:total].tobytes() == row_padded(chunk), f"nbytes={nbytes}"


class TestRaggedLowering:
    def test_ragged_impl_lowers_on_cpu_mesh(self):
        # compile-time trace check: the ragged path must build a valid HLO even
        # where no CPU kernel exists to run it
        from sparkucx_tpu.ops._compat import HAS_RAGGED_ALL_TO_ALL

        if not HAS_RAGGED_ALL_TO_ALL:
            pytest.skip("jax.lax.ragged_all_to_all absent on this JAX (< 0.5)")
        n, slot_rows = 8, 4
        spec = ExchangeSpec(
            num_executors=n,
            send_rows=n * slot_rows,
            recv_rows=n * slot_rows,
            lane=LANE,
            impl="ragged",
        )
        mesh = make_mesh(n)
        fn = build_exchange(mesh, spec)
        import jax

        data = jax.ShapeDtypeStruct((n * n * slot_rows, LANE), np.int32)
        sizes = jax.ShapeDtypeStruct((n, n), np.int32)
        lowered = fn.lower(data, sizes)
        assert "ragged" in lowered.as_text().lower()
