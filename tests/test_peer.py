"""Tests for the peer socket path: block server, batched fetch, handshake,
mapper-info broadcast, and a true multi-process executor pair."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.peer import (
    PeerTransport,
    pack_batch_fetch_req,
    unpack_batch_fetch_req,
)


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


@pytest.fixture
def pair():
    conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20, max_blocks_per_request=4)
    a = PeerTransport(conf, executor_id=1)
    b = PeerTransport(conf, executor_id=2)
    addr_a, addr_b = a.init(), b.init()
    a.add_executor(2, addr_b)
    b.add_executor(1, addr_a)
    yield a, b
    a.close()
    b.close()


def _drive(t, reqs, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while not all(r.completed() for r in reqs):
        t.progress()
        if time.monotonic() > deadline:
            raise TimeoutError("requests did not complete")
        time.sleep(0.001)


class TestWire:
    def test_batch_header_roundtrip(self):
        bids = [ShuffleBlockId(1, 2, 3), ShuffleBlockId(4, 5, 6)]
        tag, got = unpack_batch_fetch_req(pack_batch_fetch_req(77, bids))
        assert tag == 77 and got == bids


class TestPeerFetch:
    def test_registered_block_fetch(self, pair):
        a, b = pair
        bid = ShuffleBlockId(0, 0, 0)
        b.register(bid, BytesBlock(b"over-the-wire"))
        out = _buf(64)
        [req] = a.fetch_blocks_by_block_ids(2, [bid], [out], [None])
        assert not req.completed()  # explicit-poll contract
        _drive(a, [req])
        assert req.wait(1).status == OperationStatus.SUCCESS
        assert out.host_view()[: out.size].tobytes() == b"over-the-wire"

    def test_batched_fetch_with_windowing(self, pair):
        a, b = pair
        payloads = {r: bytes([r + 1]) * (100 * (r + 1)) for r in range(10)}
        for r, p in payloads.items():
            b.register(ShuffleBlockId(1, 0, r), BytesBlock(p))
        bids = [ShuffleBlockId(1, 0, r) for r in range(10)]
        bufs = [_buf(2048) for _ in range(10)]
        reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None] * 10)  # 3 windows of 4
        _drive(a, reqs)
        for r in range(10):
            assert reqs[r].wait(1).status == OperationStatus.SUCCESS
            assert bufs[r].host_view()[: bufs[r].size].tobytes() == payloads[r]

    def test_partial_batch_failure(self, pair):
        a, b = pair
        b.register(ShuffleBlockId(2, 0, 0), BytesBlock(b"found"))
        bids = [ShuffleBlockId(2, 0, 0), ShuffleBlockId(2, 0, 99)]
        bufs = [_buf(64), _buf(64)]
        reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None, None])
        _drive(a, reqs)
        assert reqs[0].wait(1).status == OperationStatus.SUCCESS
        res1 = reqs[1].wait(1)
        assert res1.status == OperationStatus.FAILURE
        assert "not found" in str(res1.error)

    def test_staged_store_fetch(self, pair):
        a, b = pair
        b.store.create_shuffle(3, 1, 2)
        w = b.store.map_writer(3, 0)
        w.write_partition(0, b"staged-over-wire")
        w.commit()
        out = _buf(64)
        req = a.fetch_block(2, 3, 0, 0, out)
        _drive(a, [req])
        assert req.wait(1).status == OperationStatus.SUCCESS
        assert out.host_view()[: out.size].tobytes() == b"staged-over-wire"

    def test_unknown_executor(self, pair):
        a, _ = pair
        [req] = a.fetch_blocks_by_block_ids(42, [ShuffleBlockId(0, 0, 0)], [_buf(8)], [None])
        assert req.wait(1).status == OperationStatus.FAILURE

    def test_callbacks_fire_under_progress(self, pair):
        a, b = pair
        b.register(ShuffleBlockId(4, 0, 0), BytesBlock(b"cb"))
        got = []
        [req] = a.fetch_blocks_by_block_ids(2, [ShuffleBlockId(4, 0, 0)], [_buf(8)], [got.append])
        _drive(a, [req])
        assert got and got[0].status == OperationStatus.SUCCESS


class TestThreadSlots:
    def test_threads_use_distinct_connections(self):
        # threadId % numClientWorkers routing (UcxShuffleTransport.scala:277-279)
        import threading

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18, num_client_workers=4)
        a = PeerTransport(conf, executor_id=1)
        b = PeerTransport(conf, executor_id=2)
        a.init()
        a.add_executor(2, b.init())
        b.register(ShuffleBlockId(0, 0, 0), BytesBlock(b"slot"))
        done = []

        def worker():
            [req] = a.fetch_blocks_by_block_ids(2, [ShuffleBlockId(0, 0, 0)], [_buf(16)], [None])
            _drive(a, [req])
            done.append(req.wait(1).status)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(s == OperationStatus.SUCCESS for s in done)
        # multiple slots were actually opened for the single peer
        assert len({k for k in a._conns if k[0] == 2}) >= 2
        a.close()
        b.close()


class TestControlMessages:
    def test_init_executor_handshake(self, pair):
        a, b = pair
        a.init_executor(4, 8)
        assert b.server.handshaken[1] == b"4x8"

    def test_commit_block_broadcast(self, pair):
        from sparkucx_tpu.core.definitions import MapperInfo
        import time

        a, b = pair
        b.store.create_shuffle(5, 2, 2)
        blob = MapperInfo(5, 1, ((0, 64), (512, 32))).pack()
        a.commit_block(blob)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if b.store.block_length(5, 1, 0) == 64:
                break
            time.sleep(0.01)
        assert b.store.block_length(5, 1, 0) == 64
        assert b.store.block_length(5, 1, 1) == 32


class TestMultiProcess:
    def test_two_process_shuffle(self, tmp_path):
        """A real second process serves blocks over its BlockServer."""
        import subprocess
        import sys
        import textwrap

        script = textwrap.dedent(
            """
            import sys, numpy as np
            sys.path.insert(0, %r)
            from sparkucx_tpu.config import TpuShuffleConf
            from sparkucx_tpu.transport.peer import PeerTransport

            conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20)
            t = PeerTransport(conf, executor_id=2)
            addr = t.init()
            t.store.create_shuffle(0, 1, 4)
            w = t.store.map_writer(0, 0)
            for r in range(4):
                w.write_partition(r, bytes([r]) * (100 + r))
            w.commit()
            print(addr.decode(), flush=True)
            sys.stdin.readline()  # hold until parent is done
            t.close()
            """
            % __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__)))
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE,
            stdin=subprocess.PIPE,
            text=True,
        )
        try:
            addr = proc.stdout.readline().strip().encode()
            assert addr, "child failed to start"
            conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20)
            a = PeerTransport(conf, executor_id=1)
            a.init()
            a.add_executor(2, addr)
            bufs = [_buf(256) for _ in range(4)]
            reqs = a.fetch_blocks_by_block_ids(
                2, [ShuffleBlockId(0, 0, r) for r in range(4)], bufs, [None] * 4
            )
            _drive(a, reqs, timeout=10)
            for r in range(4):
                assert reqs[r].wait(1).status == OperationStatus.SUCCESS
                assert bufs[r].host_view()[: bufs[r].size].tobytes() == bytes([r]) * (100 + r)
            a.close()
        finally:
            try:
                proc.stdin.write("done\n")
                proc.stdin.flush()
            except OSError:
                pass
            proc.terminate()
            proc.wait(timeout=10)


class TestNativeReplyAssembly:
    """Reply construction from zero-copy views (block_staging_view +
    registry-materialized buffers): the vectored sendmsg parts (primary) and
    the ts_batch_copy contiguous assembly (no-sendmsg fallback) must produce
    identical bytes for mixed store/registry/empty/missing batches."""

    def test_mixed_sources_roundtrip(self):
        import numpy as np
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.core.block import BytesBlock, ShuffleBlockId
        from sparkucx_tpu.store.hbm_store import HbmBlockStore
        from sparkucx_tpu.transport.peer import BlockServer

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=128)
        store = HbmBlockStore(conf)
        store.create_shuffle(7, 1, 3)
        w = store.map_writer(7, 0)
        rng = np.random.default_rng(3)
        p0 = rng.integers(0, 256, size=999, dtype=np.uint8).tobytes()
        w.write_partition(0, p0)
        w.write_partition(1, b"")          # empty block
        w.write_partition(2, b"z" * 300)
        w.commit()

        reg_payload = b"registry-bytes" * 10
        registry = {ShuffleBlockId(9, 0, 0): BytesBlock(np.frombuffer(reg_payload, np.uint8))}

        srv = BlockServer(conf, store=store, registry_lookup=registry.get)
        try:
            bids = [
                ShuffleBlockId(7, 0, 0),   # store view
                ShuffleBlockId(9, 0, 0),   # registry bytes
                ShuffleBlockId(7, 0, 1),   # empty store block
                ShuffleBlockId(7, 0, 99),  # missing -> -1
                ShuffleBlockId(7, 0, 2),   # store view again (same staging)
            ]
            entries = [srv._resolve_one(b) for b in bids]
            sizes_blob, body = srv._assemble_reply(entries)
            import struct

            sizes = struct.unpack(f"<{len(bids)}q", sizes_blob)
            assert sizes == (999, len(reg_payload), 0, -1, 300)
            got = bytes(body)
            assert got == p0 + reg_payload + b"z" * 300
            # the vectored (sendmsg) form must be byte-identical to the
            # assembled fallback
            sizes_blob2, parts, total = srv._reply_parts(entries)
            assert sizes_blob2 == sizes_blob
            assert total == len(got)
            assert b"".join(bytes(p) for p in parts) == got
        finally:
            srv.close()

    def test_view_survives_seal(self):
        import numpy as np
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.store.hbm_store import HbmBlockStore

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=128)
        store = HbmBlockStore(conf)
        store.create_shuffle(1, 1, 1)
        w = store.map_writer(1, 0)
        w.write_partition(0, b"q" * 500)
        w.commit()
        store.seal(1)
        view = store.block_staging_view(1, 0, 0)
        assert view is not None
        staging, off, ln = view
        assert ln == 500
        assert staging[off : off + ln].tobytes() == b"q" * 500


class TestMalformedFrames:
    """A misbehaving client must cost only its own connection — the server
    keeps serving others (endpoint-eviction semantics,
    UcxWorkerWrapper.scala:248-253)."""

    def test_garbage_then_valid_client(self):
        import socket as socketlib
        import struct as structlib

        import numpy as np
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.core.block import BytesBlock, ShuffleBlockId
        from sparkucx_tpu.transport.peer import BlockServer, PeerTransport

        conf = TpuShuffleConf()
        payload = b"served" * 100
        registry = {ShuffleBlockId(0, 0, 0): BytesBlock(np.frombuffer(payload, np.uint8))}
        srv = BlockServer(conf, registry_lookup=registry.get)
        try:
            for garbage in (
                b"\x00" * 16,                                   # bogus frame header
                structlib.pack("<iqq", 3, 4, 10) + b"\xff" * 14,  # FETCH req, truncated header
                b"short",
            ):
                s = socketlib.create_connection(srv.address, timeout=5)
                s.sendall(garbage)
                s.close()

            # the server must still serve a well-formed client
            t = PeerTransport(conf, executor_id=5)
            t.add_executor(0, srv.address_bytes())
            from sparkucx_tpu.core.block import MemoryBlock
            buf = MemoryBlock(np.zeros(1024, np.uint8), size=1024)
            [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(0, 0, 0)], [buf], [None])
            while not req.completed():
                t.progress()
            res = req.wait(5)
            assert res.status.name == "SUCCESS", str(res.error)
            assert buf.host_view()[: buf.size].tobytes() == payload
            t.close()
        finally:
            srv.close()


class TestMalformedAck:
    """A fetch-ack whose size list disagrees with the frame body (skewed or
    buggy peer) must fail the whole batch with FAILURE results — not raise a
    slicing error out of progress() and leave the batch incomplete."""

    def _inject(self, a, header, body):
        from sparkucx_tpu.core.definitions import AmId
        from sparkucx_tpu.core.operation import OperationStats, Request

        reqs = [Request(OperationStats()) for _ in range(2)]
        bufs = [_buf(64), _buf(64)]
        a._inflight[7] = (reqs, bufs, [None, None], None)
        a._handle_frame((AmId.FETCH_BLOCK_REQ_ACK, header, body, False))
        return reqs

    def test_sizes_disagree_with_body(self):
        from sparkucx_tpu.transport import peer as peer_mod

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20)
        a = PeerTransport(conf, executor_id=1)
        try:
            # sizes claim 10+10 bytes but the body carries only 5
            header = (
                peer_mod._TAG.pack(7)
                + peer_mod._COUNT.pack(2)
                + peer_mod._SIZE.pack(10)
                + peer_mod._SIZE.pack(10)
            )
            reqs = self._inject(a, header, b"12345")
            for r in reqs:
                res = r.wait(1)
                assert res.status == OperationStatus.FAILURE
                assert "malformed" in str(res.error)
            assert 7 not in a._inflight  # batch retired, nothing leaks
        finally:
            a.close()

    def test_truncated_size_list(self):
        from sparkucx_tpu.transport import peer as peer_mod

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20)
        a = PeerTransport(conf, executor_id=1)
        try:
            # count says 2 but the header carries no size entries at all —
            # must fail the batch, not raise struct.error out of progress()
            header = peer_mod._TAG.pack(7) + peer_mod._COUNT.pack(2)
            reqs = self._inject(a, header, b"")
            for r in reqs:
                res = r.wait(1)
                assert res.status == OperationStatus.FAILURE
                assert "malformed" in str(res.error)
        finally:
            a.close()

    def test_count_disagrees_with_batch(self):
        from sparkucx_tpu.transport import peer as peer_mod

        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 20)
        a = PeerTransport(conf, executor_id=1)
        try:
            # one size entry for a two-request batch: zip would silently leave
            # the second request incomplete
            header = peer_mod._TAG.pack(7) + peer_mod._COUNT.pack(1) + peer_mod._SIZE.pack(3)
            reqs = self._inject(a, header, b"abc")
            for r in reqs:
                res = r.wait(1)
                assert res.status == OperationStatus.FAILURE
                assert "malformed" in str(res.error)
        finally:
            a.close()


class TestEvictedConnectionDrain:
    """An ack that parked before its connection was evicted must still
    complete under progress() (the zombie-drain path) — before, eviction
    removed the conn from the cache and its parked frames were lost."""

    def test_parked_ack_survives_eviction(self):
        import time as timelib

        import numpy as np
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
        from sparkucx_tpu.transport.peer import BlockServer, PeerTransport

        conf = TpuShuffleConf()
        payload = b"evict-me" * 200
        registry = {ShuffleBlockId(0, 0, 0): BytesBlock(np.frombuffer(payload, np.uint8))}
        srv = BlockServer(conf, registry_lookup=registry.get)
        t = PeerTransport(conf, executor_id=3)
        try:
            t.add_executor(0, srv.address_bytes())
            buf = MemoryBlock(np.zeros(4096, np.uint8), size=4096)
            [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(0, 0, 0)], [buf], [None])

            # wait for the ack to PARK (recv thread) without draining it
            deadline = timelib.monotonic() + 10
            conns = list(t._conns.values())
            assert conns
            while timelib.monotonic() < deadline and not any(c.inbox for c in conns):
                timelib.sleep(0.005)
            assert any(c.inbox for c in conns), "ack never parked"

            t._evict(0)  # connection gone from the cache, frame still parked

            deadline = timelib.monotonic() + 10
            while not req.completed() and timelib.monotonic() < deadline:
                t.progress()
            res = req.wait(1)
            assert res.status.name == "SUCCESS", str(res.error)
            assert buf.host_view()[: buf.size].tobytes() == payload
            # zombie retired once nothing references it
            for _ in range(10):
                t.progress()
            assert not t._zombies
        finally:
            t.close()
            srv.close()
