"""Tests for the bounded-memory reduce combine/sort (the ExternalSorter role,
UcxShuffleReader.scala:137-199)."""

import os

import numpy as np
import pytest

from sparkucx_tpu.shuffle.external import ExternalCombiner, _estimate


def oracle_aggregate(records, agg):
    out = {}
    for k, v in records:
        out[k] = agg(out[k], v) if k in out else v
    return out


class TestInMemoryPaths:
    def test_combine_no_spill(self):
        c = ExternalCombiner(aggregator=lambda a, b: a + b)
        c.insert_all([("a", 1), ("b", 2), ("a", 3)])
        assert dict(c) == {"a": 4, "b": 2}
        assert c.spill_count == 0

    def test_sort_no_spill(self):
        c = ExternalCombiner(key_ordering=True)
        c.insert_all([(3, "c"), (1, "a"), (2, "b")])
        assert list(c) == [(1, "a"), (2, "b"), (3, "c")]

    def test_combine_and_sort(self):
        c = ExternalCombiner(aggregator=lambda a, b: a + b, key_ordering=True)
        c.insert_all([(2, 1), (1, 1), (2, 1)])
        assert list(c) == [(1, 1), (2, 2)]


class TestSpillingPaths:
    def test_combine_beyond_budget(self, tmp_path):
        # ~100k distinct keys through a ~64 KB budget: dozens of spills, exact result
        agg = lambda a, b: a + b
        c = ExternalCombiner(
            aggregator=agg, memory_budget=64 << 10, spill_dir=str(tmp_path)
        )
        rng = np.random.default_rng(0)
        records = [(int(k), 1) for k in rng.integers(0, 100_000, size=200_000)]
        c.insert_all(records)
        assert c.spill_count > 5
        got = dict(c)
        assert got == oracle_aggregate(records, agg)
        c.close()
        assert os.listdir(str(tmp_path)) == []  # runs reclaimed

    def test_sort_beyond_budget(self, tmp_path):
        c = ExternalCombiner(
            key_ordering=True, memory_budget=64 << 10, spill_dir=str(tmp_path)
        )
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 1 << 30, size=100_000)
        c.insert_all([(int(k), i) for i, k in enumerate(keys)])
        assert c.spill_count > 5
        out_keys = [k for k, _ in c]
        assert out_keys == sorted(int(k) for k in keys)
        c.close()

    def test_combine_and_sort_beyond_budget(self, tmp_path):
        agg = lambda a, b: a + b
        c = ExternalCombiner(
            aggregator=agg, key_ordering=True, memory_budget=32 << 10,
            spill_dir=str(tmp_path),
        )
        rng = np.random.default_rng(2)
        records = [(int(k), 1) for k in rng.integers(0, 5_000, size=100_000)]
        c.insert_all(records)
        assert c.spill_count > 0
        out = list(c)
        expected = sorted(oracle_aggregate(records, agg).items())
        assert out == expected
        c.close()

    def test_hash_collision_groups_stay_correct(self, tmp_path):
        # unordered combine merges by hash(key); craft guaranteed collisions
        # (int hash is identity-ish: x and -x-? no — use small ints plus their
        # hash-equal float twins: hash(1) == hash(1.0))
        agg = lambda a, b: a + b
        c = ExternalCombiner(aggregator=agg, memory_budget=1, spill_dir=str(tmp_path))
        records = [(1, 10), (1.0, 100), (2, 1), (1, 3)]
        c.insert_all(records)  # budget 1 byte: spills every insert
        assert c.spill_count >= 3
        got = dict(c)
        # python dict semantics: 1 == 1.0 so they are ONE key
        assert got == oracle_aggregate(records, agg)
        c.close()

    def test_collect_style_aggregator_with_merge_combiners(self, tmp_path):
        # accumulator type != value type: cross-run merge must use
        # merge_combiners, and growing accumulators must count against the
        # budget (both regressions found in review)
        def agg(acc, v):
            return (acc if isinstance(acc, list) else [acc]) + [v]

        def merge(a, b):
            la = a if isinstance(a, list) else [a]
            lb = b if isinstance(b, list) else [b]
            return la + lb

        c = ExternalCombiner(
            aggregator=agg, merge_combiners=merge, key_ordering=True,
            memory_budget=8 << 10, spill_dir=str(tmp_path),
        )
        records = [(i % 5, i) for i in range(20_000)]
        c.insert_all(records)
        assert c.spill_count > 0, "growing accumulators never crossed the budget"
        out = dict(c)
        for k in range(5):
            vals = out[k] if isinstance(out[k], list) else [out[k]]
            assert sorted(vals) == [i for i in range(20_000) if i % 5 == k]
        c.close()

    def test_growing_accumulator_counts_against_budget(self, tmp_path):
        # few keys, list-appending aggregator: without accumulator-growth
        # accounting this never spills and memory is unbounded
        agg = lambda acc, v: acc + [v] if isinstance(acc, list) else [acc, v]
        c = ExternalCombiner(
            aggregator=agg, merge_combiners=lambda a, b: a + b,
            memory_budget=16 << 10, spill_dir=str(tmp_path),
        )
        c.insert_all([(0, i) for i in range(50_000)])
        assert c.spill_count > 0
        c.close()

    def test_in_place_aggregator_counts_growth(self, tmp_path):
        # mergeValue-style aggregator mutating and returning the SAME object:
        # sizing the old accumulator after the fold would see zero growth and
        # never spill (review regression)
        def agg(acc, v):
            if not isinstance(acc, list):
                acc = [acc]
            acc.append(v)
            return acc

        c = ExternalCombiner(
            aggregator=agg, merge_combiners=lambda a, b: a + b,
            memory_budget=16 << 10, spill_dir=str(tmp_path),
        )
        c.insert_all([(0, i) for i in range(50_000)])
        assert c.spill_count > 0, "in-place accumulator growth bypassed the budget"
        c.close()

    def test_merge_fan_in_capped(self, tmp_path):
        import os

        agg = lambda a, b: a + b
        c = ExternalCombiner(
            aggregator=agg, memory_budget=1, spill_dir=str(tmp_path), merge_fan_in=4
        )
        records = [(i % 100, 1) for i in range(300)]  # budget 1 B: spill per insert
        c.insert_all(records)
        assert c.spill_count > 20
        out = dict(c)
        assert len(c._runs) <= 4, "hierarchical compaction did not cap fan-in"
        assert out == oracle_aggregate(records, agg)
        c.close()
        assert os.listdir(str(tmp_path)) == []

    def test_spill_dir_created_on_demand(self, tmp_path):
        missing = tmp_path / "not" / "yet" / "there"
        c = ExternalCombiner(
            aggregator=lambda a, b: a + b, memory_budget=1, spill_dir=str(missing)
        )
        c.insert_all([(1, 1), (2, 2)])
        assert c.spill_count >= 1
        assert dict(c) == {1: 1, 2: 2}
        c.close()

    def test_unordered_no_aggregator_streams_all_records(self, tmp_path):
        c = ExternalCombiner(memory_budget=1 << 10, spill_dir=str(tmp_path))
        records = [(i % 50, i) for i in range(10_000)]
        c.insert_all(records)
        assert c.spill_count > 0
        got = sorted(v for _, v in c)
        assert got == list(range(10_000))
        c.close()


class TestDeepSizeEstimation:
    """The SizeEstimator role: nested values must count their payload, not
    just their container header (VERDICT r3 weak item 3)."""

    def test_nested_list_counts_payload(self):
        flat = _estimate([0] * 10_000)
        assert flat > 10_000 * 24, f"10k ints estimated at {flat} B"
        # 56 B was the old shallow answer for ANY list

    def test_nested_dict_counts_payload(self):
        d = {i: "x" * 100 for i in range(1_000)}
        assert _estimate(d) > 1_000 * 100

    def test_sampling_keeps_cost_bounded(self):
        import time

        big = [list(range(100)) for _ in range(100_000)]
        t0 = time.perf_counter()
        size = _estimate(big)
        dt = time.perf_counter() - t0
        assert size > 100_000 * 100 * 24  # payload dominates
        assert dt < 0.05, f"estimate walked the whole container ({dt:.3f}s)"

    def test_depth_bound_terminates_on_self_reference(self):
        a = []
        a.append(a)
        assert _estimate(a) > 0  # bounded depth: no RecursionError

    def test_numpy_view_counts_buffer(self):
        base = np.zeros(1 << 20, dtype=np.uint8)
        view = base[: 1 << 19]
        assert _estimate(view) >= 1 << 19

    def test_scalars_and_strings_exact(self):
        import sys

        for obj in (42, 3.14, "hello" * 100, b"x" * 1000, None, True):
            assert _estimate(obj) == sys.getsizeof(obj)

    def test_object_with_dict_attrs(self):
        class Rec:
            def __init__(self):
                self.payload = [0] * 10_000

        assert _estimate(Rec()) > 10_000 * 24

    def test_nested_values_spill_within_budget(self, tmp_path):
        # VERDICT r4 task 6 done criterion: values are nested lists of 10k
        # ints — ~10x the budget in total — and the combiner MUST spill.
        budget = 1 << 20
        c = ExternalCombiner(
            key_ordering=True, memory_budget=budget, spill_dir=str(tmp_path)
        )
        records = [(i, list(range(i, i + 10_000))) for i in range(40)]
        # real payload: 40 * 10k ints * ~32 B >> 10 MB against a 1 MB budget
        c.insert_all(records)
        assert c.spill_count > 0, "nested values bypassed the spill budget"
        out = list(c)
        assert out == records  # keys inserted pre-sorted; values intact
        c.close()


class TestReaderIntegration:
    def test_reduce_beyond_budget_end_to_end(self, tmp_path):
        """VERDICT round-1 item 7 done criterion: aggregate data several times
        larger than a small configured memory budget through the full
        manager/reader path."""
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.shuffle.manager import TpuShuffleManager
        from sparkucx_tpu.shuffle.reader import serialize_records

        budget = 32 << 10
        conf = TpuShuffleConf(
            staging_capacity_per_executor=8 << 20,
            block_alignment=128,
            num_executors=1,
            reduce_memory_budget=budget,
            spill_dir=str(tmp_path),
        )
        manager = TpuShuffleManager(conf, num_executors=1)
        M, R = 4, 2
        manager.register_shuffle(0, M, R)
        rng = np.random.default_rng(3)
        all_records = {r: [] for r in range(R)}
        for m in range(M):
            writer = manager.get_writer(0, m)
            for r in range(R):
                recs = [(int(k), 1) for k in rng.integers(0, 20_000, size=20_000)]
                all_records[r].extend(recs)
                pw = writer.get_partition_writer(r)
                with pw.open_stream() as stream:
                    stream.write(serialize_records(recs))
            writer.commit_all_partitions()
        manager.run_exchange(0)

        agg = lambda a, b: a + b
        reader = manager.get_reader(0, 0, 1, aggregator=agg, key_ordering=True)
        out = list(reader.read())
        assert reader.metrics.spills > 0, "budget never exceeded — test too small"
        expected = sorted(oracle_aggregate(all_records[0], agg).items())
        assert out == expected
        total_bytes = sum(
            len(serialize_records(all_records[r])) for r in range(R)
        )
        assert total_bytes > 4 * budget
        manager.stop()
