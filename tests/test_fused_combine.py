"""Compute-in-exchange fused combine (ROADMAP 2): the receive side of the
scheduled ring folds each landed window into a dense per-group accumulator
instead of staging O(rows) — ops/combine.py, ops/pallas_kernels.ring_combine_grid,
ops/ici_exchange.build_combine_exchange, and the relational fused bodies.

The load-bearing contracts pinned here:

* every lowering tier (scheduled-XLA walk, interpreted Pallas kernel) matches
  a numpy oracle exactly and is BIT-IDENTICAL to the other tiers;
* the fused grouped aggregate is bit-identical to the unfused path for exact
  dtypes (int32 everywhere; float32 over integral values, where sums are
  order-independent), for both the dense tier and the sorted fallback;
* the plan-driven route (run_plan_grouped_aggregate through the unified
  executor) composes with quota sub-rounds without changing a bit;
* quantized payloads stay within the per-row QuantizeSpec error bound;
* 'auto' falls back to the bounded sorted tier on high-cardinality keys.
"""

from dataclasses import replace

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.combine import (
    COMBINE_AGGS,
    CombineSpec,
    acc_init,
    agg_identity,
    combine_window,
    merge_accumulators,
)
from sparkucx_tpu.ops.exchange import ExchangeSpec, make_mesh
from sparkucx_tpu.ops.ici_exchange import build_combine_exchange
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    oracle_aggregate,
    run_grouped_aggregate,
    run_plan_grouped_aggregate,
)
from sparkucx_tpu.ops.skew import ExchangePlan

N = 4
SLOT = 8
GROUPS = 16
AGGS = ("sum", "min", "max")


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _grid_case(rng, cspec, slot=SLOT):
    """Random sender-major slot grid + the numpy fold oracle."""
    lane = cspec.row_width
    data = np.zeros((N, N * slot, lane), np.int32)
    sizes = np.zeros((N, N), np.int32)
    for s in range(N):
        for d in range(N):
            rows = int(rng.integers(0, slot + 1))
            sizes[s, d] = rows
            keys = rng.integers(0, cspec.num_groups, size=rows).astype(np.uint32)
            vals = rng.integers(-50, 50, size=(rows, cspec.width)).astype(np.int32)
            counts = rng.integers(1, 5, size=rows).astype(np.int32)
            data[s, d * slot : d * slot + rows] = np.concatenate(
                [keys.view(np.int32)[:, None], vals, counts[:, None]], axis=1
            )
    exp_v = np.zeros((N, cspec.num_groups, cspec.width), np.int64)
    for c, a in enumerate(cspec.aggs):
        exp_v[:, :, c] = agg_identity(a, np.int32)
    exp_c = np.zeros((N, cspec.num_groups), np.int64)
    for r in range(N):
        for s in range(N):
            for row in data[s, r * slot : r * slot + sizes[s, r]]:
                k = np.uint32(row[0])
                exp_c[r, k] += row[-1]
                for c, a in enumerate(cspec.aggs):
                    if a in ("sum", "avg"):
                        exp_v[r, k, c] += row[1 + c]
                    elif a == "min":
                        exp_v[r, k, c] = min(exp_v[r, k, c], row[1 + c])
                    else:
                        exp_v[r, k, c] = max(exp_v[r, k, c], row[1 + c])
    return data, sizes, exp_v, exp_c


def _run_exchange(mesh, cspec, data, sizes, lowering, chunks=2):
    lane = cspec.row_width
    spec = ExchangeSpec(
        num_executors=N, send_rows=N * SLOT, recv_rows=N * SLOT, lane=lane,
        axis_name="ex", impl="dense",
    )
    fn = build_combine_exchange(mesh, spec, cspec, chunks_per_dest=chunks, lowering=lowering)
    av0 = np.zeros((N, cspec.num_groups, cspec.width), np.int32)
    for c, a in enumerate(cspec.aggs):
        av0[:, :, c] = agg_identity(a, np.int32)
    ac0 = np.zeros((N, cspec.num_groups, 1), np.int32)
    row_sh = NamedSharding(mesh, P("ex", None))
    return fn(
        jax.device_put(data.reshape(N * N * SLOT, lane), row_sh),
        jax.device_put(sizes, row_sh),
        jax.device_put(av0.reshape(N * cspec.num_groups, cspec.width), row_sh),
        jax.device_put(ac0.reshape(N * cspec.num_groups, 1), row_sh),
    )


# ----------------------------------------------------------------------------
# kernel / lowering tiers
# ----------------------------------------------------------------------------


@pytest.mark.parametrize("lowering", ["xla", "interpret"])
def test_combine_exchange_matches_oracle(mesh, rng, lowering):
    cspec = CombineSpec(num_groups=GROUPS, aggs=AGGS, dtype=np.int32)
    data, sizes, exp_v, exp_c = _grid_case(rng, cspec)
    accv, accc, recv = _run_exchange(mesh, cspec, data, sizes, lowering)
    accv = np.asarray(accv).reshape(N, GROUPS, len(AGGS))
    accc = np.asarray(accc).reshape(N, GROUPS)
    # recv_sizes is the receive-side view: row r = rows each sender sent to r
    assert np.array_equal(np.asarray(recv), sizes.T)
    assert np.array_equal(accc, exp_c)
    assert np.array_equal(accv.astype(np.int64), exp_v)


def test_combine_exchange_tiers_bit_identical(mesh, rng):
    """interpret (the Pallas kernel body, CPU-interpreted) vs the scheduled
    XLA walk: same canonical fold order, so bytes must match exactly."""
    cspec = CombineSpec(num_groups=GROUPS, aggs=AGGS, dtype=np.int32)
    data, sizes, _, _ = _grid_case(rng, cspec)
    rx = _run_exchange(mesh, cspec, data, sizes, "xla")
    ri = _run_exchange(mesh, cspec, data, sizes, "interpret")
    for a, b in zip(rx, ri):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_combine_window_and_merge_unit(rng):
    """Single-window fold + accumulator merge vs plain numpy."""
    cspec = CombineSpec(num_groups=8, aggs=("sum", "max"), dtype=np.int32)
    rows = 16
    keys = rng.integers(0, 8, size=rows).astype(np.uint32)
    vals = rng.integers(-9, 9, size=(rows, 2)).astype(np.int32)
    counts = rng.integers(0, 3, size=rows).astype(np.int32)  # some invalid
    window = np.concatenate([keys.view(np.int32)[:, None], vals, counts[:, None]], axis=1)
    av, ac = acc_init(cspec)
    av, ac = combine_window(cspec, window, av, ac)
    for g in range(8):
        hit = (keys == g) & (counts > 0)
        assert int(ac[g, 0]) == counts[hit].sum()
        assert int(av[g, 0]) == vals[hit, 0].sum()
        want_max = vals[hit, 1].max() if hit.any() else agg_identity("max", np.int32)
        assert int(av[g, 1]) == want_max
    # merging with a fresh identity accumulator is the identity
    bv, bc = acc_init(cspec)
    mv, mc = merge_accumulators(cspec, (av, ac), (bv, bc))
    assert np.array_equal(np.asarray(mv), np.asarray(av))
    assert np.array_equal(np.asarray(mc), np.asarray(ac))


def test_combine_spec_validation():
    with pytest.raises(ValueError, match="num_groups"):
        CombineSpec(num_groups=0, aggs=("sum",)).validate()
    with pytest.raises(ValueError, match="count_distinct"):
        CombineSpec(num_groups=4, aggs=("count_distinct",)).validate()
    with pytest.raises(ValueError, match="float dtype"):
        CombineSpec(num_groups=4, aggs=("sum",), quantize_mode="int8").validate()
    q = CombineSpec(
        num_groups=4, aggs=("sum",), dtype=np.float32, quantize_mode="int8"
    )
    q.validate()
    assert q.payload_width > q.width  # packed words + per-block scales
    assert set(COMBINE_AGGS) == {"sum", "min", "max", "avg"}


# ----------------------------------------------------------------------------
# fused grouped aggregate vs unfused — bit-equality for exact dtypes
# ----------------------------------------------------------------------------


def _agg_spec(**kw):
    base = dict(
        num_executors=N, capacity=256, recv_capacity=256,
        aggs=("sum", "min", "max", "avg"), partial=True,
    )
    base.update(kw)
    return AggregateSpec(**base)


def _dense_case(rng, dtype=np.int32, total=700, domain=60):
    keys = rng.integers(0, domain, size=total).astype(np.uint32)
    vals = rng.integers(-100, 100, size=(total, 4)).astype(dtype)
    return keys, vals


@pytest.mark.parametrize("tier", ["dense", "sorted"])
@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_fused_bit_identical_to_unfused(mesh, rng, tier, dtype):
    """Exact dtypes: int32 always; float32 over integral values (segment sums
    of exactly-representable integers are order-independent)."""
    keys, vals = _dense_case(rng, dtype=dtype)
    spec = _agg_spec(
        dtype=np.dtype(dtype), combine=tier,
        combine_groups=64 if tier == "dense" else 0,
    )
    ref = run_grouped_aggregate(mesh, replace(spec, combine="off"), keys, vals)
    got = run_grouped_aggregate(mesh, spec, keys, vals)
    for a, b in zip(ref, got):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    ok, _, oc = oracle_aggregate(keys, vals, spec.aggs)
    assert np.array_equal(got[0], ok)
    assert np.array_equal(got[2], oc)


def test_fused_interpret_lowering_bit_identical(mesh, rng):
    """The Pallas kernel tier through the RELATIONAL body (not just the raw
    exchange): combine_lowering='interpret' runs ring_combine_grid."""
    keys, vals = _dense_case(rng)
    spec = _agg_spec(combine="dense", combine_groups=64)
    r_x = run_grouped_aggregate(mesh, spec, keys, vals)
    r_i = run_grouped_aggregate(
        mesh, replace(spec, combine_lowering="interpret"), keys, vals
    )
    for a, b in zip(r_x, r_i):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_fused_with_filter(mesh, rng):
    keys, vals = _dense_case(rng)
    mask = rng.random(keys.size) < 0.7
    spec = _agg_spec(with_filter=True, combine="dense", combine_groups=64)
    ref = run_grouped_aggregate(mesh, replace(spec, combine="off"), keys, vals, mask=mask)
    got = run_grouped_aggregate(mesh, spec, keys, vals, mask=mask)
    for a, b in zip(ref, got):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


@pytest.mark.parametrize("quota,chunks", [(256, 1), (64, 4), (128, 2)])
def test_plan_driven_quota_subrounds_bit_identical(mesh, rng, quota, chunks):
    """The unified-executor route: quota sub-rounds through execute_plan /
    build_plan_exchange, per-sub-round accumulators merged in finish_round —
    any chunking must reproduce the unfused bytes exactly (int32)."""
    keys, vals = _dense_case(rng, total=600)
    spec = _agg_spec(combine="dense", combine_groups=64)
    ref = run_grouped_aggregate(mesh, replace(spec, combine="off"), keys, vals)
    plan = ExchangePlan(slot_rows=quota, chunks_per_round=(chunks,), combine="dense")
    got = run_plan_grouped_aggregate(mesh, spec, plan, keys, vals)
    for a, b in zip(ref, got):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_plan_driven_non_dense_falls_back(mesh, rng):
    keys, vals = _dense_case(rng, total=300)
    spec = _agg_spec()
    plan = ExchangePlan(slot_rows=256, chunks_per_round=(1,), combine="off")
    ref = run_grouped_aggregate(mesh, spec, keys, vals)
    got = run_plan_grouped_aggregate(mesh, spec, plan, keys, vals)
    for a, b in zip(ref, got):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# ----------------------------------------------------------------------------
# quantized tier — error-bound vs the unfused oracle
# ----------------------------------------------------------------------------


@pytest.mark.parametrize("tier", ["dense", "sorted"])
def test_quantized_fused_within_error_bound(mesh, rng, tier):
    keys = rng.integers(0, 48, size=600).astype(np.uint32)
    vals = (rng.random((600, 2), np.float32) * 200 - 100).astype(np.float32)
    spec = _agg_spec(
        aggs=("sum", "avg"), dtype=np.dtype(np.float32), quantize_mode="int8",
        combine=tier, combine_groups=64 if tier == "dense" else 0,
    )
    gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, vals)
    ok, ov, oc = oracle_aggregate(keys, vals, spec.aggs)
    assert np.array_equal(gk, ok)
    assert np.array_equal(gc, oc)  # counts are NEVER quantized
    # per partial row the error is bounded by error_bound(row amax); with at
    # most n partial rows per group the group sum error is n * bound
    bound = spec.qspec.error_bound(np.abs(vals).max()) * N + 1e-4
    assert np.abs(gv[:, 0] - ov[:, 0]).max() <= bound * gc.max()
    # the same lossy payload flows through the unfused path — fused results
    # must sit in the same error class
    uk, uv, uc = run_grouped_aggregate(mesh, replace(spec, combine="off"), keys, vals)
    assert np.array_equal(gk, uk)
    assert np.abs(gv - uv).max() <= 2 * bound * gc.max()


def test_unfused_quantized_reuses_donated_accumulator(mesh, rng):
    """Satellite: the unfused quantized fallback threads ONE donated
    dequantize accumulator through repeated calls instead of
    double-buffering — results stay identical call over call."""
    from sparkucx_tpu.ops.relational import build_grouped_aggregate
    from sparkucx_tpu.ops.columnar import shard_rows_host

    spec = _agg_spec(
        aggs=("sum", "avg"), dtype=np.dtype(np.float32), quantize_mode="int8"
    )
    fn = build_grouped_aggregate(mesh, spec)
    keys = rng.integers(0, 32, size=400).astype(np.uint32)
    vals = (rng.random((400, 2), np.float32) * 50).astype(np.float32)
    pk, pv, nv = shard_rows_host(keys, vals, N, spec.capacity, value_dtype=spec.dtype)
    key_sh = NamedSharding(mesh, P("ex"))
    row_sh = NamedSharding(mesh, P("ex", None))
    args = (
        jax.device_put(pk, key_sh),
        jax.device_put(pv, row_sh),
        jax.device_put(nv, key_sh),
    )
    first = [np.asarray(o) for o in fn(*args)]
    assert len(first) == 5  # public contract unchanged
    for _ in range(2):  # the donated buffer round-trips across calls
        again = fn(*args)
        for a, b in zip(first, again):
            assert np.array_equal(a, np.asarray(b))


# ----------------------------------------------------------------------------
# tier resolution — auto / fallback / conf plumbing
# ----------------------------------------------------------------------------


def test_auto_falls_back_to_sorted_on_high_cardinality(mesh, rng):
    """Hash-like keys: the dense accumulator would dwarf the exchanged slot
    grid, so 'auto' must take the bounded sorted tier — and still agree with
    the unfused path bit for bit."""
    keys = rng.integers(0, 1 << 31, size=500).astype(np.uint32)
    vals = rng.integers(-100, 100, size=(500, 4)).astype(np.int32)
    spec = _agg_spec(combine="auto")
    g = 1 << int(np.max(keys)).bit_length()
    resolved = replace(spec, combine_groups=g).resolve_combine()
    assert resolved.combine == "sorted"
    ref = run_grouped_aggregate(mesh, replace(spec, combine="off"), keys, vals)
    got = run_grouped_aggregate(mesh, spec, keys, vals)
    for a, b in zip(ref, got):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_auto_picks_dense_on_small_domain():
    spec = _agg_spec(combine="auto", combine_groups=64)
    assert spec.resolve_combine().combine == "dense"


def test_from_conf_downgrades_like_quantize():
    from sparkucx_tpu.config import TpuShuffleConf

    conf = TpuShuffleConf(num_executors=N, exchange_fused_combine=True)
    on = AggregateSpec.from_conf(
        conf, capacity=64, recv_capacity=64, aggs=("sum",), partial=True
    )
    assert on.combine == "auto"
    off = AggregateSpec.from_conf(
        conf, capacity=64, recv_capacity=64, aggs=("sum",), partial=False
    )
    assert off.combine == "off"  # silent downgrade: fused folds PARTIAL rows
    cd = AggregateSpec.from_conf(
        conf, capacity=64, recv_capacity=64, aggs=("count_distinct",)
    )
    assert cd.combine == "off" and not cd.partial
    plain = AggregateSpec.from_conf(
        TpuShuffleConf(num_executors=N),
        capacity=64, recv_capacity=64, aggs=("sum",), partial=True,
    )
    assert plain.combine == "off"  # default-off knob


def test_validate_rejects_bad_combine():
    with pytest.raises(ValueError, match="combine tier"):
        _agg_spec(impl="dense", combine="fused").validate()
    with pytest.raises(ValueError, match="partial"):
        _agg_spec(impl="dense", partial=False, combine="dense", combine_groups=8).validate()
    with pytest.raises(ValueError, match="combine_groups"):
        _agg_spec(impl="dense", combine="dense").validate()


def test_planner_learns_combine_tier():
    """Satellite: StaticPlanner/AdaptivePlanner fill ExchangePlan.combine from
    all-gathered aggregation geometry; the plan trace instant carries it."""
    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.ops.planner import AdaptivePlanner, PlanContext, StaticPlanner

    conf = TpuShuffleConf(num_executors=N, exchange_fused_combine=True)
    dense_ctx = PlanContext(
        num_executors=N, staging_slot_rows=1024, round_max_rows=(512,),
        used_rows_total=2048, row_bytes=64, agg_partial=True, agg_groups=256,
        agg_width=4,
    )
    plan = StaticPlanner(conf).plan(dense_ctx)
    assert plan.combine == "dense"
    assert plan.describe()["combine"] == "dense"
    # huge domain: static keeps the sorted fallback, adaptive goes off
    wide_ctx = replace_ctx(dense_ctx, agg_groups=1 << 24)
    assert StaticPlanner(conf).plan(wide_ctx).combine == "sorted"
    assert AdaptivePlanner(conf).plan(wide_ctx).combine == "off"
    # no aggregation geometry (raw block shuffle): always off
    raw_ctx = replace_ctx(dense_ctx, agg_partial=False)
    assert StaticPlanner(conf).plan(raw_ctx).combine == "off"
    # knob off: off even with dense geometry
    off_conf = TpuShuffleConf(num_executors=N)
    assert StaticPlanner(off_conf).plan(dense_ctx).combine == "off"


def replace_ctx(ctx, **kw):
    from dataclasses import replace as _r

    return _r(ctx, **kw)
