"""Tests for the L1 memory pool (MemoryPool.scala semantics)."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.memory.pool import MemoryPool, round_up_to_next_power_of_two


class TestRounding:
    def test_power_of_two(self):
        assert round_up_to_next_power_of_two(1) == 1
        assert round_up_to_next_power_of_two(2) == 2
        assert round_up_to_next_power_of_two(3) == 4
        assert round_up_to_next_power_of_two(4096) == 4096
        assert round_up_to_next_power_of_two(4097) == 8192


class TestMemoryPool:
    def test_get_respects_min_buffer_size(self):
        with MemoryPool(TpuShuffleConf(min_buffer_size=4096)) as pool:
            mb = pool.get(10)
            assert mb.size == 10
            assert mb.data.size == 4096  # bucket floor (MemoryPool.scala:34-49)
            mb.close()

    def test_recycling(self):
        with MemoryPool() as pool:
            mb = pool.get(100)
            backing = mb.data
            mb.host_view()[:] = 7
            mb.close()
            mb2 = pool.get(200)  # same 4096 bucket
            assert mb2.data is backing  # LIFO reuse
            mb2.close()

    def test_distinct_buffers_when_held(self):
        with MemoryPool() as pool:
            a, b = pool.get(50), pool.get(50)
            assert a.data.ctypes.data != b.data.ctypes.data
            a.host_view()[:] = 1
            b.host_view()[:] = 2
            assert a.host_view()[0] == 1 and b.host_view()[0] == 2
            a.close(); b.close()

    def test_slab_carving_for_small_buckets(self):
        conf = TpuShuffleConf(min_buffer_size=4096, min_allocation_size=1 << 20)
        with MemoryPool(conf) as pool:
            pool.preallocate(4096, 1)
            stats = pool.stats()[4096]
            # one 1 MiB slab carved into 256 x 4 KiB views (MemoryPool.scala:64-70)
            assert stats["allocated_bytes"] == 1 << 20
            assert stats["free"] == 256

    def test_large_bucket_allocates_exact(self):
        conf = TpuShuffleConf(min_allocation_size=1 << 20)
        with MemoryPool(conf) as pool:
            mb = pool.get(4 << 20)
            assert pool.stats()[4 << 20]["allocated_bytes"] == 4 << 20
            mb.close()

    def test_preallocate_from_conf(self):
        conf = TpuShuffleConf(prealloc_buffers={8192: 4, 1 << 16: 2})
        with MemoryPool(conf) as pool:
            pool.preallocate_from_conf()
            assert pool.stats()[8192]["free"] >= 4
            assert pool.stats()[1 << 16]["free"] >= 2

    def test_alignment(self):
        with MemoryPool() as pool:
            for size in (100, 5000, 1 << 20):
                mb = pool.get(size)
                assert mb.data.ctypes.data % 64 == 0
                mb.close()

    def test_close_raises_on_leak(self):
        pool = MemoryPool()
        leaked = pool.get(128)
        with pytest.raises(ResourceWarning):
            pool.close()
        leaked.close()

    def test_get_after_close_fails(self):
        pool = MemoryPool()
        pool.close()
        with pytest.raises(RuntimeError):
            pool.get(16)

    def test_double_close_is_noop(self):
        # A stale holder's second close() must not double-free (no aliasing).
        # Sanitize mode (SPARKUCX_TPU_SANITIZE=1 CI leg) tightens the no-op
        # into a raise so the stale holder is pinpointed — either way the
        # free list never aliases.
        with MemoryPool() as pool:
            mb = pool.get(100)
            mb.close()
            if pool.sanitizer.enabled:
                with pytest.raises(Exception, match="double release"):
                    mb.close()
            else:
                mb.close()
            a, b = pool.get(100), pool.get(100)
            assert a.data.ctypes.data != b.data.ctypes.data
            a.close(); b.close()

    def test_invalid_size(self):
        with MemoryPool() as pool:
            with pytest.raises(ValueError):
                pool.get(0)

    def test_concurrent_get_put(self):
        import threading

        with MemoryPool() as pool:
            errors = []

            def worker():
                try:
                    for _ in range(200):
                        mb = pool.get(1000)
                        view = mb.host_view()
                        view[:] = 5
                        assert view[-1] == 5
                        mb.close()
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
