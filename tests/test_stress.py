"""Concurrency stress: many mapper threads writing one shuffle while commits
and reads race — structural-safety evidence the reference never had
(SURVEY.md section 5.2: no race detection, safety is structural only)."""

import threading

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

N_EXEC = 4


def _payload(m, r):
    rng = np.random.default_rng(1000 * m + r)
    return rng.integers(0, 256, size=int(rng.integers(1, 1200)), dtype=np.uint8).tobytes()


class TestConcurrentShuffle:
    def test_parallel_map_writers_then_exchange(self):
        """All map tasks write concurrently from threads (the Spark executor
        thread-pool shape); one exchange; every block verified."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=2 << 20, block_alignment=128, num_executors=N_EXEC
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 16, 16
        meta = cluster.create_shuffle(0, M, R)
        errors = []

        def map_task(m):
            try:
                t = cluster.transport(meta.map_owner[m])
                w = t.store.map_writer(0, m)
                for r in range(R):
                    w.write_partition(r, _payload(m, r))
                t.commit_block(w.commit().pack())
            except Exception as e:  # surfaced below
                errors.append((m, e))

        threads = [threading.Thread(target=map_task, args=(m,)) for m in range(M)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors

        cluster.run_exchange(0)

        read_errors = []

        def reduce_task(r):
            try:
                consumer = meta.owner_of_reduce(r)
                t = cluster.transport(consumer)
                bids = [ShuffleBlockId(0, m, r) for m in range(M)]
                bufs = [MemoryBlock(np.zeros(2048, np.uint8), size=2048) for _ in range(M)]
                reqs = t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
                for m, (req, buf) in enumerate(zip(reqs, bufs)):
                    res = req.wait(5)
                    assert res.status == OperationStatus.SUCCESS, str(res.error)
                    got = buf.host_view()[: buf.size].tobytes()
                    assert got == _payload(m, r), f"mismatch map={m} reduce={r}"
            except Exception as e:
                read_errors.append((r, e))

        rthreads = [threading.Thread(target=reduce_task, args=(r,)) for r in range(R)]
        for th in rthreads:
            th.start()
        for th in rthreads:
            th.join()
        assert not read_errors, read_errors

    def test_task_retry_race_first_commit_wins(self):
        """Two attempts of the same map task race; exactly one set of writes
        lands (IndexShuffleBlockResolver's check-or-replace semantics)."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        meta = cluster.create_shuffle(0, 1, 2)
        t = cluster.transport(meta.map_owner[0])

        barrier = threading.Barrier(2)
        results = []

        def attempt(tag):
            barrier.wait()
            w = t.store.map_writer(0, 0)
            for r in range(2):
                w.write_partition(r, bytes([tag]) * 400)
            info = w.commit()
            results.append((tag, w.is_retry_discard, info))

        threads = [threading.Thread(target=attempt, args=(tag,)) for tag in (1, 2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        # both commits returned a consistent table; the store holds ONE attempt
        t.commit_block(results[0][2].pack())
        cluster.run_exchange(0)
        blocks = [
            cluster.locate_received_block(meta.owner_of_reduce(r), 0, 0, r)[0].tobytes()
            for r in range(2)
        ]
        tags = {b[0] for b in blocks if b}
        assert len(tags) == 1, f"mixed attempts visible: {tags}"
        assert all(len(b) == 400 for b in blocks)

    def test_concurrent_shuffle_create_remove(self):
        """Shuffle lifecycle churn from threads: create/write/exchange/remove
        many shuffles concurrently without cross-talk."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        errors = []

        def lifecycle(sid):
            try:
                meta = cluster.create_shuffle(sid, 2, 2)
                for m in range(2):
                    t = cluster.transport(meta.map_owner[m])
                    w = t.store.map_writer(sid, m)
                    for r in range(2):
                        w.write_partition(r, bytes([sid]) * 256)
                    t.commit_block(w.commit().pack())
                cluster.run_exchange(sid)
                for r in range(2):
                    view, ln = cluster.locate_received_block(
                        meta.owner_of_reduce(r), sid, 0, r
                    )
                    assert view.tobytes() == bytes([sid]) * 256
                cluster.remove_shuffle(sid)
            except Exception as e:
                errors.append((sid, e))

        threads = [threading.Thread(target=lifecycle, args=(sid,)) for sid in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors
