"""Concurrency stress: many mapper threads writing one shuffle while commits
and reads race — structural-safety evidence the reference never had
(SURVEY.md section 5.2: no race detection, safety is structural only)."""

import threading
import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus, TransportError
from sparkucx_tpu.store.hbm_store import HbmBlockStore
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

N_EXEC = 4


def _payload(m, r):
    rng = np.random.default_rng(1000 * m + r)
    return rng.integers(0, 256, size=int(rng.integers(1, 1200)), dtype=np.uint8).tobytes()


class TestConcurrentShuffle:
    def test_parallel_map_writers_then_exchange(self):
        """All map tasks write concurrently from threads (the Spark executor
        thread-pool shape); one exchange; every block verified."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=2 << 20, block_alignment=128, num_executors=N_EXEC
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 16, 16
        meta = cluster.create_shuffle(0, M, R)
        errors = []

        def map_task(m):
            try:
                t = cluster.transport(meta.map_owner[m])
                w = t.store.map_writer(0, m)
                for r in range(R):
                    w.write_partition(r, _payload(m, r))
                t.commit_block(w.commit().pack())
            except Exception as e:  # surfaced below
                errors.append((m, e))

        threads = [threading.Thread(target=map_task, args=(m,)) for m in range(M)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors

        cluster.run_exchange(0)

        read_errors = []

        def reduce_task(r):
            try:
                consumer = meta.owner_of_reduce(r)
                t = cluster.transport(consumer)
                bids = [ShuffleBlockId(0, m, r) for m in range(M)]
                bufs = [MemoryBlock(np.zeros(2048, np.uint8), size=2048) for _ in range(M)]
                reqs = t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
                for m, (req, buf) in enumerate(zip(reqs, bufs)):
                    res = req.wait(5)
                    assert res.status == OperationStatus.SUCCESS, str(res.error)
                    got = buf.host_view()[: buf.size].tobytes()
                    assert got == _payload(m, r), f"mismatch map={m} reduce={r}"
            except Exception as e:
                read_errors.append((r, e))

        rthreads = [threading.Thread(target=reduce_task, args=(r,)) for r in range(R)]
        for th in rthreads:
            th.start()
        for th in rthreads:
            th.join()
        assert not read_errors, read_errors

    def test_task_retry_race_first_commit_wins(self):
        """Two attempts of the same map task race; exactly one set of writes
        lands (IndexShuffleBlockResolver's check-or-replace semantics)."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        meta = cluster.create_shuffle(0, 1, 2)
        t = cluster.transport(meta.map_owner[0])

        barrier = threading.Barrier(2)
        results = []

        def attempt(tag):
            barrier.wait()
            w = t.store.map_writer(0, 0)
            for r in range(2):
                w.write_partition(r, bytes([tag]) * 400)
            info = w.commit()
            results.append((tag, w.is_retry_discard, info))

        threads = [threading.Thread(target=attempt, args=(tag,)) for tag in (1, 2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        # both commits returned a consistent table; the store holds ONE attempt
        t.commit_block(results[0][2].pack())
        cluster.run_exchange(0)
        blocks = [
            cluster.locate_received_block(meta.owner_of_reduce(r), 0, 0, r)[0].tobytes()
            for r in range(2)
        ]
        tags = {b[0] for b in blocks if b}
        assert len(tags) == 1, f"mixed attempts visible: {tags}"
        assert all(len(b) == 400 for b in blocks)

    def test_concurrent_shuffle_create_remove(self):
        """Shuffle lifecycle churn from threads: create/write/exchange/remove
        many shuffles concurrently without cross-talk."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        errors = []

        def lifecycle(sid):
            try:
                meta = cluster.create_shuffle(sid, 2, 2)
                for m in range(2):
                    t = cluster.transport(meta.map_owner[m])
                    w = t.store.map_writer(sid, m)
                    for r in range(2):
                        w.write_partition(r, bytes([sid]) * 256)
                    t.commit_block(w.commit().pack())
                cluster.run_exchange(sid)
                for r in range(2):
                    view, ln = cluster.locate_received_block(
                        meta.owner_of_reduce(r), sid, 0, r
                    )
                    assert view.tobytes() == bytes([sid]) * 256
                cluster.remove_shuffle(sid)
            except Exception as e:
                errors.append((sid, e))

        threads = [threading.Thread(target=lifecycle, args=(sid,)) for sid in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors


ALIGN = 128


class TestDiskTierConcurrency:
    """Pull-fallback reads racing ``_rollover`` and ``remove_shuffle`` across
    many spill rounds (VERDICT r4 task 7).  Every payload is a single
    map-distinctive byte repeated over the whole region, so ANY torn read —
    bytes from two rounds, a half-zeroed epoch swap, a recycled buffer —
    shows up as a wrong byte, not a flaky length."""

    def _store(self, tmp_path, **kw):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=4096,
            block_alignment=ALIGN,
            spill_dir=str(tmp_path),
            **kw,
        )
        return HbmBlockStore(conf)

    @staticmethod
    def _pattern(m):
        return bytes([(m % 250) + 1])

    def test_reads_race_rollover_across_rounds(self, tmp_path):
        """Readers hammer committed blocks while a writer forces >= 6 epoch
        rollovers into the memmap tier; every read must return the exact
        pattern of its round."""
        s = self._store(tmp_path)
        ROUNDS = 8
        s.create_shuffle(0, ROUNDS, 1)
        region = s.region_bytes(0)
        committed = []  # map ids with a finished commit (reader work list)
        stop = threading.Event()
        failures = []

        def reader():
            rng = np.random.default_rng(threading.get_ident() % (1 << 32))
            # any exception is a failure — committed blocks must stay readable
            # through rollovers; a non-TransportError crash must not pass
            # silently as a dead thread
            try:
                while not stop.is_set() or committed:
                    if not committed:
                        time.sleep(0.0005)
                        continue
                    m = committed[int(rng.integers(0, len(committed)))]
                    expect = self._pattern(m) * region
                    got = s.read_block(0, m, 0)
                    if got != expect:
                        failures.append(f"torn read_block map={m}")
                        return
                    view = s.block_staging_view(0, m, 0)
                    if view is not None:
                        arr, off, ln = view
                        if bytes(arr[off : off + ln]) != expect:
                            failures.append(f"torn staging_view map={m}")
                            return
                    if stop.is_set():
                        return
            except BaseException as e:
                failures.append(f"reader crashed: {type(e).__name__}: {e}")

        readers = [threading.Thread(target=reader) for _ in range(4)]
        for th in readers:
            th.start()
        for m in range(ROUNDS):
            w = s.map_writer(0, m)
            w.write_partition(0, self._pattern(m) * region)
            w.commit()
            committed.append(m)
            time.sleep(0.002)  # give readers a window inside each round
        stop.set()
        for th in readers:
            th.join(timeout=30)
        assert not failures, failures
        assert s.num_rounds(0) >= 6, "staging never rolled over — test lost its point"
        # rounds really went to the disk tier
        assert any(isinstance(p, np.memmap) for p, _ in s._state(0).prev_rounds)
        s.remove_shuffle(0)
        s.close()

    def test_reads_race_remove_shuffle(self, tmp_path):
        """remove_shuffle fires while readers are mid-read on spilled rounds:
        each read returns exact bytes or a clean TransportError — never torn
        data, never a crash.  Spill accounting drains to zero afterwards."""
        s = self._store(tmp_path)
        ROUNDS = 5
        s.create_shuffle(0, ROUNDS, 1)
        region = s.region_bytes(0)
        for m in range(ROUNDS):
            w = s.map_writer(0, m)
            w.write_partition(0, self._pattern(m) * region)
            w.commit()
        failures = []
        started = threading.Barrier(5)

        def reader():
            rng = np.random.default_rng(threading.get_ident() % (1 << 32))
            started.wait()
            try:
                for _ in range(400):
                    m = int(rng.integers(0, ROUNDS))
                    try:
                        got = s.read_block(0, m, 0)
                    except TransportError:
                        return  # shuffle removed underneath us — clean refusal
                    if got != self._pattern(m) * region:
                        failures.append(f"torn read after remove map={m}")
                        return
            except BaseException as e:  # anything else = dirty failure, not clean refusal
                failures.append(f"reader crashed: {type(e).__name__}: {e}")

        readers = [threading.Thread(target=reader) for _ in range(4)]
        for th in readers:
            th.start()
        started.wait()
        time.sleep(0.005)  # land the removal mid-hammer
        s.remove_shuffle(0)
        for th in readers:
            th.join(timeout=30)
        assert not failures, failures
        assert s._spill_bytes == 0, "spill accounting leaked after remove"
        s.close()

    def test_reads_race_remove_shuffle_shm_arm(self, tmp_path):
        """Same race over shm-backed staging (the zero-copy serving tier):
        block_staging_view hands out private copies exactly because the shm
        mapping can be munmapped at any time after the lock drops."""
        from sparkucx_tpu import native

        if not native.native_available():
            pytest.skip(f"native build unavailable: {native.build_error()}")
        s = self._store(tmp_path, use_shm_staging=True)
        M = 4
        s.create_shuffle(0, M, 1)
        region = s.region_bytes(0)
        payload_len = region // M // ALIGN * ALIGN  # all maps fit in ONE round (shm can't roll over)
        for m in range(M):
            w = s.map_writer(0, m)
            w.write_partition(0, self._pattern(m) * payload_len)
            w.commit()
        failures = []
        started = threading.Barrier(5)

        def reader():
            rng = np.random.default_rng(threading.get_ident() % (1 << 32))
            started.wait()
            try:
                for _ in range(300):
                    m = int(rng.integers(0, M))
                    try:
                        view = s.block_staging_view(0, m, 0)
                        if view is None:
                            return  # removed — staging gone, clean refusal
                        arr, off, ln = view
                        got = bytes(arr[off : off + ln])
                    except TransportError:
                        return
                    if got != self._pattern(m) * payload_len:
                        failures.append(f"torn shm read map={m}")
                        return
            except BaseException as e:  # e.g. SIGSEGV-adjacent munmap errors surface here
                failures.append(f"reader crashed: {type(e).__name__}: {e}")

        readers = [threading.Thread(target=reader) for _ in range(4)]
        for th in readers:
            th.start()
        started.wait()
        time.sleep(0.003)
        s.remove_shuffle(0)  # munmaps the shm arena under the store lock
        for th in readers:
            th.join(timeout=30)
        assert not failures, failures
        s.close()

    def test_spill_cap_enforced_under_concurrent_writers(self, tmp_path):
        """Writer threads race rollovers against a 2-round disk cap: the cap
        must hold (TransportError, no overshoot) and accounting must stay
        exact through the failures and the final remove."""
        cap = 2 * 4096
        s = self._store(tmp_path, spill_disk_cap_bytes=cap)
        M = 10
        s.create_shuffle(0, M, 1)
        region = s.region_bytes(0)
        cap_hits = []
        ok = []

        unexpected = []

        def writer(m):
            try:
                w = s.map_writer(0, m)
                w.write_partition(0, self._pattern(m) * region)
                w.commit()
                ok.append(m)
            except TransportError as e:
                if "spill cap" in str(e):
                    cap_hits.append(m)
                else:
                    unexpected.append(f"map {m}: {e}")
            except BaseException as e:
                unexpected.append(f"map {m} crashed: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=writer, args=(m,)) for m in range(M)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not unexpected, unexpected
        assert cap_hits, "cap never enforced despite 10 full rounds vs a 2-round cap"
        assert 0 < s._spill_bytes <= cap, f"spilled {s._spill_bytes} B past cap {cap}"
        # committed rounds still read back exactly
        for m in ok:
            assert s.read_block(0, m, 0) == self._pattern(m) * region
        s.remove_shuffle(0)
        assert s._spill_bytes == 0
        s.close()
