"""Ragged block-gather kernels (ops/pallas_kernels.py) and the device-resident
batch fetch built on them (TpuShuffleCluster.fetch_blocks_to_device).

On the CPU test mesh the 'xla' lowering runs compiled and the 'tiled' Pallas
lowering runs in interpret mode; the 'dma' lowering needs real Mosaic
dynamic-size DMA and is covered by the TPU-gated test at the bottom (run on
hardware; skipped here)."""

import jax
import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.ops.pallas_kernels import (
    build_block_gather,
    build_block_scatter,
    pack_plan,
)
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

ROW = 512
LANE = ROW // 4


def _oracle(src, starts, counts):
    parts = [np.asarray(src)[s : s + c] for s, c in zip(starts, counts)]
    return (
        np.concatenate(parts)
        if parts
        else np.zeros((0, src.shape[1]), dtype=np.asarray(src).dtype)
    )


@pytest.fixture(scope="module")
def src(request):
    rng = np.random.default_rng(7)
    return jax.numpy.asarray(rng.integers(0, 1 << 30, size=(512, LANE), dtype=np.int32))


PLANS = [
    # (byte offset, byte length) pairs — ragged, with empties and sub-row tails
    [(0, ROW), (3 * ROW, 2 * ROW), (10 * ROW, 0), (40 * ROW, 7 * ROW + 17)],
    [(100 * ROW, 30 * ROW), (5 * ROW, 100), (200 * ROW, ROW * 8)],
    [(0, 13)],
    [],
]


class TestGatherLowering:
    @pytest.mark.parametrize("plan", PLANS)
    def test_xla_matches_oracle(self, src, plan):
        starts, counts, outs, total = pack_plan(plan, ROW)
        fn = build_block_gather(len(plan), max(total, 1), impl="xla")
        if not len(plan):
            return  # nothing to run; pack_plan handled the degenerate shape
        out = np.asarray(fn(starts, counts, outs, src))
        assert np.array_equal(out[:total], _oracle(src, starts, counts))

    @pytest.mark.parametrize("plan", PLANS[:3])
    def test_tiled_interpret_matches_oracle(self, src, plan):
        starts, counts, outs, total = pack_plan(plan, ROW)
        fn = build_block_gather(len(plan), max(total, 1), impl="tiled", interpret=True)
        out = np.asarray(fn(starts, counts, outs, src))
        assert np.array_equal(out[:total], _oracle(src, starts, counts))

    def test_tiled_covers_all_tail_shapes(self, src):
        # every residue mod TILE_ROWS, including count < TILE_ROWS
        plan = [(i * 16 * ROW, (i + 1) * ROW) for i in range(12)]
        starts, counts, outs, total = pack_plan(plan, ROW)
        fn = build_block_gather(len(plan), total, impl="tiled", interpret=True)
        out = np.asarray(fn(starts, counts, outs, src))
        assert np.array_equal(out[:total], _oracle(src, starts, counts))

    def test_pack_plan_rejects_misaligned(self):
        with pytest.raises(ValueError, match="aligned"):
            pack_plan([(ROW + 1, ROW)], ROW)

    def test_pack_plan_rows(self):
        starts, counts, outs, total = pack_plan([(0, 1), (ROW, ROW + 1)], ROW)
        assert counts.tolist() == [1, 2]
        assert outs.tolist() == [0, 1]
        assert total == 3

    def test_unknown_impl(self):
        with pytest.raises(ValueError, match="unknown impl"):
            build_block_gather(1, 1, impl="bogus")


OUT_ROWS = 256

# (dst slot row, row count) pairs — non-overlapping dst windows, with empties
SCATTER_PLANS = [
    [(3, 5), (40, 0), (64, 8), (200, 3)],
    [(0, 8), (16, 16), (250, 1)],
    [(95, 5)],
    [(0, 0)],
]


def _scatter_oracle(dst, src, starts, counts, outs):
    exp = np.asarray(dst).copy()
    s = np.asarray(src)
    for start, count, out in zip(starts, counts, outs):
        exp[start : start + count] = s[out : out + count]
    return exp


def _scatter_args(plan):
    starts = np.asarray([s for s, _ in plan], dtype=np.int32)
    counts = np.asarray([c for _, c in plan], dtype=np.int32)
    outs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
    return starts, counts, outs, int(counts.sum())


class TestScatterLowering:
    """build_block_scatter — the inverse kernel: packed src -> slot-layout dst.

    Every case pre-fills dst with a sentinel pattern and asserts both the
    placed blocks AND that uncovered dst rows survive untouched (scatter is a
    read-modify-write; a lowering that zeroes the staging buffer would pass a
    blocks-only check while destroying earlier writes in the same round)."""

    def _dst(self):
        rng = np.random.default_rng(23)
        return jax.numpy.asarray(
            rng.integers(0, 1 << 30, size=(OUT_ROWS, LANE), dtype=np.int32)
        )

    @pytest.mark.parametrize("plan", SCATTER_PLANS)
    def test_xla_matches_oracle(self, src, plan):
        starts, counts, outs, total = _scatter_args(plan)
        dst = self._dst()
        fn = build_block_scatter(len(plan), OUT_ROWS, impl="xla")
        out = np.asarray(fn(starts, counts, outs, src[: max(total, 1)], dst))
        assert np.array_equal(out, _scatter_oracle(dst, src, starts, counts, outs))

    @pytest.mark.parametrize("plan", SCATTER_PLANS[:3])
    def test_tiled_interpret_matches_oracle(self, src, plan):
        starts, counts, outs, total = _scatter_args(plan)
        dst = self._dst()
        fn = build_block_scatter(len(plan), OUT_ROWS, impl="tiled", interpret=True)
        out = np.asarray(fn(starts, counts, outs, src[: max(total, 1)], dst))
        assert np.array_equal(out, _scatter_oracle(dst, src, starts, counts, outs))

    def test_tiled_covers_all_tail_shapes(self, src):
        # every residue mod TILE_ROWS, including counts < TILE_ROWS
        plan = [(i * 20, i + 1) for i in range(12)]
        starts, counts, outs, total = _scatter_args(plan)
        dst = self._dst()
        fn = build_block_scatter(len(plan), OUT_ROWS, impl="tiled", interpret=True)
        out = np.asarray(fn(starts, counts, outs, src[:total], dst))
        assert np.array_equal(out, _scatter_oracle(dst, src, starts, counts, outs))

    def test_xla_window_clamp_at_buffer_edge(self, src):
        # regression: a block ending exactly at the last dst row must not have
        # its dynamic_slice window clamped backwards (would shift src rows)
        plan = [(OUT_ROWS - 7, 7)]
        starts, counts, outs, total = _scatter_args(plan)
        dst = self._dst()
        fn = build_block_scatter(1, OUT_ROWS, impl="xla", max_block_rows=7)
        out = np.asarray(fn(starts, counts, outs, src[:total], dst))
        assert np.array_equal(out, _scatter_oracle(dst, src, starts, counts, outs))

    def test_zero_count_padding_entries_are_noops(self, src):
        # cache-bucket padding appends (0, 0, total) entries; they must not
        # disturb dst row 0
        starts = np.asarray([10, 0, 0], dtype=np.int32)
        counts = np.asarray([4, 0, 0], dtype=np.int32)
        outs = np.asarray([0, 4, 4], dtype=np.int32)
        dst = self._dst()
        for impl, interp in (("xla", False), ("tiled", True)):
            fn = build_block_scatter(3, OUT_ROWS, impl=impl, interpret=interp)
            out = np.asarray(fn(starts, counts, outs, src[:4], dst))
            assert np.array_equal(
                out, _scatter_oracle(dst, src, starts, counts, outs)
            ), impl

    def test_unknown_impl(self):
        with pytest.raises(ValueError, match="unknown impl"):
            build_block_scatter(1, 1, impl="bogus")

    def test_dma_lowers_aot_for_tpu(self):
        # AOT Mosaic lowering: the dma kernel must export for the tpu platform
        # even from the CPU test mesh (catches pallas lowering regressions
        # without hardware; same pattern as the radix-sort AOT test)
        from jax import export as jax_export

        import jax.numpy as jnp

        fn = build_block_scatter(8, OUT_ROWS, impl="dma")
        i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)
        exported = jax_export.export(jax.jit(fn), platforms=["tpu"])(
            i32(8), i32(8), i32(8), i32(64, LANE), i32(OUT_ROWS, LANE)
        )
        assert len(exported.mlir_module_serialized) > 0


N_EXEC = 4


@pytest.fixture(scope="module")
def exchanged_cluster():
    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20,
        block_alignment=128,
        num_executors=N_EXEC,
        gather_impl="xla",  # CPU mesh: the portable lowering
        keep_device_recv=True,  # device-side fetch is the subject under test
    )
    cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
    rng = np.random.default_rng(11)
    M, R = 8, 8
    meta = cluster.create_shuffle(0, M, R)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=int(rng.integers(0, 3000)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(0)
    return cluster, meta, oracle, M, R


class TestDeviceFetch:
    def test_packed_blocks_match_oracle(self, exchanged_cluster):
        cluster, meta, oracle, M, R = exchanged_cluster
        lane = cluster.row_bytes // 4
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            bids = [ShuffleBlockId(0, m, r) for m in range(M)]
            packed, entries = cluster.fetch_blocks_to_device(consumer, 0, bids)
            packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
            assert packed.shape[1] == lane
            for (row_start, length), bid in zip(entries, bids):
                start = int(row_start) * cluster.row_bytes
                got = packed_bytes[start : start + int(length)].tobytes()
                assert got == oracle[(bid.map_id, bid.reduce_id)]

    @pytest.mark.parametrize("nblocks", [3, 5, 6, 7])
    def test_non_pow2_batch_padding(self, exchanged_cluster, nblocks):
        # regression: cache-bucket padding entries must keep the xla lowering's
        # outs+counts non-decreasing — with outs padded to 0 the last real
        # block came back zeroed
        cluster, meta, oracle, M, R = exchanged_cluster
        r = 1
        consumer = meta.owner_of_reduce(r)
        bids = [ShuffleBlockId(0, m, r) for m in range(nblocks)]
        packed, entries = cluster.fetch_blocks_to_device(consumer, 0, bids)
        packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
        for (row_start, length), bid in zip(entries, bids):
            start = int(row_start) * cluster.row_bytes
            assert packed_bytes[start : start + int(length)].tobytes() == oracle[
                (bid.map_id, bid.reduce_id)
            ], f"block {bid} corrupted with batch of {nblocks}"

    def test_facet_delegation(self, exchanged_cluster):
        cluster, meta, oracle, M, R = exchanged_cluster
        r = 0
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        bids = [ShuffleBlockId(0, m, r) for m in range(M)]
        packed, entries = t.fetch_blocks_device(bids)
        packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
        row_start, length = entries[2]
        got = packed_bytes[int(row_start) * cluster.row_bytes :][: int(length)].tobytes()
        assert got == oracle[(2, r)]

    def test_empty_request(self, exchanged_cluster):
        cluster, meta, *_ = exchanged_cluster
        packed, entries = cluster.fetch_blocks_to_device(0, 0, [])
        assert packed.shape[0] == 0 and entries.shape == (0, 2)

    def test_wrong_owner_rejected(self, exchanged_cluster):
        cluster, meta, oracle, M, R = exchanged_cluster
        r = 0
        wrong = (meta.owner_of_reduce(r) + 1) % N_EXEC
        with pytest.raises(TransportError, match="owned by"):
            cluster.fetch_blocks_to_device(wrong, 0, [ShuffleBlockId(0, 0, r)])

    def test_disabled_without_device_recv(self):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20,
            block_alignment=128,
            num_executors=2,
            keep_device_recv=False,
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        cluster.create_shuffle(0, 1, 2)
        t = cluster.transport(0)
        w = t.store.map_writer(0, 0)
        w.write_partition(0, b"x" * 100)
        w.write_partition(1, b"y" * 100)
        t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        with pytest.raises(TransportError, match="keep_device_recv"):
            cluster.fetch_blocks_to_device(0, 0, [ShuffleBlockId(0, 0, 0)])

    def test_multi_round_fetch(self):
        # tiny regions force a staging rollover -> blocks span two rounds
        conf = TpuShuffleConf(
            staging_capacity_per_executor=4096,
            block_alignment=128,
            num_executors=2,
            gather_impl="xla",
            keep_device_recv=True,
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        meta = cluster.create_shuffle(0, 2, 2)
        rng = np.random.default_rng(3)
        oracle = {}
        for m in range(2):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(2):
                payload = rng.integers(0, 256, size=1500, dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        assert cluster.transport(0).store.num_rounds(0) >= 1
        for r in range(2):
            consumer = meta.owner_of_reduce(r)
            bids = [ShuffleBlockId(0, m, r) for m in range(2)]
            packed, entries = cluster.fetch_blocks_to_device(consumer, 0, bids)
            packed_bytes = np.asarray(packed).reshape(-1).view(np.uint8)
            for (row_start, length), bid in zip(entries, bids):
                start = int(row_start) * cluster.row_bytes
                assert packed_bytes[start : start + int(length)].tobytes() == oracle[
                    (bid.map_id, bid.reduce_id)
                ]


@pytest.mark.skipif(
    jax.devices()[0].platform != "tpu", reason="dynamic-size DMA needs real Mosaic"
)
class TestDmaOnTpu:
    def test_dma_matches_oracle(self, src):
        plan = PLANS[0] + PLANS[1]
        starts, counts, outs, total = pack_plan(plan, ROW)
        fn = build_block_gather(len(plan), total, impl="dma")
        out = np.asarray(fn(starts, counts, outs, src))
        assert np.array_equal(out[:total], _oracle(src, starts, counts))

    def test_dma_scatter_matches_oracle(self, src):
        plan = SCATTER_PLANS[0] + SCATTER_PLANS[1]
        starts = np.asarray([s for s, _ in plan], dtype=np.int32)
        counts = np.asarray([c for _, c in plan], dtype=np.int32)
        outs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
        total = int(counts.sum())
        rng = np.random.default_rng(23)
        dst = jax.numpy.asarray(
            rng.integers(0, 1 << 30, size=(OUT_ROWS, LANE), dtype=np.int32)
        )
        expect = _scatter_oracle(dst, src, starts, counts, outs)
        fn = build_block_scatter(len(plan), OUT_ROWS, impl="dma")
        out = np.asarray(fn(starts, counts, outs, src[:total], dst))
        assert np.array_equal(out, expect)
