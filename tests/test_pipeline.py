"""Pipelined multi-round superstep engine (transport/pipeline.py).

Covers the engine's contract (ordering, bounded in-flight window, error
propagation), bit-identical results across pipeline depths for every
host_recv_mode, uneven per-executor spill rounds, and the capacity bucketing
that lets varying-size shuffles share one compiled exchange.
"""

import threading
import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.ops.exchange import bucket_send_rows, rebucket_slots
from sparkucx_tpu.transport.pipeline import RoundPipeline
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


class TestRoundPipeline:
    def test_results_in_round_order_all_depths(self):
        for depth in (1, 2, 3, 8):
            out = RoundPipeline(depth, lambda r: r * 10, lambda r, t: t + r).run(6)
            assert out == [r * 11 for r in range(6)]

    def test_depth_one_is_strictly_serial(self):
        events = []
        pipe = RoundPipeline(
            1, lambda r: events.append(("submit", r)), lambda r, t: events.append(("drain", r))
        )
        pipe.run(3)
        assert events == [
            ("submit", 0), ("drain", 0), ("submit", 1), ("drain", 1),
            ("submit", 2), ("drain", 2),
        ]

    def test_depth_two_overlaps_submit_with_drain(self):
        # Round 1 must be submitted before round 0's (slow) drain completes.
        order = []
        lock = threading.Lock()

        def submit(r):
            with lock:
                order.append(("submit", r))
            return r

        def drain(r, t):
            time.sleep(0.02)
            with lock:
                order.append(("drain", r))
            return t

        RoundPipeline(2, submit, drain).run(3)
        assert order.index(("submit", 1)) < order.index(("drain", 0))
        assert [e for e in order if e[0] == "drain"] == [("drain", r) for r in range(3)]

    def test_backpressure_bounds_inflight_window(self):
        # With depth d, round k may not be submitted until round k-d drained.
        depth = 2
        inflight = []
        peak = []
        lock = threading.Lock()

        def submit(r):
            with lock:
                inflight.append(r)
                peak.append(len(inflight))
            return r

        def drain(r, t):
            time.sleep(0.01)
            with lock:
                inflight.remove(r)
            return t

        RoundPipeline(depth, submit, drain).run(8)
        assert max(peak) <= depth + 1  # the submitting round plus the ring

    def test_drain_error_propagates_earliest_first(self):
        def drain(r, t):
            if r in (1, 3):
                raise TransportError(f"boom round {r}")
            return t

        with pytest.raises(TransportError, match="boom round 1"):
            RoundPipeline(3, lambda r: r, drain).run(5)

    def test_submit_error_propagates(self):
        def submit(r):
            if r == 2:
                raise ValueError("submit died")
            return r

        for depth in (1, 3):
            with pytest.raises(ValueError, match="submit died"):
                RoundPipeline(depth, submit, lambda r, t: t).run(4)

    def test_zero_rounds_and_depth_validation(self):
        assert RoundPipeline(4, lambda r: r, lambda r, t: t).run(0) == []
        with pytest.raises(ValueError, match="depth"):
            RoundPipeline(0, lambda r: r, lambda r, t: t)


class TestBucketHelpers:
    def test_bucket_send_rows(self):
        assert bucket_send_rows(200, 2) == 256   # slot 100 -> 128
        assert bucket_send_rows(256, 2) == 256   # already a pow2 slot: identity
        assert bucket_send_rows(1, 1) == 1
        assert bucket_send_rows(100, 1) == 128
        assert bucket_send_rows(7, 4) == 8       # ceil slot 2 -> 2, x4
        with pytest.raises(ValueError):
            bucket_send_rows(0, 2)

    def test_rebucket_slots_relocates_regions(self):
        n, old_slot, new_slot, lane = 3, 4, 8, 2
        payload = np.arange(n * old_slot * lane, dtype=np.int32).reshape(n * old_slot, lane)
        out = rebucket_slots(payload, n, n * new_slot)
        assert out.shape == (n * new_slot, lane)
        for j in range(n):
            region = payload[j * old_slot : (j + 1) * old_slot]
            assert np.array_equal(out[j * new_slot : j * new_slot + old_slot], region)
            assert not out[j * new_slot + old_slot : (j + 1) * new_slot].any()

    def test_rebucket_slots_identity_and_validation(self):
        p = np.ones((8, 2), np.int32)
        assert rebucket_slots(p, 2, 8) is p
        with pytest.raises(ValueError):
            rebucket_slots(p, 2, 6)  # buckets only grow
        with pytest.raises(ValueError):
            rebucket_slots(np.ones((7, 2), np.int32), 2, 8)  # not an executor multiple


def _run_spill_shuffle(n, depth, mode, *, uneven=False, shuffle_id=0):
    """One multi-round (spilled) shuffle end-to-end; returns
    (num_rounds, recv_sizes per round, {(m, r): block bytes})."""
    conf = TpuShuffleConf(
        staging_capacity_per_executor=n * 4096,  # 4 KiB per peer region
        block_alignment=128,
        num_executors=n,
        pipeline_depth=depth,
        host_recv_mode=mode,
        keep_device_recv=(mode == "device"),
    )
    cluster = TpuShuffleCluster(conf, num_executors=n)
    M, R = 3 * n, 2 * n
    meta = cluster.create_shuffle(shuffle_id, M, R)
    rng = np.random.default_rng(7)  # same data at every depth
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            # uneven: executor 0's maps write ~4x more, so it spills more
            # rounds than its peers and the round-count agreement pads
            size = 2000 if (not uneven or meta.map_owner[m] == 0) else 500
            payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    per_exec_rounds = [t.store.num_rounds(shuffle_id) for t in cluster.transports]
    assert max(per_exec_rounds) > 1, "test should actually spill"
    if uneven and n > 1:
        assert per_exec_rounds[0] > min(per_exec_rounds), "rounds should be uneven"
    cluster.run_exchange(shuffle_id)
    blocks = {}
    for (m, r) in oracle:
        consumer = meta.owner_of_reduce(r)
        view, length = cluster.locate_received_block(consumer, shuffle_id, m, r)
        blocks[(m, r)] = bytes(view[:length])
        assert blocks[(m, r)] == oracle[(m, r)], f"block {(m, r)} corrupted"
    sizes = [np.asarray(s).copy() for s in cluster.meta(shuffle_id).recv_sizes]
    cluster.remove_shuffle(shuffle_id)
    return max(per_exec_rounds), sizes, blocks


class TestBitIdenticalAcrossDepths:
    @pytest.mark.parametrize("mode", ["array", "memmap", "device"])
    def test_depths_match_serial(self, mode):
        base_rounds, base_sizes, base_blocks = _run_spill_shuffle(8, 1, mode)
        for depth in (2, 3):
            rounds, sizes, blocks = _run_spill_shuffle(8, depth, mode)
            assert rounds == base_rounds
            assert len(sizes) == len(base_sizes)
            for a, b in zip(sizes, base_sizes):
                assert np.array_equal(a, b)
            assert blocks == base_blocks

    @pytest.mark.parametrize("mode", ["array", "memmap"])
    def test_single_executor(self, mode):
        base = _run_spill_shuffle(1, 1, mode)
        for depth in (2, 3):
            got = _run_spill_shuffle(1, depth, mode)
            assert got[0] == base[0]
            assert got[2] == base[2]

    def test_uneven_spill_rounds(self):
        base = _run_spill_shuffle(4, 1, "array", uneven=True)
        for depth in (2, 3):
            got = _run_spill_shuffle(4, depth, "array", uneven=True)
            assert got[0] == base[0] and got[2] == base[2]
            for a, b in zip(got[1], base[1]):
                assert np.array_equal(a, b)


class TestCapacityBucketing:
    def test_two_row_counts_one_compile(self):
        # 100-row and 120-row slots both bucket to 128: ONE cache entry.
        n = 2
        conf = TpuShuffleConf(block_alignment=512, num_executors=n, pipeline_depth=2)
        cluster = TpuShuffleCluster(conf, num_executors=n)
        rng = np.random.default_rng(3)
        oracle = {}
        for sid, slot_rows in ((0, 100), (1, 120)):
            meta = cluster.create_shuffle(sid, n, n, capacity=n * slot_rows * 512)
            for m in range(n):
                t = cluster.transport(meta.map_owner[m])
                w = t.store.map_writer(sid, m)
                for r in range(n):
                    payload = rng.integers(0, 256, size=700 + 100 * sid, dtype=np.uint8).tobytes()
                    oracle[(sid, m, r)] = payload
                    w.write_partition(r, payload)
                t.commit_block(w.commit().pack())
            cluster.run_exchange(sid)
        assert len(cluster._exchange_cache) == 1, (
            "different send_rows in one slot bucket must share a compiled exchange"
        )
        for (sid, m, r), expect in oracle.items():
            consumer = cluster.meta(sid).owner_of_reduce(r)
            view, length = cluster.locate_received_block(consumer, sid, m, r)
            assert bytes(view[:length]) == expect

    def test_distinct_buckets_compile_separately(self):
        n = 2
        conf = TpuShuffleConf(block_alignment=512, num_executors=n)
        cluster = TpuShuffleCluster(conf, num_executors=n)
        for sid, slot_rows in ((0, 100), (1, 300)):  # buckets 128 vs 512
            meta = cluster.create_shuffle(sid, n, n, capacity=n * slot_rows * 512)
            for m in range(n):
                t = cluster.transport(meta.map_owner[m])
                w = t.store.map_writer(sid, m)
                for r in range(n):
                    w.write_partition(r, b"x" * 600)
                t.commit_block(w.commit().pack())
            cluster.run_exchange(sid)
        assert len(cluster._exchange_cache) == 2


class TestPipelineStats:
    def test_stage_stats_recorded(self):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=2 * 4096, block_alignment=128,
            num_executors=2, pipeline_depth=2,
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        meta = cluster.create_shuffle(0, 2, 2)
        rng = np.random.default_rng(1)
        for m in range(2):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(2):
                w.write_partition(r, rng.integers(0, 256, size=2000, dtype=np.uint8).tobytes())
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        kinds = cluster.stats.kinds()
        assert "exchange.pipeline.submit" in kinds
        assert "exchange.pipeline.drain" in kinds
        drain = cluster.stats.summary("exchange.pipeline.drain")
        assert drain.ops == max(t.store.num_rounds(0) for t in cluster.transports)
        assert drain.bytes > 0  # received bytes attributed to the drain lane
