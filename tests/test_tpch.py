"""TPC-H-style query pipelines over the device relational operators.

BASELINE.md lists "Spark SQL TPC-H q5/q18" as workload configs.  These tests
run miniature versions of both physical plans — the same operator DAG at small
scale — entirely through the device GROUP BY / hash-join primitives, with host
stage boundaries where Spark would have its own (each stage's output is the
next stage's shuffle input), verified against a numpy oracle.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    JoinSpec,
    build_grouped_aggregate,
    build_hash_join,
)

N = 8
CAP = 128


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _pad_table(keys, values, cap_per_shard):
    """Scatter rows round-robin over N shards as prefix-valid padded arrays —
    the stage-boundary materialization (each stage's input layout)."""
    width = values.shape[1]
    k = np.zeros(N * cap_per_shard, np.uint32)
    v = np.zeros((N * cap_per_shard, width), np.int32)
    nvalid = np.zeros(N, np.int32)
    for i, (ki, vi) in enumerate(zip(keys, values)):
        j = i % N
        assert nvalid[j] < cap_per_shard, "test table too big for capacity"
        k[j * cap_per_shard + nvalid[j]] = ki
        v[j * cap_per_shard + nvalid[j]] = vi
        nvalid[j] += 1
    return k, v, nvalid


def _shard(mesh, k, v, n):
    return (
        jax.device_put(k, NamedSharding(mesh, P("ex"))),
        jax.device_put(v, NamedSharding(mesh, P("ex", None))),
        jax.device_put(n, NamedSharding(mesh, P("ex"))),
    )


def _groups_to_host(gk, gv, gc, ng, rt, recv_capacity):
    assert np.all(np.asarray(rt) <= recv_capacity), "exchange overflowed"
    gk = np.asarray(gk).reshape(N, -1)
    gv = np.asarray(gv).reshape(N, gk.shape[1], -1)
    gc = np.asarray(gc).reshape(N, -1)
    ng = np.asarray(ng)
    keys = np.concatenate([gk[j, : ng[j]] for j in range(N)])
    vals = np.concatenate([gv[j, : ng[j]] for j in range(N)])
    cnts = np.concatenate([gc[j, : ng[j]] for j in range(N)])
    return keys, vals, cnts


def _join_to_host(ok, ob, op, cnt, rt):
    ok = np.asarray(ok).reshape(N, -1)
    ob = np.asarray(ob).reshape(N, ok.shape[1], -1)
    op = np.asarray(op).reshape(N, ok.shape[1], -1)
    cnt = np.asarray(cnt)
    assert np.all(cnt <= ok.shape[1]), "join output overflowed out_capacity"
    keys = np.concatenate([ok[j, : cnt[j]] for j in range(N)])
    b = np.concatenate([ob[j, : cnt[j]] for j in range(N)])
    p = np.concatenate([op[j, : cnt[j]] for j in range(N)])
    return keys, b, p


def test_q18_large_volume_orders(mesh, rng):
    """Q18 shape: GROUP BY lineitem.orderkey HAVING sum(qty) > T, then join
    the qualifying aggregates with orders."""
    num_orders = 300
    lineitems = 4000
    threshold = 60

    l_orderkey = rng.integers(0, num_orders, size=lineitems, dtype=np.uint64).astype(np.uint32)
    l_quantity = rng.integers(1, 20, size=(lineitems, 1), dtype=np.int64).astype(np.int32)
    o_orderkey = np.arange(num_orders, dtype=np.uint32)
    o_vals = np.stack(
        [rng.integers(0, 50, num_orders), rng.integers(100, 9000, num_orders)], axis=1
    ).astype(np.int32)  # (custkey, totalprice)

    # Stage 1 (device): GROUP BY orderkey SUM(quantity)
    agg = build_grouped_aggregate(
        mesh,
        AggregateSpec(
            num_executors=N, capacity=-(-lineitems // N), recv_capacity=lineitems,
            aggs=("sum",), impl="dense",
        ),
    )
    out = agg(*_shard(mesh, *_pad_table(l_orderkey, l_quantity, -(-lineitems // N))))
    keys, sums, _ = _groups_to_host(*out, recv_capacity=agg.spec.recv_capacity)

    # Stage 2 (host stage boundary): HAVING sum > T
    qual = sums[:, 0] > threshold
    hk, hv = keys[qual], sums[qual]

    # Stage 3 (device): join qualifying aggregates with orders on orderkey
    join = build_hash_join(
        mesh,
        JoinSpec(
            num_executors=N,
            build_capacity=-(-num_orders // N), build_recv_capacity=num_orders, build_width=1,
            probe_capacity=-(-num_orders // N), probe_recv_capacity=num_orders, probe_width=2,
            out_capacity=num_orders, impl="dense",
        ),
    )
    bk, bv, bn = _pad_table(hk, hv, -(-num_orders // N))
    pk, pv, pn = _pad_table(o_orderkey, o_vals, -(-num_orders // N))
    jk, jb, jp = _join_to_host(*join(*_shard(mesh, bk, bv, bn), *_shard(mesh, pk, pv, pn)))

    # Oracle (pure numpy over the same inputs)
    want_sums = np.bincount(l_orderkey, weights=l_quantity[:, 0], minlength=num_orders)
    want_qual = {int(k) for k in np.nonzero(want_sums > threshold)[0]}
    assert {int(k) for k in hk} == want_qual
    assert {int(k) for k in jk} == want_qual  # orders has every orderkey exactly once
    for k, b, p in zip(jk, jb, jp):
        assert b[0] == want_sums[int(k)]
        np.testing.assert_array_equal(p, o_vals[int(k)])


def test_q5_multi_join_then_group(mesh, rng):
    """Q5 shape: customer ⋈ orders on custkey, re-key to orderkey, ⋈ lineitem,
    then GROUP BY nationkey SUM(revenue)."""
    num_cust, num_orders, lineitems, num_nations = 120, 250, 2500, 12

    c_custkey = np.arange(num_cust, dtype=np.uint32)
    c_nation = rng.integers(0, num_nations, size=(num_cust, 1), dtype=np.int64).astype(np.int32)
    o_custkey = rng.integers(0, num_cust, size=num_orders, dtype=np.uint64).astype(np.uint32)
    o_orderkey = np.arange(num_orders, dtype=np.int32)[:, None]
    l_orderkey = rng.integers(0, num_orders, size=lineitems, dtype=np.uint64).astype(np.uint32)
    l_revenue = rng.integers(1, 500, size=(lineitems, 1), dtype=np.int64).astype(np.int32)

    # Stage 1 (device): customer ⋈ orders on custkey -> (custkey, nation, orderkey)
    join1 = build_hash_join(
        mesh,
        JoinSpec(
            num_executors=N,
            build_capacity=-(-num_cust // N), build_recv_capacity=num_cust, build_width=1,
            probe_capacity=-(-num_orders // N), probe_recv_capacity=num_orders, probe_width=1,
            out_capacity=num_orders, impl="dense",
        ),
    )
    _, nation_col, orderkey_col = _join_to_host(
        *join1(
            *_shard(mesh, *_pad_table(c_custkey, c_nation, -(-num_cust // N))),
            *_shard(mesh, *_pad_table(o_custkey, o_orderkey, -(-num_orders // N))),
        )
    )

    # Stage 2 (host boundary): re-key by orderkey, carry nation
    stage2_keys = orderkey_col[:, 0].astype(np.uint32)
    stage2_vals = nation_col.astype(np.int32)

    # Stage 3 (device): ⋈ lineitem on orderkey -> (orderkey, nation, revenue)
    join2 = build_hash_join(
        mesh,
        JoinSpec(
            num_executors=N,
            build_capacity=-(-num_orders // N), build_recv_capacity=num_orders, build_width=1,
            probe_capacity=-(-lineitems // N), probe_recv_capacity=lineitems, probe_width=1,
            out_capacity=lineitems, impl="dense",
        ),
    )
    _, nation2, revenue2 = _join_to_host(
        *join2(
            *_shard(mesh, *_pad_table(stage2_keys, stage2_vals, -(-num_orders // N))),
            *_shard(mesh, *_pad_table(l_orderkey, l_revenue, -(-lineitems // N))),
        )
    )

    # Stage 4 (device): GROUP BY nation SUM(revenue)
    agg = build_grouped_aggregate(
        mesh,
        AggregateSpec(
            num_executors=N, capacity=-(-lineitems // N), recv_capacity=lineitems,
            aggs=("sum",), impl="dense",
        ),
    )
    out = agg(
        *_shard(
            mesh, *_pad_table(nation2[:, 0].astype(np.uint32), revenue2, -(-lineitems // N))
        )
    )
    keys, sums, _ = _groups_to_host(*out, recv_capacity=agg.spec.recv_capacity)
    got = {int(k): int(s) for k, s in zip(keys, sums[:, 0])}

    # Oracle: pure numpy joins
    nation_of_order = c_nation[o_custkey, 0]          # orders ⋈ customer
    nation_of_line = nation_of_order[l_orderkey]      # lineitem ⋈ orders
    want = {}
    for nk, rev in zip(nation_of_line, l_revenue[:, 0]):
        want[int(nk)] = want.get(int(nk), 0) + int(rev)
    assert got == want


def test_q1_pricing_summary(mesh, rng):
    """q1 shape: pure grouped aggregation, several agg columns at once over a
    tiny key domain (returnflag/linestatus combos) — the no-join plan."""
    rows = 600
    # 6 distinct (returnflag, linestatus) combos, encoded as one uint32 key
    flags = rng.integers(0, 6, size=rows).astype(np.uint32)
    qty = rng.integers(1, 51, size=rows).astype(np.int32)
    price = rng.integers(100, 10000, size=rows).astype(np.int32)
    disc = rng.integers(0, 10, size=rows).astype(np.int32)
    values = np.stack([qty, price, disc, qty], axis=1)  # sum, sum, min, max

    spec = AggregateSpec(
        num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
        aggs=("sum", "sum", "min", "max"),
    )
    fn = build_grouped_aggregate(mesh, spec)
    k, v, nv = _pad_table(flags, values, CAP)
    gk, gv, gc, ng, rt = fn(*_shard(mesh, k, v, nv))
    keys, vals, cnts = _groups_to_host(gk, gv, gc, ng, rt, spec.recv_capacity)

    order = np.argsort(keys)
    keys, vals, cnts = keys[order], vals[order], cnts[order]
    assert np.array_equal(keys, np.arange(6, dtype=np.uint32))
    for f in range(6):
        m = flags == f
        assert vals[f, 0] == qty[m].sum(), "sum_qty"
        assert vals[f, 1] == price[m].sum(), "sum_price"
        assert vals[f, 2] == disc[m].min(), "min_disc"
        assert vals[f, 3] == qty[m].max(), "max_qty"
        assert cnts[f] == m.sum(), "count_order"


def test_q3_join_group_topk(mesh, rng):
    """q3 shape: customer⋈orders filter-join, then GROUP BY order with SUM
    (revenue), then host-side top-k — join feeding aggregation feeding sort."""
    n_cust, n_orders = 40, 300
    # build side: customers in the BUILDING segment (the filter), value = custkey
    seg_custs = np.sort(rng.choice(n_cust, size=n_cust // 2, replace=False)).astype(np.uint32)
    cust_vals = seg_custs.astype(np.int32)[:, None]
    # probe side: orders keyed by custkey, value = (orderkey, revenue)
    order_cust = rng.integers(0, n_cust, size=n_orders).astype(np.uint32)
    order_key = np.arange(n_orders, dtype=np.int32)
    # unique revenues: the top-k cut is unambiguous regardless of seed
    revenue = (rng.permutation(n_orders) + 1).astype(np.int32)
    probe_vals = np.stack([order_key, revenue], axis=1)

    jspec = JoinSpec(
        num_executors=N,
        build_capacity=CAP, build_recv_capacity=2 * CAP, build_width=1,
        probe_capacity=CAP, probe_recv_capacity=2 * CAP, probe_width=2,
        out_capacity=2 * CAP,
    )
    jfn = build_hash_join(mesh, jspec)
    bk, bv, bn = _pad_table(seg_custs, cust_vals, CAP)
    pk, pv, pn = _pad_table(order_cust, probe_vals, CAP)
    ok, ob, op, cnt, rt = jfn(*_shard(mesh, bk, bv, bn), *_shard(mesh, pk, pv, pn))
    jkeys, _, jprobe = _join_to_host(ok, ob, op, cnt, rt)

    # stage 2: GROUP BY orderkey, SUM(revenue) over the join output
    aspec = AggregateSpec(
        num_executors=N, capacity=2 * CAP, recv_capacity=4 * CAP, aggs=("sum",)
    )
    afn = build_grouped_aggregate(mesh, aspec)
    ak, av, an = _pad_table(
        jprobe[:, 0].astype(np.uint32), jprobe[:, 1:2], 2 * CAP
    )
    gk, gv, gc, ng, art = afn(*_shard(mesh, ak, av, an))
    keys, vals, _ = _groups_to_host(gk, gv, gc, ng, art, aspec.recv_capacity)

    # stage 3 (host, like Spark's TakeOrdered): top-5 by revenue
    top = np.argsort(-vals[:, 0], kind="stable")[:5]
    got = {(int(keys[i]), int(vals[i, 0])) for i in top}

    # oracle
    in_seg = np.isin(order_cust, seg_custs)
    o_keys, o_rev = order_key[in_seg], revenue[in_seg]
    want_sorted = sorted(zip(o_rev, o_keys), reverse=True)[:5]
    want = {(int(k), int(r)) for r, k in want_sorted}
    assert got == want


def test_q6_forecast_revenue_filtered_aggregate(mesh, rng):
    """q6 shape: scan -> FILTER -> global aggregate, no join — the WHERE
    clause (shipdate/discount/quantity band) pushed down on device via
    ``AggregateSpec.with_filter`` instead of pre-filtering the host table."""
    rows = 700
    qty = rng.integers(1, 60, size=rows).astype(np.int32)
    disc = rng.integers(0, 11, size=rows).astype(np.int32)
    price = rng.integers(100, 10000, size=rows).astype(np.int32)
    revenue = price * disc  # the summed expression, precomputed as a lane
    values = np.stack([revenue], axis=1)
    keys = np.zeros(rows, np.uint32)  # global aggregate: one group

    spec = AggregateSpec(
        num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
        aggs=("sum",), with_filter=True,
    )
    fn = build_grouped_aggregate(mesh, spec)
    k, v, nv = _pad_table(keys, values, CAP)
    predicate = (qty < 24) & (disc >= 5) & (disc <= 7)  # the q6 band
    # mask rows land where _pad_table dealt them: row i -> shard i % N, slot i // N
    m = np.zeros(N * CAP, bool)
    idx = np.arange(rows)
    m[(idx % N) * CAP + idx // N] = predicate
    gk, gv, gc, ng, rt = fn(
        *_shard(mesh, k, v, nv),
        jax.device_put(m, NamedSharding(mesh, P("ex"))),
    )
    keys_h, vals_h, cnts_h = _groups_to_host(gk, gv, gc, ng, rt, spec.recv_capacity)
    if predicate.any():
        assert len(keys_h) == 1 and keys_h[0] == 0
        assert vals_h[0, 0] == revenue[predicate].sum()
        assert cnts_h[0] == predicate.sum()
    else:  # pragma: no cover - rng never produces this at rows=700
        assert len(keys_h) == 0
    # recv totals count only unfiltered rows: the filter saved exchange traffic
    assert np.asarray(rt).sum() == predicate.sum()


def test_q13_customer_order_distribution(mesh, rng):
    """q13 shape: customer LEFT OUTER JOIN orders (customers with zero orders
    must appear), COUNT(orders) per customer, then the count-of-counts
    distribution — the query the left-outer arm exists for."""
    from sparkucx_tpu.ops.relational import run_grouped_aggregate, run_hash_join

    n_cust, n_orders = 80, 400
    custkeys = np.arange(n_cust, dtype=np.uint32)
    cust_vals = np.zeros((n_cust, 1), np.int32)
    # ~25% of customers get no orders at all
    ordering_custs = custkeys[rng.random(n_cust) < 0.75]
    order_cust = ordering_custs[rng.integers(0, len(ordering_custs), size=n_orders)].astype(np.uint32)
    order_vals = np.ones((n_orders, 1), np.int32)

    # probe = customer (the preserved SQL-left side), build = orders
    jk, jb, jp, jm = run_hash_join(
        mesh, order_cust, order_vals, custkeys, cust_vals,
        impl="dense", join_type="left_outer",
    )
    # COUNT(o_orderkey) per customer = matched rows only (NULLs don't count)
    spec = AggregateSpec(
        num_executors=N, capacity=-(-len(jk) // N), recv_capacity=4 * -(-len(jk) // N),
        aggs=("sum",),
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, spec, jk, jm.astype(np.int32)[:, None]
    )
    # oracle: orders per customer, including zeros
    want = np.bincount(order_cust, minlength=n_cust)
    assert np.array_equal(gk, custkeys)          # every customer present
    assert np.array_equal(gv[:, 0], want)        # COUNT per customer
    # the q13 output: distribution of customers by order count
    dist_keys, dist_counts = np.unique(gv[:, 0], return_counts=True)
    assert dist_counts.sum() == n_cust
    assert (want == 0).sum() == dist_counts[dist_keys == 0].sum()


def test_q4_order_priority_semi_join(mesh, rng):
    """q4 shape: orders SEMI JOIN lineitem (EXISTS a late lineitem), the
    lineitem predicate pushed down as a filter mask, then GROUP BY
    o_orderpriority COUNT(*) — semi join + WHERE pushdown composed."""
    from sparkucx_tpu.ops.columnar import shard_rows_host
    from sparkucx_tpu.ops.relational import run_grouped_aggregate

    num_orders, lineitems = 120, 900
    o_orderkey = np.arange(num_orders, dtype=np.uint32)
    o_priority = rng.integers(0, 5, size=num_orders).astype(np.int32)
    l_orderkey = rng.integers(0, num_orders, size=lineitems, dtype=np.uint64).astype(np.uint32)
    l_late = rng.random(lineitems) < 0.3  # commitdate < receiptdate

    # device semi join with the lineitem filter below the build exchange
    bcap = -(-lineitems // N)
    pcap = -(-num_orders // N)
    spec = JoinSpec(
        num_executors=N,
        build_capacity=bcap, build_recv_capacity=lineitems, build_width=1,
        probe_capacity=pcap, probe_recv_capacity=num_orders, probe_width=1,
        out_capacity=num_orders, impl="dense",
        with_filters=True, join_type="left_semi",
    )
    fn = build_hash_join(mesh, spec)
    bk, bv, bn = shard_rows_host(l_orderkey, np.zeros((lineitems, 1), np.int32), N, bcap)
    bm, _, _ = shard_rows_host(l_late.astype(np.uint32), np.zeros((lineitems, 0), np.int32), N, bcap)
    pk, pv, pn = shard_rows_host(o_orderkey, o_priority[:, None], N, pcap)
    out = fn(
        *_shard(mesh, bk, bv, bn), *_shard(mesh, pk, pv, pn),
        jax.device_put(bm.astype(bool), NamedSharding(mesh, P("ex"))),
        jax.device_put(np.ones(N * pcap, bool), NamedSharding(mesh, P("ex"))),
    )
    jk, _, jp = _join_to_host(*out[:4], out[4])

    # GROUP BY priority COUNT(*) over the qualifying orders
    agg_spec = AggregateSpec(
        num_executors=N, capacity=-(-max(len(jk), 1) // N),
        recv_capacity=4 * -(-max(len(jk), 1) // N), aggs=(),
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, agg_spec, jp[:, 0].astype(np.uint32), np.zeros((len(jk), 0), np.int32)
    )

    # numpy oracle: orders with >= 1 late lineitem, counted by priority
    exists = np.isin(o_orderkey, np.unique(l_orderkey[l_late]))
    want_k, want_c = np.unique(o_priority[exists], return_counts=True)
    assert np.array_equal(gk, want_k.astype(np.uint32))
    assert np.array_equal(gc, want_c)


def test_q16_supplier_count_distinct_with_exclusion(mesh, rng):
    """q16 shape: COUNT(DISTINCT ps_suppkey) GROUP BY part attributes, after
    excluding complained-about suppliers — a NOT IN anti join feeding a
    count-distinct aggregation (both round-5 vocabulary arms, composed the
    way the real plan composes them)."""
    from sparkucx_tpu.ops.relational import (
        oracle_aggregate,
        run_grouped_aggregate,
        run_hash_join,
    )

    n_parts, n_suppliers = 40, 60
    rows = 800
    # partsupp: (partkey, suppkey) pairs with duplication
    partkey = rng.integers(0, n_parts, size=rows, dtype=np.uint64).astype(np.uint32)
    suppkey = rng.integers(0, n_suppliers, size=rows).astype(np.int32)
    # suppliers with complaints (the NOT IN subquery's result)
    complained = rng.choice(n_suppliers, size=12, replace=False).astype(np.uint32)

    # stage 1: partsupp ANTI JOIN complaints ON suppkey (probe keyed by supp)
    jk, jb, jp = run_hash_join(
        mesh,
        complained, np.zeros((len(complained), 1), np.int32),
        suppkey.astype(np.uint32), np.stack([partkey.astype(np.int32), suppkey], axis=1),
        impl="dense", join_type="left_anti",
    )
    surv_part = jp[:, 0].astype(np.uint32)
    surv_supp = jp[:, 1][:, None].astype(np.int32)

    # stage 2: COUNT(DISTINCT suppkey) GROUP BY partkey over the survivors
    spec = AggregateSpec(
        num_executors=N, capacity=max(1, -(-len(surv_part) // N)) + 8,
        recv_capacity=4 * CAP, aggs=("count_distinct",),
    )
    gk, gv, gc = run_grouped_aggregate(mesh, spec, surv_part, surv_supp)

    keep = ~np.isin(suppkey, complained.astype(np.int64))
    wk, wv, wc = oracle_aggregate(
        partkey[keep], suppkey[keep][:, None], ("count_distinct",)
    )
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gv, wv)
    np.testing.assert_array_equal(gc, wc)  # per-group COUNT(*) rides along
    # and against the SQL meaning directly
    for k, cnt in zip(gk, gv[:, 0]):
        m = (partkey == k) & keep
        assert cnt == len(np.unique(suppkey[m]))


def test_q22_global_sales_opportunity(mesh, rng):
    """q22 shape: customers with above-average account balance and NO orders —
    a scalar AVG subquery (fused avg), a WHERE filter against it, and a NOT
    EXISTS anti join, then COUNT/SUM per country code."""
    from sparkucx_tpu.ops.relational import (
        oracle_aggregate,
        run_grouped_aggregate,
        run_hash_join,
    )

    n_cust = 300
    custkey = np.arange(n_cust, dtype=np.uint32)
    country = rng.integers(10, 17, size=n_cust).astype(np.uint32)  # cntrycode
    acctbal = rng.integers(-500, 5000, size=n_cust).astype(np.int32)
    # orders: ~half the customers have at least one
    order_cust = rng.choice(n_cust, size=n_cust // 2, replace=False).astype(np.uint32)

    # stage 1: scalar subquery AVG(acctbal) WHERE acctbal > 0 — one global
    # group through the fused-avg aggregation
    pos = acctbal > 0
    # ONE global group: every surviving row lands on a single shard, so the
    # receive buffer must hold all n_cust rows up front (a smaller bound
    # would deterministically retry-recompile)
    spec_avg = AggregateSpec(
        num_executors=N, capacity=max(1, -(-n_cust // N)) + 8,
        recv_capacity=n_cust, aggs=("avg",), with_filter=True,
    )
    ak, av, ac = run_grouped_aggregate(
        mesh, spec_avg, np.zeros(n_cust, np.uint32), acctbal[:, None], mask=pos
    )
    threshold = float(av[0, 0])
    assert threshold == acctbal[pos].astype(np.float64).mean()

    # stage 2: customers above threshold ANTI JOIN orders (NOT EXISTS)
    rich = acctbal.astype(np.float64) > threshold
    jk, jb, jp = run_hash_join(
        mesh,
        order_cust, np.zeros((len(order_cust), 1), np.int32),
        custkey[rich], np.stack([country[rich].astype(np.int32), acctbal[rich]], axis=1),
        impl="dense", join_type="left_anti",
    )

    # stage 3: COUNT(*), SUM(acctbal) GROUP BY cntrycode
    spec_f = AggregateSpec(
        num_executors=N, capacity=max(1, -(-max(len(jk), 1) // N)) + 8,
        recv_capacity=2 * CAP, aggs=("sum",),
    )
    gk, gv, gc = run_grouped_aggregate(
        mesh, spec_f, jp[:, 0].astype(np.uint32), jp[:, 1][:, None]
    )

    want_mask = rich & ~np.isin(custkey, order_cust)
    wk, wv, wc = oracle_aggregate(
        country[want_mask], acctbal[want_mask][:, None], ("sum",)
    )
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gv, wv)
    np.testing.assert_array_equal(gc, wc)
