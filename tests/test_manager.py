"""Tests for L4-L7: writer/reader/resolver/manager — the GroupByTest-style flow.

The reference's integration gate is stock Spark GroupByTest on a 2-executor
cluster (buildlib/test.sh:163-167); here the same shape runs through the manager
API: map tasks partition (key, value) records by hash, the collective superstep
moves blocks, reducers aggregate + sort and the result is checked against a pure
CPU groupBy oracle.
"""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.shuffle.manager import TpuShuffleManager
from sparkucx_tpu.shuffle.reader import serialize_records

N_EXEC = 4


@pytest.fixture(scope="module")
def manager():
    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20,
        num_executors=N_EXEC,
        max_blocks_per_request=3,  # force windowing in tests
    )
    mgr = TpuShuffleManager(conf, num_executors=N_EXEC)
    yield mgr
    mgr.stop()


def _write_records(manager, shuffle_id, map_id, num_reducers, records):
    """Partition records by hash(key) % R and write through the SPI writer."""
    writer = manager.get_writer(shuffle_id, map_id)
    by_part = {}
    for k, v in records:
        by_part.setdefault(hash(k) % num_reducers, []).append((k, v))
    for r in sorted(by_part):
        pw = writer.get_partition_writer(r)
        with pw.open_stream() as stream:
            stream.write(serialize_records(by_part[r]))
    return writer.commit_all_partitions()


class TestGroupByFlow:
    def test_groupby_end_to_end(self, manager, rng):
        M, R, SID = 6, 8, 0
        manager.register_shuffle(SID, M, R)
        oracle = {}
        for m in range(M):
            records = [(f"key-{int(rng.integers(0, 50))}", int(rng.integers(0, 1000))) for _ in range(200)]
            for k, v in records:
                oracle[k] = oracle.get(k, 0) + v
            lengths = _write_records(manager, SID, m, R, records)
            assert lengths.sum() > 0
        assert manager.exchange_ready(SID)
        manager.run_exchange(SID)

        got = {}
        for r in range(R):
            reader = manager.get_reader(
                SID, r, r + 1, aggregator=lambda a, b: a + b, key_ordering=True
            )
            out = list(reader.read())
            keys = [k for k, _ in out]
            assert keys == sorted(keys)  # key_ordering honored
            for k, v in out:
                assert hash(k) % R == r  # partition integrity
                got[k] = v
            assert reader.metrics.records_read >= len(out)
        assert got == oracle

    def test_reader_range_spanning_partitions(self, manager, rng):
        M, R, SID = 2, 8, 1
        manager.register_shuffle(SID, M, R)
        for m in range(M):
            _write_records(manager, SID, m, R, [(f"k{i}", i) for i in range(64)])
        manager.run_exchange(SID)
        # one reader over an executor's full contiguous range (R/N_EXEC partitions)
        meta = manager.cluster.meta(SID)
        start, end = meta.peer_ranges[0]
        reader = manager.get_reader(SID, start, end)
        records = list(reader.read())
        expected = [
            (f"k{i}", i) for i in range(64) if start <= hash(f"k{i}") % R < end
        ] * M
        assert sorted(map(str, records)) == sorted(map(str, expected))
        # windowing actually happened (max_blocks_per_request=3)
        assert reader.metrics.remote_blocks_fetched > 3

    def test_metrics_accounting(self, manager):
        # Deterministic partition placement (hash() is seed-randomized).
        M, R, SID = 1, 2, 2
        manager.register_shuffle(SID, M, R)
        writer = manager.get_writer(SID, 0)
        for r, records in [(0, [("a", 1)]), (1, [("b", 2), ("c", 3)])]:
            pw = writer.get_partition_writer(r)
            with pw.open_stream() as stream:
                stream.write(serialize_records(records))
        writer.commit_all_partitions()
        manager.run_exchange(SID)
        reader = manager.get_reader(SID, 0, 1)
        records = list(reader.read())
        m = reader.metrics
        assert records == [("a", 1)]
        assert m.remote_bytes_read > 0
        assert m.remote_blocks_fetched == 1
        assert m.records_read == 1
        assert m.fetch_wait_ns >= 0


class TestTeraSortFlow:
    def test_terasort_style_global_sort(self, manager, rng):
        """TeraSort shape (BASELINE.md config: 'TeraSort 10GB'): range-partition
        random keys so partition order == global order, sort within partitions,
        verify the concatenation is globally sorted and complete."""
        M, R, SID = 4, 8, 30
        manager.register_shuffle(SID, M, R)
        all_keys = []
        bounds = [int(2**32 * (i + 1) / R) for i in range(R - 1)]  # range partitioner

        def partition_of(key):
            import bisect

            return bisect.bisect_right(bounds, key)

        for m in range(M):
            keys = [int(k) for k in rng.integers(0, 2**32, size=500)]
            all_keys.extend(keys)
            writer = manager.get_writer(SID, m)
            by_part = {}
            for k in keys:
                by_part.setdefault(partition_of(k), []).append((k, f"row-{k}"))
            for r in sorted(by_part):
                pw = writer.get_partition_writer(r)
                with pw.open_stream() as stream:
                    stream.write(serialize_records(by_part[r]))
            writer.commit_all_partitions()
        manager.run_exchange(SID)

        merged = []
        for r in range(R):
            reader = manager.get_reader(SID, r, r + 1, key_ordering=True)
            part = [k for k, _ in reader.read()]
            assert part == sorted(part)  # sorted within partition
            if merged and part:
                assert merged[-1] <= part[0]  # range partitioning: global order
            merged.extend(part)
        assert merged == sorted(all_keys)  # complete and globally sorted
        manager.unregister_shuffle(SID)


class TestWriterProtocol:
    def test_partition_order_enforced(self, manager):
        manager.register_shuffle(10, 1, 4)
        w = manager.get_writer(10, 0)
        w.get_partition_writer(2)
        with pytest.raises(TransportError, match="increasing order"):
            w.get_partition_writer(1)

    def test_double_commit_rejected(self, manager):
        manager.register_shuffle(11, 1, 2)
        w = manager.get_writer(11, 0)
        pw = w.get_partition_writer(0)
        with pw.open_stream() as s:
            s.write(b"x")
        w.commit_all_partitions()
        with pytest.raises(TransportError, match="already committed"):
            w.commit_all_partitions()

    def test_commit_registers_blocks_with_transport(self, manager):
        from sparkucx_tpu.core.block import ShuffleBlockId

        manager.register_shuffle(12, 1, 2)
        w = manager.get_writer(12, 0)
        pw = w.get_partition_writer(1)
        with pw.open_stream() as s:
            s.write(b"registered!")
        w.commit_all_partitions()
        meta = manager.cluster.meta(12)
        owner = meta.map_owner[0]
        blk = manager.cluster.transport(owner).registered_block(ShuffleBlockId(12, 0, 1))
        assert blk is not None
        assert blk.get_size() == len(b"registered!")

    def test_write_lengths_reported(self, manager):
        manager.register_shuffle(13, 1, 3)
        w = manager.get_writer(13, 0)
        for r, size in [(0, 10), (2, 500)]:
            pw = w.get_partition_writer(r)
            with pw.open_stream() as s:
                s.write(b"z" * size)
        lengths = w.commit_all_partitions()
        assert lengths.tolist() == [10, 0, 500]


class TestResolver:
    def test_get_block_data_from_store(self, manager):
        manager.register_shuffle(20, 1, 2)
        _write_records(manager, 20, 0, 2, [("p", 1)])
        meta = manager.cluster.meta(20)
        owner = meta.map_owner[0]
        resolver = manager.resolvers[owner]
        r = next(r for r in range(2) if manager.cluster.transport(owner).store.block_length(20, 0, r))
        data = resolver.get_block_data(20, 0, r)
        assert len(data) > 0

    def test_unregister_shuffle_cleans_everything(self, manager):
        from sparkucx_tpu.core.block import ShuffleBlockId

        manager.register_shuffle(21, 1, 2)
        _write_records(manager, 21, 0, 2, [("q", 1), ("r", 2)])
        meta = manager.cluster.meta(21)
        owner = meta.map_owner[0]
        manager.unregister_shuffle(21)
        t = manager.cluster.transport(owner)
        assert t.registered_block(ShuffleBlockId(21, 0, 0)) is None
        with pytest.raises(TransportError):
            t.store.read_block(21, 0, 0)
        with pytest.raises(KeyError):
            manager.get_writer(21, 0)


class TestManagerLifecycle:
    def test_unknown_shuffle(self, manager):
        with pytest.raises(KeyError):
            manager.get_reader(999, 0, 1)

    def test_stop_idempotent(self):
        mgr = TpuShuffleManager(
            TpuShuffleConf(staging_capacity_per_executor=1 << 18, num_executors=2),
            num_executors=2,
        )
        mgr.stop()
        mgr.stop()


class _FlakyTransport:
    """Delegating wrapper that fails the batch fetch of one block N times —
    the batch path breaks, the per-block pull path still works."""

    def __init__(self, inner, fail_bid, fail_times=1):
        self.inner = inner
        self.fail_bid = fail_bid
        self.remaining = fail_times

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def fetch_blocks_by_block_ids(self, executor_id, bids, bufs, cbs):
        from sparkucx_tpu.core.operation import (
            OperationResult, OperationStats, OperationStatus, Request, TransportError,
        )

        out = []
        for bid, buf, cb in zip(bids, bufs, cbs):
            if bid == self.fail_bid and self.remaining > 0:
                self.remaining -= 1
                req = Request(OperationStats())
                req.stats.mark_done()
                req.complete(OperationResult(
                    OperationStatus.FAILURE,
                    error=TransportError("injected batch-fetch failure"),
                    stats=req.stats,
                ))
                out.append(req)
            else:
                out.extend(self.inner.fetch_blocks_by_block_ids(executor_id, [bid], [buf], [cb]))
        return out


class TestFetchRetry:
    """The reference never retries a failed fetch (SURVEY.md section 5.3); the
    reader's pull-path fallback must recover and count the retry."""

    def _shuffled_cluster(self):
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.transport.tpu import TpuShuffleCluster

        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
        )
        cluster = TpuShuffleCluster(conf, num_executors=2)
        meta = cluster.create_shuffle(0, 2, 2)
        payloads = {}
        for m in range(2):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(2):
                data = serialize_records([(f"k{m}{r}", m * 10 + r)])
                payloads[(m, r)] = data
                w.write_partition(r, data)
            t.commit_block(w.commit().pack())
        cluster.run_exchange(0)
        return cluster, meta, payloads

    def test_batch_failure_recovers_via_pull_path(self):
        from sparkucx_tpu.core.block import ShuffleBlockId
        from sparkucx_tpu.shuffle.reader import TpuShuffleReader

        cluster, meta, payloads = self._shuffled_cluster()
        r = 0
        consumer = meta.owner_of_reduce(r)
        flaky = _FlakyTransport(cluster.transport(consumer), ShuffleBlockId(0, 1, r))
        reader = TpuShuffleReader(
            flaky, consumer, 0, r, r + 1, 2,
            block_sizes=lambda m, rr: len(payloads[(m, rr)]),
            sender_of=lambda m: meta.map_owner[m],
            fetch_retries=1,
        )
        got = {blk.block_id.map_id: blk.data for blk in reader.fetch_blocks()}
        assert got == {0: payloads[(0, r)], 1: payloads[(1, r)]}
        assert reader.metrics.blocks_retried == 1
        assert reader.metrics.remote_blocks_fetched == 2

    def test_retries_disabled_raises(self):
        from sparkucx_tpu.core.block import ShuffleBlockId
        from sparkucx_tpu.core.operation import TransportError
        from sparkucx_tpu.shuffle.reader import TpuShuffleReader

        cluster, meta, payloads = self._shuffled_cluster()
        r = 0
        consumer = meta.owner_of_reduce(r)
        flaky = _FlakyTransport(cluster.transport(consumer), ShuffleBlockId(0, 1, r))
        reader = TpuShuffleReader(
            flaky, consumer, 0, r, r + 1, 2,
            block_sizes=lambda m, rr: len(payloads[(m, rr)]),
            sender_of=lambda m: meta.map_owner[m],
            fetch_retries=0,
        )
        with pytest.raises(TransportError, match="injected"):
            list(reader.fetch_blocks())
