"""Smoke tests for the perf benchmark CLI (UcxPerfBenchmark analogue)."""

import threading
import time

import pytest

from sparkucx_tpu.perf import benchmark


def test_client_server_roundtrip(capsys):
    # server in a daemon thread (it loops forever; we only need it serving)
    srv = threading.Thread(
        target=benchmark.run_server,
        args=(benchmark._parse_args(["server", "-a", "127.0.0.1:0", "-n", "4", "-s", "64k"]),),
        daemon=True,
    )
    # run_server binds its own port; to discover it we use a fixed port instead
    args_srv = benchmark._parse_args(["server", "-a", "127.0.0.1:13979", "-n", "4", "-s", "64k"])
    srv = threading.Thread(target=benchmark.run_server, args=(args_srv,), daemon=True)
    srv.start()
    # generous: on a loaded single-core CI box the server thread can starve
    # behind the suite's subprocesses for several seconds
    deadline = time.monotonic() + 30
    ready = False
    import socket

    while time.monotonic() < deadline and not ready:
        try:
            socket.create_connection(("127.0.0.1", 13979), timeout=0.2).close()
            ready = True
        except OSError:
            time.sleep(0.05)
    assert ready, "server did not come up"
    benchmark.run_client(
        benchmark._parse_args(
            ["client", "-a", "127.0.0.1:13979", "-n", "4", "-s", "64k", "-i", "2", "-o", "2"]
        )
    )
    out = capsys.readouterr().out
    assert "Mb/s" in out
    assert out.count("iter") >= 2


def test_superstep_mode(capsys):
    benchmark.run_superstep(
        benchmark._parse_args(
            ["superstep", "-s", "64k", "-i", "2", "-o", "2", "--executors", "4"]
        )
    )
    out = capsys.readouterr().out
    assert "impl=dense" in out  # CPU mesh resolves to the portable lowering
    assert out.count("GB/s") == 2


def test_failover_mode(capsys):
    # executor-loss sub-metric: steady vs primary-killed-at-50% loopback fetch
    benchmark.run_failover(
        benchmark._parse_args(["failover", "-n", "4", "-s", "128k", "-i", "1"])
    )
    out = capsys.readouterr().out
    assert "failover: steady" in out
    assert "recovery" in out
    assert "failovers" in out


def test_elastic_mode(capsys):
    # degraded-recovery sub-metric: full-mesh exchange vs killed-mid-superstep
    # shrink/restage/re-run (bit-identical asserted inside the measurement)
    benchmark.run_elastic(
        benchmark._parse_args(["elastic", "--executors", "4", "-s", "4k", "-i", "1"])
    )
    out = capsys.readouterr().out
    assert "elastic: steady" in out
    assert "killed mid-superstep" in out
    assert "recovery" in out
    assert "mesh 4 -> 2" in out


def test_tenants_mode(capsys):
    # multi-tenant serving plane: N concurrent apps streaming their own
    # tenant-namespaced blocks back through the shared-selector reactor
    benchmark.run_tenants(
        benchmark._parse_args(
            ["tenants", "--apps", "3", "-n", "4", "-s", "64k", "-i", "1"]
        )
    )
    out = capsys.readouterr().out
    assert "tenants: 3 apps" in out
    assert "fairness" in out and "p99 fetch" in out
    assert out.count("GB/s,") >= 3  # one per-app line per registered app


def test_cli_flags_match_reference():
    # -a/-f/-n/-s/-i/-o/-r/-t (UcxPerfBenchmark.scala:41-59)
    args = benchmark._parse_args(
        ["client", "-a", "h:1", "-f", "f", "-n", "2", "-s", "1k", "-i", "3", "-o", "4", "-r", "5", "-t", "6"]
    )
    assert (args.address, args.file, args.num_blocks) == ("h:1", "f", 2)
    assert (args.iterations, args.outstanding, args.reports, args.threads) == (3, 4, 5, 6)


def test_gather_mode(capsys):
    benchmark.run_gather(
        benchmark._parse_args(["gather", "-n", "6", "-s", "64k", "-i", "2", "-o", "2"])
    )
    out = capsys.readouterr().out
    assert "impl=xla" in out  # CPU resolves to the portable lowering
    assert out.count("GB/s") == 2


def test_gather_mode_tiled_interpret(capsys):
    # the Pallas tiled lowering runs compiled only on TPU; 'tiled' through the
    # CLI would need interpret mode, so just check flag plumbing
    args = benchmark._parse_args(["gather", "--impl", "dma"])
    assert args.impl == "dma"


def test_sort_mode(capsys):
    benchmark.run_sort(
        benchmark._parse_args(
            ["sort", "-n", "4096", "-i", "2", "--executors", "4"]
        )
    )
    out = capsys.readouterr().out
    assert "rows/s" in out and out.count("iter") == 2


def test_groupby_mode(capsys):
    benchmark.run_groupby(
        benchmark._parse_args(
            ["groupby", "-n", "4096", "-i", "2", "-o", "2", "--executors", "4",
             "--keys", "64"]
        )
    )
    out = capsys.readouterr().out
    assert "rows/s" in out and out.count("iter") == 2


def test_sort_external_mode(capsys):
    benchmark.run_sort(
        benchmark._parse_args(
            ["sort", "-n", "8192", "-i", "1", "--executors", "2", "--batches", "4"]
        )
    )
    out = capsys.readouterr().out
    assert "external-sorted" in out and "4 device batches" in out


def test_join_mode(capsys):
    benchmark.run_join(
        benchmark._parse_args(
            ["join", "-n", "4096", "-i", "2", "-o", "2", "--executors", "4"]
        )
    )
    out = capsys.readouterr().out
    assert "rows/s" in out and out.count("iter") == 2


def test_columnar_mode(capsys):
    benchmark.run_columnar(
        benchmark._parse_args(
            ["columnar", "-n", "4096", "-s", "128", "-i", "2", "-o", "2",
             "--executors", "4"]
        )
    )
    out = capsys.readouterr().out
    assert "impl=dense" in out  # CPU resolves to the portable lowering
    assert out.count("GB/s") == 2


def test_superstep_hierarchical_mode(capsys):
    benchmark.run_superstep(
        benchmark._parse_args(
            ["superstep", "-s", "64k", "-i", "1", "-o", "2", "--executors", "8", "--slices", "2"]
        )
    )
    out = capsys.readouterr().out
    assert out.count("GB/s") == 1


def test_tpu_smoke_script():
    """The hardware acceptance smoke must pass on the CI mesh (dense/xla
    lowerings) — the same script gates real-chip deployments."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "tpu_smoke.py")],
        capture_output=True, text=True, timeout=300, cwd=root,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all 8 drives passed" in r.stdout
